"""Overload-safe multi-tenant serving core (the ROADMAP serving front).

``DocService`` multiplexes tenant sessions onto a ``DocFleet`` through
the batched seams, with per-tenant token-bucket admission control and
bounded queues (typed ``TenantThrottled``/``Overloaded`` rejection),
request deadlines honored all-or-nothing at the fused-dispatch boundary
(typed ``DeadlineExceeded``), jittered-backoff retries under per-tenant
budgets (typed ``RetriesExhausted``), and a three-stage brownout ladder
(widen fsync batching -> defer compaction -> shed background sync).
``tools/loadgen.py`` is the standing scenario testbed; bench.py's
``service`` section reports p99 request latency and sustained rounds/s.

Layering note: ``core`` is loaded lazily (PEP 562) so the light policy
modules (``backoff``, ``admission``, ``deadline``, ``brownout``) stay
importable from ``fleet/`` without a cycle — ``fleet/faults.py`` reuses
``service.backoff`` for its reconnect schedule.
"""

from .admission import AdmissionController, TokenBucket
from .backoff import Backoff, RetryBudget
from .brownout import BrownoutController, brownout_stats
from .deadline import Deadline

__all__ = [
    'DocService', 'AsyncDocService', 'Session', 'Ticket', 'service_stats',
    'AdmissionController', 'TokenBucket', 'Backoff', 'RetryBudget',
    'BrownoutController', 'brownout_stats', 'Deadline',
]

_CORE = ('DocService', 'AsyncDocService', 'Session', 'Ticket',
         'service_stats')


def __getattr__(name):
    if name in _CORE:
        from . import core
        return getattr(core, name)
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
