"""Request deadlines with an all-or-nothing commit guarantee.

A deadline is an absolute point on an injected monotonic clock. The
contract every seam honors (fleet/backend.py ``apply_changes_docs``,
fleet/sync_driver.py — both take ``deadline=``): the check runs BEFORE
the batch's fused dispatch mutates anything, so a request either fails
``DeadlineExceeded`` fully-unapplied or commits fully — never a
half-applied document. There is deliberately NO post-commit check: work
that slipped past its deadline mid-commit still commits (late useful
work beats a torn doc), and the client sees success.
"""

import time

from ..errors import DeadlineExceeded

__all__ = ['Deadline']


class Deadline:
    """An absolute deadline on a monotonic clock. ``Deadline.after(s)``
    builds one `s` seconds out; ``check(now)`` raises typed
    ``DeadlineExceeded`` once passed; ``remaining(now)`` is the budget
    left (negative = late). The clock is stored so all later checks read
    the same time source the deadline was minted from."""

    __slots__ = ('at', 'clock')

    def __init__(self, at, clock=time.monotonic):
        self.at = float(at)
        self.clock = clock

    @classmethod
    def after(cls, seconds, clock=time.monotonic):
        return cls(clock() + float(seconds), clock=clock)

    def remaining(self, now=None):
        return self.at - (self.clock() if now is None else now)

    def expired(self, now=None):
        return self.remaining(now) < 0

    def check(self, now=None, what='request'):
        late = -self.remaining(now)
        if late > 0:
            raise DeadlineExceeded(
                f'{what}: deadline exceeded by {late * 1e3:.2f}ms',
                deadline=self.at, late_by=late)
        return self

    def __repr__(self):
        return f'Deadline(at={self.at:.6f})'
