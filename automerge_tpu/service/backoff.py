"""Bounded jittered backoff + retry budgets — the shared retry policy.

Two failure-amplification patterns kill overloaded systems: synchronized
retries (every client retrying on the same fixed cadence turns one blip
into a standing wave) and unbounded retries (a persistent fault times N
retrying callers multiplies the outage by N). This module is the one
policy object both the service retry path (service/core.py) and the
sync-stall reconnect (fleet/faults.py) draw from, so every retry in the
system is jittered, capped, and budgeted:

- ``Backoff`` — a deterministic-given-its-seed schedule of exponentially
  growing, jitter-spread delays with a hard try ceiling. Delays are unit
  agnostic: the service interprets them as seconds (wall-clock
  ``not_before``), the lockstep sync driver as ROUNDS — same curve, same
  code.
- ``RetryBudget`` — a token bucket over retries (not requests): each
  retry spends a token, tokens refill at a bounded rate. When the bucket
  is dry the caller must fail typed (``RetriesExhausted``) instead of
  retrying, so a tenant's retries can never exceed ``rate`` per second
  no matter how many of its requests are failing.
"""

import random

__all__ = ['Backoff', 'RetryBudget', 'RetryBudgetPool']


class Backoff:
    """Jittered exponential backoff schedule: attempt k (0-based) waits
    ``min(cap, base * factor**k)`` scaled by a random factor in
    ``[1 - jitter, 1]``. ``delay(k)`` is the wait before retry k;
    ``exhausted(k)`` is True once k reaches ``retries`` (the caller
    should give up typed). Seeded: a seed fully determines the schedule,
    so chaos tests replay identical retry traces."""

    def __init__(self, base=0.05, factor=2.0, cap=5.0, retries=6,
                 jitter=0.5, seed=0):
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f'jitter must be in [0, 1], got {jitter}')
        self.base = float(base)
        self.factor = float(factor)
        self.cap = float(cap)
        self.retries = int(retries)
        self.jitter = float(jitter)
        self.rng = random.Random(seed)

    def delay(self, attempt):
        """Wait before retry `attempt` (0-based). Draws from the
        schedule's PRNG — one draw per call, so identical call sequences
        replay identical delays."""
        raw = min(self.cap, self.base * self.factor ** attempt)
        return raw * (1.0 - self.jitter * self.rng.random())

    def exhausted(self, attempt):
        """True once `attempt` retries have been spent."""
        return attempt >= self.retries


class RetryBudget:
    """Token bucket over RETRIES: ``spend(now)`` returns True and takes a
    token when one is available, False when the budget is dry (fail
    typed, do not retry). Tokens refill at ``rate``/sec up to ``burst``.
    The clock is passed in (monotonic seconds) so tests and the lockstep
    drivers control time."""

    def __init__(self, rate=10.0, burst=20.0):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = None
        self.spent = 0            # lifetime retries granted
        self.denied = 0           # lifetime retries refused

    def _refill(self, now):
        if self._last is not None and now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
        self._last = now

    def spend(self, now):
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            self.spent += 1
            return True
        self.denied += 1
        return False

    def available(self, now):
        self._refill(now)
        return self.tokens


class RetryBudgetPool:
    """Lazy per-tenant ``RetryBudget`` map with one shared rate/burst
    config — the memoization both ``DocService`` and ``ShardRouter``
    need, kept in ONE place so budget semantics can't diverge."""

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = float(burst)
        self._budgets = {}

    def get(self, tenant):
        b = self._budgets.get(tenant)
        if b is None:
            b = self._budgets[tenant] = RetryBudget(rate=self.rate,
                                                    burst=self.burst)
        return b
