"""Brownout ladder: graceful degradation under sustained pressure.

Overload is not binary. Between "healthy" and "shedding everything"
there is a ladder of cheap capacity the service can reclaim by spending
explicitly-bounded guarantees, in order of how much each costs the user
(SynchroStore's cost-deferral argument, PAPERS.md — background cost
should yield to the request path under pressure, not compete with it):

- **Stage 1 — widen durability batching.** The journal's
  ``fsync_bytes`` threshold rises to a configured ceiling, so group
  commits amortize across more bytes. The cost is a WIDER loss window
  (``pending_fsync_bytes``) — still bounded by the stage-1 ceiling, and
  visible as a registered health counter (fleet/durability.py).
- **Stage 2 — defer compaction/checkpoints.** Replay debt grows
  (recovery gets slower) but the request path stops paying snapshot
  cost. Deferred, not cancelled: de-escalation triggers a compaction
  check immediately.
- **Stage 3 — shed lowest-priority sync rounds.** Background
  anti-entropy (priority < ``shed_priority``) is rejected typed
  (``Overloaded`` with ``shed=True``); interactive work keeps flowing.
  CRDT sync is idempotent and delay-tolerant, so a shed round costs
  staleness, never correctness.

Transitions are hysteretic — pressure must hold above ``high`` for
``up_ticks`` service ticks to climb, below ``low`` for ``down_ticks``
to descend, one stage per transition — and every transition lands in a
health counter and a flight-recorder event, so an incident's ladder
history is in the forensic dump.
"""

from ..observability import recorder as _flight
from ..observability.metrics import Counters, register_health_source

__all__ = ['BrownoutController', 'brownout_stats']

_stats = Counters({
    'brownout_escalations': 0,     # stage climbs (monotonic)
    'brownout_deescalations': 0,   # stage descents (monotonic)
    'brownout_stage': 0,           # current stage across controllers (gauge)
    'shed_sync_rounds': 0,         # stage-3 typed sheds (monotonic)
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])


def brownout_stats():
    return dict(_stats)


class BrownoutController:
    """Pressure-driven stage machine (0 = healthy .. 3 = max brownout).

    ``observe(pressure)`` is called once per service tick with the
    admission pressure in [0, 1]; it returns the (possibly new) stage.
    The service consults ``stage`` (and helpers ``defer_compaction`` /
    ``shed_below``) when scheduling work. ``attach_journal`` points
    stage 1 at a journal whose ``fsync_bytes`` it may widen; the
    original value is restored on de-escalation below 1."""

    def __init__(self, high=0.75, low=0.35, up_ticks=3, down_ticks=8,
                 fsync_widen_bytes=4 << 20, shed_priority=1):
        self.high = float(high)
        self.low = float(low)
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        self.fsync_widen_bytes = int(fsync_widen_bytes)
        self.shed_priority = int(shed_priority)
        self.stage = 0
        self._above = 0
        self._below = 0
        self._journal = None
        self._journal_fsync_restore = None
        self.transitions = []       # (stage_from, stage_to, pressure) log

    # -- wiring ---------------------------------------------------------

    def attach_journal(self, journal):
        """The journal whose group-commit batching stage 1 widens. Safe
        to re-attach after rotation (checkpoint swaps journal objects):
        a new journal inherits the current stage's policy."""
        self._journal = journal
        if journal is not None:
            self._journal_fsync_restore = journal.fsync_bytes
            if self.stage >= 1:
                journal.fsync_bytes = max(journal.fsync_bytes,
                                          self.fsync_widen_bytes)

    # -- the ladder -----------------------------------------------------

    def observe(self, pressure):
        """One tick's pressure sample -> (possibly new) stage, with
        hysteresis so a flapping signal cannot thrash the ladder."""
        if pressure >= self.high:
            self._above += 1
            self._below = 0
        elif pressure <= self.low:
            self._below += 1
            self._above = 0
        else:
            self._above = 0
            self._below = 0
        if self._above >= self.up_ticks and self.stage < 3:
            self._transition(self.stage + 1, pressure)
            self._above = 0
        elif self._below >= self.down_ticks and self.stage > 0:
            self._transition(self.stage - 1, pressure)
            self._below = 0
        return self.stage

    def _transition(self, new_stage, pressure):
        old = self.stage
        self.stage = new_stage
        if new_stage > old:
            _stats.inc('brownout_escalations')
        else:
            _stats.inc('brownout_deescalations')
        _stats['brownout_stage'] = new_stage
        self.transitions.append((old, new_stage, pressure))
        self._apply_stage(old)
        _flight.record_event('brownout', stage_from=old,
                             stage_to=new_stage,
                             pressure=round(pressure, 4))

    def _apply_stage(self, old):
        j = self._journal
        if j is None:
            return
        if self.stage >= 1 and old < 1:
            self._journal_fsync_restore = j.fsync_bytes
            j.fsync_bytes = max(j.fsync_bytes, self.fsync_widen_bytes)
        elif self.stage < 1 and old >= 1:
            j.fsync_bytes = self._journal_fsync_restore or 0
            # the widened loss window closes NOW, not at the next
            # naturally-large commit
            j.sync()

    # -- what the service consults per tick -----------------------------

    @property
    def defer_compaction(self):
        """Stage >= 2: skip cost-based compaction checks this tick."""
        return self.stage >= 2

    def shed_below(self):
        """Priority floor below which sync work is shed (None = no
        shedding this tick)."""
        return self.shed_priority if self.stage >= 3 else None

    def count_shed(self, n=1):
        _stats.inc('shed_sync_rounds', n)
