"""The in-process multi-tenant serving core.

This is the ROADMAP's "in-process driver first" step toward the async
document service: ``DocService`` multiplexes tenant SESSIONS (each bound
to one document in a shared ``DocFleet``) onto the batched seams —
every tick's admitted apply work lands in ONE fused
``apply_changes_docs`` dispatch and every tick's sync work in one
batched receive + one batched generate round — while staying correct
and fair under overload:

- **Admission** (service/admission.py): typed ``Overloaded`` /
  ``TenantThrottled`` BEFORE any work is queued; round-robin drain so
  one tenant's flood cannot age another tenant's queue.
- **Deadlines** (service/deadline.py): checked when a request is pulled
  into a batch and re-checked by the seam immediately before the fused
  dispatch — a request fails ``DeadlineExceeded`` fully-unapplied or
  commits fully, never half-applied.
- **Retries** (service/backoff.py): a request carrying a ``payload_fn``
  (the transport re-draw — what a client retransmit delivers) is
  retried on wire-corruption faults with jittered backoff under a
  per-tenant retry budget; the budget dry or the schedule exhausted is
  a typed ``RetriesExhausted``, not another retry. Sync sessions that
  stall (traffic, no head progress — a dropped message poisoned
  ``sentHashes`` upstream) reconnect with fresh sync state on the same
  backoff curve and budget.
- **Brownout** (service/brownout.py): sustained admission pressure
  climbs the widen-fsync → defer-compaction → shed-background-sync
  ladder, every transition in the health counters and flight recorder.
- **Queries** (automerge_tpu/query/): the 'materialize_at' kind serves
  time-travel reads (all of a tick's reads in ONE fused replay
  dispatch), the 'subscribe' kind incremental patch pulls (one diff per
  (doc, cursor) equivalence class, zero device work). Subscription
  pushes default to sub-priority — the first citizens of the brownout
  shed stage.
- **SLO telemetry** (observability/slo.py): every resolution — and
  every typed admission-edge rejection — lands in the per-(tenant,
  kind) SLI accounting; one registry evaluation per tick drives the
  multi-window burn-rate alerts. ``slo=False`` is the telemetry-off
  build the <=2% overhead budget is measured against. Requests carry a
  ``TraceContext`` (observability/tracecontext.py): minted at submit
  while spans are recording, recorded as span LINKS on the fused batch
  spans, adopted from (and echoed into) the wire envelope on enveloped
  sync exchanges.

The core is deliberately tick-driven and synchronous (``pump()`` runs
one batch round; the engine below is single-threaded by contract);
``AsyncDocService`` is the asyncio facade that turns tickets into
awaitables and pumps from an event-loop task. All time flows through an
injected monotonic clock so tests and the loadgen drive it explicitly.
"""

import asyncio
import time

from ..errors import (AutomergeError, DeadlineExceeded, Overloaded,
                      RetriesExhausted, SessionClosed, WireCorruption)
from ..fleet import backend as fleet_backend
from ..fleet.hashindex import release_sync_state
from ..fleet.sync_driver import (generate_sync_messages_docs,
                                 receive_sync_messages_docs)
from ..observability import hist as _hist
from ..observability import perf as _perf
from ..observability import recorder as _flight
from ..observability import tracecontext as _trace
from ..observability.metrics import Counters, register_health_source
from ..observability.slo import SloRegistry
from ..observability.spans import on as _spans_on, span as _span
from .admission import AdmissionController
from .backoff import Backoff, RetryBudgetPool
from .brownout import BrownoutController
from .deadline import Deadline

__all__ = ['DocService', 'AsyncDocService', 'Session', 'Ticket',
           'service_stats']

_stats = Counters({
    'service_requests': 0,         # submitted (admitted) requests
    'service_completed': 0,        # tickets resolved ok
    'service_failed': 0,           # tickets resolved with a typed error
    'deadline_exceeded': 0,        # requests dropped at their deadline
    'service_retries': 0,          # transient-fault retries scheduled
    'retry_budget_exhausted': 0,   # typed RetriesExhausted resolutions
    'sync_reconnects': 0,          # stalled sessions reset with backoff
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])


def service_stats():
    return dict(_stats)


class Ticket:
    """One request's completion handle. ``status`` moves pending -> 'ok'
    (``result`` holds the reply, e.g. sync response bytes) or 'error'
    (``error`` holds the TYPED exception — shedding is never untyped).
    ``latency`` is submit-to-resolution seconds on the service clock.
    ``trace`` is the request's ``TraceContext`` (minted at submit while
    spans are recording — the span-link audience — or adopted from the
    client's wire envelope on an enveloped sync request); None when
    nobody is tracing."""

    __slots__ = ('kind', 'tenant', 'session_id', 'status', 'result',
                 'error', 'submitted_at', 'finished_at', '_future',
                 'trace', '_slo')

    def __init__(self, kind, tenant, session_id, submitted_at):
        self.kind = kind
        self.tenant = tenant
        self.session_id = session_id
        self.status = 'pending'
        self.result = None
        self.error = None
        self.submitted_at = submitted_at
        self.finished_at = None
        self._future = None
        self.trace = None
        self._slo = None

    @property
    def done(self):
        return self.status != 'pending'

    @property
    def latency(self):
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def _finish(self, now, result=None, error=None):
        if self.done:
            return
        self.finished_at = now
        if error is not None:
            self.status = 'error'
            self.error = error
            _stats.inc('service_failed')
        else:
            self.status = 'ok'
            self.result = result
            _stats.inc('service_completed')
        latency = self.finished_at - self.submitted_at
        _hist.record_value('service_request_s', latency, scale=1e9,
                           unit='s')
        if self._slo is not None:
            self._slo.record(self.tenant, self.kind, latency, error,
                             trace=None if self.trace is None
                             else self.trace.trace_id)
        if self._future is not None and not self._future.done():
            self._future.set_result(self)

    def __repr__(self):
        return (f'Ticket({self.kind}, tenant={self.tenant!r}, '
                f'status={self.status!r})')


class _Request:
    __slots__ = ('kind', 'session', 'payload', 'payload_fn', 'deadline',
                 'priority', 'ticket', 'attempts', 'not_before', 'reset',
                 'enveloped')

    def __init__(self, kind, session, payload, payload_fn, deadline,
                 priority, ticket, reset=False):
        self.kind = kind
        self.session = session
        self.payload = payload
        self.payload_fn = payload_fn
        self.deadline = deadline
        self.priority = priority
        self.ticket = ticket
        self.attempts = 0
        self.not_before = 0.0
        self.reset = reset
        self.enveloped = False     # sync payload arrived trace-wrapped

    def draw_payload(self):
        """This attempt's bytes: the transport re-draw when the client
        models retransmission, else the fixed payload."""
        if self.payload_fn is not None:
            return self.payload_fn()
        return self.payload


class Session:
    """One tenant session bound to one fleet document plus the
    service-side sync state for that client. ``sub_cursor`` is the
    session's subscription cursor: the heads frontier of the last patch
    the service pushed to it ('subscribe' requests with no explicit
    cursor continue from here)."""

    __slots__ = ('id', 'tenant', 'handle', 'sync_state', 'closed',
                 'sub_cursor', '_last_heads', '_stall_rounds',
                 '_reconnect_attempts', '_sub_served_tick',
                 '_heads_moved_tick')

    def __init__(self, sid, tenant, handle):
        self.id = sid
        self.tenant = tenant
        self.handle = handle
        self.sync_state = _init_sync_state()
        self.closed = False
        self.sub_cursor = []
        self._last_heads = None
        self._stall_rounds = 0
        self._reconnect_attempts = 0
        self._sub_served_tick = None   # tick of the last subscribe serve
        self._heads_moved_tick = None  # first heads movement since then


def _init_sync_state():
    from ..backend.sync import init_sync_state
    return init_sync_state()


class DocService:
    """See the module docstring. Construct over a fresh fleet, an
    existing ``DocFleet``, or a ``DurableFleet`` (whose journal the
    brownout ladder then manages)."""

    def __init__(self, fleet=None, durable=None, *,
                 tenant_rate=200.0, tenant_burst=50.0, tenant_queue=64,
                 max_queued=10_000, batch_limit=4096,
                 default_timeout=None,
                 backoff=None, retry_rate=20.0, retry_burst=40.0,
                 stall_rounds=8,
                 brownout=None, slo=None, tiering=None, control=None,
                 clock=time.monotonic):
        from ..fleet.backend import DocFleet
        self.durable = durable
        # `tiering`: a fleet/tiering.py TieringController. When attached,
        # the pump's background-maintenance step runs THROUGH its cost
        # model — auto-demote under watermark pressure, cost-based
        # vacuum/compaction — and brownout stage 2 becomes a pressure
        # INPUT to that model (write-cost multiplier) instead of the
        # legacy hard defer-compaction override.
        self.tiering = tiering
        # `control`: a control/ Controller. When attached, the pump
        # ticks it after the observability hooks — the feedback loop
        # (admission-rate adaptation, freshness pins) rides the same
        # cadence as the signals it consumes. The controller binds
        # itself to this service here.
        self.control = control
        if control is not None:
            control.attach(service=self)
        if durable is not None:
            fleet = durable.fleet
        self.fleet = fleet if fleet is not None else DocFleet()
        self.clock = clock
        self.admission = AdmissionController(
            rate=tenant_rate, burst=tenant_burst, queue_limit=tenant_queue,
            max_queued=max_queued)
        self.batch_limit = int(batch_limit)
        self.default_timeout = default_timeout
        self.backoff = backoff if backoff is not None else Backoff()
        self._retry_budgets = RetryBudgetPool(retry_rate, retry_burst)
        self.stall_rounds = int(stall_rounds)
        self.brownout = brownout if brownout is not None \
            else BrownoutController()
        # `slo`: None = a default SloRegistry (per-tenant SLI accounting
        # on, DEFAULT_POLICIES objectives), an SloRegistry = use that
        # (custom objectives), False = accounting fully off (the
        # telemetry-off leg the <=2% overhead budget is measured
        # against). Trace contexts are minted iff accounting is on or
        # spans are recording.
        self.slo = None if slo is False else \
            (slo if slo is not None else SloRegistry())
        self._attached_journal = None
        self._attach_brownout_journal()
        self.sessions = {}
        self._next_sid = 0
        self._delayed = []             # backoff-parked retries
        self.ticks = 0
        self._adm_counts = (0, 0, 0)   # admission deltas across ticks

    # -- wiring ---------------------------------------------------------

    def _attach_brownout_journal(self):
        journal = self.durable.journal if self.durable is not None else \
            self.fleet.journal
        if journal is not self._attached_journal:
            self.brownout.attach_journal(journal)
            self._attached_journal = journal

    def _retry_budget(self, tenant):
        return self._retry_budgets.get(tenant)

    # -- sessions -------------------------------------------------------

    def open_sessions(self, tenants):
        """Open one session per entry of `tenants` (a list of tenant
        names) with O(1) device work for the whole batch (init_docs)."""
        handles = self.durable.init_docs(len(tenants)) \
            if self.durable is not None \
            else fleet_backend.init_docs(len(tenants), self.fleet)
        out = []
        for tenant, handle in zip(tenants, handles):
            sid = self._next_sid
            self._next_sid += 1
            session = Session(sid, tenant, handle)
            self.sessions[sid] = session
            out.append(session)
        return out

    def open_session(self, tenant):
        return self.open_sessions([tenant])[0]

    def close_session(self, session):
        """Disconnect: free the doc; still-queued requests resolve typed
        ('session closed') when their turn comes."""
        if session.closed:
            return
        session.closed = True
        fleet_backend.free_docs([session.handle])
        release_sync_state(session.sync_state)
        self.sessions.pop(session.id, None)

    def adopt_session(self, tenant, handle):
        """Bind a fresh session to an EXISTING doc of this service's
        fleet — the shard failover/migration promotion path: the doc
        already lives here (a warm replica kept current by inter-shard
        replication, or a migrant revived from a transferred chunk) and
        gains a serving session without any init dispatch. The session
        starts from scratch on everything BUT the doc: fresh per-peer
        sync state (the re-homed client reconnects with ``reset=True``
        — both ends handshake fresh; delivery is idempotent) and an
        empty subscription cursor (the router re-registers the standing
        cursor it tracked, and a cursor naming heads this doc never saw
        resolves as a TYPED resync, never a silently stale patch)."""
        sid = self._next_sid
        self._next_sid += 1
        session = Session(sid, tenant, handle)
        self.sessions[sid] = session
        return session

    def release_session(self, session):
        """Unbind a session WITHOUT freeing its doc — the migration
        donor path: the doc's bytes were just parked/transferred (its
        slot is already free), so ``close_session``'s free would
        double-free. Still-queued requests resolve typed ('session
        closed') when their turn comes, exactly like a disconnect."""
        if session.closed:
            return
        session.closed = True
        release_sync_state(session.sync_state)
        self.sessions.pop(session.id, None)

    # -- submission ------------------------------------------------------

    def submit(self, session, kind, payload=None, *, payload_fn=None,
               deadline=None, timeout=None, priority=None, reset=False):
        """Admit one request. Raises typed ``Overloaded`` /
        ``TenantThrottled`` at the edge; returns a ``Ticket`` otherwise.
        `kind` is 'apply' (payload: list of change bytes for the
        session's doc), 'sync' (payload: the client's sync message
        bytes, or None to solicit a server message), 'materialize_at'
        (payload: a heads frontier — hex hash list or encoded cursor
        bytes; the result is the saved document chunk at that historical
        frontier), or 'subscribe' (payload: the client's cursor —
        encoded bytes, a heads list, or None to continue from the
        session's auto-advancing cursor; the result is a patch event
        carrying the changes since the cursor). `payload_fn` replaces a
        fixed payload with a per-attempt transport draw, which is what
        makes wire faults retryable. `timeout` seconds mint a deadline
        on the service clock; an explicit `deadline` wins. `priority`
        defaults to 1 — except 'subscribe', which defaults to 0:
        subscription pushes are the first work the brownout ladder's
        shed stage drops. `reset=True` on a sync request marks a CLIENT
        RECONNECT: the service discards its side of the handshake state
        before processing — without this, a server whose `sentHashes`
        already cover everything goes silent at a freshly-reconnected
        (state lost) client and the handshake livelocks."""
        if kind not in ('apply', 'sync', 'materialize_at', 'subscribe'):
            raise ValueError(f"kind must be 'apply', 'sync', "
                             f"'materialize_at', or 'subscribe', got "
                             f'{kind!r}')
        if priority is None:
            priority = 0 if kind == 'subscribe' else 1
        if session.closed:
            # the client's own fault (it kept a dead handle), so it
            # burns the per-tenant 'throttled' budget, NOT the
            # 'overloaded' budget that pages when the SERVICE sheds
            raise self._slo_reject(session.tenant, kind, SessionClosed(
                'session closed', retry_after=None, shed=False,
                stage=None))
        now = self.clock()
        if deadline is None:
            t = timeout if timeout is not None else self.default_timeout
            if t is not None:
                deadline = Deadline(now + t, clock=self.clock)
        ticket = Ticket(kind, session.tenant, session.id, now)
        ticket._slo = self.slo
        if self.slo is not None or _spans_on():
            # minting is lazy about its audience: mint while SLO
            # accounting is on (the forensic dumps carry the id, so an
            # alert's offending requests stitch into a trace) or while
            # spans record (the span-link audience); an enveloped sync
            # request brings its OWN context, adopted in the sync round
            ticket.trace = _trace.mint()
        request = _Request(kind, session, payload, payload_fn, deadline,
                           priority, ticket, reset=reset)
        try:
            self.admission.admit(session.tenant, request, now)
        except AutomergeError as exc:
            # edge rejections never mint a ticket, but they burn a
            # tenant's availability budget all the same — account them
            # before the typed raise leaves the building
            raise self._slo_reject(session.tenant, kind, exc)
        _stats.inc('service_requests')
        return ticket

    def _slo_reject(self, tenant, kind, exc):
        """Account a typed admission-edge rejection (latency 0: the
        request never entered the system) and hand the error back for
        raising."""
        if self.slo is not None:
            self.slo.record(tenant, kind, 0.0, exc)
        return exc

    # -- the tick --------------------------------------------------------

    def pump(self, now=None):
        """One service tick: drain a fair batch, drop expired deadlines,
        run the fused apply + sync rounds, schedule retries, feed the
        brownout ladder. Returns the tick's stats dict."""
        now = self.clock() if now is None else now
        self.ticks += 1
        start = time.perf_counter()
        with _span('service_tick', tick=self.ticks):
            stats = self._pump_inner(now)
        _hist.record_value('service_tick_s', time.perf_counter() - start,
                           scale=1e9, unit='s')
        if self.slo is not None:
            # one evaluation round per service tick: the SLO windows are
            # tick-denominated, like the brownout ladder's hysteresis
            self.slo.tick(now)
        # the seam-perf observatory rides the same cadence: a no-op flag
        # check unless perf.enable_baselines()/enable_observatory() ran
        _perf.maybe_tick()
        # the control plane ticks LAST: its decision windows read the
        # SLO/perf state the hooks above just rolled
        if self.control is not None:
            self.control.tick(now)
        return stats

    def _pump_inner(self, now):
        stats = {'completed': 0, 'failed': 0, 'deadline_dropped': 0,
                 'retried': 0, 'shed': 0}
        # pressure inputs: backlog BEFORE the drain (after it the queue
        # is empty whenever batch_limit covers the tick — an idle-looking
        # queue under heavy typed rejection), plus the rejected fraction
        # at the admission edge since the LAST tick (rejections happen at
        # submit time, between pumps)
        queue_pressure = self.admission.pressure()
        adm = self.admission.stats
        counts = (adm['admitted'], adm['rejected_overloaded'],
                  adm['rejected_throttled'])
        prev_counts = getattr(self, '_adm_counts', counts)
        self._adm_counts = counts
        admitted = counts[0] - prev_counts[0]
        rejected = (counts[1] - prev_counts[1]) + \
            (counts[2] - prev_counts[2])
        batch = self._ripe_retries(now)
        batch += self.admission.drain(self.batch_limit - len(batch))

        applies, syncs, queries, subs = [], [], [], []
        buckets = {'apply': applies, 'sync': syncs,
                   'materialize_at': queries, 'subscribe': subs}
        shed_floor = self.brownout.shed_below()
        for request in batch:
            ticket = request.ticket
            if request.session.closed:
                # client's fault (disconnect left requests queued):
                # throttled budget, same as the submit-edge twin above
                ticket._finish(now, error=SessionClosed(
                    'session closed', retry_after=None, shed=False,
                    stage=None))
                stats['failed'] += 1
                continue
            if request.deadline is not None and \
                    request.deadline.remaining(now) < 0:
                late = -request.deadline.remaining(now)
                ticket._finish(now, error=DeadlineExceeded(
                    f'{request.kind}: deadline exceeded by '
                    f'{late * 1e3:.2f}ms before dispatch',
                    deadline=request.deadline.at, late_by=late))
                _stats.inc('deadline_exceeded')
                stats['deadline_dropped'] += 1
                continue
            if request.kind in ('sync', 'subscribe') and \
                    shed_floor is not None and \
                    request.priority < shed_floor:
                # subscription pushes default to sub-priority, so they
                # are the FIRST work this stage drops (staleness, never
                # wrongness: the cursor doesn't advance on a shed)
                self.brownout.count_shed()
                ticket._finish(now, error=Overloaded(
                    f'{request.kind} shed at brownout stage '
                    f'{self.brownout.stage}', retry_after=0.1, shed=True,
                    stage=self.brownout.stage))
                stats['shed'] += 1
                continue
            buckets[request.kind].append(request)

        if applies:
            self._run_applies(applies, now, stats)
        if queries:
            self._run_queries(queries, now, stats)
        if subs:
            self._run_subscriptions(subs, now, stats)
        if syncs:
            self._run_syncs(syncs, now, stats)

        # background maintenance: with a tiering controller attached the
        # cost model owns every decision (demote, vacuum, journal
        # compaction) with the brownout stage as its pressure input —
        # stage 2 defers by raising the write-cost bar, and still fires
        # when replay debt overwhelms it (flight-recorded either way).
        # Without one, the legacy threshold + hard stage-2 defer apply.
        if self.tiering is not None:
            self.tiering.tick(stage=self.brownout.stage,
                              durable=self.durable)
            if self.durable is not None:
                self._attach_brownout_journal()
        elif self.durable is not None:
            if not self.brownout.defer_compaction:
                self.durable.maybe_compact()
            self._attach_brownout_journal()
        reject_pressure = rejected / (admitted + rejected) \
            if (admitted + rejected) >= 8 else 0.0
        self.brownout.observe(max(queue_pressure, reject_pressure))
        stats['stage'] = self.brownout.stage
        stats['queued'] = self.admission.queued + len(self._delayed)
        return stats

    def _ripe_retries(self, now):
        if not self._delayed:
            return []
        ripe = [r for r in self._delayed if r.not_before <= now]
        if ripe:
            self._delayed = [r for r in self._delayed
                             if r.not_before > now]
        return ripe

    def _min_deadline(self, requests):
        deadlines = [r.deadline for r in requests if r.deadline is not None]
        if not deadlines:
            return None
        return min(deadlines, key=lambda d: d.at)

    def _seam_deadline_abort(self, requests, now, stats):
        """The seam refused the whole batch pre-dispatch (typed
        DeadlineExceeded): nothing committed. Resolve the requests that
        are actually late; requeue the rest at the front, unserved."""
        requeue = {}
        for request in requests:
            if request.deadline is not None and \
                    request.deadline.remaining(now) < 0:
                late = -request.deadline.remaining(now)
                request.ticket._finish(now, error=DeadlineExceeded(
                    f'{request.kind}: deadline exceeded by '
                    f'{late * 1e3:.2f}ms before dispatch',
                    deadline=request.deadline.at, late_by=late))
                _stats.inc('deadline_exceeded')
                stats['deadline_dropped'] += 1
            else:
                requeue.setdefault(request.session.tenant, []).append(
                    request)
        for tenant, requests_ in requeue.items():
            self.admission.requeue_front(tenant, requests_)

    def _fail_or_retry(self, request, error, now, stats):
        """A typed per-doc failure: retry when it is plausibly transient
        (the request carries a transport re-draw and the fault class is
        wire corruption) within backoff + budget; resolve typed
        otherwise. Never an untyped escape."""
        transient = request.payload_fn is not None and \
            isinstance(error, WireCorruption)
        if transient and not self.backoff.exhausted(request.attempts) and \
                self._retry_budget(request.session.tenant).spend(now):
            delay = self.backoff.delay(request.attempts)
            request.attempts += 1
            request.not_before = now + delay
            self._delayed.append(request)
            _stats.inc('service_retries')
            stats['retried'] += 1
            return
        if transient:
            _stats.inc('retry_budget_exhausted')
            _flight.record_event('retry_exhausted',
                                 tenant=request.session.tenant,
                                 request_kind=request.kind,
                                 attempts=request.attempts,
                                 error=type(error).__name__)
            exhausted = RetriesExhausted(
                f'{request.kind}: transient fault persisted through '
                f'{request.attempts} retries',
                attempts=request.attempts, tenant=request.session.tenant)
            exhausted.__cause__ = error
            error = exhausted
        request.ticket._finish(now, error=error)
        stats['failed'] += 1

    # -- the apply round -------------------------------------------------

    def _run_applies(self, requests, now, stats):
        """All apply requests of the tick in ONE fused quarantining
        dispatch. Requests for the same session concatenate in drain
        order; a quarantined doc fails (or retries) every request that
        contributed to it — none of its changes committed."""
        by_session = {}
        for request in requests:
            by_session.setdefault(request.session.id, []).append(request)
        sessions = []
        per_doc = []
        doc_requests = []
        bad = []                    # (request, typed error) pre-dispatch
        for sid, requests_ in by_session.items():
            session = requests_[0].session
            changes = []
            kept = []
            for request in requests_:
                try:
                    payload = request.draw_payload()
                except Exception as exc:       # a payload_fn that died
                    # client-fault, like 'session closed': throttled
                    # budget, not the paging overloaded one
                    bad.append((request, Overloaded(
                        f'transport draw failed: {exc!r}',
                        retry_after=None, shed=False, stage=None,
                        budget='throttled')))
                    continue
                if payload is None:            # chaos disconnect mid-draw
                    bad.append((request, Overloaded(
                        'transport delivered nothing', retry_after=0.01,
                        shed=False, stage=None, budget='throttled')))
                    continue
                changes.extend(bytes(b) for b in payload)
                kept.append(request)
            if kept:
                sessions.append(session)
                per_doc.append(changes)
                doc_requests.append(kept)
        for request, error in bad:
            self._fail_or_retry(request, error, now, stats)
        if not sessions:
            return
        kept_requests = [r for kept in doc_requests for r in kept]
        # one fused dispatch serves N admitted requests: the batch span
        # records every member's trace id as a span LINK, so a stitched
        # trace can attribute the shared dispatch to each request tree
        # (links are built only while spans record — the off-path cost
        # is one flag check)
        batch_span = _span('service_apply_batch', docs=len(sessions))
        if _spans_on():
            batch_span.set(links=[r.ticket.trace.trace_id
                                  for r in kept_requests
                                  if r.ticket.trace is not None])
        with batch_span:
            try:
                new_handles, _patches, errors = \
                    fleet_backend.apply_changes_docs(
                        [s.handle for s in sessions], per_doc,
                        mirror=False, on_error='quarantine',
                        deadline=self._min_deadline(kept_requests))
            except DeadlineExceeded:
                self._seam_deadline_abort(kept_requests, now, stats)
                return
        for session, handle, err, requests_ in zip(
                sessions, new_handles, errors, doc_requests):
            # the quarantine seam returns a VALID handle for every slot
            # (rejected docs roll back, they don't freeze) — adopt it
            # either way; only the tickets differ
            session.handle = handle
            if err is None:
                if session._heads_moved_tick is None:
                    # the freshness SLI's anchor: the first commit that
                    # moves this doc's heads past the last subscription
                    # serve starts the staleness clock
                    session._heads_moved_tick = self.ticks
                for request in requests_:
                    request.ticket._finish(now, result=len(request.payload)
                                           if request.payload is not None
                                           else None)
                    stats['completed'] += 1
            else:
                # the doc's whole tick-batch was rejected: nothing from
                # these requests committed (all-or-nothing holds)
                for request in requests_:
                    self._fail_or_retry(request, err.error, now, stats)

    # -- the query round ---------------------------------------------------

    def _cursor_of(self, request, now, stats):
        """Resolve a request's frontier payload: encoded cursor bytes
        (typed InvalidCursor on hostile input — the fuzzed decode
        boundary), a heads list, or None (the session's auto-advancing
        subscription cursor). Returns None after resolving the ticket
        on failure."""
        from ..errors import InvalidCursor
        from ..query import _stats as _query_stats
        from ..query.subscriptions import decode_cursor
        try:
            payload = request.draw_payload()
        except Exception as exc:
            self._fail_or_retry(request, Overloaded(
                f'transport draw failed: {exc!r}', retry_after=None,
                shed=False, stage=None, budget='throttled'), now, stats)
            return None
        if payload is None:
            return list(request.session.sub_cursor)
        if isinstance(payload, (bytes, bytearray)):
            try:
                return decode_cursor(payload)
            except InvalidCursor as exc:
                _query_stats.inc('invalid_cursors')
                _flight.record_event('invalid_cursor',
                                     tenant=request.session.tenant,
                                     session=request.session.id,
                                     request_kind=request.kind,
                                     error=type(exc).__name__)
                self._fail_or_retry(request, exc, now, stats)
                return None
        return [str(h) for h in payload]

    def _run_queries(self, requests, now, stats):
        """All time-travel reads of the tick in ONE fused replay
        dispatch (query.materialize_at_docs): each request's result is
        the saved document chunk at its requested frontier. A frontier
        outside the doc's history fails typed (UnknownHeads) without
        costing the others their batch."""
        from ..query import materialize_at_docs

        live = []
        frontiers = []
        for request in requests:
            cursor = self._cursor_of(request, now, stats)
            if cursor is None:
                continue
            live.append(request)
            frontiers.append(cursor)
        if not live:
            return
        try:
            handles, errors = materialize_at_docs(
                [r.session.handle for r in live], frontiers,
                fleet=self.fleet, deadline=self._min_deadline(live),
                on_error='quarantine')
        except DeadlineExceeded:
            self._seam_deadline_abort(live, now, stats)
            return
        to_free = []
        for request, handle, err in zip(live, handles, errors):
            if err is not None:
                self._fail_or_retry(request, err.error, now, stats)
                continue
            request.ticket._finish(
                now, result=bytes(handle['state'].save()))
            stats['completed'] += 1
            to_free.append(handle)
        if to_free:
            fleet_backend.free_docs(to_free)

    def _run_subscriptions(self, requests, now, stats):
        """All subscription pulls of the tick: one diff per
        (session-doc, cursor-frontier) equivalence class — pure
        hash-graph work, zero device dispatches — shared by every
        subscriber in the class. Bogus/stale cursors get a typed full
        RESYNC event; the cursor only ever advances to heads the pushed
        changes actually reach (never a wrong patch)."""
        from ..errors import UnknownHeads
        from ..query import _stats as _query_stats
        from ..query.subscriptions import diff_since

        memo = {}
        with _span('subscription_tick', subscribers=len(requests)):
            for request in requests:
                session = request.session
                cursor = self._cursor_of(request, now, stats)
                if cursor is None:
                    continue
                ckey = (session.id, tuple(sorted(cursor)))
                event = memo.get(ckey)
                if event is None:
                    try:
                        changes, heads = diff_since(
                            session.handle, cursor,
                            what='service_subscribe')
                        event = {'kind': 'patch', 'changes': changes,
                                 'heads': heads}
                    except UnknownHeads as exc:
                        _query_stats.inc('subscription_resyncs')
                        _query_stats.inc('unknown_heads')
                        _flight.record_event(
                            'invalid_cursor', tenant=session.tenant,
                            session=session.id,
                            error=type(exc).__name__,
                            message=str(exc)[:200])
                        changes, heads = diff_since(
                            session.handle, [], what='service_resync')
                        event = {'kind': 'resync', 'changes': changes,
                                 'heads': heads,
                                 'error': type(exc).__name__}
                    memo[ckey] = event
                else:
                    _query_stats.inc('subscription_diff_reuse')
                _query_stats.inc('subscription_pushes')
                session.sub_cursor = list(event['heads'])
                if self.slo is not None:
                    # cursor lag in service ticks: how long this pull's
                    # changes sat waiting — anchored at the tick the
                    # doc's heads FIRST moved past the last serve, not
                    # at the last serve itself (a slow poller whose
                    # changes landed one tick ago reads lag 1, not its
                    # whole poll gap). An empty patch means the cursor
                    # was AT the heads: lag 0, the steady state.
                    lag = 0
                    if event['changes']:
                        moved = session._heads_moved_tick
                        if moved is not None:
                            lag = self.ticks - moved
                        elif session._sub_served_tick is not None:
                            # heads moved via an unstamped path: the
                            # poll gap is the honest upper bound
                            lag = self.ticks - session._sub_served_tick
                    self.slo.record_freshness(session.tenant, lag)
                session._sub_served_tick = self.ticks
                session._heads_moved_tick = None
                request.ticket._finish(now, result=event)
                stats['completed'] += 1

    # -- the sync round ----------------------------------------------------

    def _run_syncs(self, requests, now, stats):
        """All sync requests of the tick in one batched receive round +
        one batched generate round. Each request's result is the
        service's reply message (or None when the handshake is quiet)."""
        sessions = []
        incoming = []
        live = []
        seen = set()
        deferred = {}
        for request in requests:
            if request.session.id in seen:
                # a sync round is a handshake step: one per session per
                # tick (the batched seam needs distinct docs); extras
                # run next tick, order preserved
                deferred.setdefault(request.session.tenant, []).append(
                    request)
                continue
            try:
                payload = request.draw_payload()
            except Exception as exc:
                self._fail_or_retry(request, Overloaded(
                    f'transport draw failed: {exc!r}', retry_after=None,
                    shed=False, stage=None, budget='throttled'), now,
                    stats)
                continue
            if payload is not None:
                # a tracing client prepends the trace envelope to its
                # sync bytes: adopt ITS trace id for this request (the
                # client owns the trace) and remember to wrap the reply
                ctx, payload = _trace.unwrap(payload)
                # probed PER ATTEMPT: enveloped follows what THIS
                # attempt's bytes carried, so a corrupt payload that
                # happened to start with the magic (stripped here, then
                # rejected by the decoder) cannot latch a plain client
                # into enveloped replies after its clean retry
                request.enveloped = ctx is not None
                if ctx is not None:
                    request.ticket.trace = ctx
            if request.reset:
                # client reconnect: both ends handshake fresh (delivery
                # is idempotent; only optimization state is discarded —
                # including the old link's peer-space, handed back here
                # so the fresh state can never inherit the sent set)
                release_sync_state(request.session.sync_state)
                request.session.sync_state = _init_sync_state()
                request.session._stall_rounds = 0
            seen.add(request.session.id)
            sessions.append(request.session)
            incoming.append(bytes(payload) if payload is not None else None)
            live.append(request)
        for tenant, requests_ in deferred.items():
            self.admission.requeue_front(tenant, requests_)
        if not live:
            return
        # Reconnect rounds emulate the SIMULTANEOUS handshake: the reply
        # is generated from the fresh state BEFORE the client's message
        # lands. Receiving first would let the receive shortcut set
        # lastSentHeads without sending (the alternating-turn trap
        # documented in fleet/faults.py) and the reconnected client
        # would solicit a silent server forever.
        pre_replies = {}
        reset_sessions = [s for s, r in zip(sessions, live) if r.reset]
        if reset_sessions:
            states, messages = generate_sync_messages_docs(
                [s.handle for s in reset_sessions],
                [s.sync_state for s in reset_sessions])
            for session, state, message in zip(reset_sessions, states,
                                               messages):
                session.sync_state = state
                pre_replies[session.id] = message
        batch_span = _span('service_sync_batch', docs=len(sessions))
        if _spans_on():
            batch_span.set(links=[r.ticket.trace.trace_id for r in live
                                  if r.ticket.trace is not None])
        with batch_span:
            try:
                handles, states, _patches, errors = \
                    receive_sync_messages_docs(
                        [s.handle for s in sessions],
                        [s.sync_state for s in sessions], incoming,
                        mirror=False, on_error='quarantine',
                        deadline=self._min_deadline(live))
            except DeadlineExceeded:
                self._seam_deadline_abort(live, now, stats)
                return
        ok_sessions = []
        ok_requests = []
        served_handles = []
        for session, handle, state, err, request, message in zip(
                sessions, handles, states, errors, live, incoming):
            session.handle = handle     # valid for rejected slots too
            if err is not None:
                # corrupt client message: the doc CONTENT and sync state
                # are untouched (containment) — transient by nature
                self._fail_or_retry(request, err.error, now, stats)
                continue
            session.sync_state = state
            served_handles.append(handle)
            if message is not None and session._heads_moved_tick is None:
                # a received sync message may have applied changes: start
                # the freshness clock (conservative — a quiet handshake
                # stamps too, costing at most a one-serve overestimate)
                session._heads_moved_tick = self.ticks
            if request.reset:
                # reply = the pre-receive handshake generated above
                request.ticket._finish(now, result=self._wrap_reply(
                    request, pre_replies.get(session.id)))
                stats['completed'] += 1
                continue
            ok_sessions.append(session)
            ok_requests.append(request)
        # recency feedback from the SYNC path, not just writes: a doc
        # that answers handshakes every tick must not be auto-demoted
        self._touch_tiering(served_handles)
        if not ok_sessions:
            return
        self._detect_stalls(ok_sessions, now)
        new_states, replies = generate_sync_messages_docs(
            [s.handle for s in ok_sessions],
            [s.sync_state for s in ok_sessions])
        for session, state, reply, request in zip(
                ok_sessions, new_states, replies, ok_requests):
            session.sync_state = state
            request.ticket._finish(now,
                                   result=self._wrap_reply(request, reply))
            stats['completed'] += 1

    def _wrap_reply(self, request, reply):
        """Trace-envelope a sync reply IFF the request arrived enveloped
        (the client opted in; plain clients always get plain bytes) —
        stamped with the service's own span id so the two sides of the
        exchange are distinct nodes of one trace."""
        if reply is None or not request.enveloped or \
                request.ticket.trace is None:
            return reply
        return _trace.wrap(reply, request.ticket.trace.child())

    def _touch_tiering(self, handles):
        """Stamp served docs on the tiering demote ring (register plus
        the second-chance bit). The clock otherwise only hears about
        writes, so a read-mostly doc serving sync handshakes every tick
        would look cold and get parked mid-conversation."""
        demote = getattr(self.tiering, 'demote', None) \
            if self.tiering is not None else None
        if demote is None or not handles:
            return
        demote.register(handles)
        demote.touch(handles)

    def _detect_stalls(self, sessions, now):
        """Reconnect-on-stall with jittered backoff + the tenant retry
        budget: a session whose handshake keeps exchanging traffic
        without head movement resets its service-side sync state (change
        delivery is idempotent; only optimization state is lost). The
        stall threshold grows along the backoff curve per reset, and a
        dry retry budget SKIPS the reset (it retries when tokens refill)
        instead of hammering."""
        from ..backend import get_heads
        for session in sessions:
            heads = tuple(get_heads(session.handle))
            their = session.sync_state.get('theirHeads')
            # a stall is SPLIT BRAIN THAT PERSISTS: the peer's advertised
            # heads differ from ours and ours are not moving. A quiet
            # converged handshake (equal heads) is not a stall, however
            # long it idles — resetting there would livelock.
            split = their is not None and sorted(their) != sorted(heads)
            if split and heads == session._last_heads:
                session._stall_rounds += 1
            else:
                session._stall_rounds = 0
                if not split:
                    session._reconnect_attempts = 0
            session._last_heads = heads
            threshold = self.stall_rounds * (1 + session._reconnect_attempts)
            if session._stall_rounds < threshold:
                continue
            if not self._retry_budget(session.tenant).spend(now):
                continue
            release_sync_state(session.sync_state)
            session.sync_state = _init_sync_state()
            session._stall_rounds = 0
            session._reconnect_attempts += 1
            _stats.inc('sync_reconnects')
            _flight.record_event('sync_reconnect', session=session.id,
                                 tenant=session.tenant,
                                 attempt=session._reconnect_attempts)

    # -- drain helpers ----------------------------------------------------

    def idle(self):
        return self.admission.queued == 0 and not self._delayed

    def run_until_idle(self, max_ticks=10_000, advance=None):
        """Pump until no work is queued or parked. `advance` (seconds per
        tick) steps an injected fake clock via pump(now=...) so parked
        retries ripen without wall-clock sleeps."""
        now = self.clock()
        for _ in range(max_ticks):
            if self.idle():
                return True
            self.pump(now=now)
            if advance is not None:
                now += advance
        return self.idle()


class AsyncDocService:
    """asyncio facade: ``await submit(...)`` resolves when the pump loop
    (one ``run()`` task per service) serves the request. Admission
    rejections raise typed immediately; resolved-with-error tickets
    raise their typed error from ``await``."""

    def __init__(self, service, idle_sleep=0.001):
        self.service = service
        self.idle_sleep = idle_sleep
        self._stop = False

    async def submit(self, session, kind, payload=None, **kwargs):
        ticket = self.service.submit(session, kind, payload, **kwargs)
        ticket._future = asyncio.get_running_loop().create_future()
        await ticket._future
        if ticket.status == 'error':
            raise ticket.error
        return ticket

    async def run(self):
        """The pump task: tick while work is queued, sleep until the
        earliest parked retry ripens when backoff parking is the only
        pending work (pumping through a parked delay would busy-spin a
        core on no-op ticks), yield while idle."""
        while not self._stop:
            service = self.service
            if service.admission.queued:
                service.pump()
                await asyncio.sleep(0)
            elif service._delayed:
                wait = min(r.not_before for r in service._delayed) - \
                    service.clock()
                if wait <= 0:
                    service.pump()
                    await asyncio.sleep(0)
                else:
                    await asyncio.sleep(min(wait, max(self.idle_sleep,
                                                      0.001)))
            else:
                await asyncio.sleep(self.idle_sleep)

    def stop(self):
        self._stop = True
