"""Per-tenant admission control: token buckets, bounded queues, fairness.

The fleet engine happily queues unbounded work — which means one
aggressive tenant can buffer minutes of backlog and every other tenant's
requests age behind it. Admission control moves the rejection to the
EDGE, typed, before any resources are committed:

- a GLOBAL ceiling on queued requests (``max_queued``) — past it the
  service is ``Overloaded`` for everyone, which is the signal the
  brownout ladder (service/brownout.py) consumes;
- a per-tenant token bucket (``rate``/``burst``) — a flooding tenant
  runs itself dry and gets ``TenantThrottled`` with a ``retry_after``
  hint while other tenants' buckets stay full;
- a per-tenant bounded queue (``queue_limit``) — even a tenant inside
  its rate cannot buffer unbounded latency; the queue bound converts
  backlog into typed pushback.

Dequeue order is round-robin ACROSS tenants, FIFO within one — an
N-request flood from tenant A delays tenant B by at most B's own queue
depth, not A's. All clocks are injected monotonic seconds so tests and
the loadgen drive time explicitly.
"""

from ..errors import Overloaded, TenantThrottled

__all__ = ['TokenBucket', 'AdmissionController']


class TokenBucket:
    """Classic token bucket: ``take(now)`` spends one token if available,
    else returns the seconds until one refills (0 never happens: a
    refusal always names a positive wait)."""

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = None

    def _refill(self, now):
        if self._last is not None and now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
        self._last = now

    def take(self, now):
        """None = token granted; float = retry_after seconds refused."""
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return None
        if self.rate <= 0:
            return float('inf')
        return (1.0 - self.tokens) / self.rate


class _Tenant:
    __slots__ = ('name', 'bucket', 'queue', 'admitted', 'throttled')

    def __init__(self, name, rate, burst):
        self.name = name
        self.bucket = TokenBucket(rate, burst)
        self.queue = []            # FIFO of admitted-but-unserved requests
        self.admitted = 0
        self.throttled = 0


class AdmissionController:
    """Admission + fair dequeue over tenant queues.

    ``admit(tenant, request, now)`` raises ``Overloaded`` /
    ``TenantThrottled`` (typed, with ``retry_after``) or enqueues.
    ``drain(limit)`` pops up to `limit` requests round-robin across
    tenants (FIFO within each) — the service tick's fair work source.
    ``pressure()`` is queued/global-capacity in [0, 1], the brownout
    ladder's primary signal."""

    def __init__(self, rate=200.0, burst=50.0, queue_limit=64,
                 max_queued=10_000):
        self.rate = float(rate)
        self.burst = float(burst)
        self.queue_limit = int(queue_limit)
        self.max_queued = int(max_queued)
        self.tenants = {}
        self.queued = 0
        self._rr = []              # round-robin tenant order
        self._rr_pos = 0
        self.stats = {'admitted': 0, 'rejected_overloaded': 0,
                      'rejected_throttled': 0}

    def tenant(self, name):
        t = self.tenants.get(name)
        if t is None:
            t = self.tenants[name] = _Tenant(name, self.rate, self.burst)
            self._rr.append(t)
        return t

    def admit(self, tenant, request, now):
        """Admit or raise. The global check runs FIRST: an overloaded
        service refuses everyone identically rather than letting quiet
        tenants in while the backlog drains (predictable pushback beats
        admission roulette under overload)."""
        if self.queued >= self.max_queued:
            self.stats['rejected_overloaded'] += 1
            raise Overloaded(
                f'service overloaded: {self.queued} requests queued '
                f'(ceiling {self.max_queued})', retry_after=0.05,
                shed=False, stage=None)
        t = self.tenant(tenant)
        retry_after = t.bucket.take(now)
        if retry_after is not None:
            t.throttled += 1
            self.stats['rejected_throttled'] += 1
            raise TenantThrottled(
                f'tenant {tenant!r} throttled: token bucket dry '
                f'(rate {t.bucket.rate}/s)', tenant=tenant,
                retry_after=retry_after)
        if len(t.queue) >= self.queue_limit:
            t.bucket.tokens += 1.0       # the refused request spent none
            t.throttled += 1
            self.stats['rejected_throttled'] += 1
            raise TenantThrottled(
                f'tenant {tenant!r} throttled: queue full '
                f'({self.queue_limit})', tenant=tenant,
                retry_after=1.0 / t.bucket.rate if t.bucket.rate > 0
                else None)
        t.queue.append(request)
        t.admitted += 1
        self.queued += 1
        self.stats['admitted'] += 1

    def set_tenant_rate(self, name, rate=None, burst=None):
        """Retarget one tenant's token-bucket refill ``rate`` (and/or
        ``burst``) in place — the control plane's actuator. The bucket
        object survives, so tokens already accrued are kept (clamped to
        the new burst) and the next ``_refill`` accrues at the new rate
        mid-flight. Returns the bucket."""
        bucket = self.tenant(name).bucket
        if rate is not None:
            bucket.rate = float(rate)
        if burst is not None:
            bucket.burst = float(burst)
            bucket.tokens = min(bucket.tokens, bucket.burst)
        return bucket

    def requeue_front(self, tenant, requests):
        """Push unserved requests back at the FRONT of their tenant's
        queue (a batch aborted before its dispatch — deadline raced, the
        work was not done). Exempt from the admission checks: these were
        already admitted and never served."""
        t = self.tenant(tenant)
        t.queue[:0] = requests
        self.queued += len(requests)

    def drain(self, limit):
        """Up to `limit` requests, round-robin across tenants with
        non-empty queues, FIFO within a tenant."""
        out = []
        if not self._rr or limit <= 0:
            return out
        n = len(self._rr)
        idle = 0
        while len(out) < limit and idle < n:
            t = self._rr[self._rr_pos % n]
            self._rr_pos += 1
            if t.queue:
                out.append(t.queue.pop(0))
                self.queued -= 1
                idle = 0
            else:
                idle += 1
        return out

    def pressure(self):
        """Queued fraction of the global ceiling, in [0, 1]."""
        if self.max_queued <= 0:
            return 0.0
        return min(1.0, self.queued / self.max_queued)
