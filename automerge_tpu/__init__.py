"""automerge_tpu: a TPU-native CRDT framework with the capabilities of
classic Automerge.

Public API (ref src/automerge.js): conflict-free replicated JSON documents —
maps, lists, text, tables, counters — edited concurrently by many actors and
merged deterministically, with a columnar binary change/document format and a
Bloom-filter peer sync protocol. The pluggable backend (`set_default_backend`)
is the seam where the batched JAX/XLA fleet engine (automerge_tpu.fleet)
slots in.
"""

from . import backend as _default_backend
from . import errors
from . import frontend as Frontend
from .columnar import encode_change, decode_change
from .errors import (
    AutomergeError, MalformedChange, MalformedDocument, MalformedSyncMessage,
    InvalidChange, DanglingPred, DuplicateOpId, SyncOverflow, DocError,
)
from .common import uuid, set_uuid_factory
from .frontend import (
    Text, Table, Counter, Observable, Int, Uint, Float64,
    get_object_id, get_object_by_id, get_actor_id, set_actor_id,
    get_conflicts, get_last_local_change,
)
from .frontend.views import MapView, ListView

_backend = _default_backend  # mutable: overridden with set_default_backend()


def Backend():
    return _backend


def init(options=None):
    """Create a new, empty document (ref src/automerge.js:14-23)."""
    if isinstance(options, str):
        options = {'actorId': options}
    elif options is None:
        options = {}
    elif not isinstance(options, dict):
        raise TypeError(f'Unsupported options for init(): {options}')
    merged = {'backend': _backend}
    merged.update(options)
    return Frontend.init(merged)


def from_(initial_state, options=None):
    """Create a document initialized with `initial_state`
    (ref src/automerge.js:28-31). Non-mapping initial states follow the
    reference's JS object-spread semantics: sequences and strings become
    index-keyed maps, scalars contribute nothing (ref test/test.js:39-55)."""
    initial_state = Frontend.normalize_initial_state(initial_state)
    return change(init(options), {'message': 'Initialization'},
                  lambda doc: doc.update(initial_state))


def change(doc, options=None, callback=None):
    """Mutate `doc` via a callback receiving a mutable proxy; returns the new
    document (ref src/automerge.js:33-36)."""
    new_doc, _req = Frontend.change(doc, options, callback)
    return new_doc


def empty_change(doc, options=None):
    new_doc, _req = Frontend.empty_change(doc, options)
    return new_doc


def _normalize_options(options):
    if isinstance(options, str):
        return {'actorId': options}
    return options or {}


def clone(doc, options=None):
    options = _normalize_options(options)
    state = _backend.clone(Frontend.get_backend_state(doc, 'clone'))
    return _apply_patch(init(options), _backend.get_patch(state), state, [],
                        options)


def free(doc):
    _backend.free(Frontend.get_backend_state(doc, 'free'))


def load(data, options=None):
    options = _normalize_options(options)
    state = _backend.load(data)
    return _apply_patch(init(options), _backend.get_patch(state), state, [data],
                        options)


def save(doc):
    return _backend.save(Frontend.get_backend_state(doc, 'save'))


def merge(local_doc, remote_doc):
    """Merge changes from `remote_doc` into `local_doc`
    (ref src/automerge.js:61-67)."""
    local_state = Frontend.get_backend_state(local_doc, 'merge')
    remote_state = Frontend.get_backend_state(remote_doc, 'merge', 'second')
    changes = _backend.get_changes_added(local_state, remote_state)
    new_doc, _patch = apply_changes(local_doc, changes)
    return new_doc


def get_changes(old_doc, new_doc):
    old_state = Frontend.get_backend_state(old_doc, 'getChanges')
    new_state = Frontend.get_backend_state(new_doc, 'getChanges', 'second')
    return _backend.get_changes(new_state, _backend.get_heads(old_state))


def get_all_changes(doc):
    return _backend.get_all_changes(Frontend.get_backend_state(doc, 'getAllChanges'))


def _apply_patch(doc, patch, backend_state, changes, options):
    new_doc = Frontend.apply_patch(doc, patch, backend_state)
    patch_callback = options.get('patchCallback') or \
        doc._options.get('patchCallback')
    if patch_callback:
        patch_callback(patch, doc, new_doc, False, changes)
    return new_doc


def apply_changes(doc, changes, options=None):
    old_state = Frontend.get_backend_state(doc, 'applyChanges')
    new_state, patch = _backend.apply_changes(old_state, changes)
    return [_apply_patch(doc, patch, new_state, changes, options or {}), patch]


def equals(val1, val2):
    """Deep structural equality ignoring metadata (ref src/automerge.js:94-103)."""
    if isinstance(val1, (MapView, dict)) and isinstance(val2, (MapView, dict)):
        keys1, keys2 = sorted(val1.keys()), sorted(val2.keys())
        if keys1 != keys2:
            return False
        return all(equals(val1[k], val2[k]) for k in keys1)
    if isinstance(val1, (ListView, list, tuple)) and \
            isinstance(val2, (ListView, list, tuple)):
        if len(val1) != len(val2):
            return False
        return all(equals(a, b) for a, b in zip(val1, val2))
    return val1 == val2


class _HistoryEntry:
    def __init__(self, history, index, actor):
        self._history = history
        self._index = index
        self._actor = actor

    @property
    def change(self):
        return decode_change(self._history[self._index])

    @property
    def snapshot(self):
        state = _backend.load_changes(_backend.init(),
                                      self._history[:self._index + 1])
        return Frontend.apply_patch(init(self._actor), _backend.get_patch(state),
                                    state)


def get_history(doc):
    """List of {change, snapshot} with lazy snapshot reconstruction
    (ref src/automerge.js:105-118)."""
    actor = Frontend.get_actor_id(doc)
    history = get_all_changes(doc)
    return [_HistoryEntry(history, i, actor) for i in range(len(history))]


def generate_sync_message(doc, sync_state):
    state = Frontend.get_backend_state(doc, 'generateSyncMessage')
    return _backend.generate_sync_message(state, sync_state)


def receive_sync_message(doc, old_sync_state, message):
    old_backend_state = Frontend.get_backend_state(doc, 'receiveSyncMessage')
    backend_state, sync_state, patch = _backend.receive_sync_message(
        old_backend_state, old_sync_state, message)
    if not patch:
        return [doc, sync_state, patch]
    changes = None
    if doc._options.get('patchCallback'):
        changes = _backend.decode_sync_message(message)['changes']
    return [_apply_patch(doc, patch, backend_state, changes, {}), sync_state, patch]


def init_sync_state():
    return _backend.init_sync_state()


def set_default_backend(new_backend):
    """Swap in a different backend implementation — the plug-in point for the
    TPU fleet backend (ref src/automerge.js:147-149)."""
    global _backend
    _backend = new_backend
