"""Column codecs: LEB128 varints, run-length, delta, and boolean encodings.

These are the storage/wire codecs of the Automerge columnar format
(reference: backend/encoding.js). Byte-for-byte compatible with the
reference implementation: the RLE well-formedness rules (no repetition
counts of 1, no successive runs of the same kind, no repeated values
inside literals) make the encoding canonical, and the encoders here
produce exactly that canonical form.

Python integers are arbitrary precision, so unlike the JS reference
(backend/encoding.js:168-226) we do not split 64-bit values into two
32-bit halves; the width-suffixed methods differ only in their range
checks, which mirror the reference's error conditions exactly.
"""

MAX_SAFE_INTEGER = 2 ** 53 - 1
MIN_SAFE_INTEGER = -(2 ** 53 - 1)


def hex_string_to_bytes(value):
    """Convert a string of lowercase hex digit pairs to bytes (ref encoding.js:22-34)."""
    if not isinstance(value, str):
        raise TypeError('value is not a string')
    if len(value) % 2 != 0 or not all(c in '0123456789abcdef' for c in value):
        raise ValueError('value is not hexadecimal')
    return bytes.fromhex(value)


def bytes_to_hex_string(data):
    return bytes(data).hex()


def _check_int(value):
    if not isinstance(value, int) or isinstance(value, bool):
        raise ValueError('value is not an integer')


def uleb_append(out, value):
    """Append an unsigned LEB128 to a bytearray (the allocation-free
    counterpart of Encoder._append_uleb, shared by the sync message and
    Bloom filter fast paths)."""
    if value < 0 or value > 0xffffffffffffffff:
        raise ValueError('number out of range')
    while True:
        b = value & 0x7f
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


class Encoder:
    """Growable byte buffer with LEB128 append operations (ref encoding.js:57-286)."""

    def __init__(self):
        self.buf = bytearray()

    @property
    def buffer(self):
        self.finish()
        return bytes(self.buf)

    def finish(self):
        pass

    def append_byte(self, value):
        self.buf.append(value)

    def _append_uleb(self, value):
        n = 0
        while True:
            byte = value & 0x7f
            value >>= 7
            if value:
                self.buf.append(byte | 0x80)
                n += 1
            else:
                self.buf.append(byte)
                return n + 1

    def _append_sleb(self, value):
        n = 0
        while True:
            byte = value & 0x7f
            value >>= 7  # arithmetic shift: propagates sign
            done = (value == 0 and byte & 0x40 == 0) or (value == -1 and byte & 0x40)
            if done:
                self.buf.append(byte)
                return n + 1
            self.buf.append(byte | 0x80)
            n += 1

    def append_uint32(self, value):
        _check_int(value)
        if value < 0 or value > 0xffffffff:
            raise ValueError('number out of range')
        return self._append_uleb(value)

    def append_int32(self, value):
        _check_int(value)
        if value < -0x80000000 or value > 0x7fffffff:
            raise ValueError('number out of range')
        return self._append_sleb(value)

    def append_uint53(self, value):
        _check_int(value)
        if value < 0 or value > MAX_SAFE_INTEGER:
            raise ValueError('number out of range')
        return self._append_uleb(value)

    def append_int53(self, value):
        _check_int(value)
        if value < MIN_SAFE_INTEGER or value > MAX_SAFE_INTEGER:
            raise ValueError('number out of range')
        return self._append_sleb(value)

    def append_uint64(self, value):
        _check_int(value)
        if value < 0 or value > 2 ** 64 - 1:
            raise ValueError('number out of range')
        return self._append_uleb(value)

    def append_int64(self, value):
        _check_int(value)
        if value < -(2 ** 63) or value > 2 ** 63 - 1:
            raise ValueError('number out of range')
        return self._append_sleb(value)

    def append_raw_bytes(self, data):
        self.buf.extend(data)
        return len(data)

    def append_raw_string(self, value):
        if not isinstance(value, str):
            raise TypeError('value is not a string')
        return self.append_raw_bytes(value.encode('utf-8'))

    def append_prefixed_bytes(self, data):
        self.append_uint53(len(data))
        self.append_raw_bytes(data)
        return self

    def append_prefixed_string(self, value):
        if not isinstance(value, str):
            raise TypeError('value is not a string')
        self.append_prefixed_bytes(value.encode('utf-8'))
        return self

    def append_hex_string(self, value):
        self.append_prefixed_bytes(hex_string_to_bytes(value))
        return self


class Decoder:
    """Cursor over a byte buffer with LEB128 reads (ref encoding.js:293-534)."""

    def __init__(self, buffer):
        if isinstance(buffer, memoryview):
            # ZERO-COPY: a memoryview (e.g. into an mmap'd storage
            # segment) is consumed in place — raw-byte reads return
            # sub-views into the source buffer, so decoding a parked
            # chunk's header costs page-cache touches, not an arena copy
            self.buf = buffer if buffer.ndim == 1 and \
                buffer.format == 'B' else buffer.cast('B')
        elif not isinstance(buffer, (bytes, bytearray)):
            raise TypeError(f'Not a byte array: {buffer!r}')
        else:
            self.buf = bytes(buffer)
        self.offset = 0

    @property
    def done(self):
        return self.offset == len(self.buf)

    def reset(self):
        self.offset = 0

    def skip(self, num_bytes):
        if self.offset + num_bytes > len(self.buf):
            raise ValueError('cannot skip beyond end of buffer')
        self.offset += num_bytes

    def read_byte(self):
        self.offset += 1
        return self.buf[self.offset - 1]

    def _read_uleb(self, max_bytes):
        result = 0
        shift = 0
        n = 0
        while self.offset < len(self.buf):
            byte = self.buf[self.offset]
            self.offset += 1
            n += 1
            if n > max_bytes:
                raise ValueError('number out of range')
            result |= (byte & 0x7f) << shift
            shift += 7
            if byte & 0x80 == 0:
                return result
        raise ValueError('buffer ended with incomplete number')

    def _read_sleb(self, max_bytes):
        result = 0
        shift = 0
        n = 0
        while self.offset < len(self.buf):
            byte = self.buf[self.offset]
            self.offset += 1
            n += 1
            if n > max_bytes:
                raise ValueError('number out of range')
            result |= (byte & 0x7f) << shift
            shift += 7
            if byte & 0x80 == 0:
                if byte & 0x40:
                    result -= 1 << shift
                return result
        raise ValueError('buffer ended with incomplete number')

    def read_uint32(self):
        value = self._read_uleb(5)
        if value > 0xffffffff:
            raise ValueError('number out of range')
        return value

    def read_int32(self):
        value = self._read_sleb(5)
        if value < -0x80000000 or value > 0x7fffffff:
            raise ValueError('number out of range')
        return value

    def read_uint53(self):
        value = self._read_uleb(10)
        if value > MAX_SAFE_INTEGER:
            raise ValueError('number out of range')
        return value

    def read_int53(self):
        value = self._read_sleb(10)
        # ref encoding.js:402-408: valid range is (-2^53, 2^53)
        if value <= -(2 ** 53) or value >= 2 ** 53:
            raise ValueError('number out of range')
        return value

    def read_uint64(self):
        value = self._read_uleb(10)
        if value > 2 ** 64 - 1:
            raise ValueError('number out of range')
        return value

    def read_int64(self):
        value = self._read_sleb(10)
        if value < -(2 ** 63) or value > 2 ** 63 - 1:
            raise ValueError('number out of range')
        return value

    def read_raw_bytes(self, length):
        start = self.offset
        if start + length > len(self.buf):
            raise ValueError('subarray exceeds buffer size')
        self.offset += length
        return self.buf[start:self.offset]

    def read_raw_string(self, length):
        # bytes() is a no-op copy for bytes inputs; required for the
        # memoryview (zero-copy) path, which has no .decode
        return bytes(self.read_raw_bytes(length)).decode('utf-8')

    def read_prefixed_bytes(self):
        return self.read_raw_bytes(self.read_uint53())

    def read_prefixed_string(self):
        return bytes(self.read_prefixed_bytes()).decode('utf-8')

    def read_hex_string(self):
        return bytes_to_hex_string(self.read_prefixed_bytes())


class RLEEncoder(Encoder):
    """Run-length encoder over int/uint/utf8 values, nulls allowed.

    Wire format (ref encoding.js:536-557): a sequence of records, each a
    LEB128 signed repetition count n followed by: one value repeated n
    times (n > 0); n literal values (count encoded as -n); or, when the
    count is 0, a LEB128 unsigned count of nulls.
    """

    def __init__(self, type):
        super().__init__()
        self.type = type
        self.state = 'empty'
        self.last_value = None
        self.count = 0
        self.literal = []

    def append_value(self, value, repetitions=1):
        self._append_value(value, repetitions)

    def _append_value(self, value, repetitions=1):
        if repetitions <= 0:
            return
        if self.state == 'empty':
            self.state = ('nulls' if value is None
                          else ('loneValue' if repetitions == 1 else 'repetition'))
            self.last_value = value
            self.count = repetitions
        elif self.state == 'loneValue':
            if value is None:
                self.flush()
                self.state = 'nulls'
                self.count = repetitions
            elif value == self.last_value:
                self.state = 'repetition'
                self.count = 1 + repetitions
            elif repetitions > 1:
                self.flush()
                self.state = 'repetition'
                self.count = repetitions
                self.last_value = value
            else:
                self.state = 'literal'
                self.literal = [self.last_value]
                self.last_value = value
        elif self.state == 'repetition':
            if value is None:
                self.flush()
                self.state = 'nulls'
                self.count = repetitions
            elif value == self.last_value:
                self.count += repetitions
            elif repetitions > 1:
                self.flush()
                self.state = 'repetition'
                self.count = repetitions
                self.last_value = value
            else:
                self.flush()
                self.state = 'loneValue'
                self.last_value = value
        elif self.state == 'literal':
            if value is None:
                self.literal.append(self.last_value)
                self.flush()
                self.state = 'nulls'
                self.count = repetitions
            elif value == self.last_value:
                self.flush()
                self.state = 'repetition'
                self.count = 1 + repetitions
            elif repetitions > 1:
                self.literal.append(self.last_value)
                self.flush()
                self.state = 'repetition'
                self.count = repetitions
                self.last_value = value
            else:
                self.literal.append(self.last_value)
                self.last_value = value
        elif self.state == 'nulls':
            if value is None:
                self.count += repetitions
            elif repetitions > 1:
                self.flush()
                self.state = 'repetition'
                self.count = repetitions
                self.last_value = value
            else:
                self.flush()
                self.state = 'loneValue'
                self.last_value = value

    def copy_from(self, decoder, count=None, sum_values=False, sum_shift=None):
        """Copy `count` values (or all) from `decoder` without expanding runs.

        Returns (non_null_values, sum) where sum is None unless sum_values
        (ref encoding.js:667-737).
        """
        if not isinstance(decoder, RLEDecoder) or decoder.type != self.type:
            raise TypeError('incompatible type of decoder')
        remaining = count if count is not None else float('inf')
        non_null = 0
        total = 0
        if count and remaining > 0 and decoder.done:
            raise ValueError(f'cannot copy {count} values')
        if remaining == 0 or decoder.done:
            return (non_null, total if sum_values else None)

        # Copy the first value(s) through the state machine so that encoder
        # and decoder agree on run boundaries; then splice at record level.
        first_value = decoder.read_value()
        if first_value is None:
            num_nulls = min(decoder.count + 1, remaining)
            remaining -= num_nulls
            decoder.count -= num_nulls - 1
            self.append_value(None, num_nulls)
            if count and remaining > 0 and decoder.done:
                raise ValueError(f'cannot copy {count} values')
            if remaining == 0 or decoder.done:
                return (non_null, total if sum_values else None)
            first_value = decoder.read_value()
            if first_value is None:
                raise ValueError('null run must be followed by non-null value')
        self.append_value(first_value)
        remaining -= 1
        non_null += 1
        if sum_values:
            total += (first_value >> sum_shift) if sum_shift else first_value
        if count and remaining > 0 and decoder.done:
            raise ValueError(f'cannot copy {count} values')
        if remaining == 0 or decoder.done:
            return (non_null, total if sum_values else None)

        first_run = decoder.count > 0
        while remaining > 0 and not decoder.done:
            if not first_run:
                decoder.read_record()
            num_values = min(decoder.count, remaining)
            decoder.count -= num_values

            if decoder.state == 'literal':
                non_null += num_values
                for _ in range(num_values):
                    if decoder.done:
                        raise ValueError('incomplete literal')
                    value = decoder.read_raw_value()
                    if value == decoder.last_value:
                        raise ValueError('Repetition of values is not allowed in literal')
                    decoder.last_value = value
                    self._append_value(value)
                    if sum_values:
                        total += (value >> sum_shift) if sum_shift else value
            elif decoder.state == 'repetition':
                non_null += num_values
                if sum_values:
                    v = decoder.last_value
                    total += num_values * ((v >> sum_shift) if sum_shift else v)
                value = decoder.last_value
                self._append_value(value)
                if num_values > 1:
                    self._append_value(value)
                    if self.state != 'repetition':
                        raise ValueError(f'Unexpected state {self.state}')
                    self.count += num_values - 2
            elif decoder.state == 'nulls':
                self._append_value(None)
                if self.state != 'nulls':
                    raise ValueError(f'Unexpected state {self.state}')
                self.count += num_values - 1

            first_run = False
            remaining -= num_values
        if count and remaining > 0 and decoder.done:
            raise ValueError(f'cannot copy {count} values')
        return (non_null, total if sum_values else None)

    def flush(self):
        if self.state == 'loneValue':
            self.append_int32(-1)
            self.append_raw_value(self.last_value)
        elif self.state == 'repetition':
            self.append_int53(self.count)
            self.append_raw_value(self.last_value)
        elif self.state == 'literal':
            self.append_int53(-len(self.literal))
            for v in self.literal:
                self.append_raw_value(v)
        elif self.state == 'nulls':
            self.append_int32(0)
            self.append_uint53(self.count)
        self.state = 'empty'

    def append_raw_value(self, value):
        if self.type == 'int':
            self.append_int53(value)
        elif self.type == 'uint':
            self.append_uint53(value)
        elif self.type == 'utf8':
            self.append_prefixed_string(value)
        else:
            raise ValueError(f'Unknown RLEEncoder datatype: {self.type}')

    def finish(self):
        if self.state == 'literal':
            self.literal.append(self.last_value)
        # An all-null sequence encodes to nothing (ref encoding.js:778-782)
        if self.state != 'nulls' or len(self.buf) > 0:
            self.flush()


class RLEDecoder(Decoder):
    """Counterpart to RLEEncoder (ref encoding.js:789-920)."""

    def __init__(self, type, buffer):
        super().__init__(buffer)
        self.type = type
        self.last_value = None
        self.count = 0
        self.state = None

    @property
    def done(self):
        return self.count == 0 and self.offset == len(self.buf)

    def reset(self):
        self.offset = 0
        self.last_value = None
        self.count = 0
        self.state = None

    def read_value(self):
        if self.done:
            return None
        if self.count == 0:
            self.read_record()
        self.count -= 1
        if self.state == 'literal':
            value = self.read_raw_value()
            if value == self.last_value:
                raise ValueError('Repetition of values is not allowed in literal')
            self.last_value = value
            return value
        return self.last_value

    def skip_values(self, num_skip):
        while num_skip > 0 and not self.done:
            if self.count == 0:
                self.count = self.read_int53()
                if self.count > 0:
                    if self.count <= num_skip:
                        self.skip_raw_values(1)
                        self.last_value = None
                    else:
                        self.last_value = self.read_raw_value()
                    self.state = 'repetition'
                elif self.count < 0:
                    self.count = -self.count
                    self.state = 'literal'
                else:
                    self.count = self.read_uint53()
                    self.last_value = None
                    self.state = 'nulls'
            consume = min(num_skip, self.count)
            if self.state == 'literal':
                self.skip_raw_values(consume)
            num_skip -= consume
            self.count -= consume

    def read_record(self):
        self.count = self.read_int53()
        if self.count > 1:
            value = self.read_raw_value()
            if self.state in ('repetition', 'literal') and self.last_value == value:
                raise ValueError('Successive repetitions with the same value are not allowed')
            self.state = 'repetition'
            self.last_value = value
        elif self.count == 1:
            raise ValueError('Repetition count of 1 is not allowed, use a literal instead')
        elif self.count < 0:
            self.count = -self.count
            if self.state == 'literal':
                raise ValueError('Successive literals are not allowed')
            self.state = 'literal'
        else:
            if self.state == 'nulls':
                raise ValueError('Successive null runs are not allowed')
            self.count = self.read_uint53()
            if self.count == 0:
                raise ValueError('Zero-length null runs are not allowed')
            self.last_value = None
            self.state = 'nulls'

    def read_raw_value(self):
        if self.type == 'int':
            return self.read_int53()
        elif self.type == 'uint':
            return self.read_uint53()
        elif self.type == 'utf8':
            return self.read_prefixed_string()
        raise ValueError(f'Unknown RLEDecoder datatype: {self.type}')

    def skip_raw_values(self, num):
        if self.type == 'utf8':
            for _ in range(num):
                self.skip(self.read_uint53())
        else:
            while num > 0 and self.offset < len(self.buf):
                if self.buf[self.offset] & 0x80 == 0:
                    num -= 1
                self.offset += 1
            if num > 0:
                raise ValueError('cannot skip beyond end of buffer')


class DeltaEncoder(RLEEncoder):
    """RLE over successive differences (ref encoding.js:932-998)."""

    def __init__(self):
        super().__init__('int')
        self.absolute_value = 0

    def append_value(self, value, repetitions=1):
        if repetitions <= 0:
            return
        if isinstance(value, int) and not isinstance(value, bool):
            super().append_value(value - self.absolute_value, 1)
            self.absolute_value = value
            if repetitions > 1:
                super().append_value(0, repetitions - 1)
        else:
            super().append_value(value, repetitions)

    def copy_from(self, decoder, count=None, sum_values=False, sum_shift=None):
        if sum_values:
            raise ValueError('unsupported options for DeltaEncoder.copy_from()')
        if not isinstance(decoder, DeltaDecoder):
            raise TypeError('incompatible type of decoder')

        remaining = count
        if remaining is not None and remaining > 0 and decoder.done:
            raise ValueError(f'cannot copy {remaining} values')
        if remaining == 0 or decoder.done:
            return

        # First non-null value is copied via append_value so it is re-encoded
        # relative to this encoder's absolute value; the rest splice verbatim.
        value = decoder.read_value()
        nulls = 0
        self.append_value(value)
        if value is None:
            nulls = decoder.count + 1
            if remaining is not None and remaining < nulls:
                nulls = remaining
            decoder.count -= nulls - 1
            self.count += nulls - 1
            if remaining is not None and remaining > nulls and decoder.done:
                raise ValueError(f'cannot copy {remaining} values')
            if remaining == nulls or decoder.done:
                return
            if decoder.count == 0:
                self.append_value(decoder.read_value())

        if remaining is not None:
            remaining -= nulls + 1
        non_null, total = RLEEncoder.copy_from(self, decoder, count=remaining,
                                               sum_values=True)
        if non_null > 0:
            self.absolute_value = total
            decoder.absolute_value = total


class DeltaDecoder(RLEDecoder):
    """Counterpart to DeltaEncoder (ref encoding.js:1004-1051)."""

    def __init__(self, buffer):
        super().__init__('int', buffer)
        self.absolute_value = 0

    def reset(self):
        super().reset()
        self.absolute_value = 0

    def read_value(self):
        value = super().read_value()
        if value is None:
            return None
        self.absolute_value += value
        return self.absolute_value

    def skip_values(self, num_skip):
        while num_skip > 0 and not self.done:
            if self.count == 0:
                self.read_record()
            consume = min(num_skip, self.count)
            if self.state == 'literal':
                for _ in range(consume):
                    self.last_value = self.read_raw_value()
                    self.absolute_value += self.last_value
            elif self.state == 'repetition':
                self.absolute_value += consume * self.last_value
            num_skip -= consume
            self.count -= consume


class BooleanEncoder(Encoder):
    """Alternating false/true run lengths, starting with false (ref encoding.js:1061-1135)."""

    def __init__(self):
        super().__init__()
        self.last_value = False
        self.count = 0

    def append_value(self, value, repetitions=1):
        if value is not False and value is not True:
            raise ValueError(f'Unsupported value for BooleanEncoder: {value}')
        if repetitions <= 0:
            return
        if self.last_value == value:
            self.count += repetitions
        else:
            self.append_uint53(self.count)
            self.last_value = value
            self.count = repetitions

    def copy_from(self, decoder, count=None):
        if not isinstance(decoder, BooleanDecoder):
            raise TypeError('incompatible type of decoder')
        remaining = count if count is not None else float('inf')
        if count and remaining > 0 and decoder.done:
            raise ValueError(f'cannot copy {count} values')
        if remaining == 0 or decoder.done:
            return

        self.append_value(decoder.read_value())
        remaining -= 1
        first_copy = min(decoder.count, remaining)
        self.count += first_copy
        decoder.count -= first_copy
        remaining -= first_copy

        while remaining > 0 and not decoder.done:
            decoder.count = decoder.read_uint53()
            if decoder.count == 0:
                raise ValueError('Zero-length runs are not allowed')
            decoder.last_value = not decoder.last_value
            self.append_uint53(self.count)

            num_copied = min(decoder.count, remaining)
            self.count = num_copied
            self.last_value = decoder.last_value
            decoder.count -= num_copied
            remaining -= num_copied

        if count and remaining > 0 and decoder.done:
            raise ValueError(f'cannot copy {count} values')

    def finish(self):
        if self.count > 0:
            self.append_uint53(self.count)
            self.count = 0


class BooleanDecoder(Decoder):
    """Counterpart to BooleanEncoder (ref encoding.js:1141-1207)."""

    def __init__(self, buffer):
        super().__init__(buffer)
        self.last_value = True  # negated on the first run
        self.first_run = True
        self.count = 0

    @property
    def done(self):
        return self.count == 0 and self.offset == len(self.buf)

    def reset(self):
        self.offset = 0
        self.last_value = True
        self.first_run = True
        self.count = 0

    def read_value(self):
        if self.done:
            return False
        while self.count == 0:
            self.count = self.read_uint53()
            self.last_value = not self.last_value
            if self.count == 0 and not self.first_run:
                raise ValueError('Zero-length runs are not allowed')
            self.first_run = False
        self.count -= 1
        return self.last_value

    def skip_values(self, num_skip):
        while num_skip > 0 and not self.done:
            if self.count == 0:
                self.count = self.read_uint53()
                self.last_value = not self.last_value
                if self.count == 0 and not self.first_run:
                    raise ValueError('Zero-length runs are not allowed')
                self.first_run = False
            consume = min(num_skip, self.count)
            num_skip -= consume
            self.count -= consume
