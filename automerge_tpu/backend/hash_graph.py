"""Causal change-log machinery shared by every backend engine.

The reference keeps this state inside BackendDoc (new.js:1694-1768): the
SHA-256 hash graph over changes (changes, changeIndexByHash,
dependenciesByHash, dependentsByHash, hashesByActor), the vector clock and
heads, and the queue of causally-premature changes with the per-actor seq
contiguity gate (new.js:1550-1597). Both the host OpSet engine
(automerge_tpu.backend.op_set) and the device fleet documents
(automerge_tpu.fleet.backend) need exactly this bookkeeping — it is
inherently host-side, irregular dict/graph work — so it lives here once.
"""

from ..columnar import (
    decode_change, decode_change_meta, decode_document, encode_change,
    split_containers, CHUNK_TYPE_CHANGE, CHUNK_TYPE_DEFLATE,
    CHUNK_TYPE_DOCUMENT,
)


def decode_change_buffers(change_buffers):
    """Decode a list of byte buffers (change chunks, deflated changes, or
    whole document chunks) into decoded-change dicts carrying their binary
    form under 'buffer' (ref new.js:1797-1813)."""
    if isinstance(change_buffers, (bytes, bytearray)):
        raise TypeError('applyChanges takes an array of byte buffers, '
                        'not just a single buffer')
    decoded = []
    for buffer in change_buffers:
        for chunk in split_containers(buffer):
            if chunk[8] in (CHUNK_TYPE_CHANGE, CHUNK_TYPE_DEFLATE):
                change = decode_change(chunk)
                change['buffer'] = chunk
                decoded.append(change)
            elif chunk[8] == CHUNK_TYPE_DOCUMENT:
                # decode_document normalizes each change through an
                # encode/decode round-trip, so only the buffer is missing
                for change in decode_document(chunk):
                    change['buffer'] = encode_change(change)
                    decoded.append(change)
    return decoded


class HashGraph:
    """Hash-graph + causal-gate state over a change log.

    __slots__ keeps per-document construction cheap: fleets create one
    engine per doc, so bulk init at 10k+ docs is on the turbo seam's
    critical path. Subclasses that want ad-hoc attributes (the host OpSet)
    simply omit __slots__ and get a __dict__ as usual."""

    __slots__ = ('max_op', 'actor_ids', 'heads', 'clock', 'queue',
                 'changes', 'changes_meta', 'change_index_by_hash',
                 'dependencies_by_hash', 'dependents_by_hash',
                 'hashes_by_actor', '_deferred')

    def __init__(self):
        self.max_op = 0
        self.actor_ids = []
        self.heads = []
        self.clock = {}
        self.queue = []
        self.changes = []           # binary changes, in application order
        self.changes_meta = []      # per-change metadata for document encoding
        self.change_index_by_hash = {}
        self.dependencies_by_hash = {}
        self.dependents_by_hash = {}
        self.hashes_by_actor = {}
        # Deferred-index log (the reference's deferred hash graph,
        # new.js:1709-1749): bulk appends record only (index, hash, deps,
        # actor, meta) tuples here; the query dicts above materialize lazily
        self._deferred = []

    def _defer_record(self, change):
        """Record an applied change without building the graph indexes;
        self.changes must already hold its buffer at the captured index."""
        self._deferred.append((len(self.changes) - 1, change['hash'],
                               list(change['deps']), change['actor'], {
            'actor': change['actor'], 'seq': change['seq'],
            'maxOp': change['startOp'] + len(change['ops']) - 1,
            'time': change.get('time', 0),
            'message': change.get('message') or '',
            'deps': list(change['deps']),
            'extraBytes': change.get('extraBytes'),
        }))

    def _ensure_graph(self):
        """Materialize the query dicts from the deferred log. Entries are
        either eager 5-tuples (index, hash, deps, actor, meta) or lazy
        3-tuples (index, batch, i) resolved via batch.resolve(i)."""
        if not self._deferred:
            return

        def record(index, hash, deps, actor, meta):
            self.hashes_by_actor.setdefault(actor, []).append(hash)
            self.change_index_by_hash[hash] = index
            self.dependencies_by_hash[hash] = deps
            self.dependents_by_hash.setdefault(hash, [])
            for dep in deps:
                self.dependents_by_hash.setdefault(dep, []).append(hash)
            self.changes_meta.append(meta)

        for entry in self._deferred:
            if len(entry) == 3:
                index, batch, i = entry
                if isinstance(i, (list, tuple, range)):
                    # One record covering a run of log entries [index, ...)
                    for off, j in enumerate(i):
                        record(index + off, *batch.resolve(int(j)))
                    continue
                record(index, *batch.resolve(i))
            else:
                index, hash, deps, actor, meta = entry
                record(index, hash, deps, actor, meta)
        self._deferred = []

    def _causal_gate(self, changes, applied_hashes=None):
        """Partition changes into causally-ready (applied to clock/heads) and
        enqueued (ref new.js:1550-1586). `applied_hashes` carries the hashes
        applied by earlier passes of the same apply_changes call (they are not
        yet in change_index_by_hash, but satisfy deps and must be deduped)."""
        self._ensure_graph()
        heads = set(self.heads)
        change_hashes = applied_hashes if applied_hashes is not None else set()
        clock = dict(self.clock)
        applied, enqueued = [], []
        for change in changes:
            if change['hash'] in self.change_index_by_hash or change['hash'] in change_hashes:
                continue
            expected_seq = clock.get(change['actor'], 0) + 1
            ready = all(dep in self.change_index_by_hash or dep in change_hashes
                        for dep in change['deps'])
            if not ready:
                enqueued.append(change)
            elif change['seq'] < expected_seq:
                raise ValueError(
                    f"Reuse of sequence number {change['seq']} for actor {change['actor']}")
            elif change['seq'] > expected_seq:
                raise ValueError(
                    f"Skipped sequence number {expected_seq} for actor {change['actor']}")
            else:
                clock[change['actor']] = change['seq']
                change_hashes.add(change['hash'])
                for dep in change['deps']:
                    heads.discard(dep)
                heads.add(change['hash'])
                applied.append(change)
        if applied:
            self.heads = sorted(heads)
            self.clock = clock
        return applied, enqueued

    def _drain_queue(self, decoded, apply_fn):
        """Run the causal-gate drain loop (ref new.js:1825-1841): repeatedly
        gate `decoded` + the held-back queue, calling apply_fn(change) for
        each causally-ready change, until a pass applies nothing new.
        Returns (all_applied, remaining_queue); does not commit the queue."""
        queue = decoded + self.queue
        all_applied = []
        applied_hashes = set()
        while True:
            applied, queue = self._causal_gate(queue, applied_hashes)
            for change in applied:
                apply_fn(change)
            all_applied.extend(applied)
            if not applied or not queue:
                break
        return all_applied, queue

    def _record_applied(self, change):
        """Record one applied change into the log and hash graph
        (ref new.js appendChange:1680-1692)."""
        self.changes.append(change['buffer'])
        self.hashes_by_actor.setdefault(change['actor'], []).append(change['hash'])
        self.change_index_by_hash[change['hash']] = len(self.changes) - 1
        self.dependencies_by_hash[change['hash']] = list(change['deps'])
        self.dependents_by_hash.setdefault(change['hash'], [])
        for dep in change['deps']:
            self.dependents_by_hash.setdefault(dep, []).append(change['hash'])
        self.changes_meta.append({
            'actor': change['actor'], 'seq': change['seq'],
            'maxOp': change['startOp'] + len(change['ops']) - 1,
            'time': change.get('time', 0), 'message': change.get('message') or '',
            'deps': list(change['deps']),
            'extraBytes': change.get('extraBytes'),
        })

    # ------------------------------------------------------------------
    # History / hash graph queries (ref new.js:1921-2028)
    # ------------------------------------------------------------------

    def get_changes(self, have_deps):
        if not have_deps:
            self._ensure_graph()
            return list(self.changes)
        return [self.changes[self.change_index_by_hash[h]]
                for h in self.get_change_hashes(have_deps)]

    def get_change_hashes(self, have_deps):
        """Hashes of get_changes(have_deps), without touching the change
        buffers — the sync driver's Bloom builds need only hashes, and
        re-decoding every buffer per round (the reference's own TODO at
        sync.js:378) dominated fleet-scale sync profiles. get_changes is
        a buffer lookup over this (single copy of the traversal)."""
        if have_deps and sorted(have_deps) == sorted(self.heads):
            # have_deps IS the current frontier: every change is an
            # ancestor of it, so the delta is empty BY DEFINITION — a
            # heads compare, no graph walk, and crucially no _ensure_graph
            # (a freshly loaded doc answering a converged handshake would
            # otherwise build its whole O(history) dict set to learn
            # "nothing since lastSync"). The quiet steady state of every
            # sync/replication round lands here.
            return []
        self._ensure_graph()

        def ordered_hashes():
            out = [None] * len(self.changes)
            for h, i in self.change_index_by_hash.items():
                out[i] = h
            return out

        if not have_deps:
            return ordered_hashes()
        stack, seen, to_return = [], set(), []
        for h in have_deps:
            seen.add(h)
            successors = self.dependents_by_hash.get(h)
            if successors is None:
                raise ValueError(f'hash not found: {h}')
            stack.extend(successors)
        while stack:
            h = stack.pop()
            seen.add(h)
            to_return.append(h)
            if not all(dep in seen for dep in self.dependencies_by_hash[h]):
                break
            stack.extend(self.dependents_by_hash[h])
        if not stack and all(head in seen for head in self.heads):
            return to_return
        # Slow path: collect ancestors of have_deps, return everything else
        stack, seen = list(have_deps), set()
        while stack:
            h = stack.pop()
            if h not in seen:
                deps = self.dependencies_by_hash.get(h)
                if deps is None:
                    raise ValueError(f'hash not found: {h}')
                stack.extend(deps)
                seen.add(h)
        return [h for h in ordered_hashes() if h not in seen]

    def get_changes_added(self, other):
        self._ensure_graph()
        if isinstance(other, HashGraph):
            other._ensure_graph()
        stack, seen, to_return = list(self.heads), set(), []
        while stack:
            h = stack.pop()
            if h not in seen and h not in other.change_index_by_hash:
                seen.add(h)
                to_return.append(h)
                stack.extend(self.dependencies_by_hash[h])
        return [self.changes[self.change_index_by_hash[h]] for h in reversed(to_return)]

    def get_change_by_hash(self, hash):
        self._ensure_graph()
        index = self.change_index_by_hash.get(hash)
        return self.changes[index] if index is not None else None

    def get_missing_deps(self, heads=()):
        self._ensure_graph()
        all_deps = set(heads)
        in_queue = set()
        for change in self.queue:
            in_queue.add(change['hash'])
            all_deps.update(change['deps'])
        return sorted(h for h in all_deps
                      if h not in self.change_index_by_hash and h not in in_queue)
