"""The CRDT document engine (OpSet): change application, patch generation,
and document serialization.

This is the host reference engine, semantically equivalent to the reference's
BackendDoc (backend/new.js) but with a different in-memory design: instead of
RLE-compressed op blocks merged by a streaming two-pointer scan
(new.js:1052-1290), we keep a key-indexed op store — per-object dicts of
per-key op lists for maps/tables, and an RGA-ordered element list for
lists/texts. Observable behavior (patches, error conditions, binary document
format) matches the reference:

- conflict resolution: all ops for a key kept in ascending Lamport order;
  visible ops are those with no successors (new.js:1204-1217)
- RGA list insertion: scan forward from the reference element, skipping
  elements with a greater insertion opId (new.js:145-163)
- counters: inc ops are successors of the set op but accumulate
  (new.js:937-965)
- patch grammar and edit coalescing (new.js:747-1040)
- causal gating with per-actor seq contiguity (new.js:1550-1597)

The batched/TPU execution path lives in automerge_tpu.fleet; this engine is
the correctness oracle and handles the irregular host-side work (hash graph,
patch assembly, wire format).
"""

import copy

from ..common import parse_op_id, lamport_key
from ..columnar import (
    OBJECT_TYPE, DOCUMENT_COLUMNS, VALUE_TYPE,
    decode_change, decode_change_meta, decode_document, decode_document_header,
    encode_change, encode_document_header, encode_ops, split_containers,
    CHUNK_TYPE_DOCUMENT, CHUNK_TYPE_CHANGE, CHUNK_TYPE_DEFLATE,
    materialize_columns, encoder_by_column_id,
)
from .. import encoding
from .hash_graph import HashGraph, decode_change_buffers


def _utf16_key(s):
    """Sort key giving JS-compatible UTF-16 code-unit string ordering."""
    return s.encode('utf-16-be', 'surrogatepass')


def _js_typeof(value):
    if isinstance(value, bool):
        return 'boolean'
    if isinstance(value, (int, float)):
        return 'number'
    if isinstance(value, str):
        return 'string'
    return 'object'


def empty_object_patch(object_id, type):
    if type in ('list', 'text'):
        return {'objectId': object_id, 'type': type, 'edits': []}
    return {'objectId': object_id, 'type': type, 'props': {}}


def _op_id_delta(id1, id2, delta=1):
    c1, a1 = parse_op_id(id1)
    c2, a2 = parse_op_id(id2)
    return a1 == a2 and c1 + delta == c2


def append_edit(edits, next_edit):
    """Append a list edit, coalescing runs (multi-insert, remove counts)
    (ref new.js:747-782)."""
    if not edits:
        edits.append(next_edit)
        return
    last = edits[-1]
    if last['action'] == 'insert' and next_edit['action'] == 'insert' and \
            last['index'] == next_edit['index'] - 1 and \
            last['value']['type'] == 'value' and next_edit['value']['type'] == 'value' and \
            last['elemId'] == last['opId'] and next_edit['elemId'] == next_edit['opId'] and \
            _op_id_delta(last['elemId'], next_edit['elemId'], 1) and \
            last['value'].get('datatype') == next_edit['value'].get('datatype') and \
            _js_typeof(last['value']['value']) == _js_typeof(next_edit['value']['value']):
        last['action'] = 'multi-insert'
        if next_edit['value'].get('datatype'):
            last['datatype'] = next_edit['value']['datatype']
        last['values'] = [last['value']['value'], next_edit['value']['value']]
        del last['value']
        del last['opId']
    elif last['action'] == 'multi-insert' and next_edit['action'] == 'insert' and \
            last['index'] + len(last['values']) == next_edit['index'] and \
            next_edit['value']['type'] == 'value' and \
            next_edit['elemId'] == next_edit['opId'] and \
            _op_id_delta(last['elemId'], next_edit['elemId'], len(last['values'])) and \
            last.get('datatype') == next_edit['value'].get('datatype') and \
            _js_typeof(last['values'][0]) == _js_typeof(next_edit['value']['value']):
        last['values'].append(next_edit['value']['value'])
    elif last['action'] == 'remove' and next_edit['action'] == 'remove' and \
            last['index'] == next_edit['index']:
        last['count'] += next_edit['count']
    else:
        edits.append(next_edit)


def append_update(edits, index, elem_id, op_id, value, first_update):
    """Append an UpdateEdit; consecutive updates at the same index represent a
    conflict (ref new.js:798-824)."""
    insert = False
    if first_update:
        # Pop earlier edits for the same index so they aren't misread as
        # part of this conflict set
        while not insert and edits:
            last = edits[-1]
            if last['action'] in ('insert', 'update') and last['index'] == index:
                edits.pop()
                insert = last['action'] == 'insert'
            elif last['action'] == 'multi-insert' and \
                    last['index'] + len(last['values']) - 1 == index:
                last['values'].pop()
                insert = True
            else:
                break
    if insert:
        append_edit(edits, {'action': 'insert', 'index': index, 'elemId': elem_id,
                            'opId': op_id, 'value': value})
    else:
        append_edit(edits, {'action': 'update', 'index': index, 'opId': op_id,
                            'value': value})


def convert_insert_to_update(edits, index, elem_id):
    """Rewrite a trailing insert-plus-updates suffix at `index` into updates
    (ref new.js:838-869)."""
    updates = []
    while edits:
        last = edits[-1]
        if last['action'] == 'insert':
            if last['index'] != index:
                raise ValueError('last edit has unexpected index')
            updates.insert(0, edits.pop())
            break
        elif last['action'] == 'update':
            if last['index'] != index:
                raise ValueError('last edit has unexpected index')
            updates.insert(0, edits.pop())
        else:
            raise ValueError('last edit has unexpected action')
    first_update = True
    for update in updates:
        append_update(edits, index, elem_id, update['opId'], update['value'], first_update)
        first_update = False


def _value_patch(op):
    value = {'type': 'value', 'value': op.get('value')}
    if op.get('datatype') is not None:
        value['datatype'] = op['datatype']
    return value


class Elem:
    """One list/text element: the insertion op plus all ops targeting it,
    in ascending Lamport order. Visibility (any op with no successors) is
    cached and refreshed by the mutation paths."""
    __slots__ = ('elem_id', 'ops', 'vis')

    def __init__(self, elem_id, ops):
        self.elem_id = elem_id
        self.ops = ops
        self.vis = any(len(op['succ']) == 0 for op in ops)

    def visible(self):
        return self.vis

    def recompute_visibility(self):
        self.vis = any(len(op['succ']) == 0 for op in self.ops)
        return self.vis


# Sequence objects store elements in blocks with cached visible counts so
# that position lookups are O(blocks + block_size) instead of O(elements) —
# the same trick as the reference's op blocks (ref new.js MAX_BLOCK_SIZE=600,
# blocks carry numVisible metadata for list index computation)
_BLOCK_SIZE = 256


class _Block:
    __slots__ = ('elems', 'visible')

    def __init__(self, elems=None, visible=0):
        self.elems = elems if elems is not None else []
        self.visible = visible


class ObjState:
    """State of one object in the document tree."""
    __slots__ = ('type', 'keys', 'blocks', 'elem_block')

    def __init__(self, type):
        self.type = type
        if type in ('list', 'text'):
            self.keys = None
            self.blocks = [_Block()]
            self.elem_block = {}
        else:
            self.keys = {}
            self.blocks = None
            self.elem_block = None

    @property
    def is_seq(self):
        return self.blocks is not None

    # -- sequence operations ------------------------------------------------

    def iter_elems(self):
        for block in self.blocks:
            yield from block.elems

    def find(self, elem_id):
        entry = self.elem_block.get(elem_id)
        return entry[1] if entry is not None else None

    def visible_index_of(self, elem_id):
        """Number of visible elements strictly before the given element."""
        entry = self.elem_block.get(elem_id)
        if entry is None:
            raise ValueError(f'Reference element not found: {elem_id}')
        target_block = entry[0]
        count = 0
        for block in self.blocks:
            if block is target_block:
                for elem in block.elems:
                    if elem.elem_id == elem_id:
                        return count
                    if elem.visible():
                        count += 1
                break
            count += block.visible
        raise ValueError(f'Reference element not found: {elem_id}')

    def insert_rga(self, ref_elem_id, elem, my_key):
        """Insert `elem` after `ref_elem_id` ('_head' for the front), skipping
        concurrent insertions with greater packed opIds (the RGA rule, ref
        new.js:145-163). Returns the visible index of the insertion point."""
        if ref_elem_id == '_head':
            bi, pos, count = 0, 0, 0
        else:
            entry = self.elem_block.get(ref_elem_id)
            if entry is None:
                raise ValueError(f'Reference element not found: {ref_elem_id}')
            block = entry[0]
            bi = self.blocks.index(block)
            count = sum(b.visible for b in self.blocks[:bi])
            pos = None
            for i, e in enumerate(block.elems):
                if e.elem_id == ref_elem_id:
                    pos = i + 1
                    if e.visible():
                        count += 1
                    break
                if e.visible():
                    count += 1
            if pos is None:
                raise ValueError(f'Reference element not found: {ref_elem_id}')
        # Skip concurrent siblings with greater insertion opIds
        while True:
            block = self.blocks[bi]
            while pos < len(block.elems):
                nxt = block.elems[pos]
                if lamport_key(nxt.elem_id) > my_key:
                    if nxt.visible():
                        count += 1
                    pos += 1
                else:
                    break
            else:
                if bi + 1 < len(self.blocks):
                    bi += 1
                    pos = 0
                    continue
            break
        block = self.blocks[bi]
        block.elems.insert(pos, elem)
        self.elem_block[elem.elem_id] = (block, elem)
        if elem.visible():
            block.visible += 1
        if len(block.elems) > _BLOCK_SIZE:
            self._split_block(bi)
        return count

    def _split_block(self, bi):
        block = self.blocks[bi]
        half = len(block.elems) // 2
        right = _Block(block.elems[half:])
        block.elems = block.elems[:half]
        right.visible = sum(1 for e in right.elems if e.visible())
        block.visible -= right.visible
        self.blocks.insert(bi + 1, right)
        for elem in right.elems:
            self.elem_block[elem.elem_id] = (right, elem)

    def refresh_visibility(self, elem, was_visible):
        """Adjust the cached visible count after elem's ops changed."""
        now = elem.recompute_visibility()
        if now != was_visible:
            block = self.elem_block[elem.elem_id][0]
            block.visible += 1 if now else -1


def root_meta():
    """Fresh root objectMeta entry (ref new.js:1694-1768)."""
    return {'parentObj': None, 'parentKey': None, 'opId': '_root',
            'type': 'map', 'children': {}}


class OpSet(HashGraph):
    """The document engine: equivalent of the reference's BackendDoc
    (new.js:1694-2069). Causal-gate/hash-graph state lives in HashGraph."""

    def __init__(self, buffer=None):
        super().__init__()
        self.objects = {'_root': ObjState('map')}
        self.object_meta = {'_root': root_meta()}
        self.binary_doc = None
        self.extra_bytes = None
        if buffer is not None:
            self._load(buffer)

    def clone(self):
        other = copy.deepcopy(self)
        return other

    # ------------------------------------------------------------------
    # Change application
    # ------------------------------------------------------------------

    def apply_changes(self, change_buffers, is_local=False):
        """Apply binary changes; returns a patch (ref new.js:1797-1879)."""
        decoded = decode_change_buffers(change_buffers)
        patches = {'_root': empty_object_patch('_root', 'map')}
        object_ids = set()

        try:
            all_applied, queue = self._drain_queue(
                decoded,
                lambda change: self._apply_decoded_change(patches, change,
                                                          object_ids))
        except Exception:
            # Roll back to the pre-call state by replaying the (unmodified)
            # change history; cheap because it only runs on the error path
            self._restore_from_history()
            raise

        self._setup_patches(patches, object_ids)

        for change in all_applied:
            self._record_applied(change)
        self.queue = queue
        self.binary_doc = None

        patch = {'maxOp': self.max_op, 'clock': dict(self.clock), 'deps': list(self.heads),
                 'pendingChanges': len(self.queue), 'diffs': patches['_root']}
        if is_local and len(decoded) == 1:
            patch['actor'] = decoded[0]['actor']
            patch['seq'] = decoded[0]['seq']
        return patch

    def _restore_from_history(self):
        fresh = OpSet()
        if self.changes:
            fresh.apply_changes(list(self.changes))
        self.objects = fresh.objects
        self.object_meta = fresh.object_meta
        self.max_op = fresh.max_op
        self.actor_ids = fresh.actor_ids
        self.heads = fresh.heads
        self.clock = fresh.clock

    def _apply_decoded_change(self, patches, change, object_ids):
        if change['actor'] not in self.actor_ids:
            self.actor_ids.append(change['actor'])
        start_op = change['startOp']
        for i, op in enumerate(change['ops']):
            op_id = f"{start_op + i}@{change['actor']}"
            if start_op + i > self.max_op:
                self.max_op = start_op + i
            self._apply_op(patches, op_id, op, object_ids)

    def _apply_op(self, patches, op_id, op, object_ids):
        if op['action'] == 'link':
            # `link` is a reserved slot in the wire-format action table
            # (ref columnar.js:51-52) that the reference engine never
            # emits or applies (open TODO at new.js:893, zero test
            # coverage). Storing the op anyway would leave an untracked
            # parent-child edge and a patch referencing a child object
            # that never resolves, so we reject loudly instead of
            # diverging silently. Documented in PARITY.md.
            raise ValueError(f'link operations are not supported (op {op_id})')
        object_id = op['obj']
        obj = self.objects.get(object_id)
        if obj is None:
            raise ValueError(f'modification of unknown object {object_id}')
        object_ids.add(object_id)

        record = {
            'id': op_id, 'action': op['action'], 'insert': bool(op.get('insert')),
            'succ': [],
        }
        if 'value' in op:
            record['value'] = op['value']
        if op.get('datatype') is not None:
            record['datatype'] = op['datatype']
        if op.get('child') is not None:
            record['child'] = op['child']
        if op.get('unknownCols'):
            record['unknownCols'] = op['unknownCols']
        if obj.is_seq:
            # Keep the original reference elemId (needed to serialize the
            # document's keyActor/keyCtr columns); the element's own id is
            # derived from the record id when insert is set
            record['elemId'] = op.get('elemId')
        else:
            record['key'] = op.get('key')

        # A make* op brings a new object into existence
        if op['action'] in OBJECT_TYPE and op_id not in self.objects:
            self.objects[op_id] = ObjState(OBJECT_TYPE[op['action']])

        if op.get('insert'):
            self._apply_insert(patches, object_id, obj, record, op)
        else:
            self._apply_update(patches, object_id, obj, record, op)

    def _apply_insert(self, patches, object_id, obj, record, op):
        """RGA list insertion (ref new.js seekWithinBlock:95-163)."""
        if not obj.is_seq:
            raise ValueError(f'insert into non-list object {object_id}')
        if op.get('pred'):
            pred = op['pred'][0]
            raise ValueError(f'no matching operation for pred: {pred}')
        op_id = record['id']
        if op_id in obj.elem_block:
            raise ValueError(f'duplicate operation ID: {op_id}')
        ref = op.get('elemId', '_head')
        elem = Elem(op_id, [record])
        list_index = obj.insert_rga(ref, elem, lamport_key(op_id))

        prop_state = {}
        self._update_patch_property(patches, object_id, record, prop_state,
                                    list_index, None, self.object_meta)

    def _apply_update(self, patches, object_id, obj, record, op):
        """Apply a non-insert op: merge into the target key's op list, mark
        succ on preds, and emit patch calls for every op of that key in
        ascending Lamport order (equivalent to the doc-op consumption in
        new.js mergeDocChangeOps:1067-1282)."""
        op_id = record['id']
        elem = None
        if obj.is_seq:
            elem_id = op.get('elemId')
            elem = obj.find(elem_id)
            if elem is None:
                raise ValueError(f'Reference element not found: {elem_id}')
            rows = elem.ops
        else:
            key = op.get('key')
            if key is None:
                raise ValueError(f'Unexpected operation key: {op}')
            rows = self.objects[object_id].keys.setdefault(key, [])

        # Capture old succ counts (before this op's overwrites are recorded)
        old_succ = {row['id']: len(row['succ']) for row in rows}
        was_visible = elem.visible() if elem is not None else None

        # Mark this op as successor of each of its preds
        preds = list(op.get('pred', []))
        pred_set = set(preds)
        seen = set()
        for row in rows:
            if row['id'] == op_id:
                raise ValueError(f'duplicate operation ID: {op_id}')
            if row['id'] in pred_set:
                row['succ'].append(op_id)
                row['succ'].sort(key=lamport_key)
                seen.add(row['id'])
        for pred in preds:
            if pred not in seen:
                raise ValueError(f'no matching operation for pred: {pred}')

        is_del = op['action'] == 'del'
        # Insert the new op into the key's op list in ascending Lamport order
        # (deletions exist only as succ entries, not as rows)
        if not is_del:
            insert_at = len(rows)
            my_key = lamport_key(op_id)
            for i, row in enumerate(rows):
                if lamport_key(row['id']) > my_key:
                    insert_at = i
                    break
            rows.insert(insert_at, record)

        # Keep the block's cached visible count in sync with the mutation
        if elem is not None:
            obj.refresh_visibility(elem, was_visible)

        # Emit patch calls for all ops of this key in order
        if obj.is_seq:
            list_index = obj.visible_index_of(op.get('elemId'))
        else:
            list_index = 0
        prop_state = {}
        for row in rows:
            if row is record:
                self._update_patch_property(patches, object_id, row, prop_state,
                                            list_index, None, self.object_meta)
            else:
                self._update_patch_property(patches, object_id, row, prop_state,
                                            list_index, old_succ[row['id']],
                                            self.object_meta)

    # ------------------------------------------------------------------
    # Patch generation
    # ------------------------------------------------------------------

    def _update_patch_property(self, patches, object_id, op, prop_state, list_index,
                               old_succ_num, object_meta, whole_doc=False):
        """Port of new.js updatePatchProperty (:884-1040): updates `patches`
        to reflect op, carrying conflict/counter state in `prop_state`."""
        action = op['action']
        is_make = action in OBJECT_TYPE
        type_ = OBJECT_TYPE.get(action)
        op_id = op['id']
        obj = self.objects[object_id]
        is_seq = obj.is_seq
        if is_seq:
            key = op['id'] if op.get('insert') else op.get('elemId')
        else:
            key = op.get('key')

        if is_make and op_id not in object_meta:
            object_meta[op_id] = {'parentObj': object_id, 'parentKey': key,
                                  'opId': op_id, 'type': type_, 'children': {}}
            object_meta[object_id]['children'].setdefault(key, {})[op_id] = \
                {'objectId': op_id, 'type': type_, 'props': {}}

        first_op = key not in prop_state
        state = prop_state.setdefault(
            key, {'visibleOps': [], 'hasChild': False, 'counterStates': {}, 'action': None})

        is_overwritten = old_succ_num is not None and len(op['succ']) > 0

        if not is_overwritten:
            state['visibleOps'].append(op)
            state['hasChild'] = state['hasChild'] or is_make

        prev_children = object_meta[object_id]['children'].get(key)
        if state['hasChild'] or prev_children:
            values = {}
            for vis in state['visibleOps']:
                if vis['action'] == 'set':
                    values[vis['id']] = _value_patch(vis)
                elif vis['action'] in OBJECT_TYPE:
                    values[vis['id']] = {'objectId': vis['id'],
                                         'type': OBJECT_TYPE[vis['action']], 'props': {}}
            object_meta[object_id]['children'][key] = values

        patch_key = patch_value = None

        if is_overwritten and action == 'set' and op.get('datatype') == 'counter':
            # Counter initialization: succs may be increments that accumulate
            counter_state = {'opId': op_id, 'value': op.get('value'),
                             'succs': set(op['succ'])}
            for succ in op['succ']:
                state['counterStates'][succ] = counter_state
        elif action == 'inc':
            counter_state = state['counterStates'].get(op_id)
            if counter_state is None:
                raise ValueError(f'increment operation {op_id} for unknown counter')
            counter_state['value'] += op.get('value')
            counter_state['succs'].discard(op_id)
            if not counter_state['succs']:
                patch_key = counter_state['opId']
                patch_value = {'type': 'value', 'datatype': 'counter',
                               'value': counter_state['value']}
        elif not is_overwritten:
            if action == 'set':
                patch_key = op_id
                patch_value = _value_patch(op)
            elif is_make:
                if op_id not in patches:
                    patches[op_id] = empty_object_patch(op_id, type_)
                patch_key = op_id
                patch_value = patches[op_id]

        if object_id not in patches:
            patches[object_id] = empty_object_patch(object_id,
                                                    object_meta[object_id]['type'])
        patch = patches[object_id]

        if is_seq:
            elem_id = key
            if old_succ_num == 0 and not whole_doc and state['action'] == 'insert':
                # The list element already existed, so the insert becomes an update
                state['action'] = 'update'
                convert_insert_to_update(patch['edits'], list_index, elem_id)

            if patch_value is not None:
                if not state['action'] and (old_succ_num is None or whole_doc):
                    state['action'] = 'insert'
                    append_edit(patch['edits'], {'action': 'insert', 'index': list_index,
                                                 'elemId': elem_id, 'opId': patch_key,
                                                 'value': patch_value})
                elif state['action'] == 'remove':
                    last = patch['edits'][-1]
                    if last['action'] != 'remove':
                        raise ValueError('last edit has unexpected type')
                    if last['count'] > 1:
                        last['count'] -= 1
                    else:
                        patch['edits'].pop()
                    state['action'] = 'update'
                    append_update(patch['edits'], list_index, elem_id, patch_key,
                                  patch_value, True)
                else:
                    append_update(patch['edits'], list_index, elem_id, patch_key,
                                  patch_value, not state['action'])
                    if not state['action']:
                        state['action'] = 'update'
            elif old_succ_num == 0 and not state['action']:
                state['action'] = 'remove'
                append_edit(patch['edits'], {'action': 'remove', 'index': list_index,
                                             'count': 1})
        elif patch_value is not None or not whole_doc:
            if first_op or key not in patch['props']:
                patch['props'][key] = {}
            if patch_value is not None:
                patch['props'][key][patch_key] = patch_value

    def _setup_patches(self, patches, object_ids):
        """Link child-object patches up the tree to the root (ref new.js:1461-1528)."""
        for object_id in object_ids:
            meta = self.object_meta[object_id]
            child_meta = None
            patch_exists = False
            while True:
                has_children = child_meta is not None and \
                    bool(meta['children'].get(child_meta['parentKey']))
                if object_id not in patches:
                    patches[object_id] = empty_object_patch(object_id, meta['type'])

                if child_meta and has_children:
                    if meta['type'] in ('list', 'text'):
                        for edit in patches[object_id]['edits']:
                            if edit.get('opId') and \
                                    edit['opId'] in meta['children'][child_meta['parentKey']]:
                                patch_exists = True
                        if not patch_exists:
                            obj = self.objects[object_id]
                            visible_count = obj.visible_index_of(child_meta['parentKey'])
                            for op_id, value in \
                                    meta['children'][child_meta['parentKey']].items():
                                patch_value = value
                                if value.get('objectId'):
                                    if value['objectId'] not in patches:
                                        patches[value['objectId']] = \
                                            empty_object_patch(value['objectId'], value['type'])
                                    patch_value = patches[value['objectId']]
                                append_edit(patches[object_id]['edits'],
                                            {'action': 'update', 'index': visible_count,
                                             'opId': op_id, 'value': patch_value})
                    else:
                        values = patches[object_id]['props'].setdefault(
                            child_meta['parentKey'], {})
                        for op_id, value in \
                                meta['children'][child_meta['parentKey']].items():
                            if op_id in values:
                                patch_exists = True
                            elif value.get('objectId'):
                                if value['objectId'] not in patches:
                                    patches[value['objectId']] = \
                                        empty_object_patch(value['objectId'], value['type'])
                                values[op_id] = patches[value['objectId']]
                            else:
                                values[op_id] = value

                if patch_exists or not meta['parentObj'] or \
                        (child_meta and not has_children):
                    break
                child_meta = meta
                object_id = meta['parentObj']
                meta = self.object_meta[object_id]
        return patches

    # ------------------------------------------------------------------
    # Whole-document patch (ref new.js documentPatch:1604-1635)
    # ------------------------------------------------------------------

    def get_patch(self):
        object_meta = {'_root': root_meta()}
        patches = {'_root': empty_object_patch('_root', 'map')}
        for object_id in self._document_object_order():
            obj = self.objects[object_id]
            prop_state = {}
            if obj.is_seq:
                list_index = 0
                for elem in obj.iter_elems():
                    for row in elem.ops:
                        self._update_patch_property(patches, object_id, row, prop_state,
                                                    list_index, len(row['succ']),
                                                    object_meta, whole_doc=True)
                    if elem.visible():
                        list_index += 1
            else:
                for key in sorted(obj.keys.keys(), key=_utf16_key):
                    for row in obj.keys[key]:
                        self._update_patch_property(patches, object_id, row, prop_state,
                                                    0, len(row['succ']),
                                                    object_meta, whole_doc=True)
        return {'maxOp': self.max_op, 'clock': dict(self.clock),
                'deps': list(self.heads), 'pendingChanges': len(self.queue),
                'diffs': patches['_root']}

    def _document_object_order(self):
        """Objects in document order: root first, then ascending (counter, actor)."""
        others = [oid for oid in self.objects if oid != '_root']
        others.sort(key=lamport_key)
        return ['_root'] + others

    # ------------------------------------------------------------------
    # Serialization
    # ------------------------------------------------------------------

    def _document_ops(self):
        """All ops in document order, as dicts for columnar encoding."""
        ops = []
        for object_id in self._document_object_order():
            obj = self.objects[object_id]
            if obj.is_seq:
                for elem in obj.iter_elems():
                    for row in elem.ops:
                        op = {'obj': object_id, 'action': row['action'],
                              'insert': row.get('insert', False),
                              'id': row['id'], 'succ': list(row['succ']),
                              'elemId': row['elemId']}
                        if 'value' in row:
                            op['value'] = row['value']
                        if 'datatype' in row:
                            op['datatype'] = row['datatype']
                        if 'child' in row:
                            op['child'] = row['child']
                        if 'unknownCols' in row:
                            op['unknownCols'] = row['unknownCols']
                        ops.append(op)
            else:
                for key in sorted(obj.keys.keys(), key=_utf16_key):
                    for row in obj.keys[key]:
                        op = {'obj': object_id, 'action': row['action'],
                              'key': key, 'insert': False,
                              'id': row['id'], 'succ': list(row['succ'])}
                        if 'value' in row:
                            op['value'] = row['value']
                        if 'datatype' in row:
                            op['datatype'] = row['datatype']
                        if 'child' in row:
                            op['child'] = row['child']
                        if 'unknownCols' in row:
                            op['unknownCols'] = row['unknownCols']
                        ops.append(op)
        return ops

    def _canonical_change_order(self):
        """Deterministic topological order over the applied changes, so that
        converged replicas serialize byte-identical documents regardless of
        the order changes arrived. The reference serializes in application
        order and leaves canonicalization as a TODO (new.js:2048); we order by
        a Kahn traversal with ties broken on change hash, adding implicit
        per-actor seq edges so actors' changes stay seq-ascending (required by
        the document decoder, columnar.js:876-905). Returns (order,
        hash_by_index) where `order` lists original change indexes."""
        import heapq
        self._ensure_graph()
        n = len(self.changes_meta)
        hash_by_index = [None] * n
        for h, i in self.change_index_by_hash.items():
            hash_by_index[i] = h
        children = [[] for _ in range(n)]
        indegree = [0] * n
        for i, meta in enumerate(self.changes_meta):
            for dep in meta['deps']:
                children[self.change_index_by_hash[dep]].append(i)
                indegree[i] += 1
        by_actor = {}
        for i, meta in enumerate(self.changes_meta):
            by_actor.setdefault(meta['actor'], []).append(i)
        for idxs in by_actor.values():
            idxs.sort(key=lambda i: self.changes_meta[i]['seq'])
            for a, b in zip(idxs, idxs[1:]):
                children[a].append(b)
                indegree[b] += 1
        heap = [(hash_by_index[i], i) for i in range(n) if indegree[i] == 0]
        heapq.heapify(heap)
        order = []
        while heap:
            _, i = heapq.heappop(heap)
            order.append(i)
            for child in children[i]:
                indegree[child] -= 1
                if indegree[child] == 0:
                    heapq.heappush(heap, (hash_by_index[child], child))
        return order, hash_by_index

    def save(self):
        """Serialize to the document container format (ref new.js:2033-2055).
        Unlike the reference, the encoding is canonical: changes are sorted
        into a deterministic topological order and the actor table is sorted,
        so converged replicas produce identical bytes."""
        if self.binary_doc:
            return self.binary_doc
        doc_ops = self._document_ops()
        order, hash_by_index = self._canonical_change_order()
        canonical_index = {hash_by_index[old]: pos for pos, old in enumerate(order)}
        # Unknown ACTOR_ID columns may reference actors that never authored a
        # change; they still need actor-table entries (cf. the change-encode
        # path's _collect_unknown_actors use in parse_all_op_ids)
        from ..columnar import ParsedOpId, _collect_unknown_actors
        doc_actor_set = set(self.actor_ids)
        for op in doc_ops:
            for cid, value in op.get('unknownCols', {}).items():
                _collect_unknown_actors(cid, value, doc_actor_set)
        doc_actor_ids = sorted(doc_actor_set)
        actor_index = {actor: i for i, actor in enumerate(doc_actor_ids)}

        def parse(op_id_str):
            ctr, actor = parse_op_id(op_id_str)
            return ParsedOpId(ctr, actor_index[actor], actor)

        parsed_ops = []
        for op in doc_ops:
            parsed = dict(op)
            parsed['id'] = parse(op['id'])
            parsed['obj'] = op['obj'] if op['obj'] == '_root' else parse(op['obj'])
            if parsed.get('elemId') not in (None, '_head'):
                parsed['elemId'] = parse(parsed['elemId'])
            parsed['succ'] = [parse(s) for s in op['succ']]
            if parsed.get('child') is not None:
                parsed['child'] = parse(parsed['child'])
            parsed_ops.append(parsed)
        ops_columns = encode_ops(parsed_ops, True, actor_index)

        changes_columns = self._encode_changes_columns(order, actor_index,
                                                       canonical_index)
        self.binary_doc = encode_document_header({
            'changesColumns': changes_columns,
            'opsColumns': ops_columns,
            'actorIds': doc_actor_ids,
            'heads': list(self.heads),
            'headsIndexes': [canonical_index[h] for h in sorted(self.heads)],
            'extraBytes': self.extra_bytes,
        })
        return self.binary_doc

    def _encode_changes_columns(self, order, actor_index, canonical_index):
        columns = {name: encoder_by_column_id(cid) for name, cid in DOCUMENT_COLUMNS
                   if (cid & 7) != 7}
        val_raw = encoding.Encoder()
        for i in order:
            meta = self.changes_meta[i]
            columns['actor'].append_value(actor_index[meta['actor']])
            columns['seq'].append_value(meta['seq'])
            columns['maxOp'].append_value(meta['maxOp'])
            columns['time'].append_value(meta['time'])
            columns['message'].append_value(meta['message'])
            deps = sorted(meta['deps'])
            columns['depsNum'].append_value(len(deps))
            for dep in deps:
                columns['depsIndex'].append_value(canonical_index[dep])
            extra = meta.get('extraBytes')
            if extra:
                num = val_raw.append_raw_bytes(extra)
                columns['extraLen'].append_value(num << 4 | VALUE_TYPE['BYTES'])
            else:
                columns['extraLen'].append_value(VALUE_TYPE['BYTES'])
        out = []
        for name, cid in DOCUMENT_COLUMNS:
            if name == 'extraRaw':
                out.append((cid, name, val_raw))
            else:
                out.append((cid, name, columns[name]))
        return out

    def _load(self, buffer):
        """Initialize from a saved document (or concatenated chunks)."""
        buffer = bytes(buffer)
        chunks = split_containers(buffer)
        changes = []
        for chunk in chunks:
            if chunk[8] == CHUNK_TYPE_DOCUMENT:
                header = decode_document_header(chunk)
                if header['extraBytes']:
                    self.extra_bytes = header['extraBytes']
                for change in decode_document(chunk):
                    changes.append(encode_change(change))
            else:
                changes.append(chunk)
        if changes:
            self.apply_changes(changes)
        # Deliberately NOT caching `buffer` as binary_doc: save() promises a
        # canonical encoding, and a loaded document's bytes may be a foreign
        # (application-order) encoding that converged replicas would not share
