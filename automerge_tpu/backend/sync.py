"""Peer-to-peer data synchronisation protocol (ref backend/sync.js).

Based on Kleppmann & Howard, "Byzantine Eventual Consistency and the
Fundamental Limits of Peer-to-Peer Databases" (arXiv:2012.00472): each peer
remembers the shared heads after the last successful sync, and reconciliation
exchanges Bloom filters over the changes added since then. Wire format is
byte-compatible with the reference (message type 0x42, peer state 0x43,
explicit Bloom parameters).

The batched fleet-scale Bloom build/probe lives in automerge_tpu.fleet.bloom;
this module is the host-side protocol driver.
"""

from ..encoding import (Encoder, Decoder, hex_string_to_bytes,
    bytes_to_hex_string, uleb_append as _uleb)
from ..columnar import decode_change_meta
from ..errors import MalformedSyncMessage, as_wire_error
from ..observability import register_health_source
from ..observability.metrics import Counters
from . import get_heads, get_missing_deps, get_change_by_hash, get_changes, \
    apply_changes

# Containment counter: peer Bloom filters that failed to parse/probe and
# were treated as empty (send-everything) instead of crashing the
# generate round. Registered as a health source so bench.py and the
# chaos tests can see corruption being absorbed.
_wire_stats = Counters({'rejected_filters': 0})
register_health_source('rejected_filters',
                       lambda: _wire_stats['rejected_filters'])

HASH_SIZE = 32
MESSAGE_TYPE_SYNC = 0x42  # first byte of a sync message
PEER_STATE_TYPE = 0x43    # first byte of an encoded peer state

# ~1% false positive rate; the parameters are part of the wire format so they
# can change without breaking protocol compatibility (ref sync.js:29-31)
BITS_PER_ENTRY = 10
NUM_PROBES = 7


def read_filter_header(decoder):
    """THE wire-format filter-header reader (counterpart of
    fleet/bloom.py's `_append_filter_header` writer): every site that
    parses filter bytes — BloomFilter decode, the message-boundary
    framing check, the batched device probe — goes through this one
    function so the readers cannot drift. Returns (num_entries,
    bits_per_entry, num_probes, bitmap_byte_len); rejects the
    zero-width-probe shape (entries > 0 with bits_per_entry or
    num_probes of 0), which would divide by zero at probe time."""
    num_entries = decoder.read_uint32()
    bits_per_entry = decoder.read_uint32()
    num_probes = decoder.read_uint32()
    if num_entries and (bits_per_entry == 0 or num_probes == 0):
        raise MalformedSyncMessage('bloom filter with zero-width probes')
    return (num_entries, bits_per_entry, num_probes,
            (num_entries * bits_per_entry + 7) // 8)


class BloomFilter:
    """Bloom filter over SHA-256 change hashes, using triple hashing over the
    first 12 hash bytes (Dillinger & Manolios; ref sync.js:38-125)."""

    def __init__(self, arg):
        if isinstance(arg, (list, tuple)):
            self.num_entries = len(arg)
            self.num_bits_per_entry = BITS_PER_ENTRY
            self.num_probes = NUM_PROBES
            self.bits = bytearray(
                (self.num_entries * self.num_bits_per_entry + 7) // 8)
            for hash in arg:
                self.add_hash(hash)
        elif isinstance(arg, (bytes, bytearray, memoryview)):
            arg = bytes(arg)
            if len(arg) == 0:
                self.num_entries = 0
                self.num_bits_per_entry = 0
                self.num_probes = 0
                self.bits = bytearray()
            else:
                decoder = Decoder(arg)
                (self.num_entries, self.num_bits_per_entry,
                 self.num_probes, n_bytes) = read_filter_header(decoder)
                self.bits = bytearray(decoder.read_raw_bytes(n_bytes))
        else:
            raise TypeError('invalid argument')

    @property
    def bytes(self):
        if self.num_entries == 0:
            return b''
        encoder = Encoder()
        encoder.append_uint32(self.num_entries)
        encoder.append_uint32(self.num_bits_per_entry)
        encoder.append_uint32(self.num_probes)
        encoder.append_raw_bytes(self.bits)
        return encoder.buffer

    def get_probes(self, hash):
        hash_bytes = hex_string_to_bytes(hash)
        modulo = 8 * len(self.bits)
        if len(hash_bytes) != 32:
            raise ValueError(f'Not a 256-bit hash: {hash}')
        x = int.from_bytes(hash_bytes[0:4], 'little') % modulo
        y = int.from_bytes(hash_bytes[4:8], 'little') % modulo
        z = int.from_bytes(hash_bytes[8:12], 'little') % modulo
        probes = [x]
        for _ in range(1, self.num_probes):
            x = (x + y) % modulo
            y = (y + z) % modulo
            probes.append(x)
        return probes

    def add_hash(self, hash):
        for probe in self.get_probes(hash):
            self.bits[probe >> 3] |= 1 << (probe & 7)

    def contains_hash(self, hash):
        if self.num_entries == 0:
            return False
        return all(self.bits[probe >> 3] & (1 << (probe & 7))
                   for probe in self.get_probes(hash))


def _encode_hashes(encoder, hashes):
    out = bytearray()
    _hashes_raw(out, hashes)
    # (delegates to the bytearray fast path; the count uleb matches
    # append_uint32's encoding)
    encoder.append_raw_bytes(bytes(out))


def _decode_hashes(decoder):
    return [bytes_to_hex_string(decoder.read_raw_bytes(HASH_SIZE))
            for _ in range(decoder.read_uint32())]


def _hashes_raw(out, hashes):
    """Encode a sorted hash run: count uleb + raw 32-byte hashes, with
    one C-level hex decode for the whole run instead of a per-hash
    convert+append (sync messages encode by the thousand in the fleet
    driver, and this was its hottest line). Per-hash length is validated
    up front — a joined decode alone would let malformed hashes whose
    lengths cancel out slip through as shifted garbage."""
    if not isinstance(hashes, (list, tuple)):
        raise TypeError('hashes must be an array')
    _uleb(out, len(hashes))
    if not hashes:
        return
    if any(a >= b for a, b in zip(hashes, hashes[1:])):
        raise ValueError('hashes must be sorted')
    if any(len(h) != 2 * HASH_SIZE for h in hashes):
        raise TypeError('heads hashes must be 256 bits')
    try:
        data = bytes.fromhex(''.join(hashes))
    except ValueError:
        raise TypeError('heads hashes must be 256 bits')
    if len(data) != HASH_SIZE * len(hashes):
        raise TypeError('heads hashes must be 256 bits')
    out += data


def encode_sync_message(message):
    """(ref sync.js:157-172). Built with direct bytearray ops — the
    fleet driver encodes thousands of messages per round, and the
    general Encoder's per-int checks dominated its profile."""
    out = bytearray([MESSAGE_TYPE_SYNC])
    _hashes_raw(out, message['heads'])
    _hashes_raw(out, message['need'])
    _uleb(out, len(message['have']))
    for have in message['have']:
        _hashes_raw(out, have['lastSync'])
        bloom = bytes(have['bloom'])
        _uleb(out, len(bloom))
        out += bloom
    _uleb(out, len(message['changes']))
    for change in message['changes']:
        change = bytes(change)
        _uleb(out, len(change))
        out += change
    return bytes(out)


def _validate_filter_framing(bloom):
    """Cheap structural check of a filter's wire bytes at the decode
    boundary: a corrupt filter stored into `theirHave` would poison every
    LATER generate (unprobeable, or worse: probeable but all-False, which
    makes changes_to_send permanently nonempty against a full sentHashes
    and the peer solicit forever), so the whole message quarantines NOW,
    where the peer's retry/reset machinery handles it like any other
    corrupt message."""
    if not bloom:
        return
    decoder = Decoder(bytes(bloom))
    _entries, _bpe, _probes, n_bytes = read_filter_header(decoder)
    decoder.read_raw_bytes(n_bytes)


def decode_sync_message(data):
    """(ref sync.js:177-201). Undecodable bytes — including a structurally
    corrupt Bloom filter inside `have` — raise `MalformedSyncMessage`
    (a ValueError), never a bare decoder exception: one hostile message
    must be quarantinable by type, before any of it enters sync state."""
    try:
        decoder = Decoder(data)
        message_type = decoder.read_byte()
        if message_type != MESSAGE_TYPE_SYNC:
            raise ValueError(f'Unexpected message type: {message_type}')
        message = {'heads': _decode_hashes(decoder),
                   'need': _decode_hashes(decoder),
                   'have': [], 'changes': []}
        for _ in range(decoder.read_uint32()):
            last_sync = _decode_hashes(decoder)
            bloom = decoder.read_prefixed_bytes()
            _validate_filter_framing(bloom)
            message['have'].append({'lastSync': last_sync, 'bloom': bloom})
        for _ in range(decoder.read_uint32()):
            message['changes'].append(decoder.read_prefixed_bytes())
    except Exception as exc:
        raise as_wire_error(exc, MalformedSyncMessage, 'decode_sync_message')
    # Trailing bytes are ignored for forward compatibility
    return message


def encode_sync_state(sync_state):
    """Only sharedHeads persists across restarts (ref sync.js:206-211)."""
    encoder = Encoder()
    encoder.append_byte(PEER_STATE_TYPE)
    _encode_hashes(encoder, sync_state['sharedHeads'])
    return encoder.buffer


def decode_sync_state(data):
    try:
        decoder = Decoder(data)
        record_type = decoder.read_byte()
        if record_type != PEER_STATE_TYPE:
            raise ValueError(f'Unexpected record type: {record_type}')
        state = init_sync_state()
        state['sharedHeads'] = _decode_hashes(decoder)
    except Exception as exc:
        raise as_wire_error(exc, MalformedSyncMessage, 'decode_sync_state')
    return state


# The reference re-decodes and re-hashes every change for each of the
# Bloom-filter build, the changes-to-send scan, and the sentHashes filter
# (its own TODO at sync.js:378). Change buffers are immutable, so a bounded
# memo of their metadata removes the O(rounds x changes) redundant SHA-256s.
_META_CACHE_MAX = 1 << 16
_meta_cache = {}


def _cached_meta(change):
    change = bytes(change)
    meta = _meta_cache.get(change)
    if meta is None:
        meta = decode_change_meta(change, True)
        if len(_meta_cache) >= _META_CACHE_MAX:
            _meta_cache.clear()
        _meta_cache[change] = meta
    return meta


def known_hash_flags(backend, hashes):
    """Membership of `hashes` in the backend's APPLIED history — the one
    helper behind theirHave lastSync reconciliation and received-heads
    lookup. A fleet document whose frontier index is warm
    (fleet/hashindex.py — registered by a batched sync round) answers
    from the index without ever touching the hash-graph dicts; every
    other backend takes the classic get_change_by_hash path. Both
    answers are exact and identical (the equivalence tests pin it)."""
    if not hashes:
        return []
    state = backend.get('state') if isinstance(backend, dict) else None
    probe = getattr(state, 'probe_hashes', None)
    if probe is not None:
        flags = probe(hashes)
        if flags is not None:
            return [bool(f) for f in flags]
    return [get_change_by_hash(backend, h) is not None for h in hashes]


def make_bloom_filter(backend, last_sync):
    """Bloom filter over changes applied since `last_sync` (ref sync.js:234-238)."""
    from . import get_change_hashes
    hashes = get_change_hashes(backend, last_sync)
    return {'lastSync': last_sync, 'bloom': BloomFilter(hashes).bytes}


def changes_to_send_prescan(backend, have, need):
    """Prologue of the changes-to-send scan (ref sync.js:246-306): collect
    candidate change metas and the peer filters to probe. The probe itself
    is pluggable so the fleet driver (fleet/sync_driver.py) can batch it on
    device. Returns ('need-only', final_changes) when no filters were
    attached, else ('probe', (changes_meta, filter_bytes_list))."""
    if not have:
        return 'need-only', [
            c for c in (get_change_by_hash(backend, h) for h in need)
            if c is not None]
    last_sync_hashes = set()
    for h in have:
        last_sync_hashes.update(h['lastSync'])
    changes = [_cached_meta(c)
               for c in get_changes(backend, sorted(last_sync_hashes))]
    return 'probe', (changes, [h['bloom'] for h in have])


def changes_to_send_finish(backend, changes, bloom_hits, need):
    """Epilogue of the changes-to-send scan, fed per-filter probe results
    (bloom_hits[f][j] = filter f possibly contains changes[j]): Bloom-
    negative changes, their transitive dependents, and explicit needs."""
    change_hashes = set()
    dependents = {}
    hashes_to_send = set()
    for j, change in enumerate(changes):
        change_hashes.add(change['hash'])
        for dep in change['deps']:
            dependents.setdefault(dep, []).append(change['hash'])
        if all(not hits[j] for hits in bloom_hits):
            hashes_to_send.add(change['hash'])

    # Include any changes that depend on a Bloom-negative change
    stack = list(hashes_to_send)
    while stack:
        hash = stack.pop()
        for dep in dependents.get(hash, []):
            if dep not in hashes_to_send:
                hashes_to_send.add(dep)
                stack.append(dep)

    changes_to_send = []
    for hash in need:
        hashes_to_send.add(hash)
        if hash not in change_hashes:
            change = get_change_by_hash(backend, hash)
            if change is not None:
                changes_to_send.append(change)

    for change in changes:
        if change['hash'] in hashes_to_send:
            changes_to_send.append(change['change'])
    return changes_to_send


def probe_filter_lenient(filter_bytes, hashes):
    """Probe one peer filter's wire bytes against `hashes`, CONTAINING
    corruption: a filter that fails to parse or probe (truncated framing,
    zero-width bits from a flipped byte, ...) reads as all-False —
    "peer has nothing", so every candidate change is resent. That costs
    bandwidth, never convergence, and it keeps a peer that stored a
    corrupt `theirHave` functional instead of crashing every subsequent
    generate (the filter arrived inside an already-checksummed message,
    so there is no retransmit to ask for)."""
    try:
        bloom = BloomFilter(bytes(filter_bytes))
        return [bloom.contains_hash(h) for h in hashes]
    except Exception:
        _wire_stats.inc('rejected_filters')
        return [False] * len(hashes)


def get_changes_to_send(backend, have, need):
    """Changes since lastSync whose hash misses every peer Bloom filter, plus
    transitive dependents of Bloom-negative changes, plus explicitly needed
    hashes (ref sync.js:246-306)."""
    mode, payload = changes_to_send_prescan(backend, have, need)
    if mode == 'need-only':
        return payload
    changes, filter_bytes = payload
    hashes = [c['hash'] for c in changes]
    bloom_hits = [probe_filter_lenient(fb, hashes) for fb in filter_bytes]
    return changes_to_send_finish(backend, changes, bloom_hits, need)


def init_sync_state():
    return {
        'sharedHeads': [],
        'lastSentHeads': [],
        'theirHeads': None,
        'theirNeed': None,
        'theirHave': None,
        'sentHashes': set(),
    }


def generate_sync_message(backend, sync_state):
    """Generate the next message to a peer, or None when in sync
    (ref sync.js:327-393)."""
    if backend is None:
        raise ValueError('generateSyncMessage called with no Automerge document')
    if sync_state is None:
        raise ValueError('generateSyncMessage requires a syncState, which can be '
                         'created with initSyncState()')

    shared_heads = sync_state['sharedHeads']
    last_sent_heads = sync_state['lastSentHeads']
    their_heads = sync_state['theirHeads']
    their_need = sync_state['theirNeed']
    their_have = sync_state['theirHave']
    sent_hashes = sync_state['sentHashes']
    our_heads = get_heads(backend)

    our_need = get_missing_deps(backend, their_heads or [])

    # Only attach a Bloom filter when we're not just chasing missing deps
    # caused by false positives (rationale: sync.js:341-348)
    our_have = []
    if their_heads is None or all(h in their_heads for h in our_need):
        our_have = [make_bloom_filter(backend, shared_heads)]

    # Full-resync reset if the peer's lastSync contains hashes unknown to us
    # (e.g. peer crashed without persisting; ref sync.js:352-362)
    if their_have:
        last_sync = their_have[0]['lastSync']
        if not all(known_hash_flags(backend, last_sync)):
            reset = {'heads': our_heads, 'need': [],
                     'have': [{'lastSync': [], 'bloom': b''}], 'changes': []}
            return [sync_state, encode_sync_message(reset)]

    changes_to_send = get_changes_to_send(backend, their_have, their_need) \
        if isinstance(their_have, list) and isinstance(their_need, list) else []

    heads_unchanged = isinstance(last_sent_heads, list) and \
        our_heads == last_sent_heads
    heads_equal = isinstance(their_heads, list) and our_heads == their_heads
    if heads_unchanged and heads_equal and not changes_to_send:
        return [sync_state, None]

    # A state promoted by the fleet driver carries its sentHashes as a
    # peer-space of the device table (fleet/hashindex.py PeerSentSet):
    # answer the whole filter in ONE batched probe, and stage new sends
    # in place — the copy-on-write below only ever shielded old state
    # dicts, which the peer-space path shares by identity instead.
    contains_many = getattr(sent_hashes, 'contains_many', None)
    if contains_many is not None and changes_to_send:
        already = contains_many([_cached_meta(c)['hash']
                                 for c in changes_to_send])
        changes_to_send = [c for c, hit in zip(changes_to_send, already)
                           if not hit]
    else:
        changes_to_send = [c for c in changes_to_send
                           if _cached_meta(c)['hash'] not in sent_hashes]

    message = {'heads': our_heads, 'have': our_have, 'need': our_need,
               'changes': changes_to_send}
    if changes_to_send:
        if contains_many is None:
            sent_hashes = set(sent_hashes)
        for change in changes_to_send:
            sent_hashes.add(_cached_meta(change)['hash'])

    new_state = dict(sync_state, lastSentHeads=our_heads, sentHashes=sent_hashes)
    return [new_state, encode_sync_message(message)]


def advance_heads(my_old_heads, my_new_heads, our_old_shared_heads):
    """Shared-heads algebra after applying received changes (ref sync.js:408-413)."""
    new_heads = [h for h in my_new_heads if h not in my_old_heads]
    common_heads = [h for h in our_old_shared_heads if h in my_new_heads]
    return sorted(set(new_heads + common_heads))


def receive_sync_message(backend, old_sync_state, binary_message):
    """Apply a received sync message; returns [backend, syncState, patch]
    (ref sync.js:420-473)."""
    if backend is None:
        raise ValueError('generateSyncMessage called with no Automerge document')
    if old_sync_state is None:
        raise ValueError('generateSyncMessage requires a syncState, which can be '
                         'created with initSyncState()')

    shared_heads = old_sync_state['sharedHeads']
    last_sent_heads = old_sync_state['lastSentHeads']
    sent_hashes = old_sync_state['sentHashes']
    patch = None
    message = decode_sync_message(binary_message)
    before_heads = get_heads(backend)

    # Apply received changes; Bloom false positives may leave missing deps, in
    # which case the backend queues them (repaired later via `need`)
    if message['changes']:
        backend, patch = apply_changes(backend, message['changes'])
        shared_heads = advance_heads(before_heads, get_heads(backend), shared_heads)

    if not message['changes'] and message['heads'] == before_heads:
        last_sent_heads = message['heads']

    known_heads = [h for h, known in
                   zip(message['heads'],
                       known_hash_flags(backend, message['heads']))
                   if known]
    if len(known_heads) == len(message['heads']):
        shared_heads = message['heads']
        # Remote peer lost all its data: reset for a full resync (a
        # peer-space sent set hands its table space back, see
        # fleet/hashindex.py — duck-typed so this module stays
        # fleet-agnostic)
        if len(message['heads']) == 0:
            last_sent_heads = []
            release = getattr(sent_hashes, 'release', None)
            if release is not None:
                release()
            sent_hashes = set()
    else:
        shared_heads = sorted(set(known_heads) | set(shared_heads))

    sync_state = {
        'sharedHeads': shared_heads,
        'lastSentHeads': last_sent_heads,
        'theirHave': message['have'],
        'theirHeads': message['heads'],
        'theirNeed': message['need'],
        'sentHashes': sent_hashes,
    }
    return [backend, sync_state, patch]
