"""Backend API: the exact contract a replacement backend must satisfy
(ref backend/index.js:1-8, backend/backend.js).

A backend handle is a dict {'state': OpSet, 'heads': [...]} with
freeze-on-use semantics: every mutating call freezes the old handle and
returns a new one; using a stale handle raises (ref backend/util.js:1-10).
"""

from ..columnar import encode_change
from .op_set import OpSet


def _backend_state(backend):
    if backend.get('frozen'):
        raise ValueError(
            'Attempting to use an outdated Automerge document that has already been updated. '
            'Please use the latest document state, or call Automerge.clone() if you really '
            'need to use this old document state.')
    return backend['state']


def init():
    return {'state': OpSet(), 'heads': []}


def clone(backend):
    return {'state': _backend_state(backend).clone(), 'heads': backend['heads']}


def free(backend):
    backend['state'] = None
    backend['frozen'] = True


def apply_changes(backend, changes):
    state = _backend_state(backend)
    patch = state.apply_changes(changes)
    backend['frozen'] = True
    return [{'state': state, 'heads': state.heads}, patch]


def _hash_by_actor(state, actor_id, index):
    hashes = state.hashes_by_actor.get(actor_id)
    if hashes and index < len(hashes):
        return hashes[index]
    raise ValueError(f'Unknown change: actorId = {actor_id}, seq = {index + 1}')


def apply_local_change(backend, change):
    """Apply a change request from the local frontend
    (ref backend/backend.js:54-91)."""
    state = _backend_state(backend)
    clock_seq = state.clock.get(change['actor'])
    if clock_seq is not None and change['seq'] <= clock_seq:
        raise ValueError('Change request has already been applied')

    # The backend injects the local actor's previous change hash into deps,
    # because a frontend racing ahead of an async backend doesn't know the
    # hash of its own last change (rationale: backend/backend.js:59-72)
    if change['seq'] > 1:
        last_hash = _hash_by_actor(state, change['actor'], change['seq'] - 2)
        deps = {last_hash: True}
        for h in change.get('deps', []):
            deps[h] = True
        change = dict(change, deps=sorted(deps.keys()))

    binary_change = encode_change(change)
    patch = state.apply_changes([binary_change], is_local=True)
    backend['frozen'] = True

    # Omit the local actor's own last change hash from the patch's deps
    last_hash = _hash_by_actor(state, change['actor'], change['seq'] - 1)
    patch['deps'] = [head for head in patch['deps'] if head != last_hash]
    return [{'state': state, 'heads': state.heads}, patch, binary_change]


def save(backend):
    return _backend_state(backend).save()


def load(data):
    state = OpSet(data)
    return {'state': state, 'heads': state.heads}


def load_changes(backend, changes):
    state = _backend_state(backend)
    state.apply_changes(changes)
    backend['frozen'] = True
    return {'state': state, 'heads': state.heads}


def get_patch(backend):
    return _backend_state(backend).get_patch()


def get_heads(backend):
    return backend['heads']


def get_all_changes(backend):
    return get_changes(backend, [])


def get_changes(backend, have_deps):
    if not isinstance(have_deps, (list, tuple)):
        raise TypeError('Pass an array of hashes to Backend.getChanges()')
    return _backend_state(backend).get_changes(list(have_deps))


def get_change_hashes(backend, have_deps):
    """Hashes of get_changes(backend, have_deps) without decoding the
    change buffers (the fleet sync driver's Bloom feed)."""
    if not isinstance(have_deps, (list, tuple)):
        raise TypeError('Pass an array of hashes to Backend.getChanges()')
    return _backend_state(backend).get_change_hashes(list(have_deps))


def get_changes_added(backend1, backend2):
    return _backend_state(backend2).get_changes_added(_backend_state(backend1))


def get_change_by_hash(backend, hash):
    return _backend_state(backend).get_change_by_hash(hash)


def get_missing_deps(backend, heads=()):
    return _backend_state(backend).get_missing_deps(heads)


# Sync protocol re-exports (ref backend/index.js:5-7); imported last to avoid
# a circular import, since sync.py uses the backend API above
from .sync import (  # noqa: E402
    generate_sync_message, receive_sync_message, encode_sync_message,
    decode_sync_message, init_sync_state, encode_sync_state, decode_sync_state,
    BloomFilter,
)
