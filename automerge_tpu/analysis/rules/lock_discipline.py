"""lock-discipline: shared module state mutates under a lock, or not at
all.

The threaded surfaces (native pool completion callbacks, service pump
threads, the Prometheus exporter's scrape thread, the recorder ring,
the kernel-ledger wrapper) all reach module-level containers. A
mutation of one outside a `with <lock>` block — and outside Counters,
which locks internally — is reported as a static race candidate. The
rule does not try to prove a race (no static tool here can); it
enumerates the candidates so each is either fixed or carries a written
justification in the baseline (e.g. import-time-only registration).
"""

import ast

from .. import scopes
from ..astutil import call_name, dotted
from ..core import Rule

CONTAINER_FACTORIES = frozenset({
    'dict', 'list', 'set', 'collections.defaultdict', 'defaultdict',
    'collections.OrderedDict', 'OrderedDict', 'collections.deque',
    'deque',
})

MUTATORS = frozenset({
    'append', 'appendleft', 'add', 'update', 'pop', 'popleft', 'popitem',
    'setdefault', 'clear', 'extend', 'remove', 'discard', 'insert',
})


class LockDisciplineRule(Rule):
    rule_id = 'lock-discipline'
    doc = ('module-level mutable state on threaded surfaces is mutated '
           'under a lock or is a Counters instance (static race '
           'candidates)')

    def check(self, module):
        if not scopes.threaded_scope(module.path):
            return
        state = self._module_state(module)
        if not state:
            return
        for fn in module.nodes:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                name = self._mutated_state(node, state)
                if name is None:
                    continue
                if self._under_lock(module, node):
                    continue
                yield module.finding(
                    self.rule_id, node,
                    f'static race candidate: module state {name!r} '
                    f'mutated outside a lock on a threaded surface — '
                    f'hold the module lock, use Counters, or justify '
                    f'(e.g. import-time-only) in the baseline')

    @staticmethod
    def _module_state(module):
        names = set()
        for stmt in module.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            value = stmt.value
            is_container = isinstance(
                value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                        ast.ListComp, ast.SetComp)) or \
                call_name(value) in CONTAINER_FACTORIES
            if not is_container:
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        return names

    @staticmethod
    def _mutated_state(node, state):
        # container[key] = ... / del container[key] / container[k] += ...
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = node.targets if isinstance(node, (ast.Assign,
                                                        ast.Delete)) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in state:
                    return t.value.id
        # container.append(...) etc.
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATORS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in state:
            return node.func.value.id
        return None

    @staticmethod
    def _under_lock(module, node):
        for anc in module.ancestors(node):
            if not isinstance(anc, ast.With):
                continue
            for item in anc.items:
                text = dotted(item.context_expr) or \
                    dotted(getattr(item.context_expr, 'func', None)) or ''
                if 'lock' in text.lower():
                    return True
        return False
