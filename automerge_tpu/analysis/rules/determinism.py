"""determinism: replicas must not read wall clocks or roll free dice.

Two replicas applying the same changes must converge byte-identically
(the differential suites pin this dynamically); statically that means
the fleet/backend/service/shard/query paths may not read wall-clock
time (`time.time()`, `datetime.now()` — clocks are injected, round 6)
or call the unseeded module-level `random`/`np.random` API (seeded
`random.Random(seed)` instances are the sanctioned idiom, see
fleet/faults.py). Third check: a wire encode that iterates an unsorted
dict and appends is iteration-order-dependent output — the reference
format is canonical, so encode loops sort first (encode_cursor's
`sorted(heads)` is the idiom).
"""

import ast

from .. import scopes
from ..astutil import dotted
from ..core import Rule

WALL_CLOCK = frozenset({
    'time.time', 'datetime.now', 'datetime.utcnow', 'datetime.today',
    'datetime.datetime.now', 'datetime.datetime.utcnow', 'date.today',
    'datetime.date.today',
})

UNSEEDED_RANDOM = frozenset({
    'random.random', 'random.randint', 'random.randrange',
    'random.choice', 'random.choices', 'random.shuffle', 'random.sample',
    'random.uniform', 'random.getrandbits', 'random.seed',
})

DICT_ITER_METHODS = frozenset({'items', 'keys', 'values'})
ORDER_SINKS = frozenset({'append', 'extend', 'write'})


class DeterminismRule(Rule):
    rule_id = 'determinism'
    doc = ('no wall-clock or unseeded random on deterministic replica '
           'paths; no dict-iteration-order-dependent wire encodes')

    def check(self, module):
        if scopes.deterministic_scope(module.path):
            yield from self._clock_and_random(module)
        if scopes.encode_scope(module.path):
            yield from self._encode_order(module)

    def _clock_and_random(self, module):
        for node in module.nodes:
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            if name in WALL_CLOCK:
                yield module.finding(
                    self.rule_id, node,
                    f'{name}() on a deterministic path — clocks are '
                    f'injected here (round-6 rule); take the tick/clock '
                    f'as a parameter')
            elif name in UNSEEDED_RANDOM:
                yield module.finding(
                    self.rule_id, node,
                    f'unseeded {name}() on a deterministic path — use '
                    f'a seeded random.Random(seed) instance')
            elif name.startswith(('np.random.', 'numpy.random.')) and \
                    not name.endswith(('.default_rng', '.Generator',
                                       '.RandomState')):
                yield module.finding(
                    self.rule_id, node,
                    f'global {name}() on a deterministic path — use a '
                    f'seeded np.random.default_rng(seed) generator')

    def _encode_order(self, module):
        for fn in module.nodes:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not scopes.ENCODE_NAME_RE.search(fn.name):
                continue
            for loop in ast.walk(fn):
                if not isinstance(loop, ast.For):
                    continue
                if not self._unsorted_dict_iter(loop.iter):
                    continue
                if not self._has_order_sink(loop):
                    continue
                yield module.finding(
                    self.rule_id, loop,
                    f'{fn.name}() iterates an unsorted dict and emits '
                    f'per-entry output — wire encodes must be '
                    f'canonical; wrap the iterable in sorted(...)')

    @staticmethod
    def _unsorted_dict_iter(iter_node):
        return isinstance(iter_node, ast.Call) and \
            isinstance(iter_node.func, ast.Attribute) and \
            iter_node.func.attr in DICT_ITER_METHODS

    @staticmethod
    def _has_order_sink(loop):
        for node in ast.walk(loop):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ORDER_SINKS:
                return True
            if isinstance(node, ast.AugAssign) and \
                    isinstance(node.op, ast.Add):
                return True
        return False
