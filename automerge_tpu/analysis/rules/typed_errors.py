"""typed-errors: only automerge_tpu.errors classes escape a decoder.

Three checks, each a past bug class:

1. Decode-surface raises (fuzz rounds 2/3): a public function whose
   name marks it a decode surface (decode_/parse_/read_/split_/inflate)
   may not raise a bare builtin exception — hostile bytes reach these,
   and the containment contract promises callers a typed error carrying
   doc_index. A raise is exempt when it sits inside a try whose handler
   converts (raises a typed class or routes through as_wire_error): that
   is exactly the guarded-boundary idiom decode_cursor uses.
2. `except Exception: pass` (and bare `except: pass`) anywhere: the
   silent swallow that turns corruption into later mystery state.
3. Exception-message string matching (round 11's 'session closed' bug):
   comparing/searching str(exc) or exc.args[...] against a literal
   inside an except handler — the reason SessionClosed exists as a type.
"""

import ast

from .. import scopes
from ..astutil import (
    contains_within, const_str, dotted, error_names, raises_typed)
from ..core import Rule

# Builtin exception names a decode surface may not let escape.
# TypeError is absent on purpose: argument-type guards on decode
# helpers are API validation (caller bugs), not wire corruption.
UNTYPED = frozenset({
    'ValueError', 'KeyError', 'IndexError', 'RuntimeError', 'Exception',
    'OSError', 'IOError', 'EOFError', 'AssertionError',
    'NotImplementedError', 'UnicodeDecodeError', 'OverflowError',
})

BROAD_HANDLERS = frozenset({'Exception', 'BaseException'})


class TypedErrorsRule(Rule):
    rule_id = 'typed-errors'
    doc = ('decode surfaces raise automerge_tpu.errors only; no '
           'except-pass swallows; no exception-message string matching')

    def check(self, module):
        if not scopes.lintable(module.path):
            return
        yield from self._except_pass(module)
        yield from self._message_matching(module)
        if scopes.typed_raise_scope(module.path):
            yield from self._decode_raises(module)

    # -- check 1 -------------------------------------------------------
    def _decode_raises(self, module):
        typed_names, error_modules = error_names(module.tree)
        for fn in module.nodes:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name.startswith('_'):
                continue
            if not scopes.DECODE_NAME_RE.match(fn.name):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                target = node.exc.func if isinstance(node.exc, ast.Call) \
                    else node.exc
                name = dotted(target)
                if name not in UNTYPED:
                    continue
                if self._converted_downstream(module, fn, node,
                                              typed_names, error_modules):
                    continue
                yield module.finding(
                    self.rule_id, node,
                    f'decode surface {fn.name}() raises bare {name} — '
                    f'hostile bytes reach this function, raise an '
                    f'automerge_tpu.errors class (or convert via '
                    f'as_wire_error at the boundary)')

    def _converted_downstream(self, module, fn, raise_node, typed_names,
                              error_modules):
        """Is the raise inside a try (within this function) whose
        handler converts to a typed error?"""
        for anc in module.ancestors(raise_node):
            if anc is fn:
                return False
            if not isinstance(anc, ast.Try):
                continue
            if not contains_within(module, anc.body, raise_node):
                continue  # raise lives in the handler/else, not the body
            for handler in anc.handlers:
                for sub in ast.walk(handler):
                    if isinstance(sub, ast.Raise) and sub.exc is not None \
                            and raises_typed(sub.exc, typed_names,
                                             error_modules):
                        return True
                    if isinstance(sub, ast.Call) and raises_typed(
                            sub, typed_names, error_modules):
                        return True
        return False

    # -- check 2 -------------------------------------------------------
    def _except_pass(self, module):
        for node in module.nodes:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is not None and \
                    dotted(node.type) not in BROAD_HANDLERS:
                continue
            if all(isinstance(stmt, (ast.Pass, ast.Continue))
                   for stmt in node.body):
                caught = dotted(node.type) if node.type is not None \
                    else 'everything'
                yield module.finding(
                    self.rule_id, node,
                    f'except {caught}: pass swallows failures silently '
                    f'— narrow the exception types or handle/log it')

    # -- check 3 -------------------------------------------------------
    def _message_matching(self, module):
        for handler in module.nodes:
            if not isinstance(handler, ast.ExceptHandler) or \
                    handler.name is None:
                continue
            var = handler.name
            for node in ast.walk(handler):
                if isinstance(node, ast.Compare) and \
                        self._compares_message(node, var):
                    yield module.finding(
                        self.rule_id, node,
                        f'string-matching on the message of caught '
                        f'exception {var!r} — add/raise a dedicated '
                        f'typed class instead (the SessionClosed '
                        f'lesson)')
                elif isinstance(node, ast.Call) and \
                        self._prefix_matches_message(node, var):
                    yield module.finding(
                        self.rule_id, node,
                        f'startswith/endswith on str({var}) — match the '
                        f'exception TYPE, not its message text')

    @staticmethod
    def _is_message_expr(node, var):
        """str(var) or var.args[...]"""
        if isinstance(node, ast.Call) and dotted(node.func) == 'str' and \
                len(node.args) == 1 and \
                isinstance(node.args[0], ast.Name) and \
                node.args[0].id == var:
            return True
        if isinstance(node, ast.Subscript) and \
                dotted(node.value) == f'{var}.args':
            return True
        return False

    def _compares_message(self, node, var):
        sides = [node.left] + list(node.comparators)
        if not any(self._is_message_expr(s, var) for s in sides):
            return False
        if not any(const_str(s) is not None for s in sides):
            return False
        return any(isinstance(op, (ast.In, ast.NotIn, ast.Eq, ast.NotEq))
                   for op in node.ops)

    def _prefix_matches_message(self, node, var):
        return isinstance(node.func, ast.Attribute) and \
            node.func.attr in ('startswith', 'endswith', 'find') and \
            self._is_message_expr(node.func.value, var)
