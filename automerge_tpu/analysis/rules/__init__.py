"""One module per contract rule; ALL_RULES is the CLI's default set."""

from .typed_errors import TypedErrorsRule
from .counter_discipline import CounterDisciplineRule
from .kernel_ledger import KernelLedgerRule
from .determinism import DeterminismRule
from .lock_discipline import LockDisciplineRule

ALL_RULES = (
    TypedErrorsRule,
    CounterDisciplineRule,
    KernelLedgerRule,
    DeterminismRule,
    LockDisciplineRule,
)

RULES_BY_ID = {cls.rule_id: cls for cls in ALL_RULES}


def get_rules(ids=None):
    """Instantiate the requested rules (all of them by default)."""
    if ids is None:
        return [cls() for cls in ALL_RULES]
    unknown = [i for i in ids if i not in RULES_BY_ID]
    if unknown:
        raise KeyError(f'unknown rule ids: {unknown}; '
                       f'known: {sorted(RULES_BY_ID)}')
    return [RULES_BY_ID[i]() for i in ids]
