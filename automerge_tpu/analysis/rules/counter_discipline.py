"""counter-discipline: stats/health state is Counters, names stay legal.

Round 13 retired torn raw-dict `_stats` counters across 15 files (the
GIL does not make `d[k] += 1` atomic across the native pool's callback
threads); observability.Counters is the replacement — it locks inside
`inc()` and exports atomically. This rule keeps raw dicts from creeping
back, and blocks registration of the reserved exposition names
(`total`, `fleet<N>`) that the metrics exporter synthesizes itself —
a source registered under one would silently shadow the synthesized
rollup (the runtime guard in _check_source_name becomes a parse-time
failure here).
"""

import ast

from .. import scopes
from ..astutil import call_name, const_str
from ..core import Rule

DICT_FACTORIES = frozenset({
    'dict', 'collections.defaultdict', 'defaultdict',
    'collections.OrderedDict', 'OrderedDict', 'collections.Counter',
})

REGISTER_FNS = frozenset({
    'register_dispatch_source', 'register_health_source',
})


class CounterDisciplineRule(Rule):
    rule_id = 'counter-discipline'
    doc = ('module-level stats/health counters must be '
           'observability.Counters, and reserved exposition names '
           '(total, fleet<N>) must not be registered as sources')

    def check(self, module):
        if not scopes.counter_scope(module.path):
            return
        yield from self._raw_dict_counters(module)
        yield from self._reserved_registrations(module)

    def _raw_dict_counters(self, module):
        for stmt in module.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = [t for t in stmt.targets
                           if isinstance(t, ast.Name)]
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name) and stmt.value:
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            stats_targets = [t for t in targets
                             if scopes.STATS_NAME_RE.search(t.id)]
            if not stats_targets:
                continue
            if not self._is_raw_dict(value):
                continue
            names = ', '.join(t.id for t in stats_targets)
            yield module.finding(
                self.rule_id, stmt,
                f'module-level counter {names} is a plain dict — use '
                f'observability.Counters (torn raw-dict increments are '
                f'the round-13 bug class)')

    @staticmethod
    def _is_raw_dict(value):
        if isinstance(value, ast.Dict):
            return True
        if isinstance(value, ast.DictComp):
            return True
        name = call_name(value)
        return name in DICT_FACTORIES

    def _reserved_registrations(self, module):
        for node in module.nodes:
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            if name.split('.')[-1] not in REGISTER_FNS:
                continue
            if not node.args:
                continue
            arg = const_str(node.args[0])
            if arg is None:
                continue
            if scopes.RESERVED_SOURCE_RE.fullmatch(arg):
                yield module.finding(
                    self.rule_id, node,
                    f'registers reserved source name {arg!r} — the '
                    f'exporter synthesizes total/fleet<N> rollups '
                    f'itself; pick a non-reserved name')
