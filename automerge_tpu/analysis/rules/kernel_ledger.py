"""kernel-ledger: every jit entry point is costed; no per-doc dispatch.

Round 17's cost ledger only works if every jitted kernel passes through
`instrument_kernel` — an unwrapped `jax.jit` is a kernel the floor
table cannot see. Two checks:

1. jit coverage: a `jax.jit(...)` call must be the direct argument of
   `instrument_kernel(kind, jax.jit(...))`; decorator forms (`@jax.jit`,
   `@functools.partial(jax.jit, ...)`) are always violations because a
   decorator cannot be wrapped (rebind the impl instead — the idiom
   everywhere else in fleet/).
2. per-doc dispatch (rounds 6/16's O(1)-dispatch contract): a `jnp.`
   use inside a `for` loop whose iterable is doc-shaped (docs, handles,
   peers, subscribers, n_docs, ...) in a host-path module dispatches
   one kernel per document. Per-class pool loops and fixed array-tuple
   grows don't match the iterable pattern and stay legal.
"""

import ast

from .. import scopes
from ..astutil import dotted
from ..core import Rule

JIT_NAMES = frozenset({'jax.jit', 'jit'})
WRAPPER_NAMES = frozenset({'instrument_kernel'})


def _is_jit(node):
    return isinstance(node, ast.Call) and dotted(node.func) in JIT_NAMES


def _is_partial_of_jit(node):
    if not isinstance(node, ast.Call):
        return False
    name = dotted(node.func)
    if name not in ('functools.partial', 'partial'):
        return False
    return any(dotted(a) in JIT_NAMES for a in node.args)


class KernelLedgerRule(Rule):
    rule_id = 'kernel-ledger'
    doc = ('jax.jit entry points must be instrument_kernel-wrapped; no '
           'jnp dispatch inside per-doc loops in host-path modules')

    def check(self, module):
        if scopes.kernel_scope(module.path):
            yield from self._jit_coverage(module)
        if scopes.host_loop_scope(module.path):
            yield from self._per_doc_dispatch(module)

    def _jit_coverage(self, module):
        decorators = set()
        for fn in module.nodes:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in fn.decorator_list:
                    decorators.add(id(dec))
                    if dotted(dec) in JIT_NAMES:
                        yield module.finding(
                            self.rule_id, dec,
                            f'@jax.jit on {fn.name}() bypasses the cost '
                            f'ledger — rebind as name = instrument_'
                            f'kernel(kind, jax.jit(_impl))')
                    elif _is_partial_of_jit(dec):
                        yield module.finding(
                            self.rule_id, dec,
                            f'@functools.partial(jax.jit, ...) on '
                            f'{fn.name}() bypasses the cost ledger — '
                            f'rebind as name = instrument_kernel(kind, '
                            f'jax.jit(_impl, ...))')
        for node in module.nodes:
            if not _is_jit(node) or id(node) in decorators:
                continue
            parent = module.parent_of(node)
            if isinstance(parent, ast.Call) and \
                    (dotted(parent.func) or '').split('.')[-1] in \
                    WRAPPER_NAMES:
                continue
            yield module.finding(
                self.rule_id, node,
                'jax.jit(...) result is not instrument_kernel-wrapped '
                '— the kernel is invisible to the cost ledger')

    def _per_doc_dispatch(self, module):
        for loop in module.nodes:
            if not isinstance(loop, ast.For):
                continue
            iter_text = module.text(loop.iter)
            if not scopes.PER_DOC_ITER_RE.search(iter_text):
                continue
            for node in ast.walk(loop):
                if isinstance(node, ast.Attribute) and \
                        (dotted(node) or '').startswith(('jnp.',
                                                         'jax.numpy.')):
                    yield module.finding(
                        self.rule_id, node,
                        f'jnp dispatch inside a per-doc loop (iterating '
                        f'{iter_text.strip()[:60]!r}) — batch it into '
                        f'one fused dispatch (the O(1)-dispatch '
                        f'contract)')
                    break  # one finding per loop is enough
