"""Small shared AST helpers for the rule modules."""

import ast


def dotted(node):
    """'jax.jit' for a Name/Attribute chain, None for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def call_name(node):
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return None


def const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def error_names(tree):
    """Names this module binds to automerge_tpu.errors classes (via any
    `from ...errors import X [as Y]` form), plus the module aliases
    (`from automerge_tpu import errors`) so `errors.X` resolves too."""
    names, modules = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ''
            if mod == 'errors' or mod.endswith('.errors'):
                for alias in node.names:
                    names.add(alias.asname or alias.name)
            elif mod in ('automerge_tpu', '..', '.'):
                for alias in node.names:
                    if alias.name == 'errors':
                        modules.add(alias.asname or 'errors')
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith('errors'):
                    modules.add(alias.asname or alias.name)
    return names, modules


def raises_typed(node, typed_names, error_modules):
    """Does this expression construct/reference a typed error class?"""
    target = node.func if isinstance(node, ast.Call) else node
    name = dotted(target)
    if name is None:
        return False
    if name in typed_names or name == 'as_wire_error' or \
            name.endswith('.as_wire_error'):
        return True
    head = name.split('.', 1)[0]
    return head in error_modules


def contains_within(module, container_stmts, node):
    """Is `node` lexically inside one of `container_stmts`?"""
    chain = {node}
    chain.update(module.ancestors(node))
    return any(stmt in chain for stmt in container_stmts)


def enclosing_function(module, node):
    for anc in module.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None
