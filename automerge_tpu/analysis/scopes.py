"""Which contract applies where. Paths are repo-relative posix.

The scope tables are deliberately explicit rather than clever: a rule
that silently widens its own scope is how a linter starts crying wolf,
and one that silently narrows is how it stops catching anything. Every
entry names the PR-learned reason it is (or is not) in scope.
"""

import re

ANALYSIS_PREFIX = 'automerge_tpu/analysis/'


def in_package(path):
    return path.startswith('automerge_tpu/') and \
        not path.startswith(ANALYSIS_PREFIX)


def lintable(path):
    """Everything the tree-wide checks (except-pass, message-matching,
    counter discipline) cover: the package, the tools, the bench."""
    return in_package(path) or path.startswith('tools/') or \
        path in ('bench.py',)


# --- typed-errors -----------------------------------------------------------
# The funnel modules hold the reference decoder's internal raise style
# (hundreds of intentional bare ValueErrors, converted at the guarded
# entry points); their boundary discipline is enforced DYNAMICALLY by
# tools/fuzz_wire.py, so the static rule exempts them and watches every
# other module's decode-named surface.
FUNNEL_MODULES = frozenset({
    'automerge_tpu/columnar.py',
    'automerge_tpu/encoding.py',
})

# Public functions with these name shapes are decode surfaces: hostile
# bytes (wire, disk, cursor) reach them, so only automerge_tpu.errors
# classes may escape. encode_/generate_/receive_/ingest_ names are NOT
# here on purpose: encode direction never sees hostile bytes, and the
# receive/ingest surfaces raise API-misuse errors (array-shape guards,
# fallback-routing signals) that are caller bugs, not wire corruption.
DECODE_NAME_RE = re.compile(
    r'^(decode_|parse_|read_|split_|inflate)')


def typed_raise_scope(path):
    return in_package(path) and path not in FUNNEL_MODULES


# --- kernel-ledger ----------------------------------------------------------
def kernel_scope(path):
    return in_package(path)


# Host-path modules where a `jnp.` dispatch inside a per-document loop
# breaks the O(1)-dispatch contract (round 6/16: one fused dispatch per
# batch, never one per doc). The iterable-name heuristic below keeps the
# legitimate bounded loops out: loader.py/backend.py iterate per
# SEQUENCE-CLASS pool (`self.seq_pools.pools.items()`) and per fixed
# array tuple during capacity grows — bounded by class/arity, not fleet
# size — and none of those iterables match the doc-shaped names.
PER_DOC_ITER_RE = re.compile(
    r'\b(docs|doc_ids|doc_indices|doc_handles|handles|peers|links|'
    r'subscribers|sessions|tenants|n_docs|num_docs)\b')


def host_loop_scope(path):
    return in_package(path) and (
        path.startswith(('automerge_tpu/fleet/', 'automerge_tpu/service/',
                         'automerge_tpu/shard/', 'automerge_tpu/query/',
                         'automerge_tpu/backend/')))


# --- determinism ------------------------------------------------------------
# The deterministic replica paths: two replicas applying the same
# changes must produce byte-identical state, so wall-clock and unseeded
# randomness are banned (round-6 injected-clock rule). observability/
# and frontend/ are deliberately OUT: the perf ledger timestamps real
# time, the recorder rate-limits on real time, and the frontend's
# change-timestamp default is the reference API's documented behavior.
DETERMINISTIC_RE = re.compile(
    r'^automerge_tpu/(fleet|backend|service|shard|query)/')


def deterministic_scope(path):
    return bool(DETERMINISTIC_RE.match(path))


ENCODE_NAME_RE = re.compile(r'(^|_)encode')


def encode_scope(path):
    return in_package(path)


# --- counter-discipline -----------------------------------------------------
STATS_NAME_RE = re.compile(r'(_stats|_counters|_health)$')
RESERVED_SOURCE_RE = re.compile(r'total|fleet\d+')


def counter_scope(path):
    return lintable(path)


# --- lock-discipline --------------------------------------------------------
# Modules whose module-level state is reachable from more than one
# thread: the native pool's completion callbacks, the Prometheus
# exporter's scrape thread, the service's pump threads, the recorder's
# ring consumers, the kernel-ledger wrapper. Mutating a module-level
# container here outside a `with <lock>` block (and outside Counters,
# which locks internally) is a static race candidate.
THREADED_MODULES = frozenset({
    'automerge_tpu/native/__init__.py',
    'automerge_tpu/observability/metrics.py',
    'automerge_tpu/observability/export.py',
    'automerge_tpu/observability/recorder.py',
    'automerge_tpu/observability/spans.py',
    'automerge_tpu/observability/perf.py',
    'automerge_tpu/service/core.py',
    'automerge_tpu/fleet/exchange.py',
    # the control plane: its gauges are read by the exporter's scrape
    # thread while the pump thread commits decisions (the controller
    # lock brackets both sides; module stats are Counters)
    'automerge_tpu/control/signals.py',
    'automerge_tpu/control/policies.py',
    'automerge_tpu/control/controller.py',
})


def threaded_scope(path):
    return path in THREADED_MODULES
