"""Static contract linter: the invariants past PRs learned at runtime,
enforced at parse time.

Five rounds of chaos/fuzz work kept rediscovering the same contract
classes the hard way — untyped escapes out of decoders (fuzz rounds 2/3),
an error-message string match where a typed class belonged (round 11),
torn raw-dict counters across 15 files (round 13), un-instrumented jit
entry points, wall-clock reads on deterministic paths. The contracts are
written down in BASELINE.md; this package *checks* them: an AST rule
framework (`core`), the per-surface scope tables (`scopes`), and one
module per rule under `rules/`. `tools/archlint.py` is the CLI;
tests/test_archlint.py pins every rule with positive/negative fixtures
and runs the linter over the real tree as a tier-1 gate.

Suppression contract: a violation may be silenced ONLY by an inline
justification comment (`# archlint: ok[rule-id] why this is safe`) whose
fingerprint is recorded in the checked-in baseline
(tools/archlint_baseline.json). `--check` fails on any NEW violation,
any suppression missing from the baseline (so suppressions always show
up in review), and any stale baseline entry (so the baseline can only
shrink silently, never grow).
"""

from .core import (
    Finding, Module, Rule, BaselineError, check_findings, lint_paths,
    lint_source, load_baseline, write_baseline, iter_py_files,
)
from .rules import ALL_RULES, get_rules

__all__ = [
    'Finding', 'Module', 'Rule', 'BaselineError', 'ALL_RULES',
    'get_rules', 'check_findings', 'lint_paths', 'lint_source',
    'load_baseline', 'write_baseline', 'iter_py_files',
]
