"""Rule framework: parsed modules, findings, suppressions, baseline.

Everything here is stdlib-only and jax-free on purpose: the CLI
(tools/archlint.py) must start in ~100ms so it can sit in editor hooks
and tier-1 without paying an accelerator import.

A `Module` is one parsed file handed to every rule: source, AST with
parent links (`parent_of`), the repo-relative posix path the scope
tables key on, and the file's inline suppressions. A `Finding` is one
(rule, path, line, message) with a line-number-independent fingerprint
(rule + path + stripped source text), so baseline entries survive
unrelated edits above them but die when the flagged line itself changes.
"""

import ast
import hashlib
import json
import os
import re

# `# archlint: ok[rule-id] justification` on the flagged line or the
# line directly above. The justification is REQUIRED: a bare ok-marker
# does not suppress, it converts the finding into "suppression without
# justification" — an empty excuse is not an excuse.
SUPPRESS_RE = re.compile(
    r'#\s*archlint:\s*ok\[([A-Za-z0-9_*-]+)\]\s*(.*)')

BASELINE_VERSION = 1


class BaselineError(RuntimeError):
    """The baseline file is unreadable or structurally wrong."""


class Finding:
    __slots__ = ('rule', 'path', 'line', 'message', 'snippet',
                 'suppressed', 'justification')

    def __init__(self, rule, path, line, message, snippet=''):
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message
        self.snippet = snippet
        self.suppressed = False
        self.justification = None

    @property
    def fingerprint(self):
        key = f'{self.rule}|{self.path}|{self.snippet.strip()}'
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def as_dict(self):
        return {'rule': self.rule, 'path': self.path, 'line': self.line,
                'message': self.message, 'snippet': self.snippet.strip(),
                'suppressed': self.suppressed,
                'justification': self.justification,
                'fingerprint': self.fingerprint}

    def __repr__(self):
        mark = ' [suppressed]' if self.suppressed else ''
        return f'{self.path}:{self.line}: [{self.rule}]{mark} {self.message}'


class Module:
    """One parsed source file, shared by every rule."""

    def __init__(self, path, source):
        self.path = path.replace(os.sep, '/')
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self._parents = {}
        # one walk builds both the parent map and the flat node list the
        # rules iterate — per-rule ast.walk() re-traversals dominated the
        # CLI profile before this (it must stay fast enough for tier-1)
        self.nodes = [self.tree]
        for node in self.nodes:
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
                self.nodes.append(child)
        # line -> (rule-pattern, justification)
        self.suppressions = {}
        for i, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                self.suppressions[i] = (m.group(1), m.group(2).strip())

    def parent_of(self, node):
        return self._parents.get(node)

    def ancestors(self, node):
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def text(self, node):
        # like ast.get_source_segment, but sliced out of the pre-split
        # line list — get_source_segment re-splits the whole file per
        # call, which made it the top profile entry over the real tree
        line = getattr(node, 'lineno', 0)
        end = getattr(node, 'end_lineno', None)
        if not 1 <= line <= len(self.lines):
            return ''
        col = getattr(node, 'col_offset', 0) or 0
        end_col = getattr(node, 'end_col_offset', None)
        if end is None or end_col is None or not line <= end <= len(self.lines):
            return self.lines[line - 1]
        if end == line:
            return self.lines[line - 1][col:end_col]
        parts = [self.lines[line - 1][col:]]
        parts.extend(self.lines[line:end - 1])
        parts.append(self.lines[end - 1][:end_col])
        return '\n'.join(parts)

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ''

    def finding(self, rule_id, node, message):
        line = getattr(node, 'lineno', 0)
        return Finding(rule_id, self.path, line, message,
                       snippet=self.line_text(line))

    def suppression_for(self, lineno, rule_id):
        """The (pattern, justification) covering `lineno` for `rule_id`:
        same line first, then the dedicated comment line directly above."""
        for cand in (lineno, lineno - 1):
            entry = self.suppressions.get(cand)
            if entry is None:
                continue
            if cand != lineno:
                # the line above only counts if it is a pure comment line
                # (otherwise it is some other statement's suppression)
                if not self.line_text(cand).lstrip().startswith('#'):
                    continue
            pattern, justification = entry
            if pattern == '*' or pattern == rule_id:
                return pattern, justification
        return None


class Rule:
    """Base class. Subclasses set `rule_id`/`doc` and yield Findings
    from `check(module)`; scoping (which paths the rule looks at) is the
    rule's own job via the tables in `scopes`."""

    rule_id = None
    doc = ''

    def check(self, module):
        raise NotImplementedError
        yield  # pragma: no cover


def apply_suppressions(module, findings):
    for f in findings:
        hit = module.suppression_for(f.line, f.rule)
        if hit is None:
            continue
        _pattern, justification = hit
        if not justification:
            f.message += (' (archlint ok-marker present but has no '
                          'justification text — an empty excuse does '
                          'not suppress)')
            continue
        f.suppressed = True
        f.justification = justification
    return findings


def lint_module(module, rules):
    findings = []
    for rule in rules:
        findings.extend(rule.check(module))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return apply_suppressions(module, findings)


def lint_source(source, path, rules):
    """Lint one in-memory source blob as if it lived at `path` (the
    path picks the rule scopes) — the fixture-test entry point."""
    return lint_module(Module(path, source), rules)


def iter_py_files(paths, root=None):
    """Expand files/dirs into sorted repo-relative .py paths."""
    root = os.path.abspath(root or os.getcwd())
    out = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            out.append(full)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ('__pycache__', '.git'))
            for name in sorted(filenames):
                if name.endswith('.py'):
                    out.append(os.path.join(dirpath, name))
    rel = [os.path.relpath(f, root).replace(os.sep, '/') for f in out]
    return sorted(set(rel)), root


def lint_paths(paths, rules, root=None):
    """Lint every .py under `paths`. Returns (findings, files, errors)
    where errors are (path, message) for unparseable files — a syntax
    error in the tree is a loud failure, not a silent skip."""
    files, root = iter_py_files(paths, root)
    findings, errors = [], []
    for rel in files:
        full = os.path.join(root, rel)
        try:
            with open(full, 'r', encoding='utf-8') as fh:
                source = fh.read()
            module = Module(rel, source)
        except (OSError, SyntaxError, ValueError) as exc:
            errors.append((rel, f'{type(exc).__name__}: {exc}'))
            continue
        findings.extend(lint_module(module, rules))
    return findings, files, errors


# --------------------------------------------------------------------------
# Baseline: the checked-in record of every inline suppression. --check
# fails when a suppression is missing from it (new suppressions must
# show up in review as a baseline diff) and when an entry no longer
# matches anything (stale entries must be deleted, keeping the file
# honest about how many exemptions actually exist).
# --------------------------------------------------------------------------

def load_baseline(path):
    if not os.path.exists(path):
        return {}
    try:
        with open(path, 'r', encoding='utf-8') as fh:
            data = json.load(fh)
    except (OSError, ValueError) as exc:
        raise BaselineError(f'unreadable baseline {path}: {exc}')
    if not isinstance(data, dict) or data.get('version') != BASELINE_VERSION:
        raise BaselineError(f'baseline {path}: unsupported format')
    entries = {}
    for e in data.get('entries', []):
        entries[e['fingerprint']] = e
    return entries


def write_baseline(path, findings):
    entries = [
        {'fingerprint': f.fingerprint, 'rule': f.rule, 'path': f.path,
         'snippet': f.snippet.strip(), 'justification': f.justification}
        for f in findings if f.suppressed]
    entries.sort(key=lambda e: (e['path'], e['rule'], e['fingerprint']))
    data = {'version': BASELINE_VERSION, 'entries': entries}
    with open(path, 'w', encoding='utf-8') as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write('\n')
    return entries


def check_findings(findings, baseline):
    """Split findings against the baseline. Returns a dict:
    violations (unsuppressed), unlisted (suppressed inline but missing
    from the baseline file), stale (baseline entries matching nothing).
    Clean == all three empty."""
    violations = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    seen = {f.fingerprint for f in suppressed}
    unlisted = [f for f in suppressed if f.fingerprint not in baseline]
    stale = [e for fp, e in sorted(baseline.items()) if fp not in seen]
    return {'violations': violations, 'suppressed': suppressed,
            'unlisted': unlisted, 'stale': stale}
