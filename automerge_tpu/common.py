"""Shared helpers (ref src/common.js, src/uuid.js)."""

import uuid as _uuid


def parse_op_id(op_id):
    """Parse 'counter@actorId' into (counter, actor_id) (ref src/common.js:32-38)."""
    counter, sep, actor_id = op_id.partition('@')
    if not sep or not counter.isdigit():
        # archlint: ok[typed-errors] internal funnel helper like columnar/encoding: every wire path reaching it sits under a converting as_wire_error boundary (fuzz-enforced by tools/fuzz_wire.py)
        raise ValueError(f'Not a valid opId: {op_id}')
    return int(counter), actor_id


def compare_op_ids(a, b):
    """Lamport order on 'counter@actor' strings: by counter, then actorId."""
    ac, aa = parse_op_id(a)
    bc, ba = parse_op_id(b)
    if ac != bc:
        return -1 if ac < bc else 1
    if aa != ba:
        return -1 if aa < ba else 1
    return 0


def lamport_key(op_id):
    """Sort key giving ascending Lamport order for 'counter@actor' opIds."""
    counter, actor = parse_op_id(op_id)
    return (counter, actor)


_uuid_factory = None


def set_uuid_factory(factory):
    """Override uuid generation, e.g. for deterministic tests (ref src/uuid.js:13)."""
    global _uuid_factory
    _uuid_factory = factory


def uuid():
    if _uuid_factory is not None:
        return _uuid_factory()
    return _uuid.uuid4().hex
