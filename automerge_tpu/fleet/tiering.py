"""Cost-based tiering: when to demote, when to compact, what pressure defers.

The storage engine's maintenance decisions used to be fixed byte
thresholds: ``vacuum_dead_fraction=0.5``, the journal's
``compact_bytes`` floor, manual ``park`` calls, and brownout stage 2 as
a hard compaction override. This module replaces them with one explicit
cost model in the spirit of SynchroStore (PAPERS.md): every background
action is a trade of WRITE AMPLIFICATION (bytes rewritten now, stealing
request-path bandwidth) against READ LATENCY (arena garbage polluting
the page cache, longer recovery scans) and RECOVERY-REPLAY DEBT (journal
bytes/records a crash would replay). Admission pressure — the brownout
stage — enters the model as a multiplier on write cost, so "defer
compaction under pressure" (brownout stage 2) emerges from the ledger
instead of being a switch: background work still fires under pressure
when the debt side grows large enough to justify it, and every
defer/fire verdict flip is flight-recorded for the forensic dump.

Three pieces:

- ``CostModel`` — the ledger. ``vacuum_due(main_store, stage)`` weighs
  arena garbage against a live-byte rewrite; ``compact_due(durable,
  stage)`` weighs replay debt against the incremental snapshot cost.
- ``ClockDemote`` — a second-chance clock over live fleet docs feeding
  ``StorageEngine.park`` automatically: docs touched since the hand
  last passed survive; cold docs demote in batches whenever the
  resident-bytes source (fed by the round-17 memory watermarks) sits
  above budget. Zero manual ``park`` calls.
- ``TieringController`` — one ``tick(stage)`` gluing the three planes
  together for the service loop (service/core.py calls it per pump when
  attached): demote under watermark pressure, vacuum when the model says
  the garbage pays for the rewrite, compact when replay debt beats
  snapshot cost.
"""

from ..observability import recorder as _flight
from ..observability.metrics import Counters, register_health_source

__all__ = ['CostModel', 'ClockDemote', 'TieringController']

_stats = Counters({
    'tiering_demoted_docs': 0,      # docs auto-parked by the clock
    'tiering_vacuums': 0,           # cost-model vacuums fired
    'tiering_compactions': 0,       # cost-model journal compactions
    'tiering_deferred': 0,          # verdicts flipped to defer by pressure
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])


def tiering_stats():
    return dict(_stats)


class CostModel:
    """The write-amp vs read-latency vs replay-debt ledger.

    Costs are in abstract byte-units: a byte REWRITTEN costs
    ``write_byte_cost`` (times the brownout pressure multiplier — under
    admission pressure, background writes compete with the request
    path); a byte of arena GARBAGE costs ``garbage_byte_cost`` per
    decision window (page-cache pollution + recovery-scan debt); a byte
    of journal replay debt costs ``replay_byte_cost`` and a record
    ``replay_record_cost`` (replay is decode+apply, far pricier than a
    sequential rewrite). An action fires when its debt side outweighs
    its rewrite side; pressure raises the bar rather than closing the
    gate."""

    def __init__(self, write_byte_cost=1.0, garbage_byte_cost=2.0,
                 replay_byte_cost=3.0, replay_record_cost=256.0,
                 stage_write_penalty=7.0, min_garbage_bytes=256 << 10,
                 min_replay_bytes=64 << 10, segment_stitch_cost=64 << 10):
        self.write_byte_cost = float(write_byte_cost)
        self.garbage_byte_cost = float(garbage_byte_cost)
        self.replay_byte_cost = float(replay_byte_cost)
        self.replay_record_cost = float(replay_record_cost)
        self.stage_write_penalty = float(stage_write_penalty)
        self.min_garbage_bytes = int(min_garbage_bytes)
        self.min_replay_bytes = int(min_replay_bytes)
        # per-segment recovery overhead (file open + frame validation)
        # in byte-units: many tiny segments can justify an escalation
        # even when their summed bytes look cheap
        self.segment_stitch_cost = float(segment_stitch_cost)
        self._verdicts = {}          # (kind, target id) -> last verdict

    def _pressure_mult(self, stage):
        """Brownout stage -> write-cost multiplier. Stage 2+ is the old
        'defer compaction' stage: instead of a hard override it makes
        background rewrites ~(1+penalty)x as expensive, so they still
        fire when debt overwhelms."""
        return 1.0 + (self.stage_write_penalty if stage >= 2 else 0.0)

    def _note(self, kind, target, fire, deferred_by_stage, stage):
        """Flight-record verdict FLIPS (not every tick) so an incident
        dump shows when pressure started deferring maintenance."""
        key = (kind, id(target))
        verdict = 'fire' if fire else ('defer' if deferred_by_stage
                                       else 'idle')
        if self._verdicts.get(key) != verdict:
            self._verdicts[key] = verdict
            if verdict != 'idle':
                _flight.record_event('tiering', action=kind,
                                     verdict=verdict, stage=stage)
            if verdict == 'defer':
                _stats.inc('tiering_deferred')

    def vacuum_due(self, main, stage=0):
        """Should this MainStore compact now? Benefit: reclaiming arena
        garbage (dead chunks, tombstones, stale epochs' scan debt) AND
        the RAM-resident lane bytes dead rows pin (``dead_lane_bytes``
        — RSS, weighted double: it is the very ceiling the tier
        budgets). Cost: rewriting the live bytes, scaled by pressure.
        Backstop: a store ≥90% dead rows fires regardless of byte
        ratios — row-id space and resident lanes must not leak just
        because the dead chunks were small."""
        garbage = main.garbage_bytes + 2 * main.dead_lane_bytes
        if main.dead_fraction >= 0.9 and main.n_rows >= 4096:
            self._note('vacuum', main, True, False, stage)
            return True
        if garbage < self.min_garbage_bytes and main.dead_fraction < 0.5:
            self._note('vacuum', main, False, False, stage)
            return False
        benefit = garbage * self.garbage_byte_cost
        base_cost = max(main.chunk_bytes, 1) * self.write_byte_cost
        fire = benefit > base_cost * self._pressure_mult(stage)
        deferred = (not fire) and benefit > base_cost
        self._note('vacuum', main, fire, deferred, stage)
        return fire

    def compact_due(self, durable, stage=0):
        """Should this DurableFleet compact its journal now? Benefit:
        replay debt retired (bytes re-decoded + records re-applied at
        recovery). Cost: the incremental snapshot rewrite (~the
        journaled bytes re-persisted), scaled by pressure."""
        debt = durable.replay_debt()
        if debt['bytes'] < self.min_replay_bytes:
            self._note('compact', durable, False, False, stage)
            return False
        benefit = debt['bytes'] * self.replay_byte_cost + \
            debt['records'] * self.replay_record_cost
        base_cost = debt['bytes'] * self.write_byte_cost
        fire = benefit > base_cost * self._pressure_mult(stage)
        deferred = (not fire) and benefit > base_cost
        self._note('compact', durable, fire, deferred, stage)
        return fire

    def chain_escalate_due(self, durable, stage=0):
        """Should the next incremental compaction escalate to a FULL
        checkpoint? Benefit: retiring the chain's stitch debt — the
        tail segment bytes recovery re-reads on top of the base (mostly
        superseded doc copies, i.e. disk amplification) plus a
        per-segment open/validate overhead. Cost: rewriting every live
        doc (~base + tail bytes), scaled by pressure. This replaces the
        bare ``len(chain) >= max_chain`` count as the DECIDING rule —
        ``max_chain`` survives in DurableFleet.compact as the hard
        ceiling bounding stitch work absolutely; the ledger only moves
        the escalation EARLIER when the debt pays for it. Verdict flips
        are flight-recorded like vacuum/compact."""
        debt = durable.chain_debt()
        if debt['segments'] == 0:
            self._note('chain', durable, False, False, stage)
            return False
        benefit = debt['bytes'] * self.garbage_byte_cost + \
            debt['segments'] * self.segment_stitch_cost
        base_cost = (durable.base_bytes() + debt['bytes']) * \
            self.write_byte_cost
        fire = benefit > base_cost * self._pressure_mult(stage)
        deferred = (not fire) and benefit > base_cost
        self._note('chain', durable, fire, deferred, stage)
        return fire


class ClockDemote:
    """Second-chance clock over live fleet docs feeding ``park``.

    ``register`` admits handles to the ring; ``touch`` gives a doc a
    second chance (the request path calls it on every read/write/sync
    that serves the doc). ``tick`` demotes cold docs in batches while
    the resident-bytes ``source`` reads above ``budget_bytes`` — the
    watermark feed (observability/perf.py ``sample_watermarks`` tiers,
    or process RSS by default). Docs the engine refuses to park (queued
    changes, frozen) stay in the ring for the next pass.

    Two control-plane levers (control/): ``pin``/``unpin`` exempt
    specific handles from demotion (an SLO-freshness-lagging tenant's
    docs stay resident however cold they look), and ``pressure_factor``
    scales the effective budget (<1.0 demotes the unpinned population
    harder — the memory the pins hold has to come from somewhere)."""

    def __init__(self, engine, budget_bytes, source=None, batch=128):
        self.engine = engine
        self.budget_bytes = int(budget_bytes)
        self.pressure_factor = 1.0
        if source is None:
            from ..observability.perf import rss_bytes
            source = lambda: rss_bytes()[0]      # noqa: E731
        self.source = source
        self.batch = int(batch)
        self._ring = []              # [handle, ref_bit]
        self._by_handle = {}         # id(handle) -> ring index
        self._hand = 0
        self._pinned = {}            # id(handle) -> handle (strong ref)
        self.last_parked = []        # (handle, doc_id) pairs, last tick

    def __len__(self):
        return len(self._ring)

    def register(self, handles):
        for handle in handles:
            if id(handle) in self._by_handle:
                continue
            self._by_handle[id(handle)] = len(self._ring)
            self._ring.append([handle, True])

    def touch(self, handles):
        for handle in handles:
            idx = self._by_handle.get(id(handle))
            if idx is not None:
                self._ring[idx][1] = True

    def pin(self, handles):
        """Exempt these handles from demotion (idempotent). The pin
        holds a strong ref so a pinned doc's handle id cannot be
        recycled out from under the exemption; stale (frozen/parked)
        pins drop at the next prune."""
        for handle in handles:
            self._pinned[id(handle)] = handle

    def unpin(self, handles):
        for handle in handles:
            self._pinned.pop(id(handle), None)

    def pinned_count(self):
        return len(self._pinned)

    def pressure(self):
        budget = self.budget_bytes * self.pressure_factor
        if budget <= 0:
            return 0.0
        return self.source() / budget

    def _prune(self):
        """Drop parked/frozen/dead entries, reindex, and KEEP the hand
        pointing at the same logical position (so a mid-tick prune never
        rewinds it over entries it already gave their second chance)."""
        from .backend import FleetDoc
        fresh = []
        new_hand = 0
        for idx, (handle, ref) in enumerate(self._ring):
            state = handle.get('state')
            if handle.get('frozen') or not isinstance(state, FleetDoc) \
                    or not state.is_fleet:
                continue
            if idx < self._hand:
                new_hand += 1
            fresh.append([handle, ref])
        self._ring = fresh
        self._by_handle = {id(h): i for i, (h, _r) in enumerate(fresh)}
        self._hand = new_hand % len(fresh) if fresh else 0
        if self._pinned:
            # pins on handles the seam has since frozen (each apply
            # freezes the old handle dict) are stale: drop them so the
            # pin set stays bounded by the live pinned population
            self._pinned = {
                hid: h for hid, h in self._pinned.items()
                if not h.get('frozen') and
                isinstance(h.get('state'), FleetDoc) and
                h.get('state').is_fleet}

    def _sweep(self, budget):
        """Advance the hand up to `budget` steps collecting at most
        `batch` cold candidates, clearing ref bits as it moves (second
        chance). Returns (candidates, steps consumed)."""
        out = []
        n = len(self._ring)
        steps = 0
        while steps < budget and len(out) < self.batch:
            entry = self._ring[self._hand]
            self._hand = (self._hand + 1) % n
            steps += 1
            if entry[1]:
                entry[1] = False
            elif not entry[0].get('frozen') and \
                    id(entry[0]) not in self._pinned:
                out.append(entry[0])
        return out, steps

    def tick(self, stage=0):
        """Demote while over budget, at most ONE full clock revolution
        per tick — a doc touched since the hand last passed always
        survives the tick (the second chance is per-revolution, and a
        tick never laps itself). Returns the parked doc ids."""
        parked = []
        self.last_parked = []
        if not self._ring:
            return parked
        # prune EVERY tick, not just over budget: the seam freezes the
        # old handle dict on each apply, so an under-budget service
        # would otherwise grow the ring by one stale entry per write
        # round forever
        self._prune()
        if not self._ring or self.pressure() <= 1.0:
            return parked
        budget = len(self._ring)
        while self._ring and budget > 0 and self.pressure() > 1.0:
            batch, steps = self._sweep(budget)
            budget -= steps
            if not batch:
                break
            pairs = [(h, i) for h, i in zip(batch, self.engine.park(batch))
                     if i is not None]
            self._prune()
            if not pairs:
                break               # nothing parkable left this tick
            self.last_parked.extend(pairs)
            parked.extend(i for _h, i in pairs)
        if parked:
            _stats.inc('tiering_demoted_docs', len(parked))
            _flight.record_event('tiering', action='demote',
                                 docs=len(parked), stage=stage)
        return parked


class TieringController:
    """One tick for the whole tiering plane (see module docstring).

    Attach to a service (``DocService(..., tiering=...)``) and the pump
    calls ``tick(stage=brownout.stage)`` once per service tick; or drive
    it from any loop. Attaching a controller REPLACES the engine's
    ``dead_fraction`` threshold with the cost model (the model also
    covers discard-churn vacuums between ticks)."""

    def __init__(self, engine=None, demote=None, model=None, durable=None):
        self.model = model if model is not None else CostModel()
        self.engine = engine
        self.demote = demote
        self.durable = durable
        if engine is not None:
            engine.cost_model = self.model
            engine.vacuum_dead_fraction = None
        if durable is not None:
            # chain-escalation verdicts route through the same ledger
            durable.cost_model = self.model

    def tick(self, stage=0, durable=None):
        """Returns {'demoted': n, 'vacuumed': bool, 'compacted': bool}."""
        out = {'demoted': 0, 'vacuumed': False, 'compacted': False}
        if self.engine is not None:
            # discard-churn vacuums between ticks see this stage too
            self.engine.pressure_stage = stage
        if self.demote is not None:
            out['demoted'] = len(self.demote.tick(stage=stage))
        eng = self.engine
        if eng is not None and eng.main.n_rows >= eng.VACUUM_MIN_ROWS and \
                self.model.vacuum_due(eng.main, stage=stage):
            eng.vacuum_now()
            _stats.inc('tiering_vacuums')
            out['vacuumed'] = True
        dur = durable if durable is not None else self.durable
        if dur is not None:
            # compact() consults the model for chain escalation and the
            # stage for its pressure multiplier
            dur.cost_model = self.model
            dur.pressure_stage = stage
            if self.model.compact_due(dur, stage=stage) and \
                    dur.maybe_compact(force=True):
                _stats.inc('tiering_compactions')
                out['compacted'] = True
        return out
