"""Wire ingest: binary changes decoded straight into fleet op tensors.

The pipeline stage between the network/disk and the device (the north star's
"decode straight into padded device tensors"): change chunks are parsed with
the native C++ codecs (automerge_tpu.native) — container split + checksum,
DEFLATE, LEB128/RLE/delta column decode — and land as OpBatch columns with
host-side dictionary encoding of keys and actors. String columns (keyStr)
currently decode via the Python RLE codec; numeric columns are native.

Supports the fleet-kernel op subset (root-map set/inc/del); anything else
routes to the host OpSet engine.
"""

import numpy as np

from .. import native
from ..encoding import (
    Decoder, RLEDecoder, DeltaDecoder, BooleanDecoder,
)
from ..columnar import (
    decode_container_header, decode_column_info, decode_value, inflate_change,
    COLUMN_TYPE, CHUNK_TYPE_CHANGE, CHUNK_TYPE_DEFLATE, ACTIONS,
)
from .tensor_doc import OpBatch, TOMBSTONE, pack_op_id

_SET = ACTIONS.index('set')
_INC = ACTIONS.index('inc')
_DEL = ACTIONS.index('del')

_COL_KEYSTR = 1 << 4 | COLUMN_TYPE['STRING_RLE']
_COL_ACTION = 4 << 4 | COLUMN_TYPE['INT_RLE']
_COL_VALLEN = 5 << 4 | COLUMN_TYPE['VALUE_LEN']
_COL_VALRAW = 5 << 4 | COLUMN_TYPE['VALUE_RAW']
_COL_OBJCTR = 0 << 4 | COLUMN_TYPE['INT_RLE']


from ..observability.spans import spanned as _spanned


def _inflate_chunk(buffer):
    if buffer[8] != CHUNK_TYPE_DEFLATE:
        return buffer
    return inflate_change(buffer)


def _decode_numeric_column(ctype, buf):
    """Decode a numeric column: native when available, Python codecs otherwise."""
    if native.available():
        if ctype == COLUMN_TYPE['INT_DELTA']:
            return native.decode_delta_column(buf)
        if ctype == COLUMN_TYPE['BOOLEAN']:
            return native.decode_boolean_column(buf)
        return native.decode_rle_column(buf, signed=False)
    if ctype == COLUMN_TYPE['INT_DELTA']:
        decoder = DeltaDecoder(buf)
    elif ctype == COLUMN_TYPE['BOOLEAN']:
        decoder = BooleanDecoder(buf)
    else:
        decoder = RLEDecoder('uint', buf)
    values, valid = [], []
    while not decoder.done:
        v = decoder.read_value()
        values.append(0 if v is None else int(v))
        valid.append(v is not None)
    return np.array(values, dtype=np.int64), np.array(valid, dtype=bool)


def decode_change_ops_columns(buffer):
    """Parse one binary change into (header_meta, numeric column arrays).

    Returns (actor, start_op, columns) where columns maps columnId to
    (values int64[], valid bool[]) for numeric columns and to a Python list
    for the keyStr column."""
    buffer = _inflate_chunk(bytes(buffer))
    header = decode_container_header(Decoder(buffer), False)
    chunk = Decoder(header['chunkData'])
    # change header (ref columnar.js:635-652)
    num_deps = chunk.read_uint53()
    chunk.skip(32 * num_deps)
    actor = chunk.read_hex_string()
    chunk.read_uint53()  # seq
    start_op = chunk.read_uint53()
    chunk.read_int53()   # time
    chunk.read_prefixed_string()  # message
    for _ in range(chunk.read_uint53()):
        chunk.read_hex_string()
    infos = decode_column_info(chunk)
    columns = {}
    for info in infos:
        buf = chunk.read_raw_bytes(info['bufferLen'])
        cid = info['columnId']
        ctype = cid & 7
        if cid == _COL_VALRAW:
            columns[cid] = buf
        elif cid == _COL_KEYSTR:
            decoder = RLEDecoder('utf8', buf)
            values = []
            while not decoder.done:
                values.append(decoder.read_value())
            columns[cid] = values
        elif ctype in (COLUMN_TYPE['INT_DELTA'], COLUMN_TYPE['BOOLEAN'],
                       COLUMN_TYPE['INT_RLE'], COLUMN_TYPE['ACTOR_ID'],
                       COLUMN_TYPE['VALUE_LEN'], COLUMN_TYPE['GROUP_CARD']):
            columns[cid] = _decode_numeric_column(ctype, buf)
        else:
            columns[cid] = buf
    return actor, start_op, columns


class KeyInterner:
    """Host-side dictionary encoding of map keys for the fleet key grid."""

    def __init__(self):
        self.index = {}
        self.keys = []

    def intern(self, key):
        idx = self.index.get(key)
        if idx is None:
            idx = len(self.keys)
            self.index[key] = idx
            self.keys.append(key)
        return idx

    def __len__(self):
        return len(self.keys)


def layout_doc_rows(doc, n_docs, cols, dtypes):
    """Scatter flat doc-major rows into padded [N, P] arrays (per-doc
    positions in arrival order). Returns the laid-out arrays plus the
    (doc_sorted, pos) coordinates so callers can add more columns."""
    order = np.argsort(doc, kind='stable')
    doc_sorted = doc[order]
    pos = np.arange(len(doc_sorted)) - \
        np.searchsorted(doc_sorted, doc_sorted, side='left')
    counts = np.bincount(doc, minlength=n_docs)
    max_ops = max(int(counts.max()) if counts.size else 0, 1)
    shape = (n_docs, max_ops)
    out = []
    for col, dt in zip(cols, dtypes):
        arr = np.zeros(shape, dtype=dt)
        arr[doc_sorted, pos] = col[order]
        out.append(arr)
    return out, (order, doc_sorted, pos)


def build_kill_lanes(del_doc, del_key, del_pred_counts, praw, actor_map,
                     on_bad_actor=None):
    """Shared delete kill-lane construction (used by the native flush and
    the turbo path): expand per-del (doc, key) rows over their pred runs
    into flat (kill_doc, kill_key, kill_packed) lanes with pred actor
    bits remapped to fleet numbering. `praw` is the concatenated native
    pred entries of the del rows, aligned with del_pred_counts. Preds
    naming an actor outside actor_map (< 0 after remap) pack as 0
    (inert) and report via `on_bad_actor(doc_ids)`."""
    kill_doc = np.repeat(del_doc, del_pred_counts)
    kill_key = np.repeat(del_key, del_pred_counts)
    if not len(praw):
        return kill_doc, kill_key, np.zeros(0, dtype=np.int32)
    pactor = actor_map[praw & 0xff]
    bad = (praw != 0) & (pactor < 0)
    if bad.any() and on_bad_actor is not None:
        on_bad_actor(np.unique(kill_doc[bad]))
    kill_packed = np.where(
        (praw != 0) & (pactor >= 0),
        (praw >> 8 << 8) | pactor, 0).astype(np.int32)
    return kill_doc, kill_key, kill_packed


@_spanned('exact_ingest')
def changes_to_op_batch_native(per_doc_changes, key_interner, actor_interner,
                               hazard_out=None, kills_out=None,
                               index_out=None):
    """Fast path: the whole parse + dictionary-encode runs in C++
    (native.ingest_changes), and the flat op rows scatter into OpBatch
    tensors with vectorized numpy. Returns None if any change falls outside
    the fleet subset (caller falls back to the host engine).

    When `hazard_out` is a list, the parse runs with_meta so pred columns
    are available, and one tuple (set_doc, set_key, set_packed, inc_doc,
    inc_key, inc_pred, kill_doc, kill_key, kill_packed) in fleet numbering
    is appended — the feed for DocFleet._note_grid_batch's mirror advance
    and counter-attribution check (inc_pred is the Lamport-max pred, the
    reference's attribution target; -1 when absent or unresolvable).

    When `index_out` is a list, one (doc, key, packed) triple of flat
    arrays covering every map-key op ROW (sets and incs — never dels) is
    appended, in fleet numbering — the feed for the turbo path's
    dangling-pred oracle (DocFleet._index_ops).

    When `kills_out` is a list, delete ops take the reference's
    pred-scoped semantics (new.js:1204-1217): del rows are EXCLUDED from
    the set lanes and their preds land as kill lanes — one
    (kill_key [N, Q], kill_packed [N, Q]) pair appended to kills_out, for
    apply.apply_op_batch_kills. Without kills_out, dels keep the legacy
    tombstone-scatter behavior (the standalone benchmark subset)."""
    buffers, doc_ids = [], []
    for d, changes in enumerate(per_doc_changes):
        for change in changes:
            buffers.append(change)
            doc_ids.append(d)
    want_meta = hazard_out is not None or kills_out is not None
    if not buffers:
        return OpBatch(*(np.zeros((len(per_doc_changes), 1), dtype=dt)
                         for dt in (np.int32, np.int32, np.int32, bool, bool,
                                    bool)))
    out = native.ingest_changes(buffers, doc_ids, with_meta=want_meta)
    if out is None:
        return None
    if want_meta:
        rows, keys, actors, _meta = out
    else:
        rows, keys, actors = out
    # Merge the C++ interning into the fleet-level interners
    key_map = np.array([key_interner.intern(k) for k in keys], dtype=np.int32)
    actor_map = np.array([actor_interner.intern(a) for a in actors],
                         dtype=np.int32)
    n_docs = len(per_doc_changes)
    doc = rows['doc']
    key = key_map[rows['key']] if len(keys) else rows['key']
    ctr = rows['packed'] >> 8
    actor = actor_map[rows['packed'] & 0xff] if len(actors) else 0
    packed = (ctr << 8) | actor
    flags_flat = rows['flags']
    # Dels are identifiable whenever either consumer needs them, but the
    # set-lane exclusion is gated on kills_out ALONE: without kill lanes
    # the legacy tombstone-scatter representation must stay intact, or
    # deletes would silently become no-ops (index_out never changes
    # device semantics — it only filters what gets indexed).
    del_sel = np.zeros(len(doc), dtype=bool)
    kill_doc = kill_key = kill_packed = np.zeros(0, dtype=np.int64)
    if kills_out is not None or index_out is not None:
        del_sel = (flags_flat == 1) & (rows['value'] == TOMBSTONE)
    if kills_out is not None and del_sel.any():
        pred_counts_all = np.diff(rows['pred_off'])
        kill_doc, kill_key, kill_packed = build_kill_lanes(
            doc[del_sel], key[del_sel], pred_counts_all[del_sel],
            rows['pred'][np.repeat(del_sel, pred_counts_all)], actor_map)
        (kk_arr, kp_arr), _ = layout_doc_rows(
            kill_doc, n_docs, (kill_key, kill_packed),
            (np.int32, np.int32))
        kills_out.append((kk_arr, kp_arr))
    del_for_sets = del_sel if kills_out is not None else \
        np.zeros(len(doc), dtype=bool)
    if index_out is not None:
        row_sel = ((flags_flat == 1) & ~del_sel) | (flags_flat == 2)
        index_out.append((doc[row_sel], key[row_sel], packed[row_sel]))
    if hazard_out is not None:
        from .backend import _max_pred_per_inc
        set_sel = (flags_flat == 1) & ~del_sel
        inc_sel = flags_flat == 2
        pred_counts = np.diff(rows['pred_off'])
        amap_full = np.full(256, -1, dtype=np.int64)
        amap_full[:len(actor_map)] = actor_map
        preds = _max_pred_per_inc(rows['pred'],
                                  rows['pred_off'][:-1][inc_sel],
                                  pred_counts[inc_sel], amap_full)
        hazard_out.append((doc[set_sel], key[set_sel], packed[set_sel],
                           doc[inc_sel], key[inc_sel], preds,
                           kill_doc, kill_key, kill_packed))
    # Lay out rows into [N, P] with per-doc positions
    (key_id, packed_arr, value), (order, doc_sorted, pos) = layout_doc_rows(
        doc, n_docs, (key, packed, rows['value']),
        (np.int32, np.int32, np.int32))
    is_set = np.zeros(key_id.shape, dtype=bool)
    is_inc = np.zeros(key_id.shape, dtype=bool)
    valid = np.zeros(key_id.shape, dtype=bool)
    flags = flags_flat[order]
    is_set[doc_sorted, pos] = (flags == 1) & ~del_for_sets[order]
    is_inc[doc_sorted, pos] = flags == 2
    valid[doc_sorted, pos] = True
    return OpBatch(key_id, packed_arr, value, is_set, is_inc, valid)


def changes_to_op_batch(per_doc_changes, key_interner, actor_interner,
                        value_table=None):
    """Convert per-document lists of binary changes into one OpBatch.

    Tries the native C++ batched parser first; falls back to the per-change
    Python decode. Only root-map set/inc/del ops are supported (the fleet
    kernel's op subset); raises ValueError otherwise. Ints in [0, 2^31) are
    stored inline in the value column; any other value is appended to
    `value_table` (when given) and referenced as -(index + 2) — distinct
    from TOMBSTONE (-1) and from inline ints."""
    if native.available():
        batch = changes_to_op_batch_native(per_doc_changes, key_interner,
                                           actor_interner)
        if batch is not None:
            return batch
    n_docs = len(per_doc_changes)
    rows = []  # (doc, key_id, packed, value, is_set, is_inc)
    for d, changes in enumerate(per_doc_changes):
        for change in changes:
            actor, start_op, columns = decode_change_ops_columns(change)
            actor_num = actor_interner.intern(actor)
            actions, actions_ok = columns.get(_COL_ACTION, (np.zeros(0), None))
            key_strs = columns.get(_COL_KEYSTR, [])
            obj_ctr = columns.get(_COL_OBJCTR)
            val_len, _vl_ok = columns.get(_COL_VALLEN, (None, None))
            val_raw = columns.get(_COL_VALRAW, b'')
            raw_pos = 0
            for i, action in enumerate(np.asarray(actions)):
                if obj_ctr is not None and i < len(obj_ctr[1]) and obj_ctr[1][i]:
                    raise ValueError('fleet ingest supports root-map ops only')
                key = key_strs[i] if i < len(key_strs) else None
                if key is None:
                    raise ValueError('fleet ingest supports map (string-key) ops only')
                tag = int(val_len[i]) if val_len is not None and i < len(val_len) \
                    else 0
                size = tag >> 4
                raw = val_raw[raw_pos:raw_pos + size]
                raw_pos += size
                if action == _SET or action == _INC:
                    decoded = decode_value(tag, raw)
                    value = decoded['value']
                elif action == _DEL:
                    value = None
                else:
                    raise ValueError(f'unsupported action {action} for fleet ingest')
                if action == _DEL:
                    val_idx = TOMBSTONE
                elif action == _INC:
                    # The device scatter-add consumes the value column of inc
                    # ops as a raw delta (never a table index), so any int32
                    # delta — negative included — must be stored inline
                    if not isinstance(value, int) or isinstance(value, bool) \
                            or not -(1 << 31) < value < (1 << 31):
                        raise ValueError('inc delta must be an int32 '
                                         'for fleet ingest')
                    val_idx = value
                elif isinstance(value, int) and not isinstance(value, bool) and \
                        0 <= value < (1 << 31):
                    val_idx = value
                elif value_table is not None:
                    val_idx = -(value_table.intern(value) + 2)
                else:
                    raise ValueError('non-int value requires a value_table')
                rows.append((d, key_interner.intern(key),
                             pack_op_id(start_op + i, actor_num), val_idx,
                             action != _INC, action == _INC))
    doc_counts = np.bincount([r[0] for r in rows], minlength=n_docs) \
        if rows else np.zeros(n_docs, dtype=np.int64)
    max_ops = int(doc_counts.max()) if rows else 0
    per_doc_counts = np.zeros(n_docs, dtype=np.int64)
    shape = (n_docs, max(max_ops, 1))
    key_id = np.zeros(shape, dtype=np.int32)
    packed = np.zeros(shape, dtype=np.int32)
    value = np.zeros(shape, dtype=np.int32)
    is_set = np.zeros(shape, dtype=bool)
    is_inc = np.zeros(shape, dtype=bool)
    valid = np.zeros(shape, dtype=bool)
    for (d, k, p, v, s, inc) in rows:
        j = per_doc_counts[d]
        per_doc_counts[d] += 1
        key_id[d, j] = k
        packed[d, j] = p
        value[d, j] = v
        is_set[d, j] = s
        is_inc[d, j] = inc
        valid[d, j] = True
    return OpBatch(key_id, packed, value, is_set, is_inc, valid)


class ActorInterner(KeyInterner):
    pass


def changes_to_decoded_ops(per_doc_changes):
    """Python-decode per-document change buffers into flat (doc, op_id, op)
    rows in application order — the mixed-content path used when a batch
    contains sequence-object ops (makeText/makeList/inserts), which the
    native flat-only parser rejects. Multi-inserts and multiOp deletes
    arrive pre-expanded by decode_change (ref columnar.js:446-475)."""
    from ..columnar import decode_change
    out = []
    for d, changes in enumerate(per_doc_changes):
        for buf in changes:
            change = decode_change(bytes(buf))
            start = change['startOp']
            actor = change['actor']
            for i, op in enumerate(change['ops']):
                out.append((d, f'{start + i}@{actor}', op))
    return out


def intern_composite_keys(obj, key_nat, nat_keys, nat_actors, key_interner):
    """Intern fleet key ids for rows that may live on nested objects:
    obj == 0 rows intern their bare key string, others the composite
    (objectId, key) tuple. Shared by the turbo path and the register
    ingest.

    Root rows ride a LUT over the parser's OWN key table (nat_keys is
    already dictionary-encoded, so one intern per distinct string and a
    single gather maps every row) — the previous np.unique over all
    row pairs cost a whole-batch sort to rediscover a dedup the parser
    had already done. Only nested-object rows (composite keys the
    parser cannot see) still pay a per-unique-pair walk."""
    n = len(obj)
    out = np.zeros(n, dtype=np.int32)
    if not n:
        return out
    # intern ONLY keys some root row actually references (one boolean
    # scatter — still no sort): nested-only key strings must not
    # bare-intern, or a nested-heavy workload would inflate the fleet
    # key table (and with it the [docs, keys] device grid) with ids no
    # root row ever uses
    root = obj == 0
    used = np.zeros(max(len(nat_keys), 1), dtype=bool)
    used[key_nat[root] if not root.all() else key_nat] = True
    lut = np.full(max(len(nat_keys), 1), -1, dtype=np.int32)
    for ki in np.flatnonzero(used).tolist():
        lut[ki] = key_interner.intern(nat_keys[ki])
    if root.all():
        return lut[key_nat]
    out[root] = lut[key_nat[root]]
    nest = np.flatnonzero(~root)
    pairs = obj[nest].astype(np.int64) * (1 << 32) + \
        key_nat[nest].astype(np.int64)
    uniq, inv = np.unique(pairs, return_inverse=True)
    u_ids = np.empty(len(uniq), dtype=np.int32)
    for ui, pv in enumerate(uniq):
        o = int(pv >> 32)
        ks = nat_keys[int(pv & 0xffffffff)]
        oid = f'{o >> 8}@{nat_actors[o & 0xff]}'
        u_ids[ui] = key_interner.intern((oid, ks))
    out[nest] = u_ids[inv]
    return out


def changes_to_op_rows(per_doc_changes, key_interner, actor_interner,
                       value_table=None):
    """Flat op rows with per-op pred lists, for the exact register engine
    (fleet/registers.py): returns a dict of parallel arrays
    {doc, key, packed, value, flags, pred_off, pred} in application order
    (doc-major, op order preserved), with keys/actors interned into the
    fleet tables and preds packed with fleet actor numbers.

    Native C++ path when every value is an inline int; Python decode
    otherwise (interning non-int values into value_table). flags: 1 =
    set/del (dels carry value TOMBSTONE), 2 = inc. Only flat root-map ops
    are supported; raises ValueError otherwise."""
    buffers, docs = [], []
    for d, changes in enumerate(per_doc_changes):
        for change in changes:
            buffers.append(bytes(change))
            docs.append(d)

    if native.available() and buffers:
        # with_seq=True so the wire value-type tag rides along: uint /
        # counter / timestamp set values box into the value table as
        # TypedValue, letting device-served patches keep exact datatypes
        out = native.ingest_changes(buffers, list(range(len(buffers))),
                                    with_meta=True, with_seq=True)
        if out is not None and out[0]['flags'].size and \
                out[0]['flags'].max() > 2:
            out = None    # sequence/make rows: not register material
        if out is not None:
            rows, nat_keys, nat_actors, _meta = out
            # root keys intern bare; nested map cells (rows['obj'] != 0)
            # intern composite (objectId, key) like the Python decode path
            key_ids = intern_composite_keys(rows['obj'], rows['key'],
                                            nat_keys, nat_actors,
                                            key_interner)
            actor_map = np.array([actor_interner.intern(a)
                                  for a in nat_actors], dtype=np.int32) \
                if nat_actors else np.zeros(1, np.int32)

            def remap(p):
                return np.where(
                    p != 0, (p >> 8 << 8) | actor_map[p & 0xff], 0
                ).astype(np.int32)

            values = rows['value'].astype(np.int32, copy=True)
            if value_table is not None and 'vtype' in rows:
                from ..columnar import decode_value
                from .registers import TypedValue, typed_wire_tags
                tags = typed_wire_tags()
                # values == TOMBSTONE (-1) identifies del rows: the native
                # parser boxes negative set values via the arena, so -1 on
                # a flags==1 row can only be a del
                typed = (rows['flags'] == 1) & (values != TOMBSTONE) & \
                    (rows['vlen'] == 0) & np.isin(rows['vtype'], list(tags))
                for ri in np.flatnonzero(typed):
                    values[ri] = -(value_table.intern(TypedValue(
                        int(rows['value'][ri]),
                        tags[int(rows['vtype'][ri])])) + 2)
                # arena-boxed payloads (strings/bools/None/floats/bytes,
                # out-of-lane ints): decode the raw wire bytes and box by
                # the shared datatype rule
                vlen = rows['vlen']
                off = np.cumsum(vlen, dtype=np.int64) - vlen
                # dels (value TOMBSTONE, vtype 0) are NOT boxed nulls
                boxed_sel = (rows['flags'] == 1) & (values != TOMBSTONE) & \
                    ((vlen > 0) | np.isin(rows['vtype'], (0, 1, 2)))
                blob = rows['vblob']
                for ri in np.flatnonzero(boxed_sel):
                    ln, vt = int(vlen[ri]), int(rows['vtype'][ri])
                    decoded = decode_value((ln << 4) | vt,
                                           blob[off[ri]:off[ri] + ln])
                    dt = decoded.get('datatype')
                    if isinstance(dt, str) and dt != 'int':
                        box = TypedValue(decoded['value'], dt)
                    else:
                        box = decoded['value']
                    values[ri] = -(value_table.intern(box) + 2)
            return {
                'doc': np.array(docs, dtype=np.int64)[rows['doc']],
                'key': key_ids,
                'packed': remap(rows['packed']),
                'value': values,
                'flags': rows['flags'],
                'pred_off': rows['pred_off'],
                'pred': remap(rows['pred']),
            }

    # Python fallback: full decode, arbitrary values via the value table
    from ..columnar import decode_change
    from ..common import parse_op_id
    out_doc, out_key, out_packed, out_val, out_flags = [], [], [], [], []
    pred_off, preds = [0], []

    def pack(op_id):
        ctr, actor = parse_op_id(op_id)
        return pack_op_id(ctr, actor_interner.intern(actor))

    for buf, d in zip(buffers, docs):
        change = decode_change(buf)
        for i, op in enumerate(change['ops']):
            if op['obj'] != '_root' or op.get('insert') or \
                    op.get('key') is None or \
                    op['action'] not in ('set', 'del', 'inc'):
                raise ValueError('register ingest supports flat root-map '
                                 'set/del/inc ops only')
            op_id = f"{change['startOp'] + i}@{change['actor']}"
            action = op['action']
            value = op.get('value')
            datatype = op.get('datatype')
            if action == 'del':
                val_idx = TOMBSTONE
            elif action == 'inc':
                if not isinstance(value, int) or isinstance(value, bool) or \
                        not -(1 << 31) < value < (1 << 31):
                    raise ValueError('inc delta must be an int32')
                val_idx = value
            elif datatype not in (None, 'int') and value_table is not None:
                # uint/counter/timestamp/float64 set values box with their
                # datatype so device-served patches stay exact
                from .registers import TypedValue
                val_idx = -(value_table.intern(
                    TypedValue(value, datatype)) + 2)
            elif isinstance(value, int) and not isinstance(value, bool) and \
                    0 <= value < (1 << 31):
                val_idx = value
            elif value_table is not None:
                val_idx = -(value_table.intern(value) + 2)
            else:
                raise ValueError('non-int value requires a value_table')
            out_doc.append(d)
            out_key.append(key_interner.intern(op['key']))
            out_packed.append(pack(op_id))
            out_val.append(val_idx)
            out_flags.append(2 if action == 'inc' else 1)
            for p in op.get('pred', []):
                preds.append(pack(p))
            pred_off.append(len(preds))

    return {
        'doc': np.array(out_doc, dtype=np.int64),
        'key': np.array(out_key, dtype=np.int32),
        'packed': np.array(out_packed, dtype=np.int32),
        'value': np.array(out_val, dtype=np.int32),
        'flags': np.array(out_flags, dtype=np.uint8),
        'pred_off': np.array(pred_off, dtype=np.int64),
        'pred': np.array(preds, dtype=np.int32),
    }
