"""Batched change application: the whole fleet's merge in one XLA dispatch.

This is the tensorized equivalent of the reference's per-document op-merge
loop (ref backend/new.js:1052-1290 mergeDocChangeOps + seekToOp): instead of
a streaming two-pointer merge per document, all documents' ops land as padded
[N, P] columns and per-key LWW resolution becomes a scatter-max of packed
opIds over the [N, K] key grid. Counter accumulation is a scatter-add.

Everything is static-shape, fusion-friendly gather/scatter on the VPU; no
data-dependent Python control flow, so the whole step is one `jit` region
that XLA pipelines across the fleet.
"""

import jax
import jax.numpy as jnp

from ..observability.perf import instrument_kernel
from .tensor_doc import FleetState


def _apply_op_batch_impl(state, ops):
    """Apply one OpBatch to the fleet. Returns (new_state, stats).

    `stats` is a per-fleet vector of ops applied (useful as a psum'd health
    metric when the fleet is sharded across hosts).
    """
    n_docs, n_slots = state.winners.shape
    doc_idx = jnp.arange(n_docs, dtype=jnp.int32)[:, None]
    doc_idx = jnp.broadcast_to(doc_idx, ops.key_id.shape)

    # Padded/invalid lanes scatter into the scratch column (n_slots - 1)
    scratch = n_slots - 1
    set_mask = ops.is_set & ops.valid
    inc_mask = ops.is_inc & ops.valid
    set_key = jnp.where(set_mask, ops.key_id, scratch)
    inc_key = jnp.where(inc_mask, ops.key_id, scratch)

    # LWW winner: scatter-max of packed opIds (unique per fleet, so ties are
    # impossible; overwritten ops always lose to their successors)
    winners = state.winners.at[doc_idx, set_key].max(
        jnp.where(set_mask, ops.packed, 0))

    # Find which op (if any) became the winner of its key, and scatter its
    # value. Packed opIds are unique per fleet, so at most one op per
    # (doc, key) matches; losing lanes write garbage into the scratch column.
    won = set_mask & (ops.packed == winners[doc_idx, ops.key_id])
    win_key = jnp.where(won, ops.key_id, scratch)
    values = state.values.at[doc_idx, win_key].set(jnp.where(won, ops.value, 0))

    # Counters accumulate (inc ops are successors that add, not overwrite,
    # ref new.js:937-965) — but a key whose winner changed this batch starts
    # from a fresh base: the old accumulator belonged to the overwritten op
    # (a redundant re-delivery of the standing winner leaves it intact).
    # Known corner: ops don't carry pred info on device, so an inc targeting
    # the *old* counter that lands in the same batch as the overwriting set
    # is credited to the new winner; the host mirror (fleet.backend) remains
    # exact there, and per-op pred ingest is the planned fix.
    keep = winners == state.winners
    counters = jnp.where(keep, state.counters, 0)
    counters = counters.at[doc_idx, inc_key].add(
        jnp.where(inc_mask, ops.value, 0))

    stats = jnp.sum(ops.valid, dtype=jnp.int32)
    return FleetState(winners, values, counters), stats


apply_op_batch = instrument_kernel(
    'apply_op_batch', jax.jit(_apply_op_batch_impl))


def _apply_op_batch_noinc_impl(state, ops):
    """Set-only batches (no inc lanes — the caller checks host-side):
    skips the counter machinery entirely. The counter grid passes
    through UNTOUCHED — with donation that is a buffer alias, so the
    dispatch saves the winners==old compare (2 grid reads), the
    counter where() rewrite, and the inc scatter: ~3 whole-grid memory
    passes on a path whose cost IS memory traffic.

    SOUNDNESS GATE (the caller's, not this kernel's): with all-False
    is_inc the general kernel still RESETS the accumulator of any key
    whose winner changed — so skipping the counter machinery is only
    byte-identical while the counter grid is all-zero. DocFleet tracks
    that with `_counters_touched`: the first batch carrying an inc lane
    (or a bulk load installing counter cells) pins the fleet to the
    general kernel for good. Pinned against the general kernel by
    test_noinc_kernel_matches_general."""
    n_docs, n_slots = state.winners.shape
    doc_idx = jnp.arange(n_docs, dtype=jnp.int32)[:, None]
    doc_idx = jnp.broadcast_to(doc_idx, ops.key_id.shape)
    scratch = n_slots - 1
    set_mask = ops.is_set & ops.valid
    set_key = jnp.where(set_mask, ops.key_id, scratch)
    winners = state.winners.at[doc_idx, set_key].max(
        jnp.where(set_mask, ops.packed, 0))
    won = set_mask & (ops.packed == winners[doc_idx, ops.key_id])
    win_key = jnp.where(won, ops.key_id, scratch)
    values = state.values.at[doc_idx, win_key].set(
        jnp.where(won, ops.value, 0))
    stats = jnp.sum(ops.valid, dtype=jnp.int32)
    return FleetState(winners, values, state.counters), stats


apply_op_batch_noinc_donated = instrument_kernel(
    'apply_op_batch_noinc_donated',
    jax.jit(_apply_op_batch_noinc_impl, donate_argnums=(0,)))


def _apply_op_batch_noinc_fresh_impl(ops, n_docs, n_keys):
    return _apply_op_batch_noinc_impl(
        FleetState.empty(n_docs, n_keys, xp=jnp), ops)


apply_op_batch_noinc_fresh = instrument_kernel(
    'apply_op_batch_noinc_fresh',
    jax.jit(_apply_op_batch_noinc_fresh_impl, static_argnums=(1, 2)))


def _apply_op_batch_kills_impl(state, ops, kill_key, kill_packed):
    """Apply one OpBatch plus delete "kill lanes" with the reference's
    pred-scoped delete semantics (ref backend/new.js:1204-1217: a delete
    adds succ entries ONLY to the ops it preds; concurrent sets it never
    saw stay visible and resurrect the key).

    kill_key/kill_packed are [N, Q] lanes: each carries the packed opId a
    delete op preds (0 = unused lane) and the fleet key the delete
    targets. A kill (1) clears the standing winner iff it holds exactly
    that packed opId, and (2) masks any same-batch set lane carrying that
    opId. Nothing else is touched — in particular a concurrent set with a
    LOWER packed id than the delete wins the key afterwards, which the
    old tombstone-scatter model got wrong (the delete's own opId beat it).

    Causality makes this exact for single-winner semantics across
    batches: a delete can only pred ops its change causally saw, so an op
    arriving in a LATER batch can never be a target of this delete —
    clearing to 0 and letting later scatter-max resurrect is precisely
    the reference's succNum == 0 visibility rule, projected onto the
    grid's Lamport-max single-winner view."""
    n_docs, n_slots = state.winners.shape
    scratch = n_slots - 1
    kvalid = kill_packed > 0
    kdoc = jnp.broadcast_to(jnp.arange(n_docs, dtype=jnp.int32)[:, None],
                            kill_key.shape)
    kkey = jnp.where(kvalid, kill_key, scratch)
    standing = state.winners[kdoc, kkey]
    hit = kvalid & (standing == kill_packed)
    killed = jnp.zeros(state.winners.shape, dtype=jnp.bool_) \
        .at[kdoc, jnp.where(hit, kill_key, scratch)].max(hit)
    # The scratch column absorbs miss lanes; its contents are garbage by
    # contract, so clearing it along the way is harmless
    cleared = FleetState(jnp.where(killed, 0, state.winners),
                         jnp.where(killed, 0, state.values),
                         jnp.where(killed, 0, state.counters))
    # Same-batch kills: a set lane whose packed id any kill lane names
    # never lands (the delete pred'd it). Per-doc sorted membership test
    # — a dense [N, P, Q] one-hot would scale device memory with
    # doc_capacity x batch_width x kill_lanes (GBs on delete-heavy
    # flushes of large fleets), while sort + searchsorted stays
    # O(N x (P + Q)).
    int32_max = jnp.iinfo(jnp.int32).max
    kill_sorted = jnp.sort(
        jnp.where(kvalid, kill_packed, int32_max), axis=1)
    pos = jax.vmap(jnp.searchsorted)(kill_sorted, ops.packed)
    pos = jnp.clip(pos, 0, kill_sorted.shape[1] - 1)
    lane_killed = (jnp.take_along_axis(kill_sorted, pos, axis=1) ==
                   ops.packed) & (ops.packed > 0)
    masked = type(ops)(ops.key_id, ops.packed, ops.value,
                       ops.is_set & ~lane_killed, ops.is_inc, ops.valid)
    return _apply_op_batch_impl(cleared, masked)


apply_op_batch_kills = instrument_kernel(
    'apply_op_batch_kills', jax.jit(_apply_op_batch_kills_impl))
apply_op_batch_kills_donated = instrument_kernel(
    'apply_op_batch_kills_donated',
    jax.jit(_apply_op_batch_kills_impl, donate_argnums=(0,)))

# The fleet's own dispatch paths donate the input state: the scatters then
# update the [docs, keys] grids in place instead of rewriting ~all of HBM
# per dispatch (the state is replaced by the result at every call site, so
# the donated buffers are never read again). External callers use the
# non-donating apply_op_batch, which keeps the input alive for reuse.
#
# Failure contract: if a donated dispatch fails at execution time (e.g.
# transient device OOM), the input buffers are already gone and the fleet's
# device state is unrecoverable — unlike the non-donating path, the error
# is not retryable in place. That is an accepted trade: the host-side
# change logs remain the source of truth, so documents rebuild into a
# fresh fleet (or promote to the host engine) from their logs; device
# state is always a derived cache.
apply_op_batch_donated = instrument_kernel(
    'apply_op_batch_donated',
    jax.jit(_apply_op_batch_impl, donate_argnums=(0,)))


def _apply_op_batch_fresh_impl(ops, n_docs, n_keys):
    """First dispatch of a FRESH fleet: the zero state is created inside
    the jit, so XLA fuses the fill with the scatter instead of running a
    separate whole-grid memset dispatch first — a fresh 10k-doc x 1k-key
    grid otherwise pays a ~120 MB zero-fill (measured 60-85 ms host-side
    on the bench box) before its first merge. Shapes are static args:
    one compile per capacity step, same as the growth path."""
    return _apply_op_batch_impl(FleetState.empty(n_docs, n_keys, xp=jnp),
                                ops)


apply_op_batch_fresh = instrument_kernel(
    'apply_op_batch_fresh',
    jax.jit(_apply_op_batch_fresh_impl, static_argnums=(1, 2)))


def _apply_op_batch_kills_fresh_impl(ops, kill_key, kill_packed, n_docs,
                                     n_keys):
    """Kills-aware variant of the fused fresh-state dispatch (kills
    against an all-zero grid cannot hit, but the lane masking of
    same-batch sets must still run)."""
    return _apply_op_batch_kills_impl(
        FleetState.empty(n_docs, n_keys, xp=jnp), ops, kill_key,
        kill_packed)


apply_op_batch_kills_fresh = instrument_kernel(
    'apply_op_batch_kills_fresh',
    jax.jit(_apply_op_batch_kills_fresh_impl, static_argnums=(3, 4)))


def _zero_doc_rows_impl(state, idx):
    """Zero the given docs' rows across every grid array — ONE fused
    kernel, so a batched free is genuinely one device dispatch (duplicate
    indices are fine: zeroing is idempotent, which lets callers pad idx to
    a power of two to bound recompiles)."""
    return FleetState(state.winners.at[idx].set(0),
                      state.values.at[idx].set(0),
                      state.counters.at[idx].set(0))


zero_doc_rows_donated = instrument_kernel(
    'zero_doc_rows_donated',
    jax.jit(_zero_doc_rows_impl, donate_argnums=(0,)))


def fleet_merge(state, op_batches):
    """Apply a sequence of OpBatches (e.g. one per change round)."""
    total = 0
    for ops in op_batches:
        state, stats = apply_op_batch(state, ops)
        total += int(stats)
    return state, total
