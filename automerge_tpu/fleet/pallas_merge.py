"""Pallas TPU kernel: fused fleet LWW merge.

The jnp path (fleet/apply.py) lowers to three scatters + one gather over the
HBM-resident [docs, keys] grids (scatter-max winners, scatter values,
scatter-add counters). This kernel replaces scatter with the TPU-native
formulation: tile the key grid into VMEM blocks and turn each op into a
dense one-hot contribution over its key tile — max-reduced for LWW winners,
sum-reduced for counter accumulation — so the whole merge is one pass of
VPU-friendly compares/selects with NO gather/scatter at all, and winners,
values, and counters update in a single fused kernel (one HBM read + write
per state tile instead of three scatter round-trips).

Semantics are identical to fleet.apply.apply_op_batch (differentially tested
in tests/test_pallas.py): this is the merge loop of ref backend/new.js
:1052-1290 (mergeDocChangeOps) vectorized over a doc fleet, per SURVEY §7
stage 3.

Grid: (doc_tiles, key_tiles, op_chunks). The op axis is tiled as the
innermost (sequential) grid dimension so VMEM stays bounded at
[DOC_TILE, OP_CHUNK, KEY_TILE] temporaries no matter how many ops per doc a
batch carries; the state tile [DOC_TILE, KEY_TILE] persists in VMEM across
op chunks (TPU revisiting semantics) and accumulates. Winner values carry as
(winner, value) pairs combined by take-if-greater, which is associative
across chunks and — for LWW set ops — idempotent under duplicate delivery
within a batch (redundant re-sends select the same value instead of summing
it twice; counter-increment lanes accumulate per delivery in both this and
the jnp path, so increment dedup is the sync layer's job). Padded / invalid
op lanes are masked out by `valid`.
"""

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .tensor_doc import FleetState
from ..observability.perf import instrument_kernel

# Tile sizes are env-tunable (PALLAS_DOC_TILE / PALLAS_KEY_TILE /
# PALLAS_OP_CHUNK) so on-chip VMEM pressure can be dialed without code
# edits: the dense one-hot kernel materializes [DOC_TILE, OP_CHUNK,
# KEY_TILE] int32 temporaries (32x128x128 = 2 MB each), several of which
# live at once — near the 16 MB/core VMEM budget at the defaults.
# AOT-validated against a v5e topology (tests/test_pallas.py
# TestMosaicAOT): Mosaic compiles BOTH variants at these defaults, and
# 32x128x128 is exactly the dense variant's VMEM ceiling — every larger
# axis (64 docs, 256 keys, or 256-op chunks) fails with
# RESOURCE_EXHAUSTED in vmem, so these defaults are the maximal tiles,
# not a guess.
DOC_TILE = int(os.environ.get('PALLAS_DOC_TILE', 32))
KEY_TILE = int(os.environ.get('PALLAS_KEY_TILE', 128))
OP_CHUNK = int(os.environ.get('PALLAS_OP_CHUNK', 128))

_INT32_MIN = np.iinfo(np.int32).min


def _merge_kernel(key_ref, packed_ref, value_ref, is_set_ref, is_inc_ref,
                  valid_ref, winners_in, values_in, counters_in,
                  winners_out, values_out, counters_out,
                  orig_w_ref, base_c_ref):
    j = pl.program_id(1)
    c = pl.program_id(2)
    k_base = j * KEY_TILE
    dn, p = key_ref.shape  # p == OP_CHUNK

    # First op chunk for this state tile: seed the accumulators from the
    # input state (out blocks persist in VMEM across the sequential op-chunk
    # grid axis, so later chunks read back their own partial results). The
    # pre-batch winners and counter bases stash in scratch; counters_out
    # accumulates only this batch's increments until the final chunk decides,
    # per key, whether the old base survives (winner unchanged) or resets
    # (a strictly newer set op won — matching fleet.apply.apply_op_batch).
    @pl.when(c == 0)
    def _seed():
        winners_out[:] = winners_in[:]
        values_out[:] = values_in[:]
        orig_w_ref[:] = winners_in[:]
        base_c_ref[:] = counters_in[:]
        counters_out[:] = jnp.zeros_like(counters_in)

    # Dense one-hot over the key tile, [DN, OP_CHUNK, KEY_TILE]: Mosaic
    # cannot lower per-op dynamic lane slices, so the op axis is materialized
    # and reduced instead — pure elementwise + reductions, no gather/scatter.
    k_ids = jax.lax.broadcasted_iota(jnp.int32, (dn, p, KEY_TILE), 2) + k_base
    in_tile = key_ref[:][:, :, None] == k_ids
    # Masks arrive as int32 (Mosaic only supports minor-dim insertion for
    # 32-bit types, so 8-bit bools can't be broadcast to the 3D shape)
    valid3 = valid_ref[:][:, :, None] != 0
    set3 = in_tile & (is_set_ref[:][:, :, None] != 0) & valid3
    packed3 = packed_ref[:][:, :, None]
    value3 = value_ref[:][:, :, None]

    # Chunk-local LWW winner per key, and the value of the lane that won it.
    # Packed opIds of real set ops are > 0, so 0 means "no set in this chunk";
    # duplicate packed ids (redundant delivery) carry equal values, which the
    # max-reduction selects once instead of summing.
    chunk_w = jnp.max(jnp.where(set3, packed3, 0), axis=1)
    won = set3 & (packed3 == chunk_w[:, None, :])
    chunk_v = jnp.max(jnp.where(won, value3, _INT32_MIN), axis=1)

    winners = winners_out[:]
    take = chunk_w > winners
    winners_out[:] = jnp.maximum(winners, chunk_w)
    values_out[:] = jnp.where(take, chunk_v, values_out[:])

    inc3 = in_tile & (is_inc_ref[:][:, :, None] != 0) & valid3
    counters_out[:] = counters_out[:] + \
        jnp.sum(jnp.where(inc3, value3, 0), axis=1)

    # Final chunk: fold the pre-batch counter base back in wherever the
    # winner is unchanged (a re-delivered standing winner keeps its base)
    @pl.when(c == pl.num_programs(2) - 1)
    def _finalize():
        keep = winners_out[:] == orig_w_ref[:]
        counters_out[:] = counters_out[:] + \
            jnp.where(keep, base_c_ref[:], 0)


def _merge_kernel_loop(key_ref, packed_ref, value_ref, is_set_ref,
                       is_inc_ref, valid_ref, winners_in, values_in,
                       counters_in, winners_out, values_out, counters_out,
                       orig_w_ref, base_c_ref):
    """VMEM-conservative variant: instead of materializing the dense
    [DOC_TILE, OP_CHUNK, KEY_TILE] one-hot, a STATIC unrolled loop walks
    the [DOC_TILE, OP_CHUNK] op block one width-1 column slice at a time,
    carrying the [DOC_TILE, KEY_TILE] state tile in VMEM across grid
    steps (TPU revisiting semantics). Same total VPU work (each lane
    still touches the whole
    key tile), a fraction of the VMEM footprint — the op block holds only
    [DOC_TILE, OP_CHUNK] columns (~100 KB) instead of the dense variant's
    [DOC_TILE, OP_CHUNK, KEY_TILE] 3D temporaries (MBs). Two earlier
    formulations failed Mosaic lowering — fori_loop + lax.dynamic_slice
    (minor-dim dynamic_slice unimplemented) and a [DOC_TILE, 1] op block
    (minor block dims must be 128-divisible or full) — which is why the
    walk is unrolled at trace time with static slices. Lane order
    preserves the sequential take-if-greater semantics, which equals the
    chunk-max formulation for LWW (ties keep the first-seen equal
    value)."""
    j = pl.program_id(1)
    c = pl.program_id(2)
    k_base = j * KEY_TILE
    dn, p = key_ref.shape

    @pl.when(c == 0)
    def _seed():
        winners_out[:] = winners_in[:]
        values_out[:] = values_in[:]
        orig_w_ref[:] = winners_in[:]
        base_c_ref[:] = counters_in[:]
        counters_out[:] = jnp.zeros_like(counters_in)

    k_ids = jax.lax.broadcasted_iota(jnp.int32, (dn, KEY_TILE), 1) + k_base
    keys = key_ref[:]
    packeds = packed_ref[:]
    values = value_ref[:]
    is_sets = is_set_ref[:]
    is_incs = is_inc_ref[:]
    valids = valid_ref[:]
    w = winners_out[:]
    v = values_out[:]
    cnt = counters_out[:]
    for t in range(p):
        # (dn, 1) static column broadcast against the (dn, KEY_TILE) tile
        in_tile = (keys[:, t:t + 1] == k_ids) & (valids[:, t:t + 1] != 0)
        setk = in_tile & (is_sets[:, t:t + 1] != 0)
        cand = jnp.where(setk, packeds[:, t:t + 1], 0)
        take = cand > w
        w = jnp.where(take, cand, w)
        v = jnp.where(take, values[:, t:t + 1], v)
        inck = in_tile & (is_incs[:, t:t + 1] != 0)
        cnt = cnt + jnp.where(inck, values[:, t:t + 1], 0)
    winners_out[:] = w
    values_out[:] = v
    counters_out[:] = cnt

    @pl.when(c == pl.num_programs(2) - 1)
    def _finalize():
        keep = winners_out[:] == orig_w_ref[:]
        counters_out[:] = counters_out[:] + \
            jnp.where(keep, base_c_ref[:], 0)


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    rem = (-size) % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


def _pallas_apply_op_batch_impl(state, ops, interpret=False,
                                variant='dense'):
    """Drop-in fused-kernel equivalent of fleet.apply.apply_op_batch.

    variant='dense' materializes the 3D one-hot (best VPU shape, highest
    VMEM pressure); variant='loop' walks op lanes with a carried state
    tile (same semantics, minimal VMEM — the Mosaic fallback)."""
    n_docs, n_slots = state.winners.shape
    kernel = _merge_kernel if variant == 'dense' else _merge_kernel_loop

    def prep_state(x):
        return _pad_to(_pad_to(x, 0, DOC_TILE), 1, KEY_TILE)

    def prep_ops(x, dtype=None):
        x = _pad_to(_pad_to(jnp.asarray(x), 0, DOC_TILE), 1, OP_CHUNK)
        return x if dtype is None else x.astype(dtype)

    winners = prep_state(state.winners)
    values = prep_state(state.values)
    counters = prep_state(state.counters)
    nd, nk = winners.shape

    key_id = prep_ops(ops.key_id)
    packed = prep_ops(ops.packed)
    value = prep_ops(ops.value)
    is_set = prep_ops(ops.is_set, jnp.int32)
    is_inc = prep_ops(ops.is_inc, jnp.int32)
    # Padded doc rows / op lanes carry valid=0, masking them out entirely
    valid = prep_ops(ops.valid, jnp.int32)
    p = key_id.shape[1]

    grid = (nd // DOC_TILE, nk // KEY_TILE, p // OP_CHUNK)
    ops_spec = pl.BlockSpec((DOC_TILE, OP_CHUNK), lambda i, j, c: (i, c))
    state_spec = pl.BlockSpec((DOC_TILE, KEY_TILE), lambda i, j, c: (i, j))

    out_w, out_v, out_c = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[ops_spec] * 6 + [state_spec] * 3,
        out_specs=[state_spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((nd, nk), jnp.int32)] * 3,
        input_output_aliases={6: 0, 7: 1, 8: 2},
        scratch_shapes=[pltpu.VMEM((DOC_TILE, KEY_TILE), jnp.int32)] * 2,
        interpret=interpret,
    )(key_id, packed, value, is_set, is_inc, valid,
      winners, values, counters)

    new_state = FleetState(out_w[:n_docs, :n_slots],
                           out_v[:n_docs, :n_slots],
                           out_c[:n_docs, :n_slots])
    stats = jnp.sum(ops.valid, dtype=jnp.int32)
    return new_state, stats


pallas_apply_op_batch = instrument_kernel(
    'pallas_apply_op_batch',
    jax.jit(_pallas_apply_op_batch_impl,
            static_argnames=('interpret', 'variant')))
