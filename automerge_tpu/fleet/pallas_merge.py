"""Pallas TPU kernel: fused fleet LWW merge.

The jnp path (fleet/apply.py) lowers to three scatters + one gather over the
HBM-resident [docs, keys] grids (scatter-max winners, scatter values,
scatter-add counters). This kernel replaces scatter with the TPU-native
formulation: tile the key grid into VMEM blocks and turn each op into a
dense one-hot contribution over its key tile — max-reduced for LWW winners,
sum-reduced for counter accumulation — so the whole merge is one pass of
VPU-friendly compares/selects with NO gather/scatter at all, and winners,
values, and counters update in a single fused kernel (one HBM read + write
per state tile instead of three scatter round-trips).

Semantics are identical to fleet.apply.apply_op_batch (differentially tested
in tests/test_pallas.py): this is the merge loop of ref backend/new.js
:1052-1290 (mergeDocChangeOps) vectorized over a doc fleet, per SURVEY §7
stage 3.

Grid: (doc_tiles, key_tiles). Ops columns [DN, P] ride along the doc axis;
state tiles [DN, DK] are updated in place via input_output_aliases. Padded /
invalid op lanes are masked out by `valid` — no scratch column needed (the
dense formulation has no out-of-range scatter lanes to redirect).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .tensor_doc import FleetState

DOC_TILE = 32
KEY_TILE = 128


def _merge_kernel(key_ref, packed_ref, value_ref, is_set_ref, is_inc_ref,
                  valid_ref, winners_in, values_in, counters_in,
                  winners_out, values_out, counters_out):
    j = pl.program_id(1)
    k_base = j * KEY_TILE
    dn, p = key_ref.shape

    # Dense one-hot over the key tile, [DN, P, DK]: Mosaic cannot lower
    # per-op dynamic lane slices, so the op axis is materialized and reduced
    # instead — pure elementwise + reductions, no gather/scatter.
    k_ids = jax.lax.broadcasted_iota(jnp.int32, (dn, p, KEY_TILE), 2) + k_base
    in_tile = key_ref[:][:, :, None] == k_ids
    # Masks arrive as int32 (Mosaic only supports minor-dim insertion for
    # 32-bit types, so 8-bit bools can't be broadcast to the 3D shape)
    valid3 = valid_ref[:][:, :, None] != 0
    set3 = in_tile & (is_set_ref[:][:, :, None] != 0) & valid3
    packed3 = packed_ref[:][:, :, None]
    value3 = value_ref[:][:, :, None]

    winners = jnp.maximum(
        winners_in[:], jnp.max(jnp.where(set3, packed3, 0), axis=1))

    inc3 = in_tile & (is_inc_ref[:][:, :, None] != 0) & valid3
    counters = counters_in[:] + jnp.sum(jnp.where(inc3, value3, 0), axis=1)

    # The op whose packed opId equals the final winner (unique per
    # (doc, key) — packed ids are fleet-unique) contributes its value.
    won = set3 & (packed3 == winners[:, None, :])
    values = jnp.where(jnp.any(won, axis=1),
                       jnp.sum(jnp.where(won, value3, 0), axis=1),
                       values_in[:])

    winners_out[:] = winners
    values_out[:] = values
    counters_out[:] = counters


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    rem = (-size) % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


@functools.partial(jax.jit, static_argnames=('interpret',))
def pallas_apply_op_batch(state, ops, interpret=False):
    """Drop-in fused-kernel equivalent of fleet.apply.apply_op_batch."""
    n_docs, n_slots = state.winners.shape

    def prep_state(x):
        return _pad_to(_pad_to(x, 0, DOC_TILE), 1, KEY_TILE)

    def prep_ops(x, dtype=None):
        x = _pad_to(jnp.asarray(x), 0, DOC_TILE)
        return x if dtype is None else x.astype(dtype)

    winners = prep_state(state.winners)
    values = prep_state(state.values)
    counters = prep_state(state.counters)
    nd, nk = winners.shape
    p = ops.key_id.shape[1]

    key_id = prep_ops(ops.key_id)
    packed = prep_ops(ops.packed)
    value = prep_ops(ops.value)
    is_set = prep_ops(ops.is_set, jnp.int32)
    is_inc = prep_ops(ops.is_inc, jnp.int32)
    # Padded doc rows carry valid=0, masking them out entirely
    valid = prep_ops(ops.valid, jnp.int32)

    grid = (nd // DOC_TILE, nk // KEY_TILE)
    ops_spec = pl.BlockSpec((DOC_TILE, p), lambda i, j: (i, 0))
    state_spec = pl.BlockSpec((DOC_TILE, KEY_TILE), lambda i, j: (i, j))

    out_w, out_v, out_c = pl.pallas_call(
        _merge_kernel,
        grid=grid,
        in_specs=[ops_spec] * 6 + [state_spec] * 3,
        out_specs=[state_spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((nd, nk), jnp.int32)] * 3,
        input_output_aliases={6: 0, 7: 1, 8: 2},
        interpret=interpret,
    )(key_id, packed, value, is_set, is_inc, valid,
      winners, values, counters)

    new_state = FleetState(out_w[:n_docs, :n_slots],
                           out_v[:n_docs, :n_slots],
                           out_c[:n_docs, :n_slots])
    stats = jnp.sum(ops.valid, dtype=jnp.int32)
    return new_state, stats
