"""Padded device-tensor representation of a document fleet.

A fleet of N map documents with a key universe of size K (dictionary-encoded
per fleet on the host) is:

- `winners`   [N, K+1] int32 — packed opId (counter << ACTOR_BITS | actorNum)
  of the LWW winner per key; 0 = key absent. Column K is a scratch slot that
  padded scatter lanes write into.
- `values`    [N, K+1] int32 — value-table index of the winner's value.
- `counters`  [N, K+1] int32 — accumulated increment total per key (counter
  CRDT semantics: inc ops add instead of overwriting; ref new.js:937-965).

Ops arrive as an OpBatch of parallel columns [N, P] (P = padded ops per doc),
mirroring the reference's columnar storage (ref backend/columnar.js:56-70)
so host decode feeds the device directly.

The packed-opId trick: Automerge op visibility means the LWW winner of a key
is simply the op with the greatest (counter, actorNum) among all set ops for
that key — an overwritten op always has a successor with a greater opId — so
per-key conflict resolution vectorizes to a scatter-max of packed opIds.
Deletion is a set with value TOMBSTONE (correct for causally-ordered deletes;
concurrent set-vs-delete resurrection routes through the host engine).
"""

import numpy as np

ACTOR_BITS = 8               # up to 256 distinct actors per fleet
MAX_ACTORS = 1 << ACTOR_BITS
# Packed counters occupy 23 bits (~8.4M) — a WINDOW, not a history cap: the
# LWW grid rebases each slot's window as counters grow (DocFleet.ctr_base /
# _rebase_slot), so history length is unbounded; only a slot's live-winner
# counter spread is window-bounded (beyond that, reads use the host mirror)
CTR_LIMIT = 1 << (31 - ACTOR_BITS)
TOMBSTONE = -1               # value-table index marking a deleted key


def pack_op_id(counter, actor_num):
    """Pack (counter, actorNum) into one int32 preserving Lamport order."""
    if isinstance(counter, (int, np.integer)):
        if counter >= CTR_LIMIT:
            raise ValueError(f'op counter {counter} exceeds packing limit {CTR_LIMIT}')
        if actor_num >= MAX_ACTORS:
            raise ValueError(f'actor index {actor_num} exceeds {MAX_ACTORS}')
    return (counter << ACTOR_BITS) | actor_num


def unpack_op_id(packed):
    return packed >> ACTOR_BITS, packed & (MAX_ACTORS - 1)


class FleetState:
    """Immutable pytree of fleet tensors."""

    def __init__(self, winners, values, counters):
        self.winners = winners
        self.values = values
        self.counters = counters

    @classmethod
    def empty(cls, n_docs, n_keys, xp=np):
        shape = (n_docs, n_keys + 1)
        return cls(xp.zeros(shape, dtype=np.int32),
                   xp.zeros(shape, dtype=np.int32),
                   xp.zeros(shape, dtype=np.int32))

    def tree_flatten(self):
        return (self.winners, self.values, self.counters), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


class OpBatch:
    """One batch of ops for the whole fleet, as parallel columns [N, P].

    - key_id  int32: dictionary-encoded key (scratch column K for padding)
    - packed  int32: packed opId of the op
    - value   int32: value-table index (set ops) or increment delta (inc ops)
    - is_set  bool:  set/makeX/del op (participates in LWW)
    - is_inc  bool:  increment op (accumulates into counters)
    - valid   bool:  padding mask
    """

    def __init__(self, key_id, packed, value, is_set, is_inc, valid):
        self.key_id = key_id
        self.packed = packed
        self.value = value
        self.is_set = is_set
        self.is_inc = is_inc
        self.valid = valid

    def tree_flatten(self):
        return ((self.key_id, self.packed, self.value, self.is_set,
                 self.is_inc, self.valid), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def register_pytrees(*classes):
    """Register container classes (with tree_flatten/tree_unflatten) as JAX
    pytree nodes; idempotent."""
    try:
        from jax import tree_util
    except ImportError:
        return
    for klass in classes:
        try:
            tree_util.register_pytree_node(
                klass,
                lambda obj: obj.tree_flatten(),
                klass.tree_unflatten)
        except ValueError:
            pass  # already registered


register_pytrees(FleetState, OpBatch)
