"""Mmap-backed segment arena: the on-disk chunk tier under ``MainStore``.

The delta+main storage engine (fleet/storage.py) keeps a parked doc's
CAUSAL state in RAM-resident columnar lanes, but the chunk bytes
themselves — the overwhelming majority of a parked doc's footprint —
need none of that residency: every read they get is a sequential scan
(revive parse, ``materialize_at`` extraction, the rare ``chunk()``
export), which the kernel's page cache already serves better than a
Python ``bytes`` arena can. This module is that tier:

- ``SegmentArena`` — append-only segment files (``seg-<epoch>-<n>.dat``)
  holding CRC-framed chunk records. Reads come back as zero-copy
  ``memoryview``s into an ``mmap`` of the segment, so a parked chunk
  costs page-cache presence, not RSS; a cold chunk costs a page fault,
  not a decode. Appends land in the active segment through a buffered
  writer; the mapping is refreshed lazily when a read wants bytes
  beyond the mapped length.
- Crash safety rides two mechanisms, both scanned by ``open``:
  every record is CRC-framed (a torn append is detected and the tail
  dropped, exactly like the change journal's frame discipline), and the
  arena's MANIFEST names the current *epoch* via atomic
  rename + dir fsync (fleet/durability.py's ``_atomic_write``). Vacuum
  (``rewrite_begin``/``rewrite_commit``) writes the surviving chunks
  into next-epoch segments and flips the manifest ATOMICALLY — a crash
  at any point recovers either the complete old arena or the complete
  new one, never a mix; stale-epoch files are swept on open.
- Discards append a tombstone frame (so discard state survives a
  crash); vacuum drops dead records and tombstones (the repark path
  re-parks under the caller's original ids, so frames carry them
  directly).
- ``RamArena`` — the same interface over in-process bytes objects, for
  stores that want yesterday's RAM-resident behavior (tests, ephemeral
  scratch stores, the shard rebalance staging store). No files, no
  recovery, no framing overhead.

Concurrently-held views survive a vacuum: the swap drops the arena's
OWN references to the old epoch's maps and unlinks the files, but a
``memoryview`` keeps its mmap (and therefore the unlinked inode's
pages) alive until the holder releases it — POSIX semantics do the
reference counting the store would otherwise need.
"""

import mmap
import os
import re
import struct
import zlib

__all__ = ['SegmentArena', 'RamArena', 'ArenaCorrupt']

# record frame: [u32 crc] [u8 kind] [u32 payload_len] [i64 tag] [payload]
# crc covers kind|len|tag|payload (crc32, like the change journal's frames)
_HEAD = struct.Struct('<IBIq')
_BODY = struct.Struct('<BIq')
_U32 = struct.Struct('<I')

KIND_CHUNK = 1      # payload = parked chunk bytes
KIND_TOMB = 2       # record `tag` is discarded (payload empty)

MANIFEST_NAME = 'ARENA'
_SEG_RE = re.compile(r'^seg-(\d{8})-(\d{6})\.dat$')

DEFAULT_SEGMENT_BYTES = 32 << 20


class ArenaCorrupt(ValueError):
    """The arena directory cannot be interpreted (missing/garbled
    manifest). Torn record tails are NOT this — they are expected crash
    damage and handled leniently by the scan."""


def _seg_name(epoch, n):
    return f'seg-{epoch:08d}-{n:06d}.dat'


def _atomic_write(path, data):
    from .durability import _atomic_write as aw
    aw(path, data)


def _fsync_dir(path):
    from .durability import _fsync_dir as fd
    fd(path)


class _Segment:
    """One on-disk segment: durable size + a lazily refreshed mapping."""

    __slots__ = ('path', 'size', 'map', 'mapped')

    def __init__(self, path, size=0):
        self.path = path
        self.size = size
        self.map = None
        self.mapped = 0

    def view(self, off, length):
        if off + length > self.mapped:
            self.remap()
        return memoryview(self.map)[off:off + length]

    def remap(self):
        size = os.path.getsize(self.path)
        if size == 0:
            return
        with open(self.path, 'rb') as f:
            # dropping the old map object is safe even with exported
            # views: they keep it alive; unexported maps close on GC
            self.map = mmap.mmap(f.fileno(), size, access=mmap.ACCESS_READ)
        self.mapped = size


class SegmentArena:
    """Append-only mmap'd chunk storage (see module docstring).

    ``append(tag, payload)`` returns ``(seg, off, length)`` addressing
    the payload; ``view(seg, off, length)`` serves it zero-copy. ``seg``
    indexes this epoch's segment list — addresses are only meaningful
    against the arena epoch that issued them (MainStore re-addresses on
    vacuum)."""

    def __init__(self, path, segment_bytes=DEFAULT_SEGMENT_BYTES,
                 epoch=0, _fresh=True):
        if segment_bytes >= 1 << 31:
            raise ValueError('segment_bytes must stay below 2 GiB')
        self.path = path
        self.segment_bytes = int(segment_bytes)
        self.epoch = int(epoch)
        self.segments = []          # [_Segment]
        self._f = None              # buffered writer on the active segment
        self.data_bytes = 0         # live payload bytes (chunks only)
        self.garbage_bytes = 0      # dead payload + frame/tombstone overhead
        self.fault_point = None     # test hook: name -> raise/_exit there
        if _fresh:
            os.makedirs(path, exist_ok=True)
            self._write_manifest()
            self._open_segment()

    # -- manifest ---------------------------------------------------------

    def _write_manifest(self):
        _atomic_write(os.path.join(self.path, MANIFEST_NAME),
                      b'arena-epoch %d\n' % self.epoch)

    @classmethod
    def open(cls, path, segment_bytes=DEFAULT_SEGMENT_BYTES):
        """Recover an arena: read the manifest epoch, sweep stale-epoch
        files (a killed vacuum's debris), frame-scan this epoch's
        segments dropping any torn tail, and return
        ``(arena, records)`` where records is ``{tag: (seg, off, len)}``
        for every live (non-tombstoned) chunk in append order."""
        mpath = os.path.join(path, MANIFEST_NAME)
        try:
            with open(mpath, 'rb') as f:
                head = f.read(64).split()
        except OSError as exc:
            raise ArenaCorrupt(f'no arena manifest at {mpath}') from exc
        if len(head) < 2 or head[0] != b'arena-epoch':
            raise ArenaCorrupt(f'garbled arena manifest at {mpath}')
        epoch = int(head[1])
        arena = cls(path, segment_bytes=segment_bytes, epoch=epoch,
                    _fresh=False)
        names = []
        for name in sorted(os.listdir(path)):
            m = _SEG_RE.match(name)
            if not m:
                continue
            if int(m.group(1)) != epoch:
                # a vacuum died before (future epoch) or after (past
                # epoch) its manifest flip: either way not ours
                try:
                    os.unlink(os.path.join(path, name))
                except OSError:
                    pass
                continue
            names.append(name)
        records = {}
        for name in names:
            seg_path = os.path.join(path, name)
            seg = _Segment(seg_path)
            seg_idx = len(arena.segments)
            arena.segments.append(seg)
            arena._scan_segment(seg_idx, seg_path, seg, records)
        arena.data_bytes = sum(ln for _s, _o, ln in records.values())
        # everything on disk that is not a live payload is vacuum-able
        # debt: dead records, tombstones, frame headers
        arena.garbage_bytes = max(
            0, arena.disk_bytes() - arena.data_bytes
            - len(records) * _HEAD.size)
        if not arena.segments:
            arena._open_segment()
        else:
            # append into the last segment past its verified tail (the
            # torn bytes, if any, were truncated by the scan)
            last = arena.segments[-1]
            arena._f = open(last.path, 'r+b')
            arena._f.seek(last.size)
            arena._f.truncate(last.size)
        return arena, records

    def _scan_segment(self, seg_idx, seg_path, seg, records):
        with open(seg_path, 'rb') as f:
            data = f.read()
        off = 0
        valid = 0
        while off + _HEAD.size <= len(data):
            crc, kind, ln, tag = _HEAD.unpack_from(data, off)
            end = off + _HEAD.size + ln
            if end > len(data):
                break
            body = data[off + 4:end]
            if zlib.crc32(body) & 0xffffffff != crc:
                break
            if kind == KIND_CHUNK:
                records[tag] = (seg_idx, off + _HEAD.size, ln)
            elif kind == KIND_TOMB:
                records.pop(tag, None)
            off = end
            valid = off
        seg.size = valid

    # -- appends ----------------------------------------------------------

    def _open_segment(self):
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
        name = _seg_name(self.epoch, len(self.segments))
        seg_path = os.path.join(self.path, name)
        self._f = open(seg_path, 'wb')
        self.segments.append(_Segment(seg_path))

    def _emit(self, kind, tag, payload):
        if self.fault_point is not None:
            self._check_fault(f'append_{kind}')
        seg = len(self.segments) - 1
        active = self.segments[seg]
        if active.size >= self.segment_bytes:
            self._open_segment()
            seg += 1
            active = self.segments[seg]
        n = len(payload)
        body = _BODY.pack(kind, n, tag)
        crc = (zlib.crc32(payload, zlib.crc32(body)) & 0xffffffff) \
            if n else (zlib.crc32(body) & 0xffffffff)
        off = active.size + _HEAD.size
        # ONE buffered write per record (the bulk-park hot path)
        self._f.write(b''.join((_U32.pack(crc), body, payload)) if n
                      else _U32.pack(crc) + body)
        active.size = off + n
        return seg, off, n

    def append(self, tag, payload):
        """Store one chunk under stable tag; returns (seg, off, len)."""
        out = self._emit(KIND_CHUNK, tag, payload)
        self.data_bytes += len(payload)
        return out

    def append_many(self, tags, payloads):
        """Bulk append: frames accumulate host-side and land in ONE
        buffered write per segment span (the 1M/10M-doc ingest path —
        per-record write() calls would dominate the park rate).
        Returns [(seg, off, len)] aligned with the inputs."""
        if self.fault_point is not None:
            self._check_fault('append_1')
        out = []
        frames = []
        seg = len(self.segments) - 1
        active = self.segments[seg]
        size = active.size
        crc32, u32, body_pack = zlib.crc32, _U32.pack, _BODY.pack
        for tag, payload in zip(tags, payloads):
            if size >= self.segment_bytes:
                if frames:
                    self._f.write(b''.join(frames))
                    frames.clear()
                active.size = size
                self._open_segment()
                seg += 1
                active = self.segments[seg]
                size = 0
            n = len(payload)
            body = body_pack(KIND_CHUNK, n, tag)
            crc = (crc32(payload, crc32(body)) if n else crc32(body)) \
                & 0xffffffff
            frames.append(u32(crc))
            frames.append(body)
            frames.append(payload)
            off = size + _HEAD.size
            out.append((seg, off, n))
            size = off + n
            self.data_bytes += n
        if frames:
            self._f.write(b''.join(frames))
        active.size = size
        return out

    def tombstone(self, tag, length):
        """Record `tag`'s discard durably; its `length` payload bytes
        become arena garbage until the next vacuum."""
        self._emit(KIND_TOMB, tag, b'')
        self.data_bytes -= length
        self.garbage_bytes += length + 2 * _HEAD.size

    # -- reads ------------------------------------------------------------

    def view(self, seg, off, length):
        """Zero-copy memoryview of a stored payload. Flushes the writer
        first when the address lies beyond the mapped span (read-after-
        write consistency without per-append flushes)."""
        segment = self.segments[seg]
        if off + length > segment.mapped and self._f is not None:
            self._f.flush()
        return segment.view(off, length)

    # -- accounting / maintenance ----------------------------------------

    def disk_bytes(self):
        return sum(s.size for s in self.segments)

    def resident_bytes(self):
        return 0            # chunk bytes live on the page cache, not RSS

    def flush(self):
        """Push buffered frames to the kernel (no fsync): after this, a
        PROCESS kill cannot lose or resurrect records — only an OS/power
        crash can, whose window ``sync`` closes. The storage engine
        flushes after every batched mutation (park/ingest/discard), the
        same group-commit granularity the change journal uses."""
        if self._f is not None:
            self._f.flush()

    def sync(self):
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())

    def advise_cold(self):
        """Drop this arena's clean pages from the page cache
        (posix_fadvise DONTNEED) — the bench's cold-read lever. Best
        effort; a platform without fadvise is a no-op."""
        if not hasattr(os, 'posix_fadvise'):
            return
        self.sync()
        for seg in self.segments:
            try:
                fd = os.open(seg.path, os.O_RDONLY)
                try:
                    os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
                finally:
                    os.close(fd)
            except OSError:
                pass

    # -- vacuum: rewrite + atomic swap ------------------------------------

    def rewrite_begin(self):
        """A writer arena for the NEXT epoch in the same directory. Its
        files are invisible to recovery until ``rewrite_commit`` flips
        the manifest; a crash before that leaves this epoch authoritative
        and the writer's files swept on the next open."""
        writer = SegmentArena.__new__(SegmentArena)
        writer.path = self.path
        writer.segment_bytes = self.segment_bytes
        writer.epoch = self.epoch + 1
        writer.segments = []
        writer._f = None
        writer.data_bytes = 0
        writer.garbage_bytes = 0
        writer.fault_point = self.fault_point
        writer._open_segment()
        return writer

    def rewrite_commit(self, writer):
        """Atomic swap: fsync the writer's segments, flip the manifest
        to the writer's epoch (rename is the commit point), then sweep
        this epoch's files. Concurrently-held views into the old maps
        stay valid (see module docstring)."""
        self._check_fault('pre_commit')
        writer.sync()
        writer._write_manifest()
        _fsync_dir(self.path)
        self._check_fault('post_manifest')
        # the old epoch is now garbage whatever happens below
        old = self.segments
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
        for seg in old:
            seg.map = None          # views keep theirs alive
            try:
                os.unlink(seg.path)
            except OSError:
                pass

    def _check_fault(self, point):
        fault = self.fault_point
        if fault is None:
            return
        if fault == point:
            raise RuntimeError(f'injected arena fault at {point}')
        if fault == f'exit:{point}':
            os._exit(71)        # kill-style crash for recovery tests

    def close(self):
        if self._f is not None:
            try:
                self._f.flush()
                self._f.close()
            except OSError:
                pass
            self._f = None
        for seg in self.segments:
            seg.map = None


class RamArena:
    """The arena interface over in-process bytes (no files, no frames):
    yesterday's RAM-resident MainStore behavior for ephemeral stores."""

    def __init__(self):
        self._items = []
        self.data_bytes = 0
        self.garbage_bytes = 0

    def append(self, tag, payload):
        payload = payload if type(payload) is bytes else bytes(payload)
        self._items.append(payload)
        self.data_bytes += len(payload)
        return 0, len(self._items) - 1, len(payload)

    def append_many(self, tags, payloads):
        return [self.append(t, p) for t, p in zip(tags, payloads)]

    def tombstone(self, tag, length):
        self.data_bytes -= length
        self.garbage_bytes += length

    def view(self, seg, off, length):
        return memoryview(self._items[off])

    def discard_slot(self, off):
        self._items[off] = None

    def flush(self):
        pass

    def disk_bytes(self):
        return 0

    def resident_bytes(self):
        return self.data_bytes

    def sync(self):
        pass

    def advise_cold(self):
        pass

    def rewrite_begin(self):
        return RamArena()

    def rewrite_commit(self, writer):
        self._items = []

    def close(self):
        self._items = []
