"""Fleet sharding across a device mesh.

The parallelism story for a CRDT fleet (SURVEY.md §2.12): documents are
independent, so the fleet batch axis shards data-parallel across chips; the
per-document key grid can shard across a second mesh axis when the key
universe is large. XLA inserts the collectives (scatter updates crossing the
key axis become all-to-alls; fleet-wide stats are psums riding ICI).

No NCCL/MPI translation — this is `jax.sharding.Mesh` + NamedSharding over
the fleet pytree, the idiomatic JAX equivalent of the reference's
transport-agnostic peer protocol scaled to a sharded fleet service.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .tensor_doc import FleetState
from .apply import apply_op_batch
from ..observability.perf import instrument_kernel


def fleet_mesh(devices=None, keys_axis=1):
    """Build a (docs, keys) mesh over the available devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if keys_axis > 1 and n % keys_axis == 0:
        shape = (n // keys_axis, keys_axis)
    else:
        shape = (n, 1)
    import numpy as np
    return Mesh(np.array(devices).reshape(shape), ('docs', 'keys'))


def fleet_sharding(mesh):
    """NamedShardings for FleetState ([docs, keys] grid) and OpBatch
    ([docs, ops] columns, replicated over the keys axis)."""
    state_spec = NamedSharding(mesh, P('docs', 'keys'))
    ops_spec = NamedSharding(mesh, P('docs', None))
    return state_spec, ops_spec


def shard_fleet(state, mesh):
    state_spec, _ = fleet_sharding(mesh)
    return FleetState(*(jax.device_put(x, state_spec)
                        for x in (state.winners, state.values, state.counters)))


def shard_ops(ops, mesh):
    _, ops_spec = fleet_sharding(mesh)
    import jax.tree_util as tree
    return tree.tree_map(lambda x: jax.device_put(x, ops_spec),
                         ops)


def seq_sharding(mesh):
    """NamedShardings for SeqState / SeqOpBatch, data-parallel over the docs
    axis only — the per-doc slot axis stays local (the RGA pointer walk is a
    per-document scan; sharding it would put pointer chasing on ICI). Arrays
    pick their spec by rank: [docs] vectors, [docs, slots] node arrays,
    [docs, slots, lanes] register/pred-lane arrays."""
    by_ndim = {1: NamedSharding(mesh, P('docs')),
               2: NamedSharding(mesh, P('docs', None)),
               3: NamedSharding(mesh, P('docs', None, None))}
    return by_ndim


def _put_by_ndim(tree_obj, by_ndim):
    import jax.tree_util as tree
    return tree.tree_map(
        lambda x: jax.device_put(x, by_ndim[x.ndim]), tree_obj)


def _constrain_by_ndim(tree_obj, by_ndim):
    import jax.tree_util as tree
    return tree.tree_map(
        lambda x: jax.lax.with_sharding_constraint(x, by_ndim[x.ndim]),
        tree_obj)


def shard_seq(state, mesh):
    return _put_by_ndim(state, seq_sharding(mesh))


def shard_seq_ops(ops, mesh):
    return _put_by_ndim(ops, seq_sharding(mesh))


def sharded_seq_apply(mesh):
    """Jitted sequence-fleet step, data-parallel over docs."""
    from .sequence import _apply_seq_batch_impl
    by_ndim = seq_sharding(mesh)

    def _step(state, ops):
        new_state, stats = _apply_seq_batch_impl(state, ops)
        return _constrain_by_ndim(new_state, by_ndim), stats
    return instrument_kernel('sharded_seq_apply', jax.jit(_step))


def long_seq_sharding(mesh):
    """NamedShardings for the LONG-document regime: a handful of very long
    sequences whose slot axis shards across every device of the mesh (the
    CRDT analogue of sequence/context parallelism, SURVEY.md §2.12/§5 — the
    document is too long for one chip's memory/bandwidth, so its element
    slots, pointers, and values stripe over the whole mesh)."""
    every_axis = mesh.axis_names
    by_ndim = {1: NamedSharding(mesh, P()),
               2: NamedSharding(mesh, P(None, every_axis)),
               3: NamedSharding(mesh, P(None, every_axis, None))}
    return by_ndim


def shard_long_seq(state, mesh):
    """Shard a long-document SeqState's node axis across the whole mesh,
    tail-padding to a device-count multiple first (safe because sentinels
    are front-anchored and padded tail slots read as unallocated)."""
    from .sequence import END, SeqState
    by_ndim = long_seq_sharding(mesh)
    n_dev = int(np.prod(mesh.devices.shape))
    size = state.elem_id.shape[1]
    pad = (-size) % n_dev

    def padded(x, fill):
        if pad == 0:
            return x
        shape = (x.shape[0], size + pad) + x.shape[2:]
        out = jnp.full(shape, fill, dtype=x.dtype)
        return out.at[:, :size].set(x)

    return SeqState(*(
        jax.device_put(arr, by_ndim[arr.ndim]) for arr in (
            padded(state.elem_id, 0), padded(state.nxt, END),
            padded(state.reg, 0), padded(state.killed, False),
            padded(state.val, 0), padded(state.counter, 0),
            jnp.asarray(state.n), jnp.asarray(state.inexact))))


def sharded_long_seq_apply(mesh):
    """Jitted op application for slot-sharded long documents. Per-op work is
    a one-hot referent lookup over the sharded slot axis (an all-reduce per
    op) plus the RGA pointer walk's scalar gathers; causality keeps the op
    stream itself sequential — the win is that the document's state never
    has to fit one chip."""
    from .sequence import _apply_seq_batch_impl
    by_ndim = long_seq_sharding(mesh)

    def _step(state, ops):
        new_state, stats = _apply_seq_batch_impl(state, ops)
        return _constrain_by_ndim(new_state, by_ndim), stats
    return instrument_kernel('sharded_long_seq_apply', jax.jit(_step))


def sharded_long_seq_materialize(mesh):
    """Jitted sequence-order extraction for slot-sharded long documents.

    This is the bandwidth-heavy read path and the part that genuinely
    parallelizes: pointer-doubling list ranking (Wyllie's algorithm) runs
    ceil(log2 S) rounds of gathers over the sharded pointer array, with XLA
    inserting the cross-shard collectives — the segmented-scan trick the
    survey names as the long-context equivalent (SURVEY.md §5)."""
    from .sequence import _materialize_impl
    slots = long_seq_sharding(mesh)[2]

    def _run(state):
        vals, cnts, vis, n = _materialize_impl(state)
        return (jax.lax.with_sharding_constraint(vals, slots),
                jax.lax.with_sharding_constraint(cnts, slots),
                jax.lax.with_sharding_constraint(vis, slots), n)
    return instrument_kernel('sharded_long_seq_materialize', jax.jit(_run))


def sharded_apply(mesh):
    """A jitted fleet step with explicit output shardings: data-parallel over
    docs, key grid sharded over the second mesh axis. The scatter by key_id
    crossing key shards compiles to XLA collectives; the stats reduction is a
    global psum over the mesh."""
    state_spec, _ = fleet_sharding(mesh)

    def _step(state, ops):
        new_state, stats = apply_op_batch(state, ops)
        new_state = FleetState(
            *(jax.lax.with_sharding_constraint(x, state_spec)
              for x in (new_state.winners, new_state.values, new_state.counters)))
        return new_state, stats
    return instrument_kernel('sharded_apply', jax.jit(_step))
