"""Fleet sharding across a device mesh.

The parallelism story for a CRDT fleet (SURVEY.md §2.12): documents are
independent, so the fleet batch axis shards data-parallel across chips; the
per-document key grid can shard across a second mesh axis when the key
universe is large. XLA inserts the collectives (scatter updates crossing the
key axis become all-to-alls; fleet-wide stats are psums riding ICI).

No NCCL/MPI translation — this is `jax.sharding.Mesh` + NamedSharding over
the fleet pytree, the idiomatic JAX equivalent of the reference's
transport-agnostic peer protocol scaled to a sharded fleet service.
"""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .tensor_doc import FleetState
from .apply import apply_op_batch


def fleet_mesh(devices=None, keys_axis=1):
    """Build a (docs, keys) mesh over the available devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if keys_axis > 1 and n % keys_axis == 0:
        shape = (n // keys_axis, keys_axis)
    else:
        shape = (n, 1)
    import numpy as np
    return Mesh(np.array(devices).reshape(shape), ('docs', 'keys'))


def fleet_sharding(mesh):
    """NamedShardings for FleetState ([docs, keys] grid) and OpBatch
    ([docs, ops] columns, replicated over the keys axis)."""
    state_spec = NamedSharding(mesh, P('docs', 'keys'))
    ops_spec = NamedSharding(mesh, P('docs', None))
    return state_spec, ops_spec


def shard_fleet(state, mesh):
    state_spec, _ = fleet_sharding(mesh)
    return FleetState(*(jax.device_put(x, state_spec)
                        for x in (state.winners, state.values, state.counters)))


def shard_ops(ops, mesh):
    _, ops_spec = fleet_sharding(mesh)
    import jax.tree_util as tree
    return tree.tree_map(lambda x: jax.device_put(x, ops_spec),
                         ops)


def seq_sharding(mesh):
    """NamedShardings for SeqState / SeqOpBatch: data-parallel over the docs
    axis only — the per-doc slot axis stays local (the RGA pointer walk is a
    per-document scan; sharding it would put pointer chasing on ICI)."""
    row = NamedSharding(mesh, P('docs', None))
    vec = NamedSharding(mesh, P('docs'))
    return row, vec


def shard_seq(state, mesh):
    from .sequence import SeqState
    row, vec = seq_sharding(mesh)
    return SeqState(
        jax.device_put(state.elem_id, row), jax.device_put(state.nxt, row),
        jax.device_put(state.winner, row), jax.device_put(state.vis, row),
        jax.device_put(state.val, row), jax.device_put(state.n, vec))


def shard_seq_ops(ops, mesh):
    row, _ = seq_sharding(mesh)
    import jax.tree_util as tree
    return tree.tree_map(lambda x: jax.device_put(x, row), ops)


def sharded_seq_apply(mesh):
    """Jitted sequence-fleet step, data-parallel over docs."""
    from .sequence import SeqState, _apply_seq_batch_impl
    row, vec = seq_sharding(mesh)

    @jax.jit
    def step(state, ops):
        new_state, stats = _apply_seq_batch_impl(state, ops)
        new_state = SeqState(
            *(jax.lax.with_sharding_constraint(x, row)
              for x in (new_state.elem_id, new_state.nxt, new_state.winner,
                        new_state.vis, new_state.val)),
            jax.lax.with_sharding_constraint(new_state.n, vec))
        return new_state, stats
    return step


def sharded_apply(mesh):
    """A jitted fleet step with explicit output shardings: data-parallel over
    docs, key grid sharded over the second mesh axis. The scatter by key_id
    crossing key shards compiles to XLA collectives; the stats reduction is a
    global psum over the mesh."""
    state_spec, _ = fleet_sharding(mesh)

    @jax.jit
    def step(state, ops):
        new_state, stats = apply_op_batch(state, ops)
        new_state = FleetState(
            *(jax.lax.with_sharding_constraint(x, state_spec)
              for x in (new_state.winners, new_state.values, new_state.counters)))
        return new_state, stats
    return step
