"""Cross-shard change exchange: sync-protocol payload routing on ICI.

The reference's sync protocol is transport-agnostic byte messages
(backend/sync.js; SURVEY.md §2.11) — the application moves them. When the
document fleet itself is sharded across devices/hosts, peer reconciliation
between shards becomes a bulk payload movement problem, and the idiomatic
TPU transport is an XLA collective riding ICI rather than a host-side mesh
of sockets: every shard contributes, for every other shard, the concatenated
change buffers (or sync messages) destined there, and one `all_to_all`
delivers every shard its inbox in a single collective (SURVEY.md §5
"per-peer change exchange becomes an all-to-all of change buffers").

Payloads are ragged bytes; they ride as a padded uint8 tensor
[n_shards_out, max_len] per shard with a length vector. The collective
moves bytes only — hashing/causal gating stays host-side per shard, exactly
like the reference's split between transport and protocol.
"""

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..errors import SyncOverflow
from ..observability import register_health_source
from ..observability.metrics import Counters
from ..observability import hist as _hist
from ..observability import recorder as _flight
from ..observability.perf import instrument_kernel
from ..observability.spans import span as _span

# Fault-containment roll-up: extra sub-rounds paid to move over-limit sync
# payloads through the fixed-width wire (sync_round_multihost chunking).
_sync_stats = Counters({'sync_retries': 0})
register_health_source('sync_retries', lambda: _sync_stats['sync_retries'])


def pack_outboxes(per_dest_payloads, max_len=None):
    """per_dest_payloads: list over destination shards of bytes objects
    (b'' for none). Returns (data uint8 [n_dest, max_len], lens int32)."""
    n = len(per_dest_payloads)
    max_len = max_len if max_len is not None else \
        max((len(p) for p in per_dest_payloads), default=0)
    max_len = max(max_len, 1)
    data = np.zeros((n, max_len), dtype=np.uint8)
    lens = np.zeros((n,), dtype=np.int32)
    for d, payload in enumerate(per_dest_payloads):
        buf = np.frombuffer(bytes(payload), dtype=np.uint8)
        data[d, :len(buf)] = buf
        lens[d] = len(buf)
    return data, lens


def unpack_inbox(data, lens):
    """Inverse of pack_outboxes after the exchange: list over source shards
    of bytes."""
    data = np.asarray(data)
    lens = np.asarray(lens)
    return [data[s, :int(lens[s])].tobytes() for s in range(data.shape[0])]


def exchange_changes(mesh, axis, all_outboxes, all_lens):
    """One collective round of shard-to-shard payload delivery.

    all_outboxes: [n_shards, n_shards, L] uint8, where row i column j holds
    shard i's payload for shard j (host-assembled, then sharded over the
    first axis so each device owns its outbox row). Returns
    (inboxes [n_shards, n_shards, L], in_lens) where row j column i is the
    payload shard j received from shard i — one all_to_all on ICI plus the
    matching length exchange."""
    try:
        from jax import shard_map
    except ImportError:           # older jax
        from jax.experimental.shard_map import shard_map

    n = mesh.shape[axis]
    spec_data = P(axis, None, None)
    spec_lens = P(axis, None)

    def _run(data, lens):
        def body(data, lens):
            # shard view: [1, n, L]; exchange rows over the peer axis so
            # each shard ends with [from_peer, L] — one tiled all_to_all
            out = jax.lax.all_to_all(data[0], axis, split_axis=0,
                                     concat_axis=0, tiled=True)
            out_lens = jax.lax.all_to_all(lens[0], axis, split_axis=0,
                                          concat_axis=0, tiled=True)
            return out[None], out_lens[None]

        return shard_map(body, mesh=mesh,
                         in_specs=(spec_data, spec_lens),
                         out_specs=(spec_data, spec_lens))(data, lens)

    run = instrument_kernel('exchange_all_to_all', jax.jit(_run))

    data = jax.device_put(jnp.asarray(all_outboxes),
                          NamedSharding(mesh, spec_data))
    lens = jax.device_put(jnp.asarray(all_lens),
                          NamedSharding(mesh, spec_lens))
    return run(data, lens)


def sync_round_sharded(mesh, axis, backends, sync_states, generate, receive):
    """Drive one full sync round between every ordered pair of shards, with
    message transport on the device mesh: each shard generates its per-peer
    sync messages host-side (`generate(src, dst) -> bytes | None`), the
    payload matrix rides ONE all_to_all, and `receive(dst, src, payload)`
    applies what arrived. Returns the number of non-empty payloads moved."""
    n = mesh.shape[axis]
    row_fn = getattr(generate, 'row', None)
    rows, row_lens = [], []
    for src in range(n):
        if row_fn is not None:
            # one batched generate per shard (single Bloom build +
            # frontier-index membership dispatch) instead of one per
            # ordered pair — byte-identical messages either way
            payloads = [m or b'' for m in row_fn(src, range(n))]
        else:
            payloads = [(generate(src, dst) or b'') if dst != src
                        else b'' for dst in range(n)]
        data, lens = pack_outboxes(payloads)
        rows.append(data)
        row_lens.append(lens)
    width = max(r.shape[1] for r in rows)
    outboxes = np.zeros((n, n, width), dtype=np.uint8)
    lens = np.zeros((n, n), dtype=np.int32)
    for src in range(n):
        outboxes[src, :, :rows[src].shape[1]] = rows[src]
        lens[src] = row_lens[src]

    inboxes, in_lens = exchange_changes(mesh, axis, outboxes, lens)
    inboxes = np.asarray(jax.device_get(inboxes))
    in_lens = np.asarray(jax.device_get(in_lens))

    items = []
    for dst in range(n):
        for src in range(n):
            length = int(in_lens[dst, src])
            if length:
                items.append((dst, src,
                              inboxes[dst, src, :length].tobytes()))
    all_fn = getattr(receive, 'all', None)
    if all_fn is not None:
        # fused receive waves (see _pairwise_callbacks.receive_all):
        # O(max inbox depth) driver calls per round instead of O(pairs)
        all_fn(items)
    else:
        for dst, src, payload in items:
            receive(dst, src, payload)
    return len(items)


def _pairwise_callbacks(docs, sync_states, backend_module):
    """(generate, receive) closures over a docs container (list indexed by
    shard, or dict keyed by global shard id) and per-ordered-pair sync
    states — THE sync-state handshake, shared by the single-controller
    and multi-controller drivers so it cannot drift between them.

    ``generate.row(src, dsts)`` produces ALL of src's outgoing messages
    for one round through the batched fleet driver when the backend
    module is the fleet (ONE Bloom build + ONE frontier-index membership
    dispatch per shard instead of one of each per ordered pair — the
    per-peer scan the round used to pay); byte-identical to the per-pair
    calls (the driver's differential tests pin it), and host backend
    modules simply take the per-pair path."""

    def generate(src, dst):
        state, msg = backend_module.generate_sync_message(
            docs[src], sync_states[(src, dst)])
        sync_states[(src, dst)] = state
        return msg

    # batch through the fleet driver ONLY when the module's generate IS
    # the canonical protocol (host Backend and fleet.backend both
    # re-export it; a third-party backend module keeps per-pair calls)
    from ..backend.sync import generate_sync_message as _canonical
    if getattr(backend_module, 'generate_sync_message', None) \
            is _canonical:
        from .sync_driver import generate_sync_messages_docs as \
            batched_gen
    else:
        batched_gen = None

    def generate_row(src, dsts):
        if batched_gen is None:
            return [generate(src, dst) if dst != src else None
                    for dst in dsts]
        peers = [dst for dst in dsts if dst != src]
        new_states, msgs = batched_gen(
            [docs[src]] * len(peers),
            [sync_states[(src, dst)] for dst in peers])
        for dst, state in zip(peers, new_states):
            sync_states[(src, dst)] = state
        by_dst = dict(zip(peers, msgs))
        return [by_dst.get(dst) for dst in dsts]

    generate.row = generate_row

    def receive(dst, src, payload):
        doc, state, _patch = backend_module.receive_sync_message(
            docs[dst], sync_states[(dst, src)], payload)
        docs[dst] = doc
        sync_states[(dst, src)] = state

    from ..backend.sync import receive_sync_message as _canonical_recv
    if getattr(backend_module, 'receive_sync_message', None) \
            is _canonical_recv:
        from .sync_driver import receive_sync_messages_docs as \
            batched_recv
    else:
        batched_recv = None

    def receive_all(items):
        """Apply a whole round's inbound (dst, src, payload) triples in
        fused WAVES: wave k carries each destination's k-th message, so
        every wave is one batched receive over DISTINCT dst docs — the
        per-(dst, src) stream order the sharedHeads algebra depends on
        is preserved, wire behavior byte-identical to the per-pair
        loop, and a round costs O(max inbox depth) fused driver calls
        instead of O(pairs)."""
        if batched_recv is None:
            for dst, src, payload in items:
                receive(dst, src, payload)
            return
        queues = {}
        for dst, src, payload in items:
            queues.setdefault(dst, []).append((src, payload))
        while queues:
            wave = [(dst, q.pop(0)) for dst, q in queues.items()]
            new_docs, new_states, _patches = batched_recv(
                [docs[dst] for dst, _ in wave],
                [sync_states[(dst, src)] for dst, (src, _p) in wave],
                [payload for _dst, (_src, payload) in wave])
            for (dst, (src, _p)), doc, state in zip(wave, new_docs,
                                                    new_states):
                docs[dst] = doc
                sync_states[(dst, src)] = state
            queues = {d: q for d, q in queues.items() if q}

    receive.all = receive_all

    return generate, receive


def drive_pairwise_sync(mesh, axis, docs, backend_module, max_rounds=None):
    """Converge every ordered pair of shard documents with the mesh as the
    wire: per-pair sync states on host, one all_to_all per round, until a
    round moves nothing (the sync_test.js driver loop, shard-to-shard).
    `backend_module` supplies init_sync_state / generate_sync_message /
    receive_sync_message (host backend or fleet backend — both satisfy the
    Backend contract). Mutates `docs` in place; returns the round count."""
    n = mesh.shape[axis]
    sync_states = {(i, j): backend_module.init_sync_state()
                   for i in range(n) for j in range(n) if i != j}
    generate, receive = _pairwise_callbacks(docs, sync_states,
                                            backend_module)
    rounds = 0
    for _ in range(max_rounds if max_rounds is not None else 2 * n):
        rounds += 1
        if sync_round_sharded(mesh, axis, docs, sync_states,
                              generate, receive) == 0:
            break
    return rounds


def local_shard_ids(mesh, axis):
    """Global positions along `axis` owned by THIS process — the shards
    whose documents a multi-controller host holds. Mesh axes other than
    `axis` must be absent or size 1 for the pairwise sync drivers."""
    devs = np.asarray(mesh.devices).reshape(-1)
    if len(devs) != mesh.shape[axis]:
        raise ValueError(
            f'pairwise sync needs a 1-axis mesh: {len(devs)} devices but '
            f'axis {axis!r} spans {mesh.shape[axis]}')
    me = jax.process_index()
    return [int(i) for i, d in enumerate(devs) if d.process_index == me]


def sync_round_multihost(mesh, axis, generate, receive, max_msg=1 << 16,
                         max_chunks=64):
    """One pairwise sync round over a MULTI-PROCESS mesh (true multi-host:
    each controller holds only its local shards' documents, the payload
    matrix rides the same all_to_all — ICI within a host, DCN across
    hosts, exactly where the reference hands messages to NCCL/MPI-style
    transports). `generate(src, dst) -> bytes | None` and
    `receive(dst, src, payload)` are called ONLY for src/dst shards local
    to this process. Payloads are padded to `max_msg` bytes (a fixed
    global width keeps every controller's data shapes identical without a
    per-round width negotiation).

    Graceful degradation: a payload larger than `max_msg` no longer kills
    the round — the round splits into ceil(global_max / max_msg)
    fixed-width SUB-ROUNDS, sub-round t carrying every payload's bytes
    [t*max_msg, (t+1)*max_msg); receivers reassemble and deliver each
    payload once complete. Every controller derives the same sub-round
    count from the agreement allgather's global max, so the collectives
    stay SPMD-lock-step with no extra negotiation, and a normal-size
    round still pays exactly one all_to_all. The extra sub-rounds land in
    the 'sync_retries' health counter. Only a payload beyond
    max_msg * max_chunks raises — a typed `SyncOverflow` carrying
    (global_max, max_msg, max_chunks, locally-determinable offending
    pairs), raised identically on every controller (the condition is a
    function of allgathered values alone), so no peer is left blocking
    inside the collective. Returns the round's GLOBAL non-empty payload
    count — identical on every controller, so callers can branch on it
    without desyncing; an all-empty round returns 0 without paying the
    padded all_to_all."""
    round_start = time.perf_counter() if _hist.on() else None
    with _span('sync_round', max_msg=max_msg):
        result = _sync_round_multihost(mesh, axis, generate, receive,
                                       max_msg, max_chunks)
    if round_start is not None:
        _hist.record_value('sync_round_s', time.perf_counter() - round_start,
                           scale=1e9, unit='s')
    return result


def _sync_round_multihost(mesh, axis, generate, receive, max_msg,
                          max_chunks):
    n = mesh.shape[axis]
    mine = local_shard_ids(mesh, axis)
    row_fn = getattr(generate, 'row', None)
    per_src = []
    biggest = sent = 0
    for src in mine:
        if row_fn is not None:
            payloads = [m or b'' for m in row_fn(src, range(n))]
        else:
            payloads = [generate(src, dst) or b'' if dst != src else b''
                        for dst in range(n)]
        biggest = max(biggest, max(map(len, payloads)))
        sent += sum(1 for p in payloads if p)
        per_src.append(payloads)
    # SPMD-safe agreement round: every controller sees the global max
    # payload size (identical overflow/chunking decisions everywhere,
    # never deadlocking peers inside the collective) and the global sent
    # count (an all-empty round returns 0 everywhere WITHOUT paying the
    # padded all_to_all — the lock-step convergence signal).
    from jax.experimental import multihost_utils
    agg = np.asarray(multihost_utils.process_allgather(
        np.array([biggest, sent], dtype=np.int64))).reshape(-1, 2)
    global_max, global_sent = int(agg[:, 0].max()), int(agg[:, 1].sum())
    hard_limit = max_msg * max_chunks
    if global_max > hard_limit:
        pairs = [(src, dst)
                 for src, payloads in zip(mine, per_src)
                 for dst, p in enumerate(payloads) if len(p) > hard_limit]
        # forensic dump before the (SPMD-identical) raise: the overflow
        # aborts the round on every controller, so record what this one
        # saw — sizes, limits, and its locally-observed offending pairs
        _flight.record_event('sync_overflow', global_max=global_max,
                             max_msg=max_msg, max_chunks=max_chunks,
                             pairs=pairs[:16])
        _flight.dump_flight_record('sync_overflow', detail={
            'global_max': global_max, 'max_msg': max_msg,
            'max_chunks': max_chunks, 'hard_limit': hard_limit,
            'local_pairs': pairs[:64]})
        raise SyncOverflow(
            f'sync message {global_max}B exceeds max_msg={max_msg} x '
            f'max_chunks={max_chunks}', global_max=global_max,
            max_msg=max_msg, max_chunks=max_chunks, pairs=pairs)
    if global_sent == 0:
        return 0
    n_sub = -(-global_max // max_msg) if global_max else 1
    if n_sub > 1:
        _sync_stats.inc('sync_retries', n_sub - 1)
    sh_data = NamedSharding(mesh, P(axis, None, None))
    sh_lens = NamedSharding(mesh, P(axis, None))
    inbox_acc = {}        # (dst, src) -> bytearray of reassembled fragments
    for t in range(n_sub):
        lo = t * max_msg
        rows = np.zeros((len(mine), n, max_msg), dtype=np.uint8)
        lens = np.zeros((len(mine), n), dtype=np.int32)
        for r, payloads in enumerate(per_src):
            rows[r], lens[r] = pack_outboxes(
                [p[lo:lo + max_msg] for p in payloads], max_len=max_msg)
        data = jax.make_array_from_process_local_data(sh_data, rows,
                                                      (n, n, max_msg))
        lens_g = jax.make_array_from_process_local_data(sh_lens, lens,
                                                        (n, n))
        inboxes, in_lens = exchange_changes(mesh, axis, data, lens_g)
        lens_local = {}
        for shard in in_lens.addressable_shards:
            dst = shard.index[0].start or 0
            lens_local[dst] = np.asarray(shard.data)[0]
        for shard in inboxes.addressable_shards:
            dst = shard.index[0].start or 0
            for src, fragment in enumerate(
                    unpack_inbox(np.asarray(shard.data)[0],
                                 lens_local[dst])):
                if fragment:
                    inbox_acc.setdefault((dst, src),
                                         bytearray()).extend(fragment)
    items = [(dst, src, bytes(payload))
             for (dst, src), payload in inbox_acc.items()]
    all_fn = getattr(receive, 'all', None)
    if all_fn is not None:
        all_fn(items)
    else:
        for dst, src, payload in items:
            receive(dst, src, payload)
    # the GLOBAL count, identical on every controller: callers may branch
    # on it (the driver's lock-step break) — a process-local count here
    # would desync the round loops and deadlock the next collective
    return global_sent


def drive_pairwise_sync_multihost(mesh, axis, local_docs, backend_module,
                                  max_rounds=None, max_msg=1 << 16,
                                  max_chunks=64):
    """drive_pairwise_sync for a multi-controller mesh: `local_docs` maps
    THIS process's global shard id -> backend doc. Every controller runs
    the same round loop, and each round's agreement allgather carries the
    global sent count, so all controllers break in lock-step as soon as a
    round generates nothing anywhere (an empty round costs only the tiny
    allgather, never the padded all_to_all). Mutates local_docs; returns
    the round count."""
    n = mesh.shape[axis]
    states = {(i, j): backend_module.init_sync_state()
              for i in local_docs for j in range(n) if i != j}
    generate, receive = _pairwise_callbacks(local_docs, states,
                                            backend_module)
    rounds = 0
    for _ in range(max_rounds if max_rounds is not None else 2 * n):
        rounds += 1
        if sync_round_multihost(mesh, axis, generate, receive,
                                max_msg=max_msg,
                                max_chunks=max_chunks) == 0:
            break
    return rounds
