"""Cross-shard change exchange: sync-protocol payload routing on ICI.

The reference's sync protocol is transport-agnostic byte messages
(backend/sync.js; SURVEY.md §2.11) — the application moves them. When the
document fleet itself is sharded across devices/hosts, peer reconciliation
between shards becomes a bulk payload movement problem, and the idiomatic
TPU transport is an XLA collective riding ICI rather than a host-side mesh
of sockets: every shard contributes, for every other shard, the concatenated
change buffers (or sync messages) destined there, and one `all_to_all`
delivers every shard its inbox in a single collective (SURVEY.md §5
"per-peer change exchange becomes an all-to-all of change buffers").

Payloads are ragged bytes; they ride as a padded uint8 tensor
[n_shards_out, max_len] per shard with a length vector. The collective
moves bytes only — hashing/causal gating stays host-side per shard, exactly
like the reference's split between transport and protocol.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pack_outboxes(per_dest_payloads, max_len=None):
    """per_dest_payloads: list over destination shards of bytes objects
    (b'' for none). Returns (data uint8 [n_dest, max_len], lens int32)."""
    n = len(per_dest_payloads)
    max_len = max_len if max_len is not None else \
        max((len(p) for p in per_dest_payloads), default=0)
    max_len = max(max_len, 1)
    data = np.zeros((n, max_len), dtype=np.uint8)
    lens = np.zeros((n,), dtype=np.int32)
    for d, payload in enumerate(per_dest_payloads):
        buf = np.frombuffer(bytes(payload), dtype=np.uint8)
        data[d, :len(buf)] = buf
        lens[d] = len(buf)
    return data, lens


def unpack_inbox(data, lens):
    """Inverse of pack_outboxes after the exchange: list over source shards
    of bytes."""
    data = np.asarray(data)
    lens = np.asarray(lens)
    return [data[s, :int(lens[s])].tobytes() for s in range(data.shape[0])]


def exchange_changes(mesh, axis, all_outboxes, all_lens):
    """One collective round of shard-to-shard payload delivery.

    all_outboxes: [n_shards, n_shards, L] uint8, where row i column j holds
    shard i's payload for shard j (host-assembled, then sharded over the
    first axis so each device owns its outbox row). Returns
    (inboxes [n_shards, n_shards, L], in_lens) where row j column i is the
    payload shard j received from shard i — one all_to_all on ICI plus the
    matching length exchange."""
    try:
        from jax import shard_map
    except ImportError:           # older jax
        from jax.experimental.shard_map import shard_map

    n = mesh.shape[axis]
    spec_data = P(axis, None, None)
    spec_lens = P(axis, None)

    @jax.jit
    def run(data, lens):
        def body(data, lens):
            # shard view: [1, n, L]; exchange rows over the peer axis so
            # each shard ends with [from_peer, L] — one tiled all_to_all
            out = jax.lax.all_to_all(data[0], axis, split_axis=0,
                                     concat_axis=0, tiled=True)
            out_lens = jax.lax.all_to_all(lens[0], axis, split_axis=0,
                                          concat_axis=0, tiled=True)
            return out[None], out_lens[None]

        return shard_map(body, mesh=mesh,
                         in_specs=(spec_data, spec_lens),
                         out_specs=(spec_data, spec_lens))(data, lens)

    data = jax.device_put(jnp.asarray(all_outboxes),
                          NamedSharding(mesh, spec_data))
    lens = jax.device_put(jnp.asarray(all_lens),
                          NamedSharding(mesh, spec_lens))
    return run(data, lens)


def sync_round_sharded(mesh, axis, backends, sync_states, generate, receive):
    """Drive one full sync round between every ordered pair of shards, with
    message transport on the device mesh: each shard generates its per-peer
    sync messages host-side (`generate(src, dst) -> bytes | None`), the
    payload matrix rides ONE all_to_all, and `receive(dst, src, payload)`
    applies what arrived. Returns the number of non-empty payloads moved."""
    n = mesh.shape[axis]
    rows, row_lens = [], []
    for src in range(n):
        payloads = []
        for dst in range(n):
            msg = generate(src, dst) if dst != src else None
            payloads.append(msg or b'')
        data, lens = pack_outboxes(payloads)
        rows.append(data)
        row_lens.append(lens)
    width = max(r.shape[1] for r in rows)
    outboxes = np.zeros((n, n, width), dtype=np.uint8)
    lens = np.zeros((n, n), dtype=np.int32)
    for src in range(n):
        outboxes[src, :, :rows[src].shape[1]] = rows[src]
        lens[src] = row_lens[src]

    inboxes, in_lens = exchange_changes(mesh, axis, outboxes, lens)
    inboxes = np.asarray(jax.device_get(inboxes))
    in_lens = np.asarray(jax.device_get(in_lens))

    moved = 0
    for dst in range(n):
        for src in range(n):
            length = int(in_lens[dst, src])
            if length:
                receive(dst, src, inboxes[dst, src, :length].tobytes())
                moved += 1
    return moved


def drive_pairwise_sync(mesh, axis, docs, backend_module, max_rounds=None):
    """Converge every ordered pair of shard documents with the mesh as the
    wire: per-pair sync states on host, one all_to_all per round, until a
    round moves nothing (the sync_test.js driver loop, shard-to-shard).
    `backend_module` supplies init_sync_state / generate_sync_message /
    receive_sync_message (host backend or fleet backend — both satisfy the
    Backend contract). Mutates `docs` in place; returns the round count."""
    n = mesh.shape[axis]
    sync_states = {(i, j): backend_module.init_sync_state()
                   for i in range(n) for j in range(n) if i != j}

    def generate(src, dst):
        state, msg = backend_module.generate_sync_message(
            docs[src], sync_states[(src, dst)])
        sync_states[(src, dst)] = state
        return msg

    def receive(dst, src, payload):
        doc, state, _patch = backend_module.receive_sync_message(
            docs[dst], sync_states[(dst, src)], payload)
        docs[dst] = doc
        sync_states[(dst, src)] = state

    rounds = 0
    for _ in range(max_rounds if max_rounds is not None else 2 * n):
        rounds += 1
        if sync_round_sharded(mesh, axis, docs, sync_states,
                              generate, receive) == 0:
            break
    return rounds
