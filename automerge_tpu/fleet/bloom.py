"""Batched Bloom-filter construction and probing for fleet-scale sync.

The sync protocol's per-peer Bloom filter (ref backend/sync.js:38-125:
10 bits/entry, 7 probes, triple hashing over the first 12 bytes of each
change hash) becomes bit-tensor math over the whole fleet: hashes arrive as
[N, H, 3] uint32 words, probe indexes are computed with vectorized triple
hashing, and filters live as bit tensors built with one scatter. Probing is
a gather + reduce. Serialization (`bloom_filter_bytes`) is bit-exact with
the reference's wire format.

Batching across peers of DIFFERING filter sizes uses a flat packed layout:
every peer's filter occupies its exact wire-format byte span inside ONE
concatenated byte vector, with per-row bit offsets and per-row modulo
capacities. A whole fleet's build is therefore ONE device dispatch and a
whole fleet's probe another, regardless of how skewed the per-peer change
counts are — and batch memory stays proportional to real filter bytes (the
old power-of-two size-class buckets cost one dispatch per class, which on
real hardware made the batched sync driver dispatch-bound; round-5 VERDICT
weak #2). Filters cross the host<->device link already in the wire format's
little-bit-order byte packing (8x less transfer than unpacked bools).
"""

import numpy as np
import jax
import jax.numpy as jnp

BITS_PER_ENTRY = 10
NUM_PROBES = 7

# Device dispatches issued by the batched build/probe entry points since
# import — the sync driver's equivalent of DocFleet.metrics.dispatches
# (the driver runs over host backends, which have no fleet to count on).
# bench.py diffs this around a sync round to report dispatches/round.
_dispatches = 0


def dispatch_count():
    """Monotonic count of batched Bloom device dispatches (build + probe)."""
    return _dispatches


from ..observability import hist as _hist  # noqa: E402
from ..observability import register_dispatch_source  # noqa: E402
from ..observability.perf import instrument_kernel  # noqa: E402
from ..observability.spans import spanned as _spanned  # noqa: E402
register_dispatch_source('bloom', dispatch_count)


def hashes_to_words(hashes_hex):
    """Convert a list of hash lists (hex strings) into an [N, H, 3] uint32
    array of the first three little-endian words of each hash, padded with
    an all-ones sentinel row mask. Returns (words, valid_mask).

    One C-level hex decode + reshape for the whole fleet instead of a
    per-hash fromhex/frombuffer pair (this fed every Bloom build)."""
    n = len(hashes_hex)
    counts = np.fromiter(map(len, hashes_hex), dtype=np.int64, count=n)
    h = int(counts.max()) if n else 0
    words = np.zeros((n, max(h, 1), 3), dtype=np.uint32)
    valid = np.zeros((n, max(h, 1)), dtype=bool)
    total = int(counts.sum())
    if total:
        raw = np.frombuffer(
            bytes.fromhex(''.join(h for row in hashes_hex for h in row)),
            dtype=np.uint8).reshape(total, 32)
        w3 = raw[:, :12].copy().view('<u4').reshape(total, 3)
        rows = np.repeat(np.arange(n), counts)
        starts = np.cumsum(counts) - counts
        cols = np.arange(total) - starts[rows]
        words[rows, cols] = w3
        valid[rows, cols] = True
    return words, valid


def _probe_indexes(words, num_bits):
    """Triple hashing (Dillinger & Manolios): probe p = (x + p*y + C(p)*z)
    mod m, computed iteratively as in the reference (ref sync.js:88-102).
    `num_bits` may be a scalar (all rows share one capacity) or a [N, 1]
    array (per-row capacities, for batching filters of differing sizes)."""
    modulo = jnp.asarray(num_bits, dtype=jnp.uint32)
    x = words[..., 0] % modulo
    y = words[..., 1] % modulo
    z = words[..., 2] % modulo
    probes = [x]
    for _ in range(1, NUM_PROBES):
        x = (x + y) % modulo
        y = (y + z) % modulo
        probes.append(x)
    return jnp.stack(probes, axis=-1).astype(jnp.int32)  # [N, H, NUM_PROBES]


def num_filter_bits(num_entries):
    """Bit capacity of a filter with the reference's sizing rule (always a
    whole number of bytes)."""
    return 8 * ((num_entries * BITS_PER_ENTRY + 7) // 8)


def build_bloom_filters(words, valid, num_entries):
    """Build [N, B] bool filters for N peers, each over `num_entries` hashes
    ([N, H] padded with `valid` mask). All peers share the same B (sized for
    the max entry count) so the fleet batches into one tensor."""
    n_docs = words.shape[0]
    n_bits = max(num_filter_bits(num_entries), 8)
    bits = jnp.zeros((n_docs, n_bits), dtype=bool)
    row_bits = jnp.full((n_docs,), n_bits, dtype=jnp.uint32)
    return _build_varsize(jnp.asarray(words), jnp.asarray(valid), row_bits,
                          bits)


def probe_bloom_filters(bits, words, valid):
    """Probe [N, H] hashes against [N, B] filters; returns [N, H] bool
    (True = possibly contained)."""
    n_docs, n_bits = bits.shape
    row_bits = jnp.full((n_docs,), n_bits, dtype=jnp.uint32)
    return _probe_varsize(jnp.asarray(bits), row_bits, jnp.asarray(words),
                          jnp.asarray(valid))


def _append_filter_header(out, num_entries):
    """THE wire-format filter header (ref sync.js:67-76): explicit
    parameters ahead of the packed bits — shared by the single-row and
    batched serializers so the two cannot drift."""
    from ..encoding import uleb_append
    uleb_append(out, num_entries)
    out.append(BITS_PER_ENTRY)
    out.append(NUM_PROBES)


def bloom_filter_bytes(bits_row, num_entries):
    """Serialize one filter row ([B] bool) to the reference wire format
    (ref sync.js:67-76): explicit parameters + little-bit-order packed bits.

    The row must have been built with a filter sized for exactly
    `num_entries` (probe indexes are modulo the bit capacity, so truncating
    a larger filter would corrupt it into false negatives). Batch peers of
    differing entry counts into separate build_bloom_filters calls."""
    if num_entries == 0:
        return b''
    bits_row = np.asarray(bits_row)
    if bits_row.shape[-1] != num_filter_bits(num_entries):
        raise ValueError(
            f'filter row has {bits_row.shape[-1]} bits but num_entries='
            f'{num_entries} requires {num_filter_bits(num_entries)}; '
            f'serialize only rows built with matching sizing')
    # direct uleb bytes (the Encoder round-trip showed up at fleet scale)
    out = bytearray()
    _append_filter_header(out, num_entries)
    n_bytes = (num_entries * BITS_PER_ENTRY + 7) // 8
    packed = np.packbits(bits_row, bitorder='little')[:n_bytes]
    out += packed.tobytes()
    return bytes(out)


# ---- Variable-size batching -----------------------------------------------
# Peers generally have different change counts, hence different filter bit
# capacities (the reference sizes each filter by its entry count,
# sync.js:44-47). The uniform [N, B] build/probe pair below pads rows to the
# widest filter and takes the modulo per row; the flat packed pair after it
# concatenates every filter's exact byte span instead, so ONE dispatch
# covers arbitrarily skewed fleets without padding-driven memory blowup.

def _build_varsize(words, valid, row_bits, bits_init):
    n_rows, n_bits_max = bits_init.shape
    probes = _probe_indexes(words, row_bits[:, None])
    row_idx = jnp.broadcast_to(
        jnp.arange(n_rows, dtype=jnp.int32)[:, None, None], probes.shape)
    probes = jnp.where(valid[..., None], probes, n_bits_max)
    return bits_init.at[row_idx, probes].set(True, mode='drop')


def _probe_varsize(bits, row_bits, words, valid):
    n_rows, _ = bits.shape
    probes = _probe_indexes(words, row_bits[:, None])
    row_idx = jnp.broadcast_to(
        jnp.arange(n_rows, dtype=jnp.int32)[:, None, None], probes.shape)
    hit = bits[row_idx, probes]
    return jnp.all(hit, axis=-1) & valid


# Flat packed layout: filter i owns bits [bit_off[i], bit_off[i] +
# row_bits[i]) of one flat bit vector (byte-aligned: num_filter_bits is a
# whole number of bytes by construction). Build scatters every probe of
# every row into the flat vector and bit-packs it on device; probe gathers
# packed bytes through the same offsets. Row axes and the flat length are
# pow2-padded by the callers so JIT recompiles stay O(log fleet size).

def _build_flat_packed(words, valid, row_bits, bit_off, total_bits):
    # total_bits is static and byte-aligned; padded/invalid lanes scatter
    # out of range and drop
    assert total_bits % 8 == 0, 'flat filter layout must be byte-aligned'
    probes = _probe_indexes(words, row_bits[:, None])
    idx = bit_off[:, None, None] + probes
    idx = jnp.where(valid[..., None], idx, total_bits)
    bits = jnp.zeros((total_bits,), dtype=bool).at[idx].set(True,
                                                            mode='drop')
    weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], dtype=jnp.uint8)
    return jnp.sum(bits.reshape(total_bits // 8, 8).astype(jnp.uint8)
                   * weights, axis=-1, dtype=jnp.uint8)


def _probe_flat_packed(flat, row_bits, byte_off, words, valid):
    probes = _probe_indexes(words, row_bits[:, None])
    byte = flat[byte_off[:, None, None] + (probes >> 3)].astype(jnp.int32)
    hit = ((byte >> (probes & 7)) & 1) == 1
    return jnp.all(hit, axis=-1) & valid


# jit + ledger wrap at definition (plain calls instead of decorators so
# the cost-ledger wrapper composes with static_argnums cleanly):
_build_varsize = instrument_kernel(
    'bloom_build_varsize', jax.jit(_build_varsize))
_probe_varsize = instrument_kernel(
    'bloom_probe_varsize', jax.jit(_probe_varsize))
_build_flat_packed = instrument_kernel(
    'bloom_build_flat_packed',
    jax.jit(_build_flat_packed, static_argnums=(4,)))
_probe_flat_packed = instrument_kernel(
    'bloom_probe_flat_packed', jax.jit(_probe_flat_packed))


def _pow2(n, floor=1):
    out = max(int(floor), 1)
    n = int(n)
    while out < n:
        out *= 2
    return out


def _pad_rows(words, valid, row_bits, offs, pad_off):
    """Pad the row axis to a power of two (bounds JIT recompiles): padded
    rows carry no valid hashes, an inert 8-bit capacity (the modulo must
    never be zero), and the caller's out-of-range/zero offset."""
    n = len(row_bits)
    n_pad = _pow2(n, floor=8)
    if n_pad == n:
        return words, valid, row_bits, offs
    h = words.shape[1]
    words = np.concatenate(
        [words, np.zeros((n_pad - n, h, 3), dtype=words.dtype)])
    valid = np.concatenate(
        [valid, np.zeros((n_pad - n, h), dtype=bool)])
    row_bits = np.concatenate(
        [row_bits, np.full(n_pad - n, 8, dtype=row_bits.dtype)])
    offs = np.concatenate(
        [offs, np.full(n_pad - n, pad_off, dtype=offs.dtype)])
    return words, valid, row_bits, offs


def _pad_hash_axis(words, valid):
    """Pad the hash axis to a power of two (bounds JIT recompiles)."""
    n, h, _ = words.shape
    h_pad = _pow2(h, floor=8)
    if h_pad == h:
        return words, valid
    words = np.concatenate(
        [words, np.zeros((n, h_pad - h, 3), dtype=words.dtype)], axis=1)
    valid = np.concatenate(
        [valid, np.zeros((n, h_pad - h), dtype=bool)], axis=1)
    return words, valid


@_spanned('bloom_build')
def build_bloom_filters_batch_begin(hash_lists):
    """Issue THE device dispatch for `build_bloom_filters_batch` without
    blocking on its result (JAX dispatch is async). Returns an opaque
    handle for `build_bloom_filters_batch_finish`; host work interleaved
    between begin and finish overlaps with the device build. One dispatch
    regardless of how peers' entry counts are distributed."""
    global _dispatches
    entry_counts = [len(row) for row in hash_lists]
    live = [i for i, n in enumerate(entry_counts) if n > 0]
    # fabric fan-in visibility: how many peer links each fused build
    # actually carried (the sync_fabric bench and obs_report read the
    # histogram to confirm rounds stay fused as the link count grows)
    if _hist.on():
        _hist.record_value('bloom_fused_links', len(live), unit='links')
    if not live:
        return len(hash_lists), entry_counts, live, None, None
    words, valid = hashes_to_words([hash_lists[i] for i in live])
    words, valid = _pad_hash_axis(words, valid)
    byte_counts = np.array([num_filter_bits(entry_counts[i]) // 8
                            for i in live], dtype=np.int64)
    byte_off = np.cumsum(byte_counts) - byte_counts
    row_bits = (byte_counts * 8).astype(np.uint32)
    total_bits = _pow2(int(byte_counts.sum()) * 8, floor=64)
    words, valid, row_bits, bit_off = _pad_rows(
        words, valid, row_bits, byte_off * 8, pad_off=total_bits)
    packed = _build_flat_packed(jnp.asarray(words), jnp.asarray(valid),
                                jnp.asarray(row_bits), jnp.asarray(bit_off),
                                total_bits)
    _dispatches += 1
    return len(hash_lists), entry_counts, live, byte_off, packed


@_spanned('bloom_build_wait')
def build_bloom_filters_batch_finish(handle):
    """Materialize a `build_bloom_filters_batch_begin` handle into the list
    of wire-format filter bytes."""
    n, entry_counts, live, byte_off, packed = handle
    out = [b''] * n
    if packed is None:
        return out
    arr = np.asarray(packed)
    for k, i in enumerate(live):
        num_entries = entry_counts[i]
        row = bytearray()
        _append_filter_header(row, num_entries)
        n_bytes = (num_entries * BITS_PER_ENTRY + 7) // 8
        off = int(byte_off[k])
        row += arr[off:off + n_bytes].tobytes()
        out[i] = bytes(row)
    return out


def build_bloom_filters_batch(hash_lists):
    """Build one wire-format Bloom filter per hash list — ONE device
    dispatch for the whole batch despite differing entry counts (flat
    packed layout; memory proportional to real filter bytes). Returns a
    list of `bytes` (b'' for empty lists), byte-identical to the host
    BloomFilter."""
    return build_bloom_filters_batch_finish(
        build_bloom_filters_batch_begin(hash_lists))


@_spanned('bloom_probe')
def probe_bloom_filters_batch_begin(filter_bytes, hash_lists):
    """Issue THE device dispatch for `probe_bloom_filters_batch` without
    blocking (filters are uploaded in their packed wire-format bytes, not
    unpacked bools, concatenated into one flat byte vector). Returns a
    handle for `probe_bloom_filters_batch_finish`."""
    global _dispatches
    from ..encoding import Decoder
    out = [[False] * len(row) for row in hash_lists]
    rows = []          # (orig index, packed byte array, n_bits)
    for i, fb in enumerate(filter_bytes):
        if not fb or not hash_lists[i]:
            continue
        try:
            from ..backend.sync import read_filter_header
            decoder = Decoder(bytes(fb))
            num_entries, bits_per_entry, num_probes, n_bytes = \
                read_filter_header(decoder)
            if num_entries == 0:
                continue
            if bits_per_entry != BITS_PER_ENTRY or num_probes != NUM_PROBES:
                # The wire format carries these so they can vary
                # (sync.js:68-76); nonstandard peers fall back to the
                # generic host filter rather than failing the whole batch
                from ..backend.sync import BloomFilter
                host = BloomFilter(bytes(fb))
                out[i] = [host.contains_hash(h) for h in hash_lists[i]]
                continue
            raw = decoder.read_raw_bytes(n_bytes)
        except Exception:
            # Corrupt filter bytes read as all-False ("peer has nothing":
            # resend everything) instead of aborting the other N-1 docs'
            # probes — same containment rule as the host path's
            # probe_filter_lenient; the shared counter records it
            from ..backend.sync import _wire_stats
            _wire_stats.inc('rejected_filters')
            continue
        rows.append((i, np.frombuffer(raw, dtype=np.uint8), 8 * len(raw)))
    if _hist.on():
        _hist.record_value('bloom_fused_probe_links', len(rows), unit='links')
    if not rows:
        return out, hash_lists, None, None
    words, valid = hashes_to_words([hash_lists[i] for i, _, _ in rows])
    words, valid = _pad_hash_axis(words, valid)
    byte_counts = np.array([len(raw) for _, raw, _ in rows], dtype=np.int64)
    byte_off = np.cumsum(byte_counts) - byte_counts
    total_bytes = _pow2(int(byte_counts.sum()), floor=8)
    flat = np.zeros(total_bytes, dtype=np.uint8)
    for k, (_, raw, _) in enumerate(rows):
        flat[byte_off[k]:byte_off[k] + len(raw)] = raw
    row_bits = np.array([n for _, _, n in rows], dtype=np.uint32)
    words, valid, row_bits, byte_off_p = _pad_rows(
        words, valid, row_bits, byte_off, pad_off=0)
    hit = _probe_flat_packed(jnp.asarray(flat), jnp.asarray(row_bits),
                             jnp.asarray(byte_off_p), jnp.asarray(words),
                             jnp.asarray(valid))
    _dispatches += 1
    return out, hash_lists, rows, hit


@_spanned('bloom_probe_wait')
def probe_bloom_filters_batch_finish(handle):
    """Materialize a `probe_bloom_filters_batch_begin` handle into the
    per-row lists of probe results."""
    out, hash_lists, rows, hit = handle
    if rows is None:
        return out
    hit = np.asarray(hit)
    for k, (i, _, _) in enumerate(rows):
        out[i] = [bool(h) for h in hit[k, :len(hash_lists[i])]]
    return out


def probe_bloom_filters_batch(filter_bytes, hash_lists):
    """Probe each row's hashes against that row's wire-format filter, all
    rows in ONE device dispatch (flat packed layout). `filter_bytes[i]` is
    a serialized filter (b'' = empty: contains nothing); `hash_lists[i]`
    the hex hashes to test. Returns a list of lists of bool (True =
    possibly contained)."""
    return probe_bloom_filters_batch_finish(
        probe_bloom_filters_batch_begin(filter_bytes, hash_lists))
