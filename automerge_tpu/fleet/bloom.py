"""Batched Bloom-filter construction and probing for fleet-scale sync.

The sync protocol's per-peer Bloom filter (ref backend/sync.js:38-125:
10 bits/entry, 7 probes, triple hashing over the first 12 bytes of each
change hash) becomes bit-tensor math over the whole fleet: hashes arrive as
[N, H, 3] uint32 words, probe indexes are computed with vectorized triple
hashing, and filters live as an [N, B] bool tensor built with one scatter.
Probing is a gather + reduce. Serialization (`bloom_filter_bytes`) is
bit-exact with the reference's wire format.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..encoding import Encoder

BITS_PER_ENTRY = 10
NUM_PROBES = 7


def hashes_to_words(hashes_hex):
    """Convert a list of hash lists (hex strings) into an [N, H, 3] uint32
    array of the first three little-endian words of each hash, padded with
    an all-ones sentinel row mask. Returns (words, valid_mask).

    One C-level hex decode + reshape for the whole fleet instead of a
    per-hash fromhex/frombuffer pair (this fed every Bloom build)."""
    n = len(hashes_hex)
    counts = np.fromiter(map(len, hashes_hex), dtype=np.int64, count=n)
    h = int(counts.max()) if n else 0
    words = np.zeros((n, max(h, 1), 3), dtype=np.uint32)
    valid = np.zeros((n, max(h, 1)), dtype=bool)
    total = int(counts.sum())
    if total:
        raw = np.frombuffer(
            bytes.fromhex(''.join(h for row in hashes_hex for h in row)),
            dtype=np.uint8).reshape(total, 32)
        w3 = raw[:, :12].copy().view('<u4').reshape(total, 3)
        rows = np.repeat(np.arange(n), counts)
        starts = np.cumsum(counts) - counts
        cols = np.arange(total) - starts[rows]
        words[rows, cols] = w3
        valid[rows, cols] = True
    return words, valid


def _probe_indexes(words, num_bits):
    """Triple hashing (Dillinger & Manolios): probe p = (x + p*y + C(p)*z)
    mod m, computed iteratively as in the reference (ref sync.js:88-102).
    `num_bits` may be a scalar (all rows share one capacity) or a [N, 1]
    array (per-row capacities, for batching filters of differing sizes)."""
    modulo = jnp.asarray(num_bits, dtype=jnp.uint32)
    x = words[..., 0] % modulo
    y = words[..., 1] % modulo
    z = words[..., 2] % modulo
    probes = [x]
    for _ in range(1, NUM_PROBES):
        x = (x + y) % modulo
        y = (y + z) % modulo
        probes.append(x)
    return jnp.stack(probes, axis=-1).astype(jnp.int32)  # [N, H, NUM_PROBES]


def num_filter_bits(num_entries):
    """Bit capacity of a filter with the reference's sizing rule."""
    return 8 * ((num_entries * BITS_PER_ENTRY + 7) // 8)


def build_bloom_filters(words, valid, num_entries):
    """Build [N, B] bool filters for N peers, each over `num_entries` hashes
    ([N, H] padded with `valid` mask). All peers share the same B (sized for
    the max entry count) so the fleet batches into one tensor."""
    n_docs = words.shape[0]
    n_bits = max(num_filter_bits(num_entries), 8)
    bits = jnp.zeros((n_docs, n_bits), dtype=bool)
    row_bits = jnp.full((n_docs,), n_bits, dtype=jnp.uint32)
    return _build_varsize(jnp.asarray(words), jnp.asarray(valid), row_bits,
                          bits)


def probe_bloom_filters(bits, words, valid):
    """Probe [N, H] hashes against [N, B] filters; returns [N, H] bool
    (True = possibly contained)."""
    n_docs, n_bits = bits.shape
    row_bits = jnp.full((n_docs,), n_bits, dtype=jnp.uint32)
    return _probe_varsize(jnp.asarray(bits), row_bits, jnp.asarray(words),
                          jnp.asarray(valid))


def bloom_filter_bytes(bits_row, num_entries):
    """Serialize one filter row ([B] bool) to the reference wire format
    (ref sync.js:67-76): explicit parameters + little-bit-order packed bits.

    The row must have been built with a filter sized for exactly
    `num_entries` (probe indexes are modulo the bit capacity, so truncating
    a larger filter would corrupt it into false negatives). Batch peers of
    differing entry counts into separate build_bloom_filters calls."""
    if num_entries == 0:
        return b''
    bits_row = np.asarray(bits_row)
    if bits_row.shape[-1] != num_filter_bits(num_entries):
        raise ValueError(
            f'filter row has {bits_row.shape[-1]} bits but num_entries='
            f'{num_entries} requires {num_filter_bits(num_entries)}; '
            f'serialize only rows built with matching sizing')
    # direct uleb bytes (the Encoder round-trip showed up at fleet scale)
    from ..encoding import uleb_append
    out = bytearray()
    uleb_append(out, num_entries)
    out.append(BITS_PER_ENTRY)
    out.append(NUM_PROBES)
    n_bytes = (num_entries * BITS_PER_ENTRY + 7) // 8
    packed = np.packbits(bits_row, bitorder='little')[:n_bytes]
    out += packed.tobytes()
    return bytes(out)


# ---- Variable-size batching -----------------------------------------------
# Peers generally have different change counts, hence different filter bit
# capacities (the reference sizes each filter by its entry count,
# sync.js:44-47). Padding rows to the widest filter and taking the modulo
# per row (the [N, 1] form of `_probe_indexes`' num_bits) keeps the whole
# fleet in ONE build dispatch / ONE probe dispatch.

@jax.jit
def _build_varsize(words, valid, row_bits, bits_init):
    n_rows, n_bits_max = bits_init.shape
    probes = _probe_indexes(words, row_bits[:, None])
    row_idx = jnp.broadcast_to(
        jnp.arange(n_rows, dtype=jnp.int32)[:, None, None], probes.shape)
    probes = jnp.where(valid[..., None], probes, n_bits_max)
    return bits_init.at[row_idx, probes].set(True, mode='drop')


@jax.jit
def _probe_varsize(bits, row_bits, words, valid):
    n_rows, _ = bits.shape
    probes = _probe_indexes(words, row_bits[:, None])
    row_idx = jnp.broadcast_to(
        jnp.arange(n_rows, dtype=jnp.int32)[:, None, None], probes.shape)
    hit = bits[row_idx, probes]
    return jnp.all(hit, axis=-1) & valid


# Batched filters cross the host<->device link in the wire format's own
# little-bit-order byte packing (8x less transfer than [N, bits] bool — the
# link, tunneled or PCIe, was the dominant cost of the batched sync driver
# on real hardware) and the packing/unpacking runs on device.

@jax.jit
def _build_varsize_packed(words, valid, row_bits, bits_init):
    bits = _build_varsize(words, valid, row_bits, bits_init)
    n_rows, n_bits = bits.shape
    weights = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], dtype=jnp.uint8)
    return jnp.sum(bits.reshape(n_rows, n_bits // 8, 8).astype(jnp.uint8)
                   * weights, axis=-1, dtype=jnp.uint8)


@jax.jit
def _probe_varsize_packed(packed, row_bits, words, valid):
    n_rows, _ = packed.shape
    probes = _probe_indexes(words, row_bits[:, None])
    row_idx = jnp.broadcast_to(
        jnp.arange(n_rows, dtype=jnp.int32)[:, None, None], probes.shape)
    byte = packed[row_idx, probes >> 3].astype(jnp.int32)
    hit = ((byte >> (probes & 7)) & 1) == 1
    return jnp.all(hit, axis=-1) & valid


def _size_class(n_bits):
    """Power-of-two padding class: keeps batch memory proportional to real
    filter bytes under skewed per-peer change counts (one huge peer must not
    inflate every row to its width) and bounds JIT recompiles to one shape
    per class."""
    return 1 << max(int(n_bits) - 1, 1).bit_length()


def build_bloom_filters_batch_begin(hash_lists):
    """Issue the device dispatches for `build_bloom_filters_batch` without
    blocking on their results (JAX dispatch is async). Returns an opaque
    handle for `build_bloom_filters_batch_finish`; host work interleaved
    between begin and finish overlaps with the device build."""
    entry_counts = [len(row) for row in hash_lists]
    classes = {}
    for i, n in enumerate(entry_counts):
        if n > 0:
            classes.setdefault(_size_class(num_filter_bits(n)),
                               []).append(i)
    pending = []
    for width, live in sorted(classes.items()):
        words, valid = hashes_to_words([hash_lists[i] for i in live])
        row_bits = np.array([num_filter_bits(entry_counts[i])
                             for i in live], dtype=np.uint32)
        bits = jnp.zeros((len(live), width), dtype=bool)
        packed = _build_varsize_packed(
            jnp.asarray(words), jnp.asarray(valid), jnp.asarray(row_bits),
            bits)
        pending.append((live, packed))
    return len(hash_lists), entry_counts, pending


def build_bloom_filters_batch_finish(handle):
    """Materialize a `build_bloom_filters_batch_begin` handle into the list
    of wire-format filter bytes."""
    from ..encoding import uleb_append
    n, entry_counts, pending = handle
    out = [b''] * n
    for live, packed in pending:
        arr = np.asarray(packed)
        for k, i in enumerate(live):
            num_entries = entry_counts[i]
            row = bytearray()
            uleb_append(row, num_entries)
            row.append(BITS_PER_ENTRY)
            row.append(NUM_PROBES)
            n_bytes = (num_entries * BITS_PER_ENTRY + 7) // 8
            row += arr[k, :n_bytes].tobytes()
            out[i] = bytes(row)
    return out


def build_bloom_filters_batch(hash_lists):
    """Build one wire-format Bloom filter per hash list, batched into one
    device dispatch per power-of-two size class despite differing entry
    counts. Returns a list of `bytes` (b'' for empty lists), byte-identical
    to the host BloomFilter."""
    return build_bloom_filters_batch_finish(
        build_bloom_filters_batch_begin(hash_lists))


def probe_bloom_filters_batch_begin(filter_bytes, hash_lists):
    """Issue the device dispatches for `probe_bloom_filters_batch` without
    blocking (filters are uploaded in their packed wire-format bytes, not
    unpacked bools). Returns a handle for
    `probe_bloom_filters_batch_finish`."""
    from ..encoding import Decoder
    out = [[False] * len(row) for row in hash_lists]
    rows = []          # (orig index, packed byte array, n_bits)
    for i, fb in enumerate(filter_bytes):
        if not fb or not hash_lists[i]:
            continue
        decoder = Decoder(bytes(fb))
        num_entries = decoder.read_uint32()
        bits_per_entry = decoder.read_uint32()
        num_probes = decoder.read_uint32()
        if num_entries == 0:
            continue
        if bits_per_entry != BITS_PER_ENTRY or num_probes != NUM_PROBES:
            # The wire format carries these so they can vary (sync.js:68-76);
            # nonstandard peers fall back to the generic host filter rather
            # than failing the whole batch
            from ..backend.sync import BloomFilter
            host = BloomFilter(bytes(fb))
            out[i] = [host.contains_hash(h) for h in hash_lists[i]]
            continue
        raw = decoder.read_raw_bytes(
            (num_entries * bits_per_entry + 7) // 8)
        rows.append((i, np.frombuffer(raw, dtype=np.uint8), 8 * len(raw)))
    classes = {}
    for row in rows:
        classes.setdefault(_size_class(row[2]), []).append(row)
    pending = []
    for width, group in sorted(classes.items()):
        words, valid = hashes_to_words([hash_lists[i] for i, _, _ in group])
        packed = np.zeros((len(group), width // 8), dtype=np.uint8)
        for k, (_, raw, _) in enumerate(group):
            packed[k, :len(raw)] = raw
        row_bits = np.array([n for _, _, n in group], dtype=np.uint32)
        hit = _probe_varsize_packed(
            jnp.asarray(packed), jnp.asarray(row_bits), jnp.asarray(words),
            jnp.asarray(valid))
        pending.append((group, hit))
    return out, hash_lists, pending


def probe_bloom_filters_batch_finish(handle):
    """Materialize a `probe_bloom_filters_batch_begin` handle into the
    per-row lists of probe results."""
    out, hash_lists, pending = handle
    for group, hit in pending:
        hit = np.asarray(hit)
        for k, (i, _, _) in enumerate(group):
            out[i] = [bool(h) for h in hit[k, :len(hash_lists[i])]]
    return out


def probe_bloom_filters_batch(filter_bytes, hash_lists):
    """Probe each row's hashes against that row's wire-format filter, all
    rows in one device dispatch per size class. `filter_bytes[i]` is a
    serialized filter (b'' = empty: contains nothing); `hash_lists[i]` the
    hex hashes to test. Returns a list of lists of bool (True = possibly
    contained)."""
    return probe_bloom_filters_batch_finish(
        probe_bloom_filters_batch_begin(filter_bytes, hash_lists))
