"""Batched Bloom-filter construction and probing for fleet-scale sync.

The sync protocol's per-peer Bloom filter (ref backend/sync.js:38-125:
10 bits/entry, 7 probes, triple hashing over the first 12 bytes of each
change hash) becomes bit-tensor math over the whole fleet: hashes arrive as
[N, H, 3] uint32 words, probe indexes are computed with vectorized triple
hashing, and filters live as an [N, B] bool tensor built with one scatter.
Probing is a gather + reduce. Serialization (`bloom_filter_bytes`) is
bit-exact with the reference's wire format.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..encoding import Encoder

BITS_PER_ENTRY = 10
NUM_PROBES = 7


def hashes_to_words(hashes_hex):
    """Convert a list of hash lists (hex strings) into an [N, H, 3] uint32
    array of the first three little-endian words of each hash, padded with
    an all-ones sentinel row mask. Returns (words, valid_mask)."""
    n = len(hashes_hex)
    h = max((len(row) for row in hashes_hex), default=0)
    words = np.zeros((n, max(h, 1), 3), dtype=np.uint32)
    valid = np.zeros((n, max(h, 1)), dtype=bool)
    for i, row in enumerate(hashes_hex):
        for j, hash in enumerate(row):
            raw = bytes.fromhex(hash)[:12]
            words[i, j] = np.frombuffer(raw, dtype='<u4')
            valid[i, j] = True
    return words, valid


def _probe_indexes(words, num_bits):
    """Triple hashing (Dillinger & Manolios): probe p = (x + p*y + C(p)*z)
    mod m, computed iteratively as in the reference (ref sync.js:88-102)."""
    modulo = jnp.asarray(num_bits, dtype=jnp.uint32)
    x = words[..., 0] % modulo
    y = words[..., 1] % modulo
    z = words[..., 2] % modulo
    probes = [x]
    for _ in range(1, NUM_PROBES):
        x = (x + y) % modulo
        y = (y + z) % modulo
        probes.append(x)
    return jnp.stack(probes, axis=-1).astype(jnp.int32)  # [N, H, NUM_PROBES]


def num_filter_bits(num_entries):
    """Bit capacity of a filter with the reference's sizing rule."""
    return 8 * ((num_entries * BITS_PER_ENTRY + 7) // 8)


@jax.jit
def _build(words, valid, bits_init):
    n_docs, n_bits = bits_init.shape
    probes = _probe_indexes(words, n_bits)  # [N, H, P]
    doc_idx = jnp.broadcast_to(
        jnp.arange(n_docs, dtype=jnp.int32)[:, None, None], probes.shape)
    # Invalid hash lanes scatter out of range and are dropped
    probes = jnp.where(valid[..., None], probes, n_bits)
    return bits_init.at[doc_idx, probes].set(True, mode='drop')


def build_bloom_filters(words, valid, num_entries):
    """Build [N, B] bool filters for N peers, each over `num_entries` hashes
    ([N, H] padded with `valid` mask). All peers share the same B (sized for
    the max entry count) so the fleet batches into one tensor."""
    n_docs = words.shape[0]
    n_bits = max(num_filter_bits(num_entries), 8)
    bits = jnp.zeros((n_docs, n_bits), dtype=bool)
    return _build(jnp.asarray(words), jnp.asarray(valid), bits)


@jax.jit
def probe_bloom_filters(bits, words, valid):
    """Probe [N, H] hashes against [N, B] filters; returns [N, H] bool
    (True = possibly contained)."""
    n_docs, n_bits = bits.shape
    probes = _probe_indexes(jnp.asarray(words), n_bits)
    doc_idx = jnp.broadcast_to(
        jnp.arange(n_docs, dtype=jnp.int32)[:, None, None], probes.shape)
    hit = bits[doc_idx, probes]  # [N, H, P]
    return jnp.all(hit, axis=-1) & jnp.asarray(valid)


def bloom_filter_bytes(bits_row, num_entries):
    """Serialize one filter row ([B] bool) to the reference wire format
    (ref sync.js:67-76): explicit parameters + little-bit-order packed bits.

    The row must have been built with a filter sized for exactly
    `num_entries` (probe indexes are modulo the bit capacity, so truncating
    a larger filter would corrupt it into false negatives). Batch peers of
    differing entry counts into separate build_bloom_filters calls."""
    if num_entries == 0:
        return b''
    bits_row = np.asarray(bits_row)
    if bits_row.shape[-1] != num_filter_bits(num_entries):
        raise ValueError(
            f'filter row has {bits_row.shape[-1]} bits but num_entries='
            f'{num_entries} requires {num_filter_bits(num_entries)}; '
            f'serialize only rows built with matching sizing')
    encoder = Encoder()
    encoder.append_uint32(num_entries)
    encoder.append_uint32(BITS_PER_ENTRY)
    encoder.append_uint32(NUM_PROBES)
    n_bytes = (num_entries * BITS_PER_ENTRY + 7) // 8
    packed = np.packbits(bits_row, bitorder='little')[:n_bytes]
    encoder.append_raw_bytes(packed.tobytes())
    return encoder.buffer
