"""Seeded fault-injection wire for the sync protocol.

The sync drivers treat the transport as a reliable in-order byte pipe —
which the all_to_all collective is, but the "millions of users" north star
(ROADMAP.md) is served over networks that drop, duplicate, reorder,
truncate, and bit-flip. `LossyLink` is the adversarial wire: a seeded
per-message fault injector that wraps any generate/receive message flow and
applies exactly one fault class per message draw, so chaos tests can prove
two containment properties of the Bloom-based protocol
(backend/sync.py):

- LOSS IS SURVIVABLE: a peer that misses a message keeps generating
  (its view of the remote heads stays stale, so `generate_sync_message`
  never goes quiet while heads genuinely differ), and the handshake
  self-heals once the wire delivers again — convergence needs no
  retransmit layer.
- CORRUPTION IS CONTAINED, NEVER PROPAGATED: a truncated/flipped message
  either fails `decode_sync_message` (typed `MalformedSyncMessage`) or
  carries a change whose checksum fails at apply (typed
  `MalformedChange`) — both are equivalent to a drop at the receiver;
  garbage that decodes (flipped hash bytes, corrupt Bloom filters) only
  ever costs extra sends (the lenient filter probe) and repairs through
  the protocol's own need/dependents machinery. No fault class can make
  a healthy replica commit corrupt state, because every change re-hashes
  before it lands.

Faults draw from a dedicated PRNG so a seed fully determines the fault
trace, and an optional `budget` bounds the total faults injected — the
transient-fault network model under which convergence is guaranteed, and
what lets chaos tests assert a hard post-quiet equality instead of a
probabilistic one. Injected-fault totals land in the 'wire_faults' health
counter (observability.health_counts)."""

import random

from ..errors import AutomergeError, SyncStalled
from ..observability import register_health_source
# the light policy module only — service/__init__ loads its core lazily,
# so this import cannot cycle back into fleet/
from ..service.backoff import Backoff

__all__ = ['LossyLink', 'sync_until_quiet']

_FAULT_KINDS = ('dropped', 'duplicated', 'reordered', 'truncated', 'flipped')

_fault_totals = {'injected': 0, 'stall_resets': 0}
register_health_source('wire_faults', lambda: _fault_totals['injected'])
register_health_source('sync_stall_resets',
                       lambda: _fault_totals['stall_resets'])


class LossyLink:
    """One direction of a lossy wire. `transmit(payload)` returns the list
    of payloads the receiver actually sees for this send (possibly empty,
    possibly two, possibly corrupted); `flush()` releases any message still
    held back by an in-flight reorder. Stats count per fault class plus
    sent/delivered totals.

    Beyond the five PER-MESSAGE fault kinds, the link models two
    STATEFUL faults — a peer going dark for K ticks and then healing —
    because a dead peer is a different failure class from per-message
    loss: every message in the window vanishes (no lucky deliveries for
    a retry to ride), and chaos tests of failover need exactly that
    shape. ``tick()`` is the link's clock (drivers call it once per
    sync round); ``partition(k)`` darkens the wire for k ticks (the
    peer is fine, the network is not); ``crash(k)`` darkens it AND
    drops any reorder-held in-flight message (the peer process died —
    everything in its buffers died with it; the caller models the rest
    of a crash by resetting the peer's sync state). ``p_partition``
    draws partitions randomly at transmit time, each lasting
    ``partition_ticks``. One dark WINDOW counts as one injected fault
    (one budget token) in its own stats bucket; the messages it
    swallows are consequences, tallied under ``dark_dropped``."""

    def __init__(self, seed=0, p_drop=0.0, p_dup=0.0, p_reorder=0.0,
                 p_truncate=0.0, p_flip=0.0, budget=None,
                 p_partition=0.0, partition_ticks=8):
        self.rng = random.Random(seed)
        self.p = {'dropped': p_drop, 'duplicated': p_dup,
                  'reordered': p_reorder, 'truncated': p_truncate,
                  'flipped': p_flip}
        self.budget = budget          # None = unbounded fault injection
        self.p_partition = float(p_partition)
        self.partition_ticks = int(partition_ticks)
        self.stats = dict.fromkeys(
            _FAULT_KINDS + ('partitioned', 'crashed', 'dark_dropped',
                            'sent', 'delivered'), 0)
        self._held = None             # message delayed by a reorder fault
        self._ticks = 0               # the link clock (tick())
        self._dark_until = 0          # ticks < this = peer dark

    # -- stateful faults ------------------------------------------------

    @property
    def dark(self):
        """True while a partition/crash window is open."""
        return self._ticks < self._dark_until

    def tick(self):
        """Advance the link clock one round; dark windows heal when the
        clock reaches their end."""
        self._ticks += 1

    def _spend_budget(self):
        if self.budget is not None:
            if self.budget <= 0:
                return False
            self.budget -= 1
        return True

    def _darken(self, ticks, kind):
        if not self._spend_budget():
            return False
        self._dark_until = max(self._dark_until, self._ticks + int(ticks))
        self.stats[kind] += 1
        _fault_totals['injected'] += 1
        return True

    def partition(self, ticks=None):
        """Open (or extend) a partition: the wire is dark for `ticks`
        link ticks, then heals. Returns False when the fault budget is
        dry (no window opened)."""
        return self._darken(ticks if ticks is not None
                            else self.partition_ticks, 'partitioned')

    def crash(self, ticks=None):
        """The peer process dies for `ticks` link ticks: dark wire AND
        any reorder-held in-flight message is lost with the process.
        The caller completes the crash model by resetting the peer's
        sync state when it 'restarts'."""
        ok = self._darken(ticks if ticks is not None
                          else self.partition_ticks, 'crashed')
        if ok:
            self._held = None
        return ok

    def _draw_fault(self):
        """Pick at most one fault class for this message. The PRNG draw
        happens even with an exhausted budget, so the same seed walks the
        same random sequence whatever the budget — traces stay comparable
        across budget settings."""
        roll = self.rng.random()
        acc = 0.0
        for kind in _FAULT_KINDS:
            acc += self.p[kind]
            if roll < acc:
                if not self._spend_budget():
                    return None
                self.stats[kind] += 1
                _fault_totals['injected'] += 1
                return kind
        return None

    def _corrupt(self, payload, kind):
        if kind == 'truncated':
            return payload[:self.rng.randrange(len(payload))] \
                if payload else payload
        # flipped: xor one random bit
        if not payload:
            return payload
        pos = self.rng.randrange(len(payload))
        out = bytearray(payload)
        out[pos] ^= 1 << self.rng.randrange(8)
        return bytes(out)

    def transmit(self, payload):
        """Send one message (None = nothing to send this tick). Returns
        the payloads delivered to the receiver, in arrival order."""
        deliveries = []
        if payload is not None and self.p_partition > 0.0 and \
                not self.dark and self.rng.random() < self.p_partition:
            # a randomly-drawn dark window (the PRNG draw happens only
            # on real sends, so seeded traces stay send-aligned)
            self.partition()
        if payload is not None and self.dark:
            # the peer is dark: the whole send vanishes — no dup, no
            # corruption, no reorder hold, just silence
            self.stats['sent'] += 1
            self.stats['dark_dropped'] += 1
            return []
        if payload is not None:
            payload = bytes(payload)
            self.stats['sent'] += 1
            kind = self._draw_fault()
            if kind == 'dropped':
                payload = None
            elif kind == 'duplicated':
                deliveries.append(payload)
            elif kind in ('truncated', 'flipped'):
                payload = self._corrupt(payload, kind)
            elif kind == 'reordered':
                # hold this message one tick; it arrives AFTER the next
                # send (a delayed packet overtaken by its successor)
                if self._held is None:
                    self._held = payload
                    payload = None
                # a second reorder while one is held releases both swapped
        if payload is not None:
            deliveries.append(payload)
        if self._held is not None and deliveries:
            deliveries.append(self._held)
            self._held = None
        self.stats['delivered'] += len(deliveries)
        return deliveries

    def flush(self):
        """Deliver any message still held by an in-flight reorder (the
        wire draining at end of test)."""
        if self._held is None:
            return []
        out = [self._held]
        self._held = None
        self.stats['delivered'] += 1
        return out


def _deliver(receiver, payloads, quarantined):
    """Feed delivered payloads to a receive callback, treating typed
    failures as drops (containment: the doc-scoped error already rolled
    back whatever the bad bytes touched). Returns True if any payload
    was processed (delivered or quarantined)."""
    progressed = False
    for payload in payloads:
        progressed = True
        try:
            receiver(payload)
        except AutomergeError:
            quarantined[0] += 1
    return progressed


def sync_until_quiet(doc_a, doc_b, backend_a, backend_b, link_ab=None,
                     link_ba=None, max_rounds=256, stall_reset=8,
                     backoff=None):
    """Drive the two-peer sync handshake (the sync_test.js loop) over lossy
    links until both directions go quiet, corruption quarantining as drops.
    `backend_*` follow the Backend contract (generate_sync_message /
    receive_sync_message / init_sync_state).

    Stall recovery: the reference protocol assumes a reliable in-order
    channel — a DROPPED message poisons `sentHashes` (the sender filters
    out changes it believes delivered and never resends them), which
    livelocks the handshake: both sides keep generating forever while
    heads stay split. Real deployments recover by reconnecting with fresh
    sync state, which is safe because change delivery is idempotent; this
    driver models exactly that: `stall_reset` consecutive rounds with
    traffic but no head movement on either side trigger a sync-state
    reset (only `sharedHeads` survives a real reconnect via
    encode_sync_state, and even that is an optimization — the reset here
    drops everything, the worst case). Convergence under loss therefore
    means: protocol + reconnect policy, which is the deployable unit.

    Reconnects follow a bounded JITTERED BACKOFF (`backoff`, a
    service.backoff.Backoff in ROUND units — the same schedule object the
    service retry path uses): reset k+1 requires `stall_reset` plus the
    schedule's (growing, jittered) delay in stalled rounds, so a fleet of
    drivers sharing a flapping wire cannot re-handshake in lockstep.
    Once the schedule is exhausted — or `max_rounds` elapse — the driver
    gives up with a TYPED ``SyncStalled`` (carrying `rounds`, `resets`,
    and the link stats in `detail`): with a fault budget that means a
    real protocol bug, not bad luck.

    Returns (doc_a, doc_b, rounds, stats) with stats carrying
    'quarantined' (corrupt messages contained at the receiver) and
    'resets' (stall recoveries)."""
    if backoff is None:
        # round units: first re-reset after ~stall_reset extra rounds,
        # growing 2x (capped at 8x) — generous retries so bounded-budget
        # fault traces always converge before the typed give-up
        backoff = Backoff(base=stall_reset, factor=2.0,
                          cap=8.0 * stall_reset, retries=16, jitter=0.5,
                          seed=0)
    quarantined = [0]
    resets = 0
    stalled = 0
    reset_wait = stall_reset      # rounds of stall before the next reset
    last_heads = None
    box = {'a': doc_a, 'b': doc_b,
           'sa': backend_a.init_sync_state(),
           'sb': backend_b.init_sync_state()}

    def recv_b(payload):
        box['b'], box['sb'], _ = backend_b.receive_sync_message(
            box['b'], box['sb'], payload)

    def recv_a(payload):
        box['a'], box['sa'], _ = backend_a.receive_sync_message(
            box['a'], box['sa'], payload)

    for rounds in range(1, max_rounds + 1):
        # Duplex round: BOTH sides generate from their current state, then
        # both deliveries land. Generating before delivering matters after
        # a reset — with alternating turns, the second peer would see the
        # first's fresh handshake advertising equal heads and short-circuit
        # its own reply (`lastSentHeads = message.heads`), leaving the
        # first soliciting forever; simultaneous handshakes (what a real
        # reconnect does) cannot interleave that way.
        box['sa'], msg_ab = backend_a.generate_sync_message(box['a'],
                                                            box['sa'])
        box['sb'], msg_ba = backend_b.generate_sync_message(box['b'],
                                                            box['sb'])
        out_ab = link_ab.transmit(msg_ab) if link_ab is not None else \
            ([msg_ab] if msg_ab is not None else [])
        out_ba = link_ba.transmit(msg_ba) if link_ba is not None else \
            ([msg_ba] if msg_ba is not None else [])
        _deliver(recv_b, out_ab, quarantined)
        _deliver(recv_a, out_ba, quarantined)
        # the round IS the link clock: stateful dark windows
        # (partition/crash) heal after their K rounds
        if link_ab is not None:
            link_ab.tick()
        if link_ba is not None:
            link_ba.tick()

        if msg_ab is None and msg_ba is None:
            # quiet — but drain any reorder-held messages first: a held
            # message may reopen the handshake
            drained = False
            if link_ab is not None:
                drained |= _deliver(recv_b, link_ab.flush(), quarantined)
            if link_ba is not None:
                drained |= _deliver(recv_a, link_ba.flush(), quarantined)
            if not drained:
                return box['a'], box['b'], rounds, {
                    'quarantined': quarantined[0], 'resets': resets}
            continue

        heads = (tuple(backend_a.get_heads(box['a'])),
                 tuple(backend_b.get_heads(box['b'])))
        if heads == last_heads:
            stalled += 1
        else:
            stalled = 0
        last_heads = heads
        if stalled >= reset_wait:
            if backoff.exhausted(resets):
                raise SyncStalled(
                    f'sync stalled: no head progress through {resets} '
                    f'reconnects over {rounds} rounds', rounds=rounds,
                    resets=resets,
                    detail={'ab': link_ab.stats if link_ab else None,
                            'ba': link_ba.stats if link_ba else None})
            box['sa'] = backend_a.init_sync_state()
            box['sb'] = backend_b.init_sync_state()
            # next reset waits longer, jittered — no lockstep re-handshake
            reset_wait = max(1, round(stall_reset + backoff.delay(resets)))
            resets += 1
            _fault_totals['stall_resets'] += 1
            stalled = 0
    raise SyncStalled(
        f'sync not quiet after {max_rounds} rounds ({resets} reconnects)',
        rounds=max_rounds, resets=resets,
        detail={'ab': link_ab.stats if link_ab else None,
                'ba': link_ba.stats if link_ba else None})
