"""The TPU-native fleet engine: batched CRDT computation over document fleets.

This is the performance core of automerge_tpu (BASELINE.json north star): a
fleet of thousands of documents lives as padded device tensors, the
change-application loop (Lamport-clock LWW resolution, counter accumulation)
runs as one XLA dispatch over the whole fleet, and sync Bloom-filter
construction/probing is batched bit-tensor math. The host OpSet engine
(automerge_tpu.backend.op_set) is the correctness oracle; kernels here are
differentially tested against it.

Scaling: fleet state shards across a `jax.sharding.Mesh` (data-parallel over
the docs axis, optionally a second axis over the key grid), with XLA inserting
the collectives — see automerge_tpu.fleet.sharding.
"""

from .tensor_doc import FleetState, OpBatch, TOMBSTONE, pack_op_id, unpack_op_id
from .apply import apply_op_batch, fleet_merge
from .bloom import build_bloom_filters, probe_bloom_filters, bloom_filter_bytes
from .sequence import (SeqState, SeqOpBatch, SeqEncoder, apply_seq_batch,
                       linearize, materialize, visible_text)
from .sync_driver import (generate_sync_messages_docs,
                          receive_sync_messages_docs)
from .loader import load_docs
from .hashindex import (HashIndex, FleetFrontierIndex, frontier_compare,
                        hashes_to_rows)

__all__ = [
    'load_docs',
    'HashIndex', 'FleetFrontierIndex', 'frontier_compare', 'hashes_to_rows',
    'FleetState', 'OpBatch', 'TOMBSTONE', 'pack_op_id', 'unpack_op_id',
    'apply_op_batch', 'fleet_merge',
    'build_bloom_filters', 'probe_bloom_filters', 'bloom_filter_bytes',
    'SeqState', 'SeqOpBatch', 'SeqEncoder', 'apply_seq_batch',
    'linearize', 'materialize', 'visible_text',
    'generate_sync_messages_docs', 'receive_sync_messages_docs',
]
