"""Exact multi-value registers on device: actor-slotted visible sets.

The scatter-max engine (fleet/apply.py) materializes LWW winners only; the
reference's per-key state is richer — a *multi-value register* holding every
op with no successor (new.js:1204-1217), which is what conflict sets,
concurrent set-vs-delete resurrection, and per-op counter accumulation are
read from. This engine stores that state exactly, on device:

    reg     [N, K+1, A] int32  packed opId of actor-slot a's live set op
    killed  [N, K+1, A] bool   that op has a successor (overwritten/deleted)
    value   [N, K+1, A] int32  the op's payload (inline int / table ref)
    counter [N, K+1, A] int32  per-op accumulated inc deltas (new.js:937-965)

Key observation: in causally well-formed histories each actor's newest set
op on a key supersedes that actor's previous one (the frontend always preds
its own visible op, frontend/context.js:576-586), so the visible set holds
at most one op per actor and an actor-indexed slot axis of width A (a small
power of two >= the fleet's actor count) represents it losslessly. Deletes
kill exactly their preds — never concurrent ops — and increments accumulate
into the *target op's* slot, so both reference corner cases the LWW engine
documents away (set-vs-delete resurrection, counter overwrite) are exact
here.

Ops carry their pred lists (from the native parser's pred columns,
codec.cpp) padded to a static width D. Application is ordered *within* a
document — a lax.scan over the op axis, with every document's op-i applied
in one [N]-wide step (the same vmap-over-docs x scan-over-ops shape as the
sequence engine) — because a successor can arrive in the same batch as the
op it kills.

Histories outside the one-op-per-actor shape (an actor overwriting its own
key without pred'ing it — only constructible by hand-built changes) and ops
with more than D preds raise an `inexact` per-doc flag instead of silently
diverging; callers route flagged documents to the host engine.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..observability.perf import instrument_kernel
from .tensor_doc import MAX_ACTORS, register_pytrees

ACTOR_MASK = MAX_ACTORS - 1


class RegisterState:
    """Pytree of actor-slotted register tensors + per-doc inexact flags."""

    def __init__(self, reg, killed, value, counter, inexact):
        self.reg = reg
        self.killed = killed
        self.value = value
        self.counter = counter
        self.inexact = inexact   # [N] bool: doc needs the host engine

    @classmethod
    def empty(cls, n_docs, n_keys, n_actor_slots, xp=np):
        shape = (n_docs, n_keys + 1, n_actor_slots)
        return cls(xp.zeros(shape, dtype=np.int32),
                   xp.zeros(shape, dtype=bool),
                   xp.zeros(shape, dtype=np.int32),
                   xp.zeros(shape, dtype=np.int32),
                   xp.zeros((n_docs,), dtype=bool))

    def tree_flatten(self):
        return ((self.reg, self.killed, self.value, self.counter,
                 self.inexact), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


class RegisterOpBatch:
    """Sequenced op columns [N, P] + pred lists [N, P, D].

    kind: 0 pad, 1 set, 2 del, 3 inc. Ops apply in column order per doc.
    preds are packed opIds (0 = unused lane); an op with more than D preds
    must set `overflow` for its lane (flags the doc inexact)."""

    def __init__(self, kind, key_id, packed, value, preds, overflow):
        self.kind = kind
        self.key_id = key_id
        self.packed = packed
        self.value = value
        self.preds = preds
        self.overflow = overflow

    def tree_flatten(self):
        return ((self.kind, self.key_id, self.packed, self.value, self.preds,
                 self.overflow), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


register_pytrees(RegisterState, RegisterOpBatch)

PAD, SET, DEL, INC = 0, 1, 2, 3


def _apply_step(state, op, n_slots, n_actor_slots):
    """Apply op-column i (one op per document, [N] wide)."""
    reg, killed, value, counter, inexact = state
    kind, key_id, packed, val, preds, overflow = op
    n_docs = reg.shape[0]
    docs = jnp.arange(n_docs, dtype=jnp.int32)
    scratch = n_slots - 1

    live = kind != PAD
    k = jnp.where(live, key_id, scratch)

    reg_row = reg[docs, k]          # [N, A]
    killed_row = killed[docs, k]
    value_row = value[docs, k]
    counter_row = counter[docs, k]

    # Kill preds: each pred lane targets its actor's slot; the kill lands
    # only if that slot still holds exactly the pred'd op. Increments do
    # not kill (they are successors that accumulate, new.js:937-965).
    # A pred that resolves to no live slot is NOT flagged: it can be a
    # legitimately already-superseded op (killed rows are reclaimed when the
    # same actor writes again), which the reference also accepts as a no-op
    # succ entry.
    kills = kind != INC
    slot_oob = jnp.zeros((n_docs,), dtype=bool)
    d_preds = preds.shape[1]
    for d in range(d_preds):
        p = preds[:, d]
        s = (p & ACTOR_MASK).astype(jnp.int32)
        slot_oob |= live & (p != 0) & (s >= n_actor_slots)
        hit = live & (p != 0) & (s < n_actor_slots) & (reg_row[docs, s] == p)
        do_kill = hit & kills
        killed_row = killed_row.at[docs, s].set(killed_row[docs, s] | do_kill)

    # INC: an increment on a conflicted counter carries one pred per
    # conflicting set op (the frontend preds every conflict opId). The
    # reference attributes such an inc to the LAMPORT-MAX pred — even a
    # dead one: `counterStates[succOp] = counterState` overwrites earlier
    # sets' registrations (new.js:942-945) — and every other pred'd set
    # never completes its counter state, so it stays invisible forever
    # (round-4 50x-chaos find, seed 18). Device equivalent: add to the
    # max pred's lane iff that lane still holds it live; kill every other
    # live pred'd lane (a dead max pred consumes the inc silently, and
    # the lower branches hide either way).
    is_inc = kind == INC
    max_pred = jnp.zeros((n_docs,), dtype=jnp.int32)
    any_live_hit = jnp.zeros((n_docs,), dtype=bool)
    for d in range(d_preds):
        p = preds[:, d]
        s = (p & ACTOR_MASK).astype(jnp.int32)
        max_pred = jnp.where(is_inc & (p != 0),
                             jnp.maximum(max_pred, p), max_pred)
        any_live_hit |= is_inc & (p != 0) & (s < n_actor_slots) & \
            (reg_row[docs, s] == p) & ~killed_row[docs, s]
    s_max = (max_pred & ACTOR_MASK).astype(jnp.int32)
    max_live = is_inc & (max_pred != 0) & (s_max < n_actor_slots) & \
        (reg_row[docs, s_max] == max_pred) & ~killed_row[docs, s_max]
    counter_row = counter_row.at[
        docs, jnp.where(max_live, s_max, n_actor_slots)].add(
        jnp.where(max_live, val, 0), mode='drop')
    for d in range(d_preds):
        p = preds[:, d]
        s = (p & ACTOR_MASK).astype(jnp.int32)
        lose = is_inc & (p != 0) & (s < n_actor_slots) & \
            (reg_row[docs, s] == p) & ~killed_row[docs, s] & (p != max_pred)
        killed_row = killed_row.at[docs, s].set(killed_row[docs, s] | lose)
    inc_hit = any_live_hit | max_live

    # SET: occupy own actor slot. If the slot already holds a live op this
    # op did NOT pred, the reference would keep both visible — outside the
    # one-op-per-actor shape, so flag the doc instead of losing data.
    a = (packed & ACTOR_MASK).astype(jnp.int32)
    is_set = kind == SET
    own_prev = reg_row[docs, a]
    own_pred = jnp.zeros((n_docs,), dtype=bool)
    for d in range(d_preds):
        own_pred |= preds[:, d] == own_prev
    self_conflict = is_set & (own_prev != 0) & ~killed_row[docs, a] & \
        ~own_pred & (own_prev != packed)
    # An inc whose target is missing/killed is invalid input (the exact
    # paths reject it up front); under turbo it flags the doc for replay.
    # Actor numbers beyond the configured slot width also flag (the write
    # below would otherwise silently drop).
    bad_inc = (kind == INC) & ~inc_hit
    actor_oob = live & (a >= n_actor_slots)
    inexact = inexact | self_conflict | overflow | bad_inc | slot_oob | \
        actor_oob

    set_slot = jnp.where(is_set & ~actor_oob, a, n_actor_slots)
    reg_row = reg_row.at[docs, set_slot].set(packed, mode='drop')
    killed_row = killed_row.at[docs, set_slot].set(False, mode='drop')
    value_row = value_row.at[docs, set_slot].set(val, mode='drop')
    counter_row = counter_row.at[docs, set_slot].set(0, mode='drop')

    reg = reg.at[docs, k].set(reg_row)
    killed = killed.at[docs, k].set(killed_row)
    value = value.at[docs, k].set(value_row)
    counter = counter.at[docs, k].set(counter_row)
    return (reg, killed, value, counter, inexact), live.astype(jnp.int32)


def _apply_register_batch_impl(state, ops):
    n_slots = state.reg.shape[1]
    n_actor_slots = state.reg.shape[2]

    def step(carry, op):
        return _apply_step(carry, op, n_slots, n_actor_slots)

    xs = (ops.kind.T, ops.key_id.T, ops.packed.T, ops.value.T,
          jnp.transpose(ops.preds, (1, 0, 2)), ops.overflow.T)
    carry = (state.reg, state.killed, state.value, state.counter,
             state.inexact)
    carry, applied = lax.scan(step, carry, xs)
    return RegisterState(*carry), jnp.sum(applied)


apply_register_batch = instrument_kernel(
    'apply_register_batch', jax.jit(_apply_register_batch_impl))
# In-place variant for the fleet's own dispatch paths (see
# apply.apply_op_batch_donated): the register tensors update without a
# full-state rewrite; callers must replace their state reference.
apply_register_batch_donated = instrument_kernel(
    'apply_register_batch_donated',
    jax.jit(_apply_register_batch_impl, donate_argnums=(0,)))


def _zero_register_rows_impl(state, idx):
    """Zero the given docs' rows across every register array — ONE fused
    kernel (idempotent under duplicate indices, so callers may pad idx)."""
    return RegisterState(state.reg.at[idx].set(0),
                         state.killed.at[idx].set(False),
                         state.value.at[idx].set(0),
                         state.counter.at[idx].set(0),
                         state.inexact.at[idx].set(False))


zero_register_rows_donated = instrument_kernel(
    'zero_register_rows_donated',
    jax.jit(_zero_register_rows_impl, donate_argnums=(0,)))


def _visible_registers_impl(state):
    """(visible [N, K+1, A] bool, winner_slot [N, K+1] int32,
    winner_packed [N, K+1] int32): the multi-value register contents and the
    Lamport winner per key (packed ids order like lamportCompare because
    actor numbers are hex-sorted, see fleet/backend._SortedActorTable)."""
    visible = (state.reg != 0) & ~state.killed
    masked = jnp.where(visible, state.reg, -1)
    winner_slot = jnp.argmax(masked, axis=-1).astype(jnp.int32)
    winner_packed = jnp.max(jnp.where(visible, state.reg, 0), axis=-1)
    return visible, winner_slot, winner_packed


visible_registers = instrument_kernel(
    'visible_registers', jax.jit(_visible_registers_impl))


def rows_to_register_batch(doc_ids, flags, key_ids, packed, values,
                           pred_off, pred, n_docs, d_preds=4,
                           force_overflow=None):
    """Lay flat native-ingest op rows (application order, doc-contiguous)
    into a RegisterOpBatch [n_docs, P]. Inputs are the arrays the native
    parser emits with with_meta=True — flags (1 = set/del, 2 = inc; dels
    carry value -1), pred_off/pred per-row pred lists — already remapped to
    fleet key/actor numbering by the caller. Stable layout preserves each
    document's op order (the scan applies columns in order)."""
    doc_ids = np.asarray(doc_ids, dtype=np.int64)
    n_rows = len(doc_ids)
    counts = np.bincount(doc_ids, minlength=n_docs) if n_rows else \
        np.zeros(n_docs, dtype=np.int64)
    width = max(int(counts.max()) if n_rows else 0, 1)
    order = np.argsort(doc_ids, kind='stable')
    doc_sorted = doc_ids[order]
    pos = np.arange(n_rows) - np.searchsorted(doc_sorted, doc_sorted,
                                              side='left')
    kind = np.zeros((n_docs, width), dtype=np.int32)
    key_col = np.zeros((n_docs, width), dtype=np.int32)
    packed_col = np.zeros((n_docs, width), dtype=np.int32)
    value_col = np.zeros((n_docs, width), dtype=np.int32)
    preds_col = np.zeros((n_docs, width, d_preds), dtype=np.int32)
    overflow = np.zeros((n_docs, width), dtype=bool)

    flags = np.asarray(flags)
    values = np.asarray(values)
    kinds_flat = np.where(flags == 2, INC,
                          np.where(values == -1, DEL, SET)).astype(np.int32)
    kind[doc_sorted, pos] = kinds_flat[order]
    key_col[doc_sorted, pos] = np.asarray(key_ids)[order]
    packed_col[doc_sorted, pos] = np.asarray(packed)[order]
    # -1 is the DEL sentinel only for set/del rows; an inc delta of -1 is a
    # legitimate negative increment and must pass through untouched
    value_col[doc_sorted, pos] = np.where(
        (values == -1) & (flags != 2), 0, values)[order]

    pred_off = np.asarray(pred_off)
    pred = np.asarray(pred)
    pred_counts = np.diff(pred_off)
    oflow_flat = pred_counts > d_preds
    if force_overflow is not None:
        # Caller-detected per-row badness (e.g. a pred naming an actor the
        # fleet has never seen): route the doc to host replay via inexact
        oflow_flat = oflow_flat | np.asarray(force_overflow, dtype=bool)
    overflow[doc_sorted, pos] = oflow_flat[order]
    for d in range(d_preds):
        has = pred_counts > d
        lane = np.zeros(n_rows, dtype=np.int32)
        lane[has] = pred[pred_off[:-1][has] + d]
        preds_col[doc_sorted, pos, d] = lane[order]
    return RegisterOpBatch(kind, key_col, packed_col, value_col, preds_col,
                           overflow)


def materialize_registers(state, keys, value_table=None):
    """Host-side read: per doc {key: (winner_value, conflict_dict)} where
    conflict_dict maps packed opId -> value for every visible op (empty for
    unanimous keys). Counter accumulators are added to their op's base."""
    visible, winner_slot, winner_packed = jax.device_get(
        visible_registers(state))
    reg = np.asarray(jax.device_get(state.reg))
    value = np.asarray(jax.device_get(state.value))
    counter = np.asarray(jax.device_get(state.counter))

    def decode(v, c):
        out = value_table[-v - 2] if v <= -2 and value_table is not None else v
        if isinstance(out, TypedValue):
            return out.value + int(c) if out.datatype == 'counter' \
                else out.value
        if isinstance(out, int) and not isinstance(out, bool):
            out += int(c)
        return out

    docs = []
    for n in range(reg.shape[0]):
        doc = {}
        for k in range(len(keys)):
            vis = np.flatnonzero(visible[n, k])
            if not len(vis):
                continue
            w = winner_slot[n, k]
            winner_value = decode(int(value[n, k, w]), counter[n, k, w])
            conflicts = {int(reg[n, k, s]): decode(int(value[n, k, s]),
                                                   counter[n, k, s])
                         for s in vis} if len(vis) > 1 else {}
            doc[keys[k]] = (winner_value, conflicts)
        docs.append(doc)
    return docs


def typed_wire_tags():
    """Wire value-type tag -> datatype string for root-map set values that
    must box as TypedValue (uint/counter/timestamp ride int32 value lanes;
    the datatype survives only via the box). The single source of truth for
    every ingest path — native rows, turbo, and the mixed Python decode —
    so device-served patches emit identical datatype leaves regardless of
    which path a change took."""
    from ..columnar import VALUE_TYPE
    return {VALUE_TYPE['LEB128_UINT']: 'uint',
            VALUE_TYPE['COUNTER']: 'counter',
            VALUE_TYPE['TIMESTAMP']: 'timestamp'}


class TypedValue:
    """Boxed register value carrying its wire datatype (uint / timestamp /
    counter / float64 …) so device-served patches reproduce the host patch
    grammar exactly (datatype survives the int32 value lanes)."""

    __slots__ = ('value', 'datatype')

    def __init__(self, value, datatype):
        self.value = value
        self.datatype = datatype

    def __repr__(self):
        return f'TypedValue({self.value!r}, {self.datatype!r})'

    def __eq__(self, other):
        return isinstance(other, TypedValue) and \
            other.value == self.value and other.datatype == self.datatype

    def __hash__(self):
        return hash(('TypedValue', self.value, self.datatype))


def _patch_leaf(raw, counter_fold, value_table):
    """One visible register lane -> host-grammar patch value leaf."""
    boxed = value_table[-raw - 2] if raw <= -2 and value_table is not None \
        else raw
    if isinstance(boxed, TypedValue):
        value = boxed.value
        if boxed.datatype == 'counter':
            value += int(counter_fold)
        return {'type': 'value', 'value': value, 'datatype': boxed.datatype}
    if isinstance(boxed, bool) or boxed is None or isinstance(boxed, str):
        return {'type': 'value', 'value': boxed}
    if isinstance(boxed, float):
        return {'type': 'value', 'value': boxed, 'datatype': 'float64'}
    if isinstance(boxed, int):
        return {'type': 'value', 'value': boxed, 'datatype': 'int'}
    return None    # links / unsupported payloads: caller uses the mirror


