"""Crash-safe durability for the fleet: change journal, checkpoints, recovery.

A process crash loses every in-memory document — the per-doc
``save()``/``load()`` round trip is a backup policy, not a durability
story. This module is the fleet-level one, the snapshot-plus-log shape of
the LSM lineage (PAPERS.md: LSM-OPD; SynchroStore's cost-based
compaction):

- ``ChangeJournal`` — an append-only log of CRC-framed records, each
  carrying a durable doc id plus raw change bytes. Appends buffer in
  memory and land in ONE ``write`` per group commit; ``fsync`` batches
  under a byte threshold so the group-commit cost amortizes across the
  batched seam. A change is crash-durable once the commit that covered
  it has fsynced (``durable_bytes``); everything after the last fsync is
  the explicit loss window (``pending_fsync_bytes``, reported through
  ``DocFleet.memory_stats``).
- Whole-fleet **checkpoints** — one snapshot file holding every
  registered document's canonical ``save()`` bytes (plus causally
  held-back queue entries), written via temp file + fsync + atomic
  rename, with a ``MANIFEST`` binding snapshot ↔ journal file/offset the
  same way. The journal rotates at each checkpoint, so replay debt
  resets to zero, and the old generation is deleted only after the new
  manifest is durable — a crash at ANY step leaves a recoverable pair on
  disk.
- ``DurableFleet.recover`` — loads the latest valid snapshot, truncates
  any torn journal tail at the first bad CRC frame, resynchronizes past
  mid-file bit rot (frame-magic scan), and replays the surviving suffix
  through ``apply_changes_docs(on_error='quarantine')`` so a single
  rotted record quarantines ONE document (typed error in the report,
  health counter incremented) while the rest of the fleet recovers —
  the same one-doc blast radius hostile wire bytes already get.
- Cost-triggered **compaction** — ``maybe_compact`` checkpoints once the
  journal's replay debt (bytes or records since the last checkpoint)
  crosses a threshold, so recovery time stays bounded by the compaction
  policy instead of history length.

Journal hooks live on the backend's mutation seams (``DocFleet.journal``
is consulted by ``FleetDoc.apply_changes``, the turbo batch commit in
``apply_changes_docs``, ``FleetDoc.free``/``free_docs`` and
``FleetDoc.clone``), so ordinary workloads — local commits, batched
applies, sync rounds through ``receive_sync_messages_docs`` — journal
transparently once a journal is attached. Documents are keyed by a
durable id the journal assigns (NOT the fleet slot: slots recycle on
free and vanish on promotion; the durable id survives both).

Failure envelope: every decode path here raises only typed errors —
``MalformedJournal``/``TornTail`` for journal frames,
``MalformedSnapshot`` for snapshot/manifest damage — and the journal
scanner itself never raises on arbitrary corruption: it returns the
surviving records plus a damage report (containment is the contract;
tools/fuzz_wire.py enforces it).
"""

import contextlib
import json
import os
import struct
import time
import weakref
import zlib

import numpy as np

from ..errors import (AutomergeError, DocError, MalformedJournal,
                      MalformedSnapshot, TornTail, as_wire_error)
from ..observability import register_health_source
from ..observability.metrics import Counters
from ..observability.perf import register_mem_source
from ..observability import hist as _hist
from ..observability import recorder as _flight
from ..observability.spans import (span as _span, span_seq as _span_seq,
                                   spanned as _spanned)

__all__ = [
    'ChangeJournal', 'DurableFleet', 'RecoveryReport',
    'KIND_CHANGE', 'KIND_FREE', 'KIND_DOC', 'KIND_QUEUED', 'KIND_END',
    'KIND_INIT', 'KIND_SMETA',
    'encode_frame', 'parse_journal_bytes', 'parse_snapshot_bytes',
    'parse_manifest_bytes', 'read_state', 'durability_stats',
    'pending_fsync_bytes_total', 'set_fsync_alert_threshold',
]

# ---------------------------------------------------------------------------
# Frame layout (journal and snapshot share it):
#
#   magic   2B  b'\xa6J'
#   kind    1B  record type
#   doc_id  4B  <I durable doc id
#   length  4B  <I payload length
#   hcrc    4B  <I crc32 over the 11-byte magic|kind|doc_id|length prefix
#   payload length bytes
#   pcrc    4B  <I crc32 over payload
#
# Two CRCs on purpose: a rotted PAYLOAD leaves the header trustworthy, so
# recovery can attribute the loss to exactly one doc and keep the stream
# (the frame boundary is still known); a rotted HEADER forfeits
# attribution and recovery resynchronizes by scanning for the next valid
# frame — the victim doc's later records then hold back at the causal
# gate, which contains the damage to that one doc anyway.
# ---------------------------------------------------------------------------

FRAME_MAGIC = b'\xa6J'
_MHEAD = struct.Struct('<2sBII')       # magic, kind, doc_id, length
_U32 = struct.Struct('<I')
FRAME_OVERHEAD = _MHEAD.size + 4 + 4   # prefix + hcrc + pcrc

KIND_CHANGE = 1      # journal: raw change (or document-chunk) bytes
KIND_FREE = 2        # journal: document freed (empty payload); in a
#                      SNAPSHOT SEGMENT: tombstone — the doc was freed
#                      since the previous segment and must not resurrect
KIND_DOC = 3         # snapshot: document save() bytes
KIND_QUEUED = 4      # snapshot: causally held-back queue buffer
KIND_END = 5         # snapshot/manifest: terminator
KIND_INIT = 6        # journal: document created, no changes yet
KIND_SMETA = 8       # snapshot: segment metadata (JSON: base flag, seq)
#                      — absent in pre-segment snapshots, which read as
#                      base (full) snapshots. Written with the sentinel
#                      doc id below (never a real durable id, which are
#                      assigned monotonically from 0), so payload rot in
#                      the SMETA frame reads as STRUCTURAL damage
#                      instead of quarantining document 0 — a segment
#                      whose base-ness cannot be trusted must not be
#                      stitched at all.
SMETA_DOC_ID = 0xfffffffe
# Columnar batch frame — the hot-seam format (ChangeJournal.record_seam):
# ONE outer frame whose doc_id field carries the record count and whose
# payload is two independently-CRC'd copies of a (doc_id, length,
# payload-crc32) table followed by the concatenated change payloads.
# Encoding cost is one crc32 call per record instead of per-record
# framing (the <=15% journal-overhead budget lives here), while damage
# stays record-localized: payload rot is attributed through the table's
# per-record crc, table rot falls back to the duplicate copy, and a torn
# tail salvages every record whose payload fully landed (the tables are
# front-loaded). Residual envelope: rot inside the outer frame's
# magic/kind/count bytes (7 per batch) loses the whole batch to the
# resync scan; length/hcrc/pcrc rot fully salvages.
KIND_BATCH = 7

_TBL = struct.Struct('<III')           # per-record: doc_id, length, pcrc
_BATCH_MIN = 8                         # below this, per-record frames win

SNAP_MAGIC = b'AMSN\x01'
MANIFEST_MAGIC = b'AMMF\x01'
MANIFEST_NAME = 'MANIFEST'

_MAX_FRAME = 1 << 31   # sanity ceiling on a declared payload length


def _crc(data):
    return zlib.crc32(data) & 0xffffffff


def encode_frame(kind, doc_id, payload):
    prefix = _MHEAD.pack(FRAME_MAGIC, kind, doc_id, len(payload))
    return b''.join((prefix, _U32.pack(_crc(prefix)),
                     payload, _U32.pack(_crc(payload))))


_TBL_DTYPE = np.dtype([('d', '<u4'), ('l', '<u4'), ('c', '<u4')])


def _encode_batch(dids, bufs):
    """One KIND_BATCH frame for parallel (doc_id, payload) lists: the
    outer doc_id field carries the count; the payload is two CRC'd table
    copies + concatenated payloads (format note at KIND_BATCH)."""
    crc = zlib.crc32
    count = len(bufs)
    tbl = np.empty(count, dtype=_TBL_DTYPE)
    tbl['d'] = dids
    tbl['l'] = np.fromiter(map(len, bufs), dtype=np.uint32, count=count)
    tbl['c'] = np.fromiter(map(crc, bufs), dtype=np.uint32, count=count)
    tb = tbl.tobytes()
    block = _U32.pack(crc(tb)) + tb
    total = 2 * len(block) + int(tbl['l'].sum())
    prefix = _MHEAD.pack(FRAME_MAGIC, KIND_BATCH, count, total)
    payload = b''.join([block, block] + bufs)
    return b''.join((prefix, _U32.pack(crc(prefix)), payload,
                     _U32.pack(crc(payload))))


def _read_batch_table(data, poff, count, limit):
    """One table block (u4 crc + count x 12B) at poff; None when it does
    not fit below `limit` or its crc fails."""
    tlen = 12 * count
    if poff + 4 + tlen > limit:
        return None
    (tcrc,) = _U32.unpack_from(data, poff)
    tbl = data[poff + 4:poff + 4 + tlen]
    if _crc(tbl) != tcrc:
        return None
    arr = np.frombuffer(tbl, dtype=_TBL_DTYPE)
    return arr['d'], arr['l'].astype(np.int64), arr['c']


def _batch_spans(data, off, count, limit):
    """(dids, rcrcs, starts, ends, expected_end) for a batch frame at
    `off`, using whichever table copy validates — None when neither
    does (the batch cannot be decoded)."""
    poff = off + _MHEAD.size + 4
    blk = 4 + 12 * count
    tbl = _read_batch_table(data, poff, count, limit)
    if tbl is None:
        tbl = _read_batch_table(data, poff + blk, count, limit)
    if tbl is None:
        return None
    dids, lens, rcrcs = tbl
    pstart = poff + 2 * blk
    ends = pstart + np.cumsum(lens)
    starts = ends - lens
    expected_end = (int(ends[-1]) if count else pstart) + 4
    return dids, rcrcs, starts, ends, expected_end


def _batch_decode(data, off, count, records, rotted, verified):
    """Decode a batch frame's records into `records`/`rotted` in order.
    verified=True (outer pcrc passed) skips the per-record crc walk;
    otherwise every record re-validates against its table crc, so
    payload rot is attributed to exactly its doc. Returns (resume_end,
    complete) or None when neither table copy survives."""
    spans = _batch_spans(data, off, count, len(data))
    if spans is None:
        return None
    dids, rcrcs, starts, ends, expected_end = spans
    n = len(data)
    crc = _crc
    for i in range(count):
        s, e = int(starts[i]), int(ends[i])
        if e > n:
            return (s, False)      # torn mid-payload: prefix salvaged
        if verified or crc(data[s:e]) == int(rcrcs[i]):
            records.append((KIND_CHANGE, int(dids[i]), data[s:e]))
        else:
            rotted.append((int(dids[i]), s, len(records)))
    if expected_end > n:
        return (int(ends[-1]) if count else n, False)
    return (expected_end, True)


def _frame_at(data, off):
    """Decode one frame at `off`. Returns (kind, doc_id, payload, end,
    status) with status 'ok' | 'rotted' (header valid, payload CRC bad —
    the boundary is still known) | 'badhead' | 'nomagic' | 'short'.
    Never raises."""
    n = len(data)
    if data[off:off + 2] != FRAME_MAGIC:
        return (None, None, None, off, 'nomagic')
    if off + _MHEAD.size + 4 > n:
        return (None, None, None, n, 'short')
    prefix = data[off:off + _MHEAD.size]
    (hcrc,) = _U32.unpack_from(data, off + _MHEAD.size)
    if _crc(prefix) != hcrc:
        return (None, None, None, off, 'badhead')
    _magic, kind, doc_id, length = _MHEAD.unpack(prefix)
    if length > _MAX_FRAME:
        return (None, None, None, off, 'badhead')
    poff = off + _MHEAD.size + 4
    end = poff + length + 4
    if end > n:
        return (None, None, None, n, 'short')
    payload = data[poff:poff + length]
    (pcrc,) = _U32.unpack_from(data, poff + length)
    if _crc(payload) != pcrc:
        return (kind, doc_id, None, end, 'rotted')
    return (kind, doc_id, payload, end, 'ok')


def parse_journal_bytes(data, offset=0, strict=False):
    """Journal scan. Returns (records, info): records is
    [(kind, doc_id, payload)] for every intact frame in order; info
    carries 'torn_tail_bytes' (trailing bytes dropped at the first frame
    that runs past EOF, or trailing garbage with no later valid frame),
    'rotted' ([(doc_id | None, byte_offset, record_index)] for mid-stream
    frames whose payload or header CRC failed — record_index is the
    number of intact records BEFORE the rot, so consumers can keep the
    victim's prefix), 'valid_end' (the offset appends may safely resume
    at — records salvaged from a torn BATCH frame may lie beyond it;
    truncating there drops them from the file, so re-persist replayed
    records before resuming, as recovery's re-checkpoint does) and
    'scanned_bytes'.

    Default (lenient) mode NEVER raises on hostile bytes — containment
    is the contract and recovery consumes the report. strict=True raises
    instead: TornTail for a torn tail, MalformedJournal for mid-stream
    rot (integrity-audit mode, and the typed-raise surface the wire
    fuzzer exercises)."""
    data = bytes(data)
    records = []
    rotted = []
    off = offset
    n = len(data)
    valid_end = offset
    torn = 0
    while off < n:
        kind, doc_id, payload, end, status = _frame_at(data, off)
        # Batch frames decode through their own table-driven path, which
        # tolerates outer-frame damage (rot or a torn tail) as long as
        # one table copy validates — damage localizes to the records it
        # actually hit. The kind byte is consulted even when the header
        # crc failed: salvage validates it implicitly through the table.
        if kind == KIND_BATCH or (
                status in ('short', 'badhead') and off + 3 <= n and
                data[off:off + 2] == FRAME_MAGIC and
                data[off + 2] == KIND_BATCH):
            count = doc_id if status in ('ok', 'rotted') else (
                _MHEAD.unpack_from(data, off)[2]
                if off + _MHEAD.size <= n else -1)
            out = None
            if 0 <= count <= (n - off) // 12 + 1:
                out = _batch_decode(data, off, count, records, rotted,
                                    verified=status == 'ok')
            if out is not None:
                bend, complete = out
                if not complete:
                    # torn mid-batch: records up to `bend` salvaged.
                    # valid_end stays at the FRAME start — that is the
                    # only safe append-resume point (the frame's outer
                    # header claims bytes past the tear, so appending
                    # at `bend` would be swallowed by a later parse);
                    # salvaged records beyond valid_end are already in
                    # `records` and recovery re-checkpoints them. torn
                    # is >= 1 even when only the trailing pcrc was cut,
                    # so an incomplete frame always reports as torn.
                    torn = max(n - bend, 1)
                    break
                off = valid_end = bend
                continue
            if status in ('ok', 'rotted'):
                # both table copies dead inside a structurally-bounded
                # frame: the batch is lost, unattributable
                rotted.append((None, off, len(records)))
                off = valid_end = end
                continue
            # short/badhead/nomagic with no salvageable table: fall
            # through to the generic torn-tail / resync handling
        if status == 'ok':
            records.append((kind, doc_id, payload))
            off = valid_end = end
            continue
        if status == 'rotted':
            # header intact, payload rotted: boundary known, loss
            # attributable to exactly this doc
            rotted.append((doc_id, off, len(records)))
            off = valid_end = end
            continue
        if status == 'short':
            # frame runs past EOF: a torn tail (the crash landed
            # mid-write) — truncate here
            torn = n - off
            break
        # nomagic / badhead: resynchronize — scan forward for the next
        # offset where a decodable frame begins; the skipped span is rot
        resync = None
        scan = off + 1
        while scan < n:
            scan = data.find(FRAME_MAGIC, scan)
            if scan < 0:
                break
            _k, _d, _p, _e, s2 = _frame_at(data, scan)
            if s2 in ('ok', 'rotted'):
                resync = scan
                break
            scan += 1
        if resync is None:
            torn = n - off
            break
        rotted.append((None, off, len(records)))
        off = resync
    if strict:
        if rotted:
            did, at, _idx = rotted[0]
            raise MalformedJournal(
                f'journal: rotted frame at byte {at}'
                + (f' (doc {did})' if did is not None else ''),
                doc_index=did)
        if torn:
            raise TornTail(f'journal: torn tail, {torn} trailing bytes '
                           f'after offset {valid_end}')
    return records, {
        'torn_tail_bytes': torn,
        'rotted': rotted,
        'valid_end': valid_end,
        'scanned_bytes': n - offset,
    }


def parse_snapshot_bytes(data):
    """Decode a snapshot (base or incremental segment) body. Returns
    (docs, queued, errors, meta): docs is {doc_id: save_bytes | None}
    (None = KIND_FREE tombstone — the doc was freed since the previous
    segment), queued {doc_id: [buffers]}, errors
    [(doc_id | None, MalformedSnapshot)] for rotted per-doc frames (one
    rotted frame quarantines ONE doc — the rest of the snapshot still
    loads), meta the segment's KIND_SMETA JSON ({'base': True} for
    pre-segment snapshots without one). Raises MalformedSnapshot only
    for STRUCTURAL damage: bad file magic, or a missing/corrupt END
    terminator (the snapshot cannot be proven complete)."""
    data = bytes(data)
    if data[:len(SNAP_MAGIC)] != SNAP_MAGIC:
        raise MalformedSnapshot('snapshot: bad magic')
    records, info = parse_journal_bytes(data, offset=len(SNAP_MAGIC))
    if info['torn_tail_bytes'] or not records or records[-1][0] != KIND_END:
        raise MalformedSnapshot('snapshot: missing or torn END terminator')
    _kind, _doc, end_payload = records[-1]
    try:
        (declared,) = _U32.unpack(end_payload)
    except struct.error as exc:
        raise MalformedSnapshot('snapshot: bad END payload') from exc
    body = records[:-1]
    if declared != len(body) + len(info['rotted']):
        raise MalformedSnapshot(
            f'snapshot: END declares {declared} records, found '
            f'{len(body)} intact + {len(info["rotted"])} rotted')
    errors = []
    for doc_id, at, _idx in info['rotted']:
        if doc_id == SMETA_DOC_ID:
            # rotted segment metadata: the segment's identity (base vs
            # incremental) is unknowable — structural damage
            raise MalformedSnapshot(
                f'snapshot: rotted segment metadata at byte {at}')
        errors.append((doc_id, MalformedSnapshot(
            f'snapshot: rotted frame at byte {at}'
            + (f' (doc {doc_id})' if doc_id is not None else ''),
            doc_index=doc_id)))
    docs, queued = {}, {}
    meta = {'base': True}
    for kind, doc_id, payload in body:
        if kind == KIND_DOC:
            docs[doc_id] = bytes(payload)
        elif kind == KIND_QUEUED:
            queued.setdefault(doc_id, []).append(bytes(payload))
        elif kind == KIND_FREE:
            docs[doc_id] = None
            queued.pop(doc_id, None)
        elif kind == KIND_SMETA:
            try:
                meta = json.loads(bytes(payload).decode('utf8'))
            except Exception as exc:
                raise as_wire_error(exc, MalformedSnapshot,
                                    'snapshot segment meta')
            if not isinstance(meta, dict):
                raise MalformedSnapshot('snapshot: bad segment meta')
        # unknown kinds: forward-compatible skip
    return docs, queued, errors, meta


def parse_manifest_bytes(data):
    """Decode a manifest: magic + ONE CRC frame of JSON. Raises
    MalformedSnapshot (the manifest is checkpoint metadata) on any
    damage."""
    data = bytes(data)
    if data[:len(MANIFEST_MAGIC)] != MANIFEST_MAGIC:
        raise MalformedSnapshot('manifest: bad magic')
    kind, _doc, payload, _end, status = _frame_at(data, len(MANIFEST_MAGIC))
    if status != 'ok' or kind != KIND_END:
        raise MalformedSnapshot(f'manifest: bad frame ({status})')
    try:
        meta = json.loads(payload.decode('utf8'))
    except Exception as exc:
        raise as_wire_error(exc, MalformedSnapshot, 'manifest json')
    if not isinstance(meta, dict) or 'seq' not in meta:
        raise MalformedSnapshot('manifest: missing fields')
    return meta


# ---------------------------------------------------------------------------
# health counters (observability roll-up; monotonic, module-level)
# ---------------------------------------------------------------------------

_stats = Counters({
    'checkpoints': 0,            # snapshots written (incl. compactions)
    'compactions': 0,            # cost-triggered checkpoints
    'journal_commits': 0,        # group commits
    'journal_fsyncs': 0,         # actual fsync calls (batching visible)
    'journal_records': 0,        # records appended (lifetime)
    'replayed_records': 0,       # journal records replayed at recovery
    'journal_truncations': 0,    # torn tails truncated at recovery
    'rotted_records': 0,         # mid-stream CRC failures contained
    'recovered_docs': 0,         # documents recovered from disk
    'fsync_window_alerts': 0,    # loss-window threshold crossings
    'segments': 0,               # incremental (per-doc) compaction segments
    'segment_docs': 0,           # doc frames written by incremental
    #                              compaction — the O(churn) signal: after
    #                              touching K of N docs this grows by K
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])

# The durability LOSS WINDOW as a first-class health signal: the sum of
# written-but-not-fsynced bytes across every open journal. The brownout
# ladder WIDENS this window deliberately (stage 1 raises fsync_bytes);
# registering it here is what lets operators — and the overload tests —
# watch the window move instead of trusting the policy. Crossing the
# alert threshold is edge-triggered per journal into the
# 'fsync_window_alerts' counter + a flight-recorder event.

_open_journals = weakref.WeakSet()
# The alert only fires while pending < fsync_bytes (a commit at or past
# fsync_bytes fsyncs instead, closing the window), so the threshold must
# sit BELOW the widest fsync batching in use or it is unreachable: 1 MB
# default, under the brownout stage-1 widen ceiling (4 MB).
_fsync_alert_bytes = int(os.environ.get(
    'AUTOMERGE_TPU_FSYNC_ALERT_BYTES', 1 << 20))


def set_fsync_alert_threshold(n_bytes):
    """Configure the loss-window alert threshold (bytes; <= 0 disables).
    Returns the previous value."""
    global _fsync_alert_bytes
    prev = _fsync_alert_bytes
    _fsync_alert_bytes = int(n_bytes)
    return prev


def pending_fsync_bytes_total():
    """Sum of every open journal's pending_fsync_bytes — the bytes a
    crash right now would lose (on top of unwritten buffers)."""
    return sum(j.pending_fsync_bytes for j in _open_journals
               if not j.closed)


register_health_source('pending_fsync_bytes', pending_fsync_bytes_total)
# ...and the same number as a memory-watermark tier: the loss window is
# ALSO resident bytes (buffered records waiting on the fsync cadence)
register_mem_source('journal_pending_fsync_bytes',
                    pending_fsync_bytes_total)


def durability_stats():
    """Snapshot of this module's monotonic counters (also visible via
    observability.health_counts)."""
    return dict(_stats)


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write(path, data):
    """temp file + fsync + atomic rename + directory fsync: after this
    returns, `path` durably holds exactly `data` (or, across a crash,
    its previous content — never a torn mix)."""
    tmp = path + '.tmp'
    with open(tmp, 'wb') as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or '.')


# ---------------------------------------------------------------------------
# the journal
# ---------------------------------------------------------------------------


class ChangeJournal:
    """Append-only CRC-framed change log with group commit.

    ``append`` only buffers; ``commit`` lands the whole buffer in ONE
    write and fsyncs when the unsynced backlog passes ``fsync_bytes``
    (0 = fsync on every commit, the strict default). ``sync`` forces
    write + fsync. The journal also owns the durable-doc-id registry:
    ``doc_id_for(state)`` assigns a monotonic id to a document the first
    time it journals and keeps a reference so checkpoints can snapshot
    every journaled document without callers maintaining a registry."""

    def __init__(self, path, fsync_bytes=0, docs=None, next_doc_id=0):
        self.path = path
        self.fsync_bytes = int(fsync_bytes)
        self.docs = docs if docs is not None else {}   # doc_id -> state
        self.next_doc_id = next_doc_id
        self._f = open(path, 'ab')
        self._pending = bytearray()
        self._group_depth = 0     # >0: commits defer to group() exit
        size = os.path.getsize(path)
        self.written_bytes = size       # bytes handed to the OS
        self.durable_bytes = size       # bytes known fsynced
        self.records = 0                # records appended this generation
        # Churn tracking for incremental compaction: every doc id that
        # journaled a record this generation (dirty), and the subset
        # freed. Compaction re-persists EXACTLY the dirty set — work
        # proportional to churn, not fleet size (SynchroStore).
        self.dirty = set()
        self.freed = set()
        self.closed = False
        self._window_alerted = False    # edge trigger for the loss alert
        _open_journals.add(self)

    # -- doc identity ---------------------------------------------------

    def doc_id_for(self, state):
        """Durable id for a document state, assigning and registering on
        first use. Ids are monotonic and never recycled, so they survive
        slot reuse and promotion."""
        did = getattr(state, '_dur_id', None)
        if did is not None and self.docs.get(did) is state:
            return did
        did = self.next_doc_id
        self.next_doc_id += 1
        try:
            state._dur_id = did
        except AttributeError:
            pass                      # non-slotted stand-ins (tests)
        self.docs[did] = state
        return did

    # -- appends --------------------------------------------------------

    @property
    def buffered_bytes(self):
        return len(self._pending)

    @property
    def pending_fsync_bytes(self):
        """Bytes written but not yet fsynced — the crash-loss window on
        top of whatever is still buffered."""
        return self.written_bytes - self.durable_bytes

    def append(self, doc_id, payload, kind=KIND_CHANGE):
        self._pending += encode_frame(kind, doc_id, bytes(payload))
        self.records += 1
        self.dirty.add(doc_id)
        _stats.inc('journal_records')

    def record_changes(self, state, buffers, commit=True):
        """Journal a batch of accepted change buffers for one document
        (the seam hook entry point)."""
        did = self.doc_id_for(state)
        for buf in buffers:
            self.append(did, buf)
        if commit:
            self.commit()

    @_spanned('journal_append')
    def record_seam(self, handles, per_doc_changes, errors=None):
        """The hot seam hook for the 10k-doc turbo batch: every ACCEPTED
        doc's buffers collected in one flattened pass and framed as a
        single columnar KIND_BATCH frame — one crc32 call per record
        instead of per-record framing; this path is what the <=15%
        journal-overhead budget is measured on. Small batches (below
        _BATCH_MIN) keep per-record frames, whose fixed overhead is
        lower. Docs with errors[d] set contribute nothing — the journal
        never holds refused bytes."""
        docs = self.docs
        next_id = self.next_doc_id
        dids = []
        bufs = []
        add_d = dids.append
        add_b = bufs.append
        for d, (handle, buffers) in enumerate(zip(handles,
                                                  per_doc_changes)):
            if not buffers or (errors is not None and
                               errors[d] is not None):
                continue
            state = handle['state']
            did = getattr(state, '_dur_id', None)
            if did is None or docs.get(did) is not state:
                did = next_id
                next_id += 1
                try:
                    state._dur_id = did
                except AttributeError:
                    pass
                docs[did] = state
            if len(buffers) == 1:        # the overwhelmingly common shape
                buf = buffers[0]
                add_d(did)
                add_b(buf if type(buf) is bytes else bytes(buf))
            else:
                for buf in buffers:
                    add_d(did)
                    add_b(buf if type(buf) is bytes else bytes(buf))
        n_rec = len(bufs)
        if not n_rec:
            return
        self.next_doc_id = next_id
        if n_rec < _BATCH_MIN:
            for did, buf in zip(dids, bufs):
                self._pending += encode_frame(KIND_CHANGE, did, buf)
        else:
            self._pending += _encode_batch(dids, bufs)
        self.records += n_rec
        self.dirty.update(dids)
        _stats.inc('journal_records', n_rec)
        self.commit()

    def record_free(self, state, commit=True):
        """Journal a document free. No-op for documents that never
        journaled (nothing durable to retract)."""
        did = getattr(state, '_dur_id', None)
        if did is None or self.docs.get(did) is not state:
            return
        self.append(did, b'', kind=KIND_FREE)
        self.freed.add(did)
        self.docs.pop(did, None)
        if commit:
            self.commit()

    # -- durability -----------------------------------------------------

    @contextlib.contextmanager
    def group(self):
        """Defer commits to the end of the block: per-doc apply paths
        inside a batched call journal through FleetDoc.apply_changes,
        whose own commit would otherwise write+fsync once per DOCUMENT
        instead of once per batch. Reentrant; the exit commit covers
        whatever was accepted even when the block raises mid-batch."""
        self._group_depth += 1
        try:
            yield
        finally:
            self._group_depth -= 1
            if self._group_depth == 0:
                self.commit()

    def commit(self):
        """Group commit: one write for everything buffered, fsync under
        the batching policy. Inside a group() block this is a no-op —
        the block's exit performs the single real commit."""
        if self._group_depth > 0:
            return
        with _span('journal_commit', bytes=len(self._pending)):
            if self._pending:
                self._f.write(self._pending)
                self._f.flush()
                self.written_bytes += len(self._pending)
                self._pending = bytearray()
            _stats.inc('journal_commits')
            if self.fsync_bytes <= 0 or \
                    self.pending_fsync_bytes >= self.fsync_bytes:
                self._fsync()
            else:
                self._check_loss_window()

    def sync(self):
        """Force full durability: write + fsync regardless of policy."""
        if self._pending:
            self._f.write(self._pending)
            self._f.flush()
            self.written_bytes += len(self._pending)
            self._pending = bytearray()
        self._fsync()

    def _fsync(self):
        if self.durable_bytes == self.written_bytes:
            return
        start = time.perf_counter()
        with _span('journal_fsync',
                   bytes=self.written_bytes - self.durable_bytes):
            os.fsync(self._f.fileno())
        _hist.record_value('fsync_s', time.perf_counter() - start,
                           scale=1e9, unit='s')
        self.durable_bytes = self.written_bytes
        _stats.inc('journal_fsyncs')
        self._window_alerted = False    # window closed; re-arm the alert

    def _check_loss_window(self):
        """Edge-triggered loss-window alert: the first commit that
        leaves pending_fsync_bytes above the configured threshold bumps
        'fsync_window_alerts' and lands a flight event; the alert
        re-arms when an fsync closes the window."""
        if _fsync_alert_bytes <= 0 or self._window_alerted:
            return
        pending = self.pending_fsync_bytes
        if pending >= _fsync_alert_bytes:
            self._window_alerted = True
            _stats.inc('fsync_window_alerts')
            _flight.record_event('fsync_window_alert', path=self.path,
                                 pending_bytes=pending,
                                 threshold=_fsync_alert_bytes,
                                 fsync_bytes=self.fsync_bytes)

    def close(self):
        if not self.closed:
            self.sync()
            self._f.close()
            self.closed = True

    def stats(self):
        return {
            'buffered_bytes': self.buffered_bytes,
            'pending_fsync_bytes': self.pending_fsync_bytes,
            'durable_bytes': self.durable_bytes,
            'written_bytes': self.written_bytes,
            'records': self.records,
            'registered_docs': len(self.docs),
        }


# ---------------------------------------------------------------------------
# recovery report
# ---------------------------------------------------------------------------


class RecoveryReport:
    """What recovery found and did. ``quarantined`` maps doc_id ->
    DocError for documents whose snapshot frame or journal records were
    rejected (typed; the rest of the fleet recovered); ``ok`` is True
    when nothing was quarantined or truncated."""

    __slots__ = ('manifest_seq', 'used_fallback_manifest', 'snapshot_docs',
                 'queued_buffers', 'replayed_records', 'replayed_bytes',
                 'torn_tail_bytes', 'rotted_records', 'quarantined',
                 'freed_docs')

    def __init__(self):
        self.manifest_seq = None
        self.used_fallback_manifest = False
        self.snapshot_docs = 0
        self.queued_buffers = 0
        self.replayed_records = 0
        self.replayed_bytes = 0
        self.torn_tail_bytes = 0
        self.rotted_records = 0
        self.quarantined = {}
        self.freed_docs = []

    @property
    def ok(self):
        return not self.quarantined and not self.torn_tail_bytes and \
            not self.rotted_records

    def __repr__(self):
        return (f'RecoveryReport(seq={self.manifest_seq}, '
                f'snapshot_docs={self.snapshot_docs}, '
                f'replayed={self.replayed_records}, '
                f'torn_tail={self.torn_tail_bytes}, '
                f'rotted={self.rotted_records}, '
                f'quarantined={sorted(self.quarantined)}, '
                f'freed={self.freed_docs})')


def _snap_name(seq):
    return f'snapshot-{seq:08d}.snap'


def _journal_name(seq):
    return f'journal-{seq:08d}.log'


def _stitch_segments(path, names):
    """Load + stitch a snapshot-segment chain (oldest -> newest). Raises
    MalformedSnapshot / OSError through — callers decide fallback
    policy."""
    results = []
    for name in names:
        with open(os.path.join(path, name), 'rb') as f:
            results.append(parse_snapshot_bytes(f.read()))
    return _stitch_parsed(results)


def _stitch_parsed(seg_results):
    """Stitch already-parsed segments (oldest -> newest): a later
    KIND_DOC supersedes earlier copies (and replaces the doc's queued
    list), a KIND_FREE tombstone erases the doc. Per-doc rot errors from
    an OLDER segment are dropped when a newer segment supersedes the doc
    (the newest persisted copy is what matters)."""
    docs, queued = {}, {}
    errors_by_doc = {}
    unattributed = []
    for seg_docs, seg_queued, seg_errors, _meta in seg_results:
        for did, payload in seg_docs.items():
            if payload is None:
                docs.pop(did, None)
                queued.pop(did, None)
                errors_by_doc.pop(did, None)
            else:
                docs[did] = payload
                queued[did] = seg_queued.get(did, [])
                if not queued[did]:
                    queued.pop(did, None)
                errors_by_doc.pop(did, None)
        for did, err in seg_errors:
            if did is None:
                unattributed.append((None, err))
            else:
                errors_by_doc[did] = err
                # the newest copy of this doc is rot: an older stitched
                # copy (if any) becomes the doc's last good prefix
    errors = unattributed + [(did, err)
                             for did, err in sorted(errors_by_doc.items())]
    return docs, queued, errors


def read_state(path):
    """Low-level recovery inputs from a durability directory, backend
    agnostic (the chaos harness rebuilds host-backend peers from this).
    Returns a dict with 'manifest', 'docs' {doc_id: save_bytes} (the
    STITCHED view over the manifest's segment chain — base snapshot plus
    incremental per-doc compaction segments, tombstones applied),
    'queued' {doc_id: [buffers]}, 'snapshot_errors'
    [(doc_id | None, MalformedSnapshot)], 'journal_records'
    [(kind, doc_id, payload)], 'journal_info' (parse_journal_bytes
    report) and 'used_fallback_manifest'. Raises MalformedSnapshot only
    when no valid manifest AND no structurally-valid snapshot exists but
    damaged ones do (an unrecoverable directory)."""
    manifest = None
    fallback = False
    stitched = None
    mpath = os.path.join(path, MANIFEST_NAME)
    if os.path.exists(mpath):
        try:
            with open(mpath, 'rb') as f:
                manifest = parse_manifest_bytes(f.read())
        except (MalformedSnapshot, OSError):
            manifest = None
    if manifest is not None:
        chain = manifest.get('chain')
        if chain is None:           # pre-segment manifest
            chain = [manifest['snapshot']] if manifest.get('snapshot') \
                else []
        manifest['chain'] = chain
        try:
            stitched = _stitch_segments(path, chain)
        except (MalformedSnapshot, OSError):
            stitched = None
            manifest = None           # fall back to the directory scan
    journal_start = None
    if manifest is None:
        # manifest missing or pointing at damage: scan for the newest
        # structurally-valid BASE snapshot on disk, then stitch every
        # structurally-valid newer segment on top of it (invalid ones
        # are skipped — their docs fall back to older copies)
        fallback = True
        found_damaged = False
        snaps = []
        for name in os.listdir(path):
            if name.startswith('snapshot-') and name.endswith('.snap'):
                try:
                    snaps.append(
                        (int(name[len('snapshot-'):-len('.snap')]), name))
                except ValueError:
                    continue
        parsed = {}
        base_seq = None
        for fseq, name in sorted(snaps, reverse=True):
            try:
                with open(os.path.join(path, name), 'rb') as f:
                    parsed[fseq] = (name, parse_snapshot_bytes(f.read()))
            except (MalformedSnapshot, OSError):
                found_damaged = True
                continue
            if parsed[fseq][1][3].get('base', True):
                base_seq = fseq
                break
        if base_seq is not None:
            valid = sorted(s for s in parsed if s >= base_seq)
            chain = [parsed[s][0] for s in valid]
            # stitch from the results the scan ALREADY parsed — no
            # second read (and no unguarded I/O escaping the fallback)
            stitched = _stitch_parsed([parsed[s][1] for s in valid])
            manifest = {'seq': valid[-1], 'snapshot': chain[-1],
                        'chain': chain,
                        'journal': _journal_name(valid[-1]),
                        'journal_offset': 0}
            # older journals may survive retention: replay everything on
            # disk from the base generation up (idempotent over segment
            # content — the hash graph dedupes, FREE follows its doc's
            # changes within a journal, ids never recycle)
            journal_start = base_seq
        else:
            if found_damaged:
                raise MalformedSnapshot(
                    'no valid manifest or snapshot in durability dir '
                    '(damaged snapshots present)')
            # brand-new or journal-only directory: synthesize gen 0
            journals = sorted((f for f in os.listdir(path)
                               if f.startswith('journal-')
                               and f.endswith('.log')), reverse=True)
            seq = int(journals[0][len('journal-'):-len('.log')]) \
                if journals else 0
            manifest = {'seq': seq, 'snapshot': None, 'chain': [],
                        'journal': _journal_name(seq), 'journal_offset': 0}
            journal_start = 0
    docs, queued, snap_errors = stitched if stitched is not None \
        else ({}, {}, [])
    # Journal CHAIN replay: walk journal files upward from the chosen
    # generation (fallback mode: from the base generation, skipping
    # retention gaps). Normally there is exactly one; a crash
    # mid-checkpoint leaves an empty successor, and a fallback onto an
    # OLDER retained generation finds the retained journals — so a
    # single rotted segment never costs the suffix.
    journal_records, journal_info = [], {
        'torn_tail_bytes': 0, 'rotted': [], 'valid_end': 0,
        'scanned_bytes': 0}
    seq = int(manifest['seq'])
    if journal_start is not None:
        jseqs = []
        for name in os.listdir(path):
            if name.startswith('journal-') and name.endswith('.log'):
                try:
                    js = int(name[len('journal-'):-len('.log')])
                except ValueError:
                    continue
                if js >= journal_start:
                    jseqs.append(js)
        jseqs.sort()
    else:
        jseqs = []
        s = seq
        while os.path.exists(os.path.join(path, _journal_name(s))):
            jseqs.append(s)
            s += 1
    for s in jseqs:
        jp = os.path.join(path, _journal_name(s))
        if not os.path.exists(jp):
            continue
        with open(jp, 'rb') as f:
            jbytes = f.read()
        recs, inf = parse_journal_bytes(
            jbytes,
            offset=int(manifest.get('journal_offset') or 0)
            if s == seq else 0)
        base = len(journal_records)
        journal_records += recs
        journal_info['torn_tail_bytes'] += inf['torn_tail_bytes']
        journal_info['rotted'] += [(did, at, base + idx)
                                   for did, at, idx in inf['rotted']]
        journal_info['valid_end'] = inf['valid_end']
        journal_info['scanned_bytes'] += inf['scanned_bytes']
    return {
        'manifest': manifest,
        'docs': docs,
        'queued': queued,
        'snapshot_errors': snap_errors,
        'journal_records': journal_records,
        'journal_info': journal_info,
        'used_fallback_manifest': fallback,
        'max_journal_seq': jseqs[-1] if jseqs else seq,
    }


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------


class DurableFleet:
    """A DocFleet bound to a durability directory: journaled mutation
    seams, whole-fleet checkpoints, cost-triggered compaction, crash
    recovery.

    ``DurableFleet(path)`` starts a FRESH durability directory (raises
    if one already holds a manifest — recover instead);
    ``DurableFleet.recover(path)`` rebuilds the fleet from disk.
    Checkpointing is synchronous with the caller: do not interleave it
    with applies from another thread (the rest of the engine is
    single-threaded by contract too)."""

    def __init__(self, path, fleet=None, *, exact_device=False,
                 fsync_bytes=0, compact_bytes=16 << 20,
                 compact_records=100_000, retain=2, max_chain=8,
                 doc_capacity=64, key_capacity=64, _recovered=None):
        from .backend import DocFleet
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.fsync_bytes = fsync_bytes
        self.compact_bytes = compact_bytes
        self.compact_records = compact_records
        # generations kept on disk: the newest (snapshot, journal) pair
        # plus retain-1 predecessors, so structural rot in the newest
        # snapshot falls back to the previous generation and replays the
        # retained journal chain instead of failing fleet-wide
        self.retain = max(int(retain), 1)
        # incremental segments allowed on top of the base snapshot before
        # compaction escalates to a full checkpoint (bounds recovery's
        # stitch work and the chain's disk amplification)
        self.max_chain = max(int(max_chain), 1)
        if _recovered is not None:
            # internal: recovery built the fleet + registry already; the
            # closing persist RE-JOURNALS what replay applied instead of
            # re-snapshotting the whole fleet — recovery work stays
            # proportional to the replayed suffix, not fleet size
            (self.fleet, last_seq, docs, next_doc_id, chain,
             rejournal) = _recovered
            self.chain = list(chain)
            new_seq = int(last_seq) + 1
            self.seq = new_seq
            self.journal = ChangeJournal(
                os.path.join(path, _journal_name(new_seq)),
                fsync_bytes=fsync_bytes, docs=docs,
                next_doc_id=next_doc_id)
            # re-frame the replayed records; runs of CHANGE records use
            # the columnar batch frame (one crc32 per record, the hot
            # seam format) so the closing persist stays cheap at scale
            pend_d, pend_b = [], []

            def _flush_changes():
                if not pend_b:
                    return
                if len(pend_b) < _BATCH_MIN:
                    for did, buf in zip(pend_d, pend_b):
                        self.journal._pending += encode_frame(
                            KIND_CHANGE, did, buf)
                else:
                    self.journal._pending += _encode_batch(pend_d, pend_b)
                self.journal.records += len(pend_b)
                self.journal.dirty.update(pend_d)
                _stats.inc('journal_records', len(pend_b))
                pend_d.clear()
                pend_b.clear()

            for kind, did, payload in rejournal:
                if kind == KIND_CHANGE:
                    pend_d.append(did)
                    pend_b.append(bytes(payload))
                    continue
                _flush_changes()
                self.journal.append(did, payload, kind=kind)
                if kind == KIND_FREE:
                    self.journal.freed.add(did)
            _flush_changes()
            self.journal.sync()
            self._fault('journal-rotated')
            self._write_manifest()
            self._fault('manifest-flipped')
            self._retention_sweep(new_seq)
            self.fleet.attach_journal(self.journal)
            return
        if os.path.exists(os.path.join(path, MANIFEST_NAME)) or \
                any(f.startswith(('snapshot-', 'journal-'))
                    for f in os.listdir(path)):
            raise ValueError(
                f'{path!r} already holds a durable fleet: use '
                f'DurableFleet.recover()')
        self.fleet = fleet if fleet is not None else DocFleet(
            doc_capacity=doc_capacity, key_capacity=key_capacity,
            exact_device=exact_device)
        self.seq = 0
        self.chain = []
        self.journal = ChangeJournal(
            os.path.join(path, _journal_name(0)), fsync_bytes=fsync_bytes)
        self._write_manifest()
        self.fleet.attach_journal(self.journal)

    # -- document lifecycle --------------------------------------------

    def init_docs(self, n):
        """Create n journaled fleet documents. Each gets an INIT record,
        so even never-edited documents survive a crash before the next
        checkpoint (alloc -> crash -> recover keeps the empty doc)."""
        from . import backend as fleet_backend
        handles = fleet_backend.init_docs(n, self.fleet)
        for handle in handles:
            did = self.journal.doc_id_for(handle['state'])
            self.journal.append(did, b'', kind=KIND_INIT)
        self.journal.commit()
        return handles

    def load_docs(self, buffers):
        """Bulk-load saved documents AND journal their chunks, so a crash
        before the next checkpoint replays the load."""
        from .loader import load_docs
        handles = load_docs([bytes(b) for b in buffers], self.fleet)
        for handle, buf in zip(handles, buffers):
            did = self.journal.doc_id_for(handle['state'])
            self.journal.append(did, bytes(buf))
        self.journal.commit()
        return handles

    def adopt(self, handle):
        """Bring an existing fleet document under durability: journal its
        full current history (one document chunk) as the baseline."""
        state = handle['state']
        did = self.journal.doc_id_for(state)
        self.journal.append(did, bytes(state.save()))
        self.journal.commit()
        return did

    def apply_changes(self, handles, per_doc_changes, mirror=False,
                      on_error='quarantine'):
        """Journaled batched apply (the seam hooks do the journaling;
        this wrapper adds the compaction check)."""
        from . import backend as fleet_backend
        out = fleet_backend.apply_changes_docs(
            handles, per_doc_changes, mirror=mirror, on_error=on_error)
        self.maybe_compact()
        return out

    def handles(self):
        """{doc_id: fresh backend handle} for every registered live
        document."""
        return {did: {'state': state, 'heads': list(state.heads)}
                for did, state in sorted(self.journal.docs.items())}

    def adopt_fleet(self, fleet):
        """Point the manager at a rebuilt fleet. backend.rebuild_docs
        (the donation-failure recovery) moves the journal and each doc's
        durable id to the new fleet already; this updates the manager's
        own reference so checkpoints keep re-attaching the rotated
        journal to the fleet that is actually live."""
        self.fleet = fleet
        if fleet.journal is None:
            fleet.attach_journal(self.journal)

    # -- replay debt / compaction --------------------------------------

    def replay_debt(self):
        """Bytes/records recovery would replay if the process died now."""
        j = self.journal
        return {'bytes': j.written_bytes + j.buffered_bytes,
                'records': j.records}

    def chain_debt(self):
        """Stitch debt of the incremental chain: the segments past the
        base snapshot and their on-disk bytes — what recovery must open
        and scan ON TOP of the base, and what the retention sweep must
        keep protected. Feeds CostModel.chain_escalate_due."""
        tail = self.chain[1:]
        total = 0
        for name in tail:
            try:
                total += os.path.getsize(os.path.join(self.path, name))
            except OSError:
                pass
        return {'segments': len(tail), 'bytes': total}

    def base_bytes(self):
        """On-disk size of the chain's base snapshot (0 when none) —
        the dominant term of a full checkpoint's rewrite cost."""
        if not self.chain:
            return 0
        try:
            return os.path.getsize(os.path.join(self.path, self.chain[0]))
        except OSError:
            return 0

    def maybe_compact(self, force=False):
        """Compact once replay debt crosses the byte/record threshold
        (the LSM-style cost trigger). Compaction is INCREMENTAL: only
        documents with journaled records this generation re-persist (a
        per-doc segment, SynchroStore-style) — touching K of N docs does
        O(K) work; the chain escalates to a full checkpoint after
        `max_chain` segments. Returns True if it compacted."""
        debt = self.replay_debt()
        if not force and debt['bytes'] < self.compact_bytes and \
                debt['records'] < self.compact_records:
            return False
        with _span('compaction', debt_bytes=debt['bytes'],
                   debt_records=debt['records']):
            did_work = self.compact()
        if did_work:
            _stats.inc('compactions')
        return did_work

    # -- checkpointing --------------------------------------------------

    def _write_manifest(self):
        meta = {'seq': self.seq,
                'snapshot': self.chain[-1] if self.chain else None,
                'chain': list(self.chain),
                'journal': _journal_name(self.seq), 'journal_offset': 0,
                'next_doc_id': self.journal.next_doc_id}
        payload = json.dumps(meta, sort_keys=True).encode('utf8')
        _atomic_write(os.path.join(self.path, MANIFEST_NAME),
                      MANIFEST_MAGIC + encode_frame(KIND_END, 0, payload))

    def _write_segment(self, new_seq, doc_items, tombstones, base):
        """Write one snapshot file (base or incremental segment) via
        temp + fsync + atomic rename. Returns (name, docs_written)."""
        snap_name = _snap_name(new_seq)
        tmp = os.path.join(self.path, snap_name + '.tmp')
        n_frames = 1
        n_docs = 0
        with open(tmp, 'wb') as f:
            f.write(SNAP_MAGIC)
            f.write(encode_frame(KIND_SMETA, SMETA_DOC_ID, json.dumps(
                {'base': bool(base), 'seq': new_seq},
                sort_keys=True).encode('utf8')))
            for did, state in doc_items:
                f.write(encode_frame(KIND_DOC, did, bytes(state.save())))
                n_frames += 1
                n_docs += 1
                for entry in getattr(state, 'queue', []) or []:
                    buf = entry.get('buffer') if isinstance(entry, dict) \
                        else None
                    if buf is not None:
                        f.write(encode_frame(KIND_QUEUED, did, bytes(buf)))
                        n_frames += 1
            for did in sorted(tombstones):
                f.write(encode_frame(KIND_FREE, did, b''))
                n_frames += 1
            f.write(encode_frame(KIND_END, 0, _U32.pack(n_frames)))
            f.flush()
            os.fsync(f.fileno())
        self._fault('snapshot-temp-written')
        os.replace(tmp, os.path.join(self.path, snap_name))
        _fsync_dir(self.path)
        self._fault('snapshot-renamed')
        return snap_name, n_docs

    def _rotate_and_flip(self, new_seq, live, next_doc_id):
        """Steps 3-5 of the checkpoint protocol: fresh journal
        generation, manifest flip, retention sweep."""
        # A stale successor journal (crash mid-checkpoint, or the
        # generation a fallback recovery just consumed) is removed only
        # NOW — after the snapshot that supersedes its records is
        # durable. Removing it earlier would lose fsynced changes if we
        # died during the snapshot write. The crash window between the
        # rename and this remove is safe: recovery would replay the
        # stale journal's records on top of a snapshot that already
        # contains them, and change application is idempotent (the hash
        # graph dedupes known changes — verified for turbo, exact and
        # bulk-loaded docs).
        new_path = os.path.join(self.path, _journal_name(new_seq))
        if os.path.exists(new_path):
            os.remove(new_path)
        if self.journal is not None:
            self.journal.close()
        self.seq = new_seq
        self.journal = ChangeJournal(
            new_path, fsync_bytes=self.fsync_bytes, docs=live,
            next_doc_id=next_doc_id)
        self.fleet.attach_journal(self.journal)
        self._fault('journal-rotated')
        self._write_manifest()
        self._fault('manifest-flipped')
        self._retention_sweep(new_seq)

    def _retention_sweep(self, new_seq):
        """Keep the newest `retain` generations plus every snapshot the
        live chain still references; delete the rest."""
        protected = set(self.chain)
        for name in os.listdir(self.path):
            for prefix, suffix in (('snapshot-', '.snap'),
                                   ('journal-', '.log')):
                if name.startswith(prefix) and name.endswith(suffix):
                    if name in protected:
                        continue
                    try:
                        fseq = int(name[len(prefix):-len(suffix)])
                    except ValueError:
                        continue
                    if fseq <= new_seq - self.retain or fseq > new_seq:
                        try:
                            os.remove(os.path.join(self.path, name))
                        except OSError:
                            pass

    @_spanned('checkpoint')
    def checkpoint(self):
        """Whole-fleet BASE snapshot + journal rotation, crash-safe at
        every step: (1) everything journaled so far is fsynced, (2) the
        snapshot lands via temp + fsync + atomic rename, (3) a fresh
        journal generation is created, (4) the manifest atomically
        flips to the new pair, (5) only then is the old generation
        deleted — a crash anywhere leaves the manifest pointing at a
        complete (snapshot chain, journal) pair. The segment chain
        resets to this snapshot."""
        self.journal.sync()
        docs = self.journal.docs
        next_doc_id = self.journal.next_doc_id
        # drop freed/dead documents from the registry (their FREE records
        # die with the rotated journal)
        live = {did: state for did, state in docs.items()
                if getattr(state, '_impl', True) is not None}
        new_seq = self.seq + 1
        snap_name, _n = self._write_segment(new_seq, sorted(live.items()),
                                            (), base=True)
        self.chain = [snap_name]
        self._rotate_and_flip(new_seq, live, next_doc_id)
        _stats.inc('checkpoints')

    @_spanned('compact_segment')
    def compact(self):
        """Incremental per-doc compaction: persist ONLY the documents
        that journaled records this generation (plus tombstones for the
        freed) as one segment appended to the chain, then rotate the
        journal — replay debt resets to zero at O(churn) cost. The
        chain escalates to a full checkpoint past `max_chain` segments
        (bounding stitch work and disk amplification). Returns True when
        anything was persisted (incl. the escalated full checkpoint),
        False when zero churn made it a no-op. Recovery stitches the
        chain; byte-identical to a full-checkpoint recovery."""
        escalate = not self.chain or len(self.chain) >= self.max_chain
        model = getattr(self, 'cost_model', None)
        if not escalate and model is not None:
            # the attached cost model (TieringController wires it) may
            # escalate EARLIER than the fixed ceiling when the chain's
            # stitch debt already outweighs the full rewrite; max_chain
            # stays the hard backstop bounding stitch work absolutely
            escalate = model.chain_escalate_due(
                self, stage=getattr(self, 'pressure_stage', 0))
        if escalate:
            # no base yet (a fleet that never checkpointed): segments
            # without a base are invisible to the manifest-rot fallback
            # scan, and retention would eventually delete the journals
            # holding their records — the first compaction MUST cut the
            # base snapshot
            self.checkpoint()
            return True
        self.journal.sync()
        docs = self.journal.docs
        next_doc_id = self.journal.next_doc_id
        dirty = set(self.journal.dirty)
        freed = set(self.journal.freed)
        live = {did: state for did, state in docs.items()
                if getattr(state, '_impl', True) is not None}
        # dirty docs that died without surviving to the registry (freed,
        # or detached by rebuild/promotion) tombstone — they must not
        # resurrect from an older segment copy
        tombstones = freed | {did for did in dirty if did not in live}
        doc_items = sorted((did, live[did]) for did in dirty
                           if did in live)
        if not doc_items and not tombstones:
            return False                 # nothing journaled: no-op
        new_seq = self.seq + 1
        snap_name, n_docs = self._write_segment(new_seq, doc_items,
                                                tombstones, base=False)
        self.chain = self.chain + [snap_name]
        self._rotate_and_flip(new_seq, live, next_doc_id)
        _stats.inc('segments')
        _stats.inc('segment_docs', n_docs)
        return True

    def _fault(self, point):
        """Crash-point hook: a no-op in production; tools/crashtest.py
        overrides it to simulate dying at each step of the checkpoint
        protocol (every step must leave a recoverable directory)."""

    def close(self):
        """Flush + fsync the journal and DETACH it from the fleet, so a
        closed manager's fleet can keep operating (un-journaled) instead
        of writing into a closed file."""
        if self.journal is not None:
            self.journal.close()
        if getattr(self, 'fleet', None) is not None and \
                self.fleet.journal is self.journal:
            self.fleet.attach_journal(None)

    # -- recovery -------------------------------------------------------

    @classmethod
    def recover(cls, path, *, exact_device=False, mirror=False,
                fsync_bytes=0, compact_bytes=16 << 20,
                compact_records=100_000, retain=2, max_chain=8,
                doc_capacity=64, key_capacity=64):
        """Rebuild a durable fleet from disk. Returns (manager, handles,
        report): handles is {doc_id: backend handle} for every recovered
        live document. Torn journal tails truncate at the first bad CRC
        frame; rotted records (and any records after them for the same
        doc) quarantine exactly their own doc; the replayed suffix goes
        through apply_changes_docs(on_error='quarantine') so hostile
        bytes ON DISK get the same one-doc blast radius as hostile bytes
        on the wire. Recovery ends with a fresh checkpoint, so the
        directory is compact and consistent when this returns."""
        rs = _span_seq()
        try:
            return cls._recover_impl(
                path, rs, exact_device=exact_device, mirror=mirror,
                fsync_bytes=fsync_bytes, compact_bytes=compact_bytes,
                compact_records=compact_records, retain=retain,
                max_chain=max_chain, doc_capacity=doc_capacity,
                key_capacity=key_capacity)
        finally:
            # done() is idempotent: on success the impl already closed
            # the last phase; on a raise this records it (with whatever
            # phase recovery died in still attributed)
            rs.done()

    @classmethod
    def _recover_impl(cls, path, rs, *, exact_device, mirror, fsync_bytes,
                      compact_bytes, compact_records, retain, max_chain,
                      doc_capacity, key_capacity):
        from . import backend as fleet_backend
        from .backend import DocFleet
        from .loader import load_docs

        rs.mark('recovery_read', path=str(path))
        st = read_state(path)
        report = RecoveryReport()
        report.manifest_seq = st['manifest']['seq']
        report.used_fallback_manifest = st['used_fallback_manifest']
        info = st['journal_info']
        report.torn_tail_bytes = info['torn_tail_bytes']
        report.rotted_records = len(info['rotted'])
        if report.torn_tail_bytes:
            _stats.inc('journal_truncations')
            _flight.record_event('recovery_truncation',
                                 bytes=report.torn_tail_bytes,
                                 path=str(path))
        _stats.inc('rotted_records', report.rotted_records)
        for _did, _at, _rec in info['rotted']:
            _flight.record_event('journal_rot', durable_id=_did,
                                 at_byte=_at, record=_rec)

        fleet = DocFleet(doc_capacity=doc_capacity,
                         key_capacity=key_capacity,
                         exact_device=exact_device)
        states = {}               # doc_id -> FleetDoc state
        handles = {}              # doc_id -> current backend handle

        def quarantine(did, stage, exc):
            report.quarantined[did] = DocError(did, stage, exc)
            # did IS the durable id here — recovery keys everything by it
            _flight.record_event('quarantine', doc=did, durable_id=did,
                                 stage=stage, error=type(exc).__name__,
                                 message=str(exc)[:200])

        # ---- snapshot load (bulk native parse, per-doc typed fallback)
        rs.mark('recovery_snapshot_load', docs=len(st['docs']))
        snap_ids = sorted(st['docs'])
        report.snapshot_docs = len(snap_ids)
        payloads = [st['docs'][d] for d in snap_ids]
        loaded = None
        if payloads:
            try:
                loaded = load_docs(payloads, fleet)
            except AutomergeError:
                loaded = []
                for did, buf in zip(snap_ids, payloads):
                    try:
                        loaded.append(load_docs([buf], fleet)[0])
                    except AutomergeError as exc:
                        quarantine(did, 'snapshot', exc)
                        loaded.append(fleet_backend.init(fleet))
        for did, handle in zip(snap_ids, loaded or []):
            handles[did] = handle
            states[did] = handle['state']
        # rotted snapshot frames: the doc recovers EMPTY (its journal
        # suffix, if any, holds back at the causal gate) and is reported
        for did, err in st['snapshot_errors']:
            if did is not None and did not in handles:
                handle = fleet_backend.init(fleet)
                handles[did] = handle
                states[did] = handle['state']
            if did is not None:
                quarantine(did, 'snapshot', err)

        # ---- queued-at-checkpoint buffers re-apply (and re-queue)
        if st['queued']:
            qids = sorted(st['queued'])
            for did in qids:
                if did not in handles:
                    handle = fleet_backend.init(fleet)
                    handles[did] = handle
                    states[did] = handle['state']
            report.queued_buffers = sum(len(v) for v in st['queued'].values())
            out, _p, errs = fleet_backend.apply_changes_docs(
                [handles[d] for d in qids],
                [st['queued'][d] for d in qids], mirror=mirror,
                on_error='quarantine')
            for did, handle, err in zip(qids, out, errs):
                handles[did] = handle
                if err is not None and did not in report.quarantined:
                    quarantine(did, 'queued', err.error)

        # ---- journal replay: batched quarantining apply, segmented at
        # FREE records; records for a quarantined doc are skipped so the
        # doc lands exactly on its last good prefix. Every record that
        # APPLIES is collected into `rejournal` — recovery's closing
        # persist re-frames them into the fresh journal generation
        # instead of re-snapshotting the whole fleet (O(replayed), not
        # O(fleet))
        rs.mark('recovery_replay', records=len(st['journal_records']))
        skip = {did for did in report.quarantined}
        pending = {}              # doc_id -> [change payloads], in order
        rejournal = []            # (kind, did, payload) for the new gen

        def flush():
            if not pending:
                return
            ids = list(pending)
            for did in ids:
                if did not in handles:
                    handle = fleet_backend.init(fleet)
                    handles[did] = handle
                    states[did] = handle['state']
            start = time.perf_counter()
            out, _p, errs = fleet_backend.apply_changes_docs(
                [handles[d] for d in ids], [pending[d] for d in ids],
                mirror=mirror, on_error='quarantine')
            # per-doc AVERAGE replay cost, one sample per replay batch
            # (the batched apply cannot see true per-doc times; per-doc
            # outliers surface through doc_materialize_s instead)
            _hist.record_value('recovery_doc_s',
                               (time.perf_counter() - start) / len(ids),
                               scale=1e9, unit='s')
            for did, handle, err in zip(ids, out, errs):
                handles[did] = handle
                if err is not None:
                    skip.add(did)
                    if did not in report.quarantined:
                        quarantine(did, 'replay', err.error)
                else:
                    rejournal.extend((KIND_CHANGE, did, payload)
                                     for payload in pending[did])
            pending.clear()

        # attribute mid-stream rot: the victim keeps every record BEFORE
        # the rotted frame (its last good prefix) and loses the rotted
        # one plus everything after — exactly one doc's suffix
        cut = {}                  # doc_id -> record index of first loss
        for did, at, rec_idx in info['rotted']:
            if did is not None:
                cut[did] = min(cut.get(did, rec_idx), rec_idx)
                if did not in report.quarantined:
                    quarantine(did, 'replay', MalformedJournal(
                        f'journal: rotted record for doc {did} '
                        f'at byte {at}', doc_index=did))
        for rec_idx, (kind, did, payload) in \
                enumerate(st['journal_records']):
            if kind == KIND_CHANGE:
                if did in skip or rec_idx >= cut.get(did, 1 << 62):
                    continue
                pending.setdefault(did, []).append(bytes(payload))
                report.replayed_records += 1
                report.replayed_bytes += len(payload)
            elif kind == KIND_INIT:
                if did not in handles:
                    handle = fleet_backend.init(fleet)
                    handles[did] = handle
                    states[did] = handle['state']
                rejournal.append((KIND_INIT, did, b''))
            elif kind == KIND_FREE:
                flush()
                handle = handles.pop(did, None)
                states.pop(did, None)
                if handle is not None:
                    fleet_backend.free_docs([handle])
                report.freed_docs.append(did)
                rejournal.append((KIND_FREE, did, b''))
        flush()
        # a quarantined doc still recovers — to its last good prefix
        # (possibly empty), never silently vanishing from the fleet
        for did in report.quarantined:
            if did not in handles and did not in report.freed_docs:
                handle = fleet_backend.init(fleet)
                handles[did] = handle
                states[did] = handle['state']
        _stats.inc('replayed_records', report.replayed_records)
        _stats.inc('recovered_docs', len(handles))

        # quarantined docs stay registered (their handle holds the last
        # good prefix); rebuild the registry for the fresh journal.
        # next_doc_id folds in EVERY id the directory ever mentioned —
        # snapshot frames, journal records (incl. freed docs), rot
        # attributions — never just the live set: durable ids are
        # never recycled, and a fallback manifest carries no counter
        seen_ids = set(handles)
        seen_ids.update(st['docs'])
        seen_ids.update(report.freed_docs)
        seen_ids.update(did for _k, did, _p in st['journal_records'])
        seen_ids.update(did for did, _e in st['snapshot_errors']
                        if did is not None)
        next_doc_id = max(
            [st['manifest'].get('next_doc_id') or 0] +
            [d + 1 for d in seen_ids])
        for did, state in states.items():
            try:
                state._dur_id = did
            except AttributeError:
                pass
        rs.done(recovered_docs=len(handles))
        if report.torn_tail_bytes or report.rotted_records or \
                report.quarantined:
            # forensic dump: recovery found damage — name every affected
            # durable id, the stage it failed in, and the typed error,
            # with the surrounding event ring for context
            _flight.dump_flight_record('recovery', detail={
                'path': str(path),
                'manifest_seq': report.manifest_seq,
                'used_fallback_manifest': report.used_fallback_manifest,
                'torn_tail_bytes': report.torn_tail_bytes,
                'rotted_records': report.rotted_records,
                'errors': [e.describe(durable_id=did) for did, e in
                           sorted(report.quarantined.items())],
            })
        mgr = cls(path, fsync_bytes=fsync_bytes,
                  compact_bytes=compact_bytes,
                  compact_records=compact_records, retain=retain,
                  max_chain=max_chain,
                  _recovered=(fleet, st['max_journal_seq'],
                              dict(states), next_doc_id,
                              st['manifest']['chain'], rejournal))
        if not report.ok:
            # damage found: the chain still holds the rotted frames, so
            # a clean recovery would re-report them forever — heal with
            # one full checkpoint (damage is rare; the O(churn) fast
            # path stays for clean recoveries)
            mgr.checkpoint()
        return mgr, {did: handles[did] for did in sorted(handles)}, report
