"""Device-resident frontier index: ONE open-addressing hash table over
32-byte change hashes, serving exact membership for the sync plane and
the subscription hub's quiet-tick frontier compare.

The sync protocol's membership questions (``theirHave`` lastSync
reconciliation, received-heads lookup, incoming-change dedup) ride
per-document Python dicts today — O(1) per probe, but each probe forces
the doc's hash-graph dicts to exist (``_ensure_graph``), which is
O(history) to build, and the per-peer probe loops are host work that
grows with the fleet. Following WarpSpeed (PAPERS.md, the technique
source for concurrent GPU open-addressing tables), this module keeps the
whole fleet's (doc, hash) membership in ONE fixed-capacity open-
addressing table with batched, JIT-compiled insert/probe kernels: a full
round's probes are one device dispatch regardless of history length or
peer count — the same O(1)-dispatch property round 6 won for Bloom
build/probe (fleet/bloom.py), extended to exact membership.

Layout and algorithm
--------------------

- Keys are (space, hash) pairs: the 32-byte SHA-256 hash as eight
  little-endian uint32 lanes plus an int32 *space* id. Spaces are
  namespaces (one per doc slot, minted monotonically, never reused) so
  one physical table serves every doc without cross-doc false hits.
- Linear probing over a power-of-two capacity. The batched insert
  resolves intra-batch collisions with a claim scatter: every pending
  row proposes itself (scatter-min of row index) for its empty slot,
  winners write, losers re-probe the same slot next iteration — a loser
  carrying the SAME key then terminates on the match instead of
  double-inserting. Duplicate inserts are therefore idempotent by
  construction, in-batch and across batches.
- Tombstone-free deletion: ``release_space`` only marks the space dead
  (host-side bitmap). Dead keys stay physically resident — probes mask
  dead spaces host-side — and are reclaimed wholesale at the next
  grow-by-migration, which re-inserts only live-space keys into the
  doubled table (one dispatch). No tombstones, no probe-chain breaks.
- Host fallback for the tiny-N case: below ``device_min`` total keys the
  spaces live as plain Python sets (zero dispatches, faster than a
  device round-trip); the first insert crossing the threshold migrates
  everything device-side in one dispatch.

``frontier_compare`` is the second consumer: one dispatch comparing K
cursor head rows against K doc head rows (the ``_DocCols`` columnar
head32/head_n lanes), collapsing the subscription hub's 10k-subscriber
quiet tick into a single device call (query/subscriptions.py).

Every kernel is wrapped in ``instrument_kernel`` so the round-17 cost
ledger and ``obs_report --floor`` see it, and the module registers
dispatch/memory sources like fleet/bloom.py does.
"""

import weakref

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['HashIndex', 'FleetFrontierIndex', 'PeerSentSet',
           'flush_peer_sets', 'probe_peer_sets', 'release_sent_hashes',
           'release_sync_state', 'frontier_compare', 'hashes_to_rows',
           'engine_hash_population', 'dispatch_count', 'probe_window',
           'set_probe_window']

_GOLD = np.uint32(0x9E3779B9)     # Fibonacci-hash mix for the space id

# Device dispatches issued by the batched insert/probe/compare entry
# points since import — the frontier-index twin of bloom.dispatch_count()
# (the table serves host-side protocol drivers, which have no fleet
# dispatch counter in scope). bench.py and the quiet-tick pin tests diff
# this around a round.
_dispatches = 0


def dispatch_count():
    """Monotonic count of frontier-index device dispatches (insert +
    probe + migrate + frontier compare)."""
    return _dispatches


# AUTOMERGE_TPU_FRONTIER_INDEX=0 pins the classic host-dict membership
# path EVERYWHERE the index would otherwise serve — the batched driver
# AND the single-doc protocol (backend/sync.py known_hash_flags routes
# through _FlatEngine.probe_hashes, which consults this) — the bench's
# old-path contrast leg and a debugging escape hatch. Default on.
import os as _os  # noqa: E402
_frontier_enabled = _os.environ.get('AUTOMERGE_TPU_FRONTIER_INDEX') != '0'


def frontier_enabled():
    return _frontier_enabled


def set_frontier_enabled(on):
    """Toggle frontier-index routing (bench / debugging; returns the
    previous setting). Covers the batched sync driver and the warm
    single-doc probe path alike."""
    global _frontier_enabled
    prev = _frontier_enabled
    _frontier_enabled = bool(on)
    return prev


def _env_int(name, default, lo, hi):
    try:
        val = int(_os.environ.get(name, '') or default)
    except ValueError:
        val = default
    return max(lo, min(hi, val))


# The windowed-probe width and the host/device crossover were both tuned
# against XLA-CPU dispatch overhead (a while_loop iteration costs
# ~0.1 ms there). On-chip both tradeoffs move, so they are env-tunable —
# no code change to re-tune the fabric — and bench.py sweeps the window.
_DEF_PROBE_WINDOW = 16
_DEF_DEVICE_MIN = 4096
_probe_window = _env_int('AUTOMERGE_TPU_PROBE_WINDOW',
                         _DEF_PROBE_WINDOW, 1, 1024)
_default_device_min = _env_int('AUTOMERGE_TPU_DEVICE_MIN',
                               _DEF_DEVICE_MIN, 0, 1 << 30)


def probe_window():
    """Current windowed-probe width (slots gathered per probe before the
    serial tail walk). Set via AUTOMERGE_TPU_PROBE_WINDOW or
    ``set_probe_window``."""
    return _probe_window


def set_probe_window(width):
    """Set the probe window width (bench sweep / on-chip retune);
    returns the previous width. The probe kernel specializes per width
    (static jit arg), so each distinct width compiles once per batch
    shape and is cached thereafter."""
    global _probe_window
    prev = _probe_window
    _probe_window = max(1, min(1024, int(width)))
    return prev


from ..observability import register_dispatch_source  # noqa: E402
from ..observability.metrics import Counters  # noqa: E402
from ..observability.perf import instrument_kernel, register_mem_source  # noqa: E402
from ..observability.spans import spanned as _spanned  # noqa: E402
register_dispatch_source('hashindex', dispatch_count)

_stats = Counters({
    'hashindex_inserts': 0,       # keys newly landed in a table
    'hashindex_probes': 0,        # membership questions answered
    'hashindex_migrations': 0,    # grow-by-migration passes
    'hashindex_promotions': 0,    # host-mode tables promoted to device
    'hashindex_backfills': 0,     # doc registrations (history backfills)
    'hashindex_peer_spaces': 0,   # peer sentHashes spaces minted
    'hashindex_peer_releases': 0,  # peer spaces handed back
})
from ..observability import register_health_source  # noqa: E402
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])

_live_indexes = weakref.WeakSet()
_live_peer_sets = weakref.WeakSet()


def _index_bytes():
    total = 0
    for ix in list(_live_indexes):
        total += ix.resident_bytes()
    for ps in list(_live_peer_sets):
        total += ps.staged_bytes()
    return total


register_mem_source('hashindex_bytes', _index_bytes)


def _pow2(n, floor=1):
    out = max(int(floor), 1)
    n = int(n)
    while out < n:
        out *= 2
    return out


def hashes_to_rows(hashes):
    """Normalize hash input to an [N, 32] uint8 array: accepts a list of
    hex strings, a list of 32-byte buffers, or an [N, 32] uint8 array
    (returned as-is). One C-level hex decode for the whole batch."""
    if isinstance(hashes, np.ndarray):
        if hashes.dtype != np.uint8 or hashes.ndim != 2 or \
                hashes.shape[1] != 32:
            raise ValueError('hash array must be [N, 32] uint8')
        return hashes
    if not hashes:
        return np.zeros((0, 32), dtype=np.uint8)
    first = hashes[0]
    if isinstance(first, str):
        raw = bytes.fromhex(''.join(hashes))
    else:
        raw = b''.join(bytes(h) for h in hashes)
    if len(raw) != 32 * len(hashes):
        raise ValueError('hashes must be 256 bits')
    return np.frombuffer(raw, dtype=np.uint8).reshape(len(hashes), 32)


def _rows_to_words(rows):
    """[N, 32] uint8 -> [N, 8] uint32 key lanes (little-endian words)."""
    return np.ascontiguousarray(rows).view('<u4').reshape(len(rows), 8)


# ---- kernels ---------------------------------------------------------
# Plain jnp + jax.jit like fleet/bloom.py: the shapes (capacity, padded
# batch) are pow2 so recompiles stay O(log^2). x64 is disabled in this
# deployment, so keys ride as eight uint32 lanes, never uint64.

def _start_pos(keys, spaces, cap):
    mask = jnp.uint32(cap - 1)
    mix = keys[:, 0] ^ (spaces.astype(jnp.uint32) * jnp.uint32(_GOLD))
    return (mix & mask).astype(jnp.int32)


def _insert_kernel(tkey, tspace, keys, spaces, valid):
    """Batched insert of (space, key) pairs into the open-addressing
    table. Returns (tkey, tspace, n_new). Idempotent for keys already
    present (in the table or earlier in the batch)."""
    cap = tkey.shape[0]
    n = keys.shape[0]
    row = jnp.arange(n, dtype=jnp.int32)
    pos = _start_pos(keys, spaces, cap)
    wrap = jnp.int32(cap - 1)

    def cond(state):
        _tk, _ts, _pos, pending, _new = state
        return pending.any()

    def body(state):
        tk, ts, pos, pending, n_new = state
        slot_space = ts[pos]
        occ = slot_space >= 0
        match = pending & occ & (slot_space == spaces) & \
            jnp.all(tk[pos] == keys, axis=-1)
        pending = pending & ~match
        want = pending & ~occ
        # claim each empty slot for exactly one row (lowest index wins);
        # losers retry the SAME slot next iteration so a duplicate key
        # sees its winner's write and terminates on the match
        claim = jnp.full((cap,), n, dtype=jnp.int32)
        claim = claim.at[jnp.where(want, pos, cap)].min(row, mode='drop')
        won = want & (claim[pos] == row)
        wpos = jnp.where(won, pos, cap)
        tk = tk.at[wpos].set(keys, mode='drop')
        ts = ts.at[wpos].set(spaces, mode='drop')
        n_new = n_new + won.sum(dtype=jnp.int32)
        pending = pending & ~won
        advance = pending & occ & ~match
        pos = jnp.where(advance, (pos + 1) & wrap, pos)
        return tk, ts, pos, pending, n_new

    tkey, tspace, _pos, _pending, n_new = jax.lax.while_loop(
        cond, body, (tkey, tspace, pos, valid,
                     jnp.zeros((), dtype=jnp.int32)))
    return tkey, tspace, n_new


def _probe_kernel(tkey, tspace, keys, spaces, valid, window):
    """Batched exact-membership probe; [N] bool (True = present). The
    first `window` slots of every row's chain are gathered and
    compared in ONE vectorized pass (XLA-CPU while_loop iterations cost
    ~0.1ms each in dispatch overhead, so the common short-chain case
    must not loop); only rows still undecided after the window — all
    occupied, no match, possible at high load — take the serial tail
    walk. `window` is a static jit arg (see ``set_probe_window``).
    Sound because slots are never emptied in place (dead spaces
    stay occupied until migration), so a chain scan ending at an empty
    slot is always conclusive."""
    cap = tkey.shape[0]
    wrap = jnp.int32(cap - 1)
    pos0 = _start_pos(keys, spaces, cap)
    w = jnp.arange(window, dtype=jnp.int32)
    win = (pos0[:, None] + w[None, :]) & wrap            # [N, W]
    slot_space = tspace[win]                             # [N, W]
    occ = slot_space >= 0
    match = occ & (slot_space == spaces[:, None]) & \
        jnp.all(tkey[win] == keys[:, None, :], axis=-1)  # [N, W]
    big = jnp.int32(window + 1)
    first_match = jnp.min(jnp.where(match, w[None, :], big), axis=1)
    first_empty = jnp.min(jnp.where(~occ, w[None, :], big), axis=1)
    found = valid & (first_match < first_empty)
    undecided = valid & (first_match == big) & (first_empty == big)

    def cond(state):
        _pos, active, _found = state
        return active.any()

    def body(state):
        pos, active, found = state
        s = tspace[pos]
        occ = s >= 0
        hit = active & occ & (s == spaces) & \
            jnp.all(tkey[pos] == keys, axis=-1)
        found = found | hit
        active = active & occ & ~hit
        pos = jnp.where(active, (pos + 1) & wrap, pos)
        return pos, active, found

    tail_pos = (pos0 + jnp.int32(window)) & wrap
    _pos, _active, found = jax.lax.while_loop(
        cond, body, (tail_pos, undecided, found))
    return found


def _compare_kernel(cur32, cur_n, doc32, doc_n):
    """Quiet iff the cursor frontier equals the doc frontier: head
    counts agree AND (both empty, or the single head32 rows are byte
    equal). Counts past 1 (multi-head) are NEVER quiet here — those
    classes are host residue; answering False routes them there."""
    eq = jnp.all(cur32 == doc32, axis=-1)
    return (cur_n == doc_n) & ((cur_n == 0) | ((cur_n == 1) & eq))


# the table operands are DONATED: an insert's output table reuses the
# input buffers instead of copying capacity-sized arrays per call (the
# old table is dead the moment the wrapper reassigns self._tkey)
_insert_kernel = instrument_kernel(
    'hashindex_insert', jax.jit(_insert_kernel, donate_argnums=(0, 1)))
_probe_kernel = instrument_kernel(
    'hashindex_probe', jax.jit(_probe_kernel, static_argnums=(5,)))
_compare_kernel = instrument_kernel('frontier_compare',
                                    jax.jit(_compare_kernel))


def _pad_batch(words, spaces, valid, floor=8):
    n = len(spaces)
    n_pad = _pow2(n, floor=floor)
    if n_pad == n:
        return words, spaces, valid
    words = np.concatenate(
        [words, np.zeros((n_pad - n, 8), dtype=np.uint32)])
    spaces = np.concatenate(
        [spaces, np.full(n_pad - n, -1, dtype=np.int32)])
    valid = np.concatenate([valid, np.zeros(n_pad - n, dtype=bool)])
    return words, spaces, valid


@_spanned('frontier_compare')
def frontier_compare(cur32, cur_n, doc32, doc_n):
    """ONE device dispatch answering K frontier-equality questions:
    ``out[k]`` is True iff cursor frontier k (head32 row + head count,
    0 = empty, 1 = the row) equals doc frontier k. Inputs are numpy
    ([K, 32] uint8 and [K] int32-ish); rows are pow2-padded. Counts
    other than 0/1 must be resolved host-side by the caller."""
    global _dispatches
    k = len(cur_n)
    if k == 0:
        return np.zeros(0, dtype=bool)
    k_pad = _pow2(k, floor=8)
    c32 = np.zeros((k_pad, 32), dtype=np.uint8)
    c32[:k] = cur32
    d32 = np.zeros((k_pad, 32), dtype=np.uint8)
    d32[:k] = doc32
    cn = np.full(k_pad, -2, dtype=np.int32)
    cn[:k] = cur_n
    dn = np.full(k_pad, -3, dtype=np.int32)
    dn[:k] = doc_n
    out = _compare_kernel(jnp.asarray(c32), jnp.asarray(cn),
                          jnp.asarray(d32), jnp.asarray(dn))
    _dispatches += 1
    return np.asarray(out)[:k]


# ---- the table -------------------------------------------------------

class HashIndex:
    """Open-addressing exact-membership table over (space, 32-byte hash)
    keys. See the module docstring for the layout. Host mode (plain
    sets) below ``device_min`` total keys; device mode past it; both
    modes answer identically (the adversarial suite pins it)."""

    def __init__(self, capacity=1024, device_min=None, load_max=0.6):
        if load_max <= 0 or load_max >= 1:
            raise ValueError('load_max must be in (0, 1)')
        # None -> AUTOMERGE_TPU_DEVICE_MIN (default 4096) so the
        # host/device crossover is re-tunable on-chip without code
        self.device_min = _default_device_min if device_min is None \
            else int(device_min)
        self.load_max = float(load_max)
        self.cap = _pow2(capacity, floor=8)
        self._tkey = None          # [cap, 8] uint32 (device)
        self._tspace = None        # [cap] int32, -1 = empty (device)
        self.occupancy = 0         # physical slots used (incl. dead keys)
        self.n_keys = 0            # live keys (dead spaces excluded)
        self._next_space = 0
        self._live = np.zeros(64, dtype=bool)   # space id -> alive
        self._sets = {}            # host mode: space -> set of 32-byte keys
        self.grows = 0
        _live_indexes.add(self)

    # -- introspection -------------------------------------------------

    @property
    def mode(self):
        return 'host' if self._sets is not None else 'device'

    def resident_bytes(self):
        if self._sets is not None:
            # sets of 32-byte bytes objects: ~80 B object overhead each
            return sum(len(s) for s in self._sets.values()) * 112
        return self.cap * (8 * 4 + 4)

    def __len__(self):
        return self.n_keys

    # -- spaces --------------------------------------------------------

    def new_space(self):
        """Mint a fresh namespace id (never reused)."""
        sid = self._next_space
        self._next_space += 1
        if sid >= len(self._live):
            grown = np.zeros(_pow2(sid + 1, floor=64), dtype=bool)
            grown[:len(self._live)] = self._live
            self._live = grown
        self._live[sid] = True
        if self._sets is not None:
            self._sets[sid] = set()
        return sid

    def release_space(self, sid):
        """Tombstone-free delete of a whole namespace: the space is
        marked dead now (probes mask it host-side); its physical slots
        are reclaimed at the next grow-by-migration."""
        if sid < 0 or sid >= self._next_space or not self._live[sid]:
            return
        self._live[sid] = False
        if self._sets is not None:
            self.n_keys -= len(self._sets.pop(sid, ()))
            self.occupancy = self.n_keys
        # device mode: n_keys for the dead space is unknown per space;
        # the migration recount restores exactness. Until then n_keys is
        # an upper bound, which only ever grows the table early.

    def live_spaces(self):
        return [int(s) for s in np.flatnonzero(self._live)]

    # -- inserts / probes ----------------------------------------------

    def _space_vec(self, spaces, n):
        if np.isscalar(spaces):
            return np.full(n, int(spaces), dtype=np.int32)
        out = np.asarray(spaces, dtype=np.int32)
        if len(out) != n:
            raise ValueError('spaces and hashes must align')
        return out

    def insert(self, spaces, hashes):
        """Insert N (space, hash) pairs — duplicates are no-ops. ONE
        device dispatch in device mode. `spaces` is an int array or a
        scalar broadcast over the batch; `hashes` as in
        ``hashes_to_rows``. Returns the number of NEW keys landed."""
        rows = hashes_to_rows(hashes)
        n = len(rows)
        if n == 0:
            return 0
        spaces = self._space_vec(spaces, n)
        valid = (spaces >= 0) & (spaces < self._next_space) & \
            self._live[np.clip(spaces, 0, len(self._live) - 1)]
        if self._sets is not None and \
                self.n_keys + n <= self.device_min:
            new = 0
            for i in np.flatnonzero(valid).tolist():
                s = self._sets[int(spaces[i])]
                k = rows[i].tobytes()
                if k not in s:
                    s.add(k)
                    new += 1
            self.n_keys += new
            self.occupancy = self.n_keys
            if new:
                _stats.inc('hashindex_inserts', new)
            return new
        if self._sets is not None:
            self._promote()
        self._ensure_capacity(self.occupancy + n)
        new = self._device_insert(_rows_to_words(rows), spaces, valid)
        if new:
            _stats.inc('hashindex_inserts', new)
        return new

    def probe(self, spaces, hashes):
        """[N] bool exact membership — ONE device dispatch in device
        mode. Unknown/dead spaces answer False."""
        rows = hashes_to_rows(hashes)
        n = len(rows)
        if n == 0:
            return np.zeros(0, dtype=bool)
        spaces = self._space_vec(spaces, n)
        valid = (spaces >= 0) & (spaces < self._next_space) & \
            self._live[np.clip(spaces, 0, len(self._live) - 1)]
        _stats.inc('hashindex_probes', n)
        if self._sets is not None:
            out = np.zeros(n, dtype=bool)
            for i in np.flatnonzero(valid).tolist():
                out[i] = rows[i].tobytes() in self._sets[int(spaces[i])]
            return out
        global _dispatches
        words, spaces_p, valid_p = _pad_batch(
            _rows_to_words(rows), spaces, valid)
        hit = _probe_kernel(self._tkey, self._tspace,
                            jnp.asarray(words), jnp.asarray(spaces_p),
                            jnp.asarray(valid_p), _probe_window)
        _dispatches += 1
        return np.asarray(hit)[:n]

    # -- device plumbing -----------------------------------------------

    def _alloc_table(self, cap):
        return (jnp.zeros((cap, 8), dtype=jnp.uint32),
                jnp.full((cap,), -1, dtype=jnp.int32))

    def _device_insert(self, words, spaces, valid):
        global _dispatches
        words, spaces, valid = _pad_batch(words, spaces, valid)
        self._tkey, self._tspace, n_new = _insert_kernel(
            self._tkey, self._tspace, jnp.asarray(words),
            jnp.asarray(spaces), jnp.asarray(valid))
        _dispatches += 1
        new = int(n_new)
        self.occupancy += new
        self.n_keys += new
        return new

    def _promote(self):
        """Host sets -> device table, one insert dispatch."""
        sets, self._sets = self._sets, None
        self._ensure_capacity(self.n_keys, alloc_only=True)
        total = sum(len(s) for s in sets.values())
        self.occupancy = self.n_keys = 0
        _stats.inc('hashindex_promotions')
        if not total:
            return
        rows = np.zeros((total, 32), dtype=np.uint8)
        spaces = np.zeros(total, dtype=np.int32)
        k = 0
        for sid, keys in sets.items():
            for key in keys:
                rows[k] = np.frombuffer(key, dtype=np.uint8)
                spaces[k] = sid
                k += 1
        self._device_insert(_rows_to_words(rows), spaces,
                            np.ones(total, dtype=bool))

    def _ensure_capacity(self, need, alloc_only=False):
        """Grow (pow2) so `need` keys fit under load_max; migration
        re-inserts only LIVE-space keys (dead spaces reclaimed here)."""
        cap = self.cap
        while need > self.load_max * cap:
            cap *= 2
        if self._tkey is None:
            self.cap = cap
            self._tkey, self._tspace = self._alloc_table(cap)
            return
        if cap == self.cap:
            return
        old_key, old_space = self._tkey, self._tspace
        self.cap = cap
        self._tkey, self._tspace = self._alloc_table(cap)
        old_occ = self.occupancy
        self.occupancy = 0
        if alloc_only or old_occ == 0:
            return
        live = self._live[:max(self._next_space, 1)]
        osp = np.asarray(old_space)
        valid = (osp >= 0) & live[np.clip(osp, 0, len(live) - 1)]
        migrated = self._device_insert(np.asarray(old_key), osp, valid)
        self.n_keys = migrated   # exact live recount
        self.grows += 1
        _stats.inc('hashindex_migrations')


# ---- peer sent-spaces ------------------------------------------------

def _release_peer_space(table, sid):
    table.release_space(sid)
    _stats.inc('hashindex_peer_releases')


class PeerSentSet:
    """One peer link's ``sentHashes`` as a *peer-space* of a shared
    ``HashIndex``: a set-like duck type (``in`` / ``add``) whose adds
    STAGE host-side (hex strings, bounded by sent volume) until
    ``flush_peer_sets`` lands every link's backlog in ONE batched
    insert per shard round. Space ids are minted monotonically and
    never reused, so a reconnecting peer can never inherit a
    predecessor's sent set; ``release()`` — and GC, via the finalizer,
    for states dropped without ceremony — hands the space back for the
    next grow-by-migration to reclaim.

    Unlike the plain-set path, the object is shared BY IDENTITY across
    sync-state generations: the classic ``set(sent_hashes)``
    copy-on-write only shielded the OLD state dict, which no caller
    ever re-generates from, and the promotion itself snapshots the old
    plain set — so membership answers are unchanged."""

    __slots__ = ('table', 'sid', '_staged', '_finalizer', '__weakref__')

    def __init__(self, table, seed=()):
        self.table = table
        self.sid = table.new_space()
        self._staged = set(seed)
        self._finalizer = weakref.finalize(
            self, _release_peer_space, table, self.sid)
        _stats.inc('hashindex_peer_spaces')
        _live_peer_sets.add(self)

    @property
    def alive(self):
        return self._finalizer.alive

    def __contains__(self, hash_hex):
        if hash_hex in self._staged:
            return True
        return bool(self.table.probe(self.sid, [hash_hex])[0])

    def add(self, hash_hex):
        self._staged.add(hash_hex)

    def stage_many(self, hashes):
        self._staged.update(hashes)

    def contains_many(self, hashes):
        """[N] bool membership without flushing: staged hashes answer
        host-side, the remainder in one probe."""
        out = np.zeros(len(hashes), dtype=bool)
        rest = []
        for i, h in enumerate(hashes):
            if h in self._staged:
                out[i] = True
            else:
                rest.append(i)
        if rest:
            out[rest] = self.table.probe(
                self.sid, [hashes[i] for i in rest])
        return out

    def flush(self):
        """Land this one link's staged rows (prefer the module-level
        ``flush_peer_sets`` — it batches N links into one insert)."""
        flush_peer_sets([self])

    def release(self):
        """Disconnect / reset: hand the space back (idempotent)."""
        if self._finalizer.alive:
            self._staged.clear()
            self._finalizer()

    def staged_bytes(self):
        # staged hex strings: ~112 B apiece (64-char str + set slot)
        return len(self._staged) * 112


def flush_peer_sets(peer_sets):
    """Land every staged (peer-space, hash) row across N links in ONE
    batched insert per underlying table — THE per-shard-round insert of
    the sync fabric. Returns the number of new keys landed."""
    by_table = {}
    for ps in peer_sets:
        if isinstance(ps, PeerSentSet) and ps._staged and ps.alive:
            by_table.setdefault(id(ps.table), (ps.table, []))[1].append(ps)
    landed = 0
    for table, group in by_table.values():
        spaces, hex_list = [], []
        for ps in group:
            staged = sorted(ps._staged)
            ps._staged.clear()
            spaces.extend([ps.sid] * len(staged))
            hex_list.extend(staged)
        landed += table.insert(np.asarray(spaces, dtype=np.int32),
                               hex_list)
    return landed


def release_sent_hashes(obj):
    """Hand back the peer-space behind a ``sentHashes`` value (no-op for
    plain sets). Call wherever a link's sync state is discarded —
    disconnect, ``reset=True``, stall reset — the GC finalizer would get
    there eventually; deterministic release gets there now."""
    if isinstance(obj, PeerSentSet):
        obj.release()


def release_sync_state(state):
    """``release_sent_hashes`` over a whole sync-state dict."""
    if isinstance(state, dict):
        release_sent_hashes(state.get('sentHashes'))


def probe_peer_sets(peer_sets, hash_lists):
    """Fused sentHashes filter: ``out[i][j]`` is True iff
    ``hash_lists[i][j]`` was already sent on link ``peer_sets[i]``.
    Every link's staged backlog flushes first (at most one insert per
    table), then ALL links' questions ride one probe dispatch per
    table. Released links answer all-False (their space is dead)."""
    flush_peer_sets(peer_sets)
    out = [np.zeros(len(hs), dtype=bool) for hs in hash_lists]
    by_table = {}
    for i, (ps, hs) in enumerate(zip(peer_sets, hash_lists)):
        if hs and isinstance(ps, PeerSentSet):
            by_table.setdefault(id(ps.table), (ps.table, []))[1].append(i)
    for table, idxs in by_table.values():
        spaces, hex_list, owner = [], [], []
        for i in idxs:
            hs = list(hash_lists[i])
            spaces.extend([peer_sets[i].sid] * len(hs))
            hex_list.extend(hs)
            owner.extend([(i, j) for j in range(len(hs))])
        hit = table.probe(np.asarray(spaces, dtype=np.int32), hex_list)
        for (i, j), h in zip(owner, hit):
            out[i][j] = bool(h)
    return out


# ---- fleet wiring ----------------------------------------------------

def engine_hash_population(engine):
    """Every APPLIED change hash (hex) of a backend engine, WITHOUT
    building the hash-graph query dicts: materialized graph keys, then
    deferred records served from their cheapest lane — the native
    extractor's hash array for a parked prefix, the turbo parser's
    hash32 lanes for pending seam segments — with a per-change header
    decode only for records that have neither. Queued (causally
    premature) changes are excluded, matching get_change_by_hash."""
    out = list(engine.change_index_by_hash.keys())
    pending = getattr(engine, '_doc_pending', None)
    if pending is not None:
        # fills _doc_hashes via the native extractor when available;
        # today's sync rounds materialize these docs anyway (the graph
        # walk in get_change_hashes), so this forces nothing new
        engine._materialize_doc()
    doc_hashes = getattr(engine, '_doc_hashes', None)
    doc_decoded = getattr(engine, '_doc_decoded', None)
    for entry in engine._deferred:
        if len(entry) == 3:
            _index, batch, i = entry
            idxs = i if isinstance(i, (list, tuple, range)) else [i]
            hash_of = getattr(batch, 'hash_hex', None)
            eng_ref = getattr(batch, 'engine', None)
            for j in idxs:
                j = int(j)
                if eng_ref is engine and doc_hashes is not None and \
                        j < len(doc_hashes):
                    out.append(doc_hashes[j])
                elif eng_ref is engine and doc_decoded is not None and \
                        j < len(doc_decoded):
                    out.append(doc_decoded[j]['hash'])
                elif hash_of is not None:
                    out.append(hash_of(j))
                else:
                    out.append(batch.resolve(j)[0])
        else:
            out.append(entry[1])
    return out


class FleetFrontierIndex:
    """The per-fleet membership view over one ``HashIndex``: doc slots
    map to table spaces, commits STAGE their (slot, hash32) rows host-
    side (no dispatch on the commit fast path), and the next probe
    flushes the backlog in one insert dispatch. Registration backfills a
    doc's existing history once (cheap lanes, see
    ``engine_hash_population``); slot frees release the space
    (reclaimed at the next migration — tombstone-free)."""

    def __init__(self, fleet, device_min=None, capacity=1024):
        self._fleet_ref = weakref.ref(fleet)
        self.table = HashIndex(capacity=capacity, device_min=device_min)
        self._spaces = {}          # slot -> space id
        self._staged = []          # (slot int, [n,32] uint8) batches
        self._staged_hex = []      # (slot, hex hash) singles

    # -- registration --------------------------------------------------

    def space_of(self, engine, register=True):
        """The engine's space id, registering (with a one-time history
        backfill) on first use. Returns None for unregistered engines
        when register=False."""
        slot = engine.slot
        sid = self._spaces.get(slot)
        if sid is not None:
            return sid
        if not register:
            return None
        sid = self.table.new_space()
        self._spaces[slot] = sid
        hashes = engine_hash_population(engine)
        _stats.inc('hashindex_backfills')
        if hashes:
            self.table.insert(sid, hashes_to_rows(hashes))
        return sid

    def registered(self, engine):
        return engine.slot in self._spaces

    def drop_slots(self, slots):
        """Slot free/reuse: release the spaces and purge staged rows so
        a recycled slot can never inherit its previous tenant's keys.
        Staged COMMIT batches carry an ndarray of slots per entry, so
        the purge masks per ROW — a batch mixing freed and live docs
        keeps exactly the live docs' rows."""
        gone = np.fromiter((int(s) for s in slots), dtype=np.int64,
                           count=len(slots))
        gone_set = set(gone.tolist())
        if self._staged:
            kept = []
            for slot_arr, rows in self._staged:
                mask = ~np.isin(slot_arr, gone)
                if mask.all():
                    kept.append((slot_arr, rows))
                elif mask.any():
                    kept.append((slot_arr[mask], rows[mask]))
            self._staged = kept
        if self._staged_hex:
            self._staged_hex = [(s, h) for s, h in self._staged_hex
                                if s not in gone_set]
        for slot in slots:
            sid = self._spaces.pop(slot, None)
            if sid is not None:
                self.table.release_space(sid)

    # -- staging (the commit-seam hook) --------------------------------

    def stage_rows(self, slots, hash32):
        """Host-side append of a commit batch's (slot, hash32) rows:
        numpy only, no dispatch — the next probe flushes. `slots` is an
        int array aligned with `hash32` [n, 32] uint8."""
        if len(hash32):
            self._staged.append((np.asarray(slots, dtype=np.int64).copy(),
                                 np.asarray(hash32, dtype=np.uint8).copy()))

    def stage_one(self, slot, hash_hex):
        self._staged_hex.append((int(slot), hash_hex))

    def flush(self):
        """Land every staged row in ONE insert dispatch. Rows for
        unregistered slots are dropped (their history backfills in full
        at registration, so nothing is lost)."""
        if not self._staged and not self._staged_hex:
            return
        staged, self._staged = self._staged, []
        staged_hex, self._staged_hex = self._staged_hex, []
        rows_list, space_list = [], []
        for slots, rows in staged:
            sids = np.array([self._spaces.get(int(s), -1) for s in slots],
                            dtype=np.int32)
            keep = sids >= 0
            if keep.any():
                rows_list.append(rows[keep])
                space_list.append(sids[keep])
        if staged_hex:
            sids = np.array([self._spaces.get(s, -1)
                             for s, _ in staged_hex], dtype=np.int32)
            keep = sids >= 0
            if keep.any():
                rows_list.append(hashes_to_rows(
                    [h for (_s, h), k in zip(staged_hex, keep) if k]))
                space_list.append(sids[keep])
        if rows_list:
            self.table.insert(np.concatenate(space_list),
                              np.concatenate(rows_list))

    # -- probes --------------------------------------------------------

    def probe_pairs(self, engines, hashes):
        """[N] bool membership for N (engine, hex hash) pairs in ONE
        dispatch (plus at most one staged-insert flush). Engines are
        registered (backfilled) on first sight."""
        self.flush()
        spaces = np.fromiter((self.space_of(e) for e in engines),
                             dtype=np.int32, count=len(engines))
        return self.table.probe(spaces, hashes_to_rows(list(hashes)))

    def resident_bytes(self):
        staged = sum(r.nbytes + s.nbytes for s, r in self._staged)
        return self.table.resident_bytes() + staged
