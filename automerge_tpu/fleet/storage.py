"""Delta+main storage engine: park ten million cold documents per host.

The fleet's in-memory footprint has two very different tenants. LIVE
documents (the write-optimized **delta**) need device rows, causal state,
host change logs, and journal hooks. COLD documents need none of that:
their entire identity is one compressed document chunk plus a few dozen
bytes of causal state — and every read those docs actually get (heads,
clock, maxOp, change count, "are we in sync?") is answerable straight
from the chunk header and metadata columns (LSM-OPD: compute on
compressed data; `columnar.DocChunkView`).

This module is the read-optimized **main** for those cold documents,
split into two tiers:

- A **RAM-resident causal index**: per-doc causal state in fleet-level
  arrays (heads in one byte arena + offset arrays, clocks as flat
  (actor, seq) runs against an interned actor table, maxOp/n_changes as
  integer lanes) — ~100-130 B/doc, and the ONLY thing `heads`/`clock`/
  `contains_head`/`needs_sync` ever touch. Sync-gate probes for parked
  docs never fault a page.
- An **on-disk segment arena** (fleet/segment.py) holding the chunk
  bytes themselves: parked chunks append to mmap'd CRC-framed segment
  files, reads come back as zero-copy ``memoryview``s into the map
  (served off the page cache), vacuum is a segment rewrite + atomic
  manifest swap that is crash-safe at every byte (kill mid-vacuum
  recovers byte-identical). Pass ``path=None`` for yesterday's fully
  RAM-resident arena (ephemeral stores, tests, rebalance staging).

With the chunk bytes on disk, the 1M-docs-per-host ceiling becomes a
disk number: RSS holds the causal lanes only (tests/test_storage_tier.py
asserts the ceiling; bench.py's ``storage_tier`` section measures
park/revive/materialize against the RAM-resident baseline).

``StorageEngine`` is the policy layer binding a live ``DocFleet`` to a
``MainStore``: ``park`` demotes cold fleet docs (canonical chunk via
``save()``, round-trip-validated by the native extractor, device slots
freed), ``revive`` promotes them back through the bulk loader (one
native parse over the mapped views + batched dispatches), and causal
reads route to the columnar lanes without touching chunk bytes at all.
Tiering POLICY — when to park, when to vacuum, how brownout pressure
defers compaction — lives in fleet/tiering.py as a cost model, replacing
the fixed ``dead_fraction`` byte trigger (which remains as the default
standalone policy).

Durability composition: parking a journaled doc frees it from the
journal's registry (the standard FREE record) — its bytes now live in
the main store's segment arena, whose manifest/frame discipline makes
parked docs recoverable via ``StorageEngine.open``; reviving through a
``DurableFleet``'s ``load_docs`` re-journals the chunk as the doc's
baseline. The incremental per-doc compaction that keeps checkpoint cost
proportional to churn lives in fleet/durability.py.
"""

import sys
import weakref
from operator import index as _op_index

import numpy as np

from ..columnar import DocChunkView
from ..errors import MalformedDocument
from ..observability.metrics import Counters, register_health_source
from ..observability.perf import register_mem_source
from ..observability.spans import span as _span
from .segment import RamArena, SegmentArena

__all__ = ['MainStore', 'StorageEngine']

_stats = Counters({
    'storage_auto_vacuums': 0,   # policy-triggered vacuums (threshold or model)
    'storage_parked_syncs_skipped': 0,   # sync rounds served parked
    'storage_recovered_docs': 0,         # docs rebuilt by MainStore.open
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])

# memory-watermark tiers: RESIDENT bytes (causal lanes + RAM arenas) vs
# the mapped on-disk arena — the split the cost-based tiering plane and
# the RSS-ceiling acceptance both budget against
_live_stores = weakref.WeakSet()
register_mem_source(
    'mainstore_bytes',
    lambda: sum(s.resident_bytes() for s in list(_live_stores)))
register_mem_source(
    'mainstore_disk_bytes',
    lambda: sum(s.disk_bytes() for s in list(_live_stores)))


class _I64:
    """Growable integer lane (amortized-doubling numpy array)."""

    __slots__ = ('data', 'n')

    def __init__(self, dtype=np.int64):
        self.data = np.zeros(16, dtype=dtype)
        self.n = 0

    def _grow(self, need):
        cap = len(self.data)
        while cap < need:
            cap *= 2
        grown = np.zeros(cap, dtype=self.data.dtype)
        grown[:self.n] = self.data[:self.n]
        self.data = grown

    def append(self, value):
        if self.n == len(self.data):
            self._grow(self.n + 1)
        self.data[self.n] = value
        self.n += 1

    def extend(self, values):
        need = self.n + len(values)
        if need > len(self.data):
            self._grow(need)
        self.data[self.n:need] = values
        self.n = need

    def reserve(self, n):
        """Pre-size for n MORE rows (kills doubling slack on bulk
        ingest — the 10M-doc RSS budget assumes reserved lanes)."""
        need = self.n + n
        if need > len(self.data):
            grown = np.zeros(need, dtype=self.data.dtype)
            grown[:self.n] = self.data[:self.n]
            self.data = grown

    @property
    def nbytes(self):
        return int(self.data.nbytes)


class _IdMap:
    """Dense doc-id -> row map. Engine ids are monotonic and never
    recycled, so a growable int64 lane (-1 = absent) replaces the Python
    dict — ~8 B/id instead of ~70: at 10M parked docs the difference
    between the id indirection fitting the RSS ceiling or dominating
    it."""

    __slots__ = ('_rows', '_live')

    def __init__(self):
        self._rows = _I64()
        self._live = 0

    def __setitem__(self, doc_id, row):
        rows = self._rows
        if doc_id >= rows.n:
            if doc_id >= len(rows.data):
                rows._grow(doc_id + 1)
            rows.data[rows.n:doc_id + 1] = -1
            rows.n = doc_id + 1
        elif rows.data[doc_id] >= 0:
            self._live -= 1
        rows.data[doc_id] = row
        self._live += 1

    def get(self, doc_id, default=None):
        try:
            doc_id = _op_index(doc_id)   # numpy ints keep working, like
        except TypeError:                # the dict this lane replaced
            return default
        if 0 <= doc_id < self._rows.n:
            row = int(self._rows.data[doc_id])
            if row >= 0:
                return row
        return default

    def pop(self, doc_id):
        row = self.get(doc_id)
        if row is None:
            raise KeyError(doc_id)
        self._rows.data[doc_id] = -1
        self._live -= 1
        return row

    def update(self, pairs):
        for doc_id, row in pairs:
            self[doc_id] = row

    def __contains__(self, doc_id):
        return self.get(doc_id) is not None

    def __len__(self):
        return self._live

    def __iter__(self):
        data, n = self._rows.data, self._rows.n
        return (i for i in range(n) if data[i] >= 0)

    def items(self):
        data, n = self._rows.data, self._rows.n
        return ((i, int(data[i])) for i in range(n) if data[i] >= 0)

    def copy(self):
        fresh = _IdMap()
        fresh._rows = _I64()
        fresh._rows._grow(max(self._rows.n, 1))
        fresh._rows.data[:self._rows.n] = self._rows.data[:self._rows.n]
        fresh._rows.n = self._rows.n
        fresh._live = self._live
        return fresh

    @property
    def nbytes(self):
        return self._rows.nbytes


class _ByteLane:
    """Growable byte arena with reserve (the heads arena)."""

    __slots__ = ('data', 'n')

    def __init__(self):
        self.data = bytearray(64)
        self.n = 0

    def extend(self, b):
        need = self.n + len(b)
        if need > len(self.data):
            cap = len(self.data)
            while cap < need:
                cap *= 2
            self.data.extend(bytes(cap - len(self.data)))
        self.data[self.n:need] = b
        self.n = need

    def reserve(self, extra):
        need = self.n + extra
        if need > len(self.data):
            self.data.extend(bytes(need - len(self.data)))

    @property
    def nbytes(self):
        return len(self.data)


class MainStore:
    """Columnar causal index over a chunk arena (RAM or mmap'd disk).

    Row ids are dense ints assigned by ``add`` and never recycled until
    ``vacuum`` (discarded rows leave arena garbage that vacuum reclaims;
    ``dead_fraction``/``garbage_bytes`` expose the trigger signals). All
    causal reads are O(row) array lookups — no chunk bytes are touched;
    ``chunk(row)`` returns a zero-copy view into the arena."""

    # contains_head satellite: past this row count a per-store 8-byte
    # head-prefix set short-circuits miss probes O(1) (the parked sync
    # gate's common case at fleet scale) instead of the per-row scan
    PREFIX_MIN_ROWS = 4096

    def __init__(self, path=None, segment_bytes=None, _arena=None):
        if _arena is not None:
            self._arena = _arena
        elif path is not None:
            kw = {} if segment_bytes is None else \
                {'segment_bytes': segment_bytes}
            self._arena = SegmentArena(path, **kw)
        else:
            self._arena = RamArena()
        self.path = path
        self._seg = _I64(np.int32)       # row -> arena segment (-1 dead)
        self._off = _I64(np.int32)       # row -> payload offset in segment
        self._len = _I64(np.int32)       # row -> payload length
        self._tag = _I64()               # row -> stable tag (arena frames)
        self._heads_arena = _ByteLane()  # 32 B per head, concatenated
        self._heads_off = _I64()
        self._heads_n = _I64(np.int32)
        self._clock_actor = _I64(np.int32)   # interned actor index
        self._clock_seq = _I64()
        self._clock_off = _I64()
        self._clock_n = _I64(np.int32)
        self._max_op = _I64()
        self._n_changes = _I64(np.int32)
        self.actors = []                # interned actor hex strings
        self._actor_index = {}
        self._live = 0
        self._next_tag = 0
        self._dead_head_bytes = 0
        self._dead_clock_rows = 0
        # prefix short-circuit state: a SORTED uint64 array (lazily
        # built past PREFIX_MIN_ROWS, vectorized off the heads arena,
        # counted in resident_bytes) + a bounded overflow set for
        # prefixes added since the last fold
        self._head_prefixes = None
        self._prefix_overflow = set()
        _live_stores.add(self)          # memory-watermark tier (perf.py)

    def __len__(self):
        return self._live

    @property
    def n_rows(self):
        return self._seg.n

    @classmethod
    def open(cls, path, segment_bytes=None, check=False):
        """Recover a disk-backed store from its segment arena: manifest
        epoch + CRC frame scan select the live chunks (fleet/segment.py),
        then the causal lanes rebuild compute-on-compressed (DocChunkView
        header reads — op columns stay cold bytes on disk). Returns
        ``(store, tags)`` with ``tags[i]`` the stable tag of row ``i``.
        A chunk the view cannot decode (torn past its CRC — shouldn't
        happen — or a hostile writer) is dropped, not fatal."""
        kw = {} if segment_bytes is None else {'segment_bytes': segment_bytes}
        arena, records = SegmentArena.open(path, **kw)
        store = cls(path=path, _arena=arena)
        tags = []
        max_tag = -1
        for tag, (seg, off, ln) in records.items():
            try:
                view = arena.view(seg, off, ln)
                dcv = DocChunkView(view, check=check)
                store._install_row(seg, off, ln, tag, dcv.heads, dcv.clock,
                                   dcv.max_op, dcv.n_changes)
            except MalformedDocument:
                continue
            tags.append(tag)
            max_tag = max(max_tag, tag)
        store._next_tag = max_tag + 1
        _stats.inc('storage_recovered_docs', len(tags))
        return store, tags

    def reserve(self, n_docs, head_bytes=None, clock_rows=None):
        """Pre-size every lane for n_docs more rows (bulk ingest)."""
        for lane in (self._seg, self._off, self._len, self._tag,
                     self._heads_off, self._heads_n, self._clock_off,
                     self._clock_n, self._max_op, self._n_changes):
            lane.reserve(n_docs)
        self._heads_arena.reserve(head_bytes if head_bytes is not None
                                  else 32 * n_docs)
        rows = clock_rows if clock_rows is not None else n_docs
        self._clock_actor.reserve(rows)
        self._clock_seq.reserve(rows)

    def resident_bytes(self):
        """RAM-resident bytes of this store: the causal lanes plus any
        RAM-arena payload — what counts against the RSS ceiling. Disk-
        backed chunk bytes are NOT here (see ``disk_bytes``); they live
        on the page cache."""
        total = self._heads_arena.nbytes + self._arena.resident_bytes()
        for col in (self._seg, self._off, self._len, self._tag,
                    self._heads_off, self._heads_n, self._clock_actor,
                    self._clock_seq, self._clock_off, self._clock_n,
                    self._max_op, self._n_changes):
            total += col.nbytes
        if self._head_prefixes is not None:
            # the prefix index is resident too (~8 B/head + the
            # overflow set's object overhead)
            total += self._head_prefixes.nbytes + \
                64 * len(self._prefix_overflow)
        return total

    def disk_bytes(self):
        """On-disk segment bytes (0 for RAM-arena stores)."""
        return self._arena.disk_bytes()

    @property
    def garbage_bytes(self):
        """Arena bytes a vacuum would reclaim — the cost model's
        read-latency/recovery-debt input."""
        return self._arena.garbage_bytes

    @property
    def dead_lane_bytes(self):
        """RAM-RESIDENT bytes pinned by discarded rows (their heads in
        the arena, clock runs, and per-row lane slots) that only a
        vacuum reclaims — the resident side of the cost model's garbage
        input: without it a store of many small dead chunks could sit
        at dead_fraction ~1.0 leaking the causal index forever."""
        dead_rows = self.n_rows - self._live
        return (self._dead_head_bytes + 12 * self._dead_clock_rows +
                64 * dead_rows)

    def _intern_actor(self, hexa):
        idx = self._actor_index.get(hexa)
        if idx is None:
            idx = len(self.actors)
            self.actors.append(hexa)
            self._actor_index[hexa] = idx
        return idx

    def _install_row(self, seg, off, ln, tag, heads, clock, max_op,
                     n_changes):
        row = self._seg.n
        self._seg.append(seg)
        self._off.append(off)
        self._len.append(ln)
        self._tag.append(tag)
        self._heads_off.append(self._heads_arena.n)
        self._heads_n.append(len(heads))
        for h in sorted(heads):
            hb = bytes.fromhex(h)
            self._heads_arena.extend(hb)
            if self._head_prefixes is not None:
                self._prefix_overflow.add(
                    int.from_bytes(hb[:8], sys.byteorder))
        self._clock_off.append(self._clock_actor.n)
        self._clock_n.append(len(clock))
        for hexa in sorted(clock):
            self._clock_actor.append(self._intern_actor(hexa))
            self._clock_seq.append(int(clock[hexa]))
        self._max_op.append(int(max_op))
        self._n_changes.append(int(n_changes))
        self._live += 1
        return row

    def add(self, chunk, heads, clock, max_op, n_changes, tag=None):
        """Store one parked doc; returns its row id. `heads` are hex
        strings, `clock` {actor_hex: seq}. `tag` is the stable id the
        arena frames (and recovery) know the doc by — callers with their
        own id space (StorageEngine) pass theirs."""
        if tag is None:
            tag = self._next_tag
        self._next_tag = max(self._next_tag, tag + 1)
        seg, off, ln = self._arena.append(tag, chunk)
        return self._install_row(seg, off, ln, tag, heads, clock, max_op,
                                 n_changes)

    def add_chunk(self, chunk, check=True, tag=None):
        """Store a chunk deriving its causal row compute-on-compressed
        (DocChunkView: header heads + change-meta columns only). Raises
        MalformedDocument on undecodable bytes."""
        view = DocChunkView(chunk, check=check)
        return self.add(chunk, view.heads, view.clock, view.max_op,
                        view.n_changes, tag=tag)

    def add_many(self, chunks, rows, tags):
        """Bulk add with pre-computed causal rows: ONE batched arena
        write for the chunk bytes (SegmentArena.append_many), then the
        lane installs. Returns row ids aligned with the inputs."""
        if tags is None:
            tags = list(range(self._next_tag, self._next_tag + len(chunks)))
        addrs = self._arena.append_many(tags, chunks)
        out = []
        for (seg, off, ln), tag, (heads, clock, max_op, n_changes) in \
                zip(addrs, tags, rows):
            self._next_tag = max(self._next_tag, tag + 1)
            out.append(self._install_row(seg, off, ln, tag, heads, clock,
                                         max_op, n_changes))
        return out

    def _check(self, row):
        if not (0 <= row < self._seg.n) or self._seg.data[row] < 0:
            raise KeyError(f'no parked doc at row {row}')

    def tag(self, row):
        self._check(row)
        return int(self._tag.data[row])

    def chunk(self, row):
        """The parked chunk as a ZERO-COPY memoryview into the arena
        (an mmap'd segment for disk-backed stores: reading it is a page-
        cache access, holding it pins the mapping across vacuums)."""
        self._check(row)
        return self._arena.view(int(self._seg.data[row]),
                                int(self._off.data[row]),
                                int(self._len.data[row]))

    def heads(self, row):
        self._check(row)
        off = int(self._heads_off.data[row])
        n = int(self._heads_n.data[row])
        arena = self._heads_arena.data
        return [arena[off + 32 * i:off + 32 * (i + 1)].hex()
                for i in range(n)]

    def clock(self, row):
        self._check(row)
        off = int(self._clock_off.data[row])
        n = int(self._clock_n.data[row])
        return {self.actors[int(self._clock_actor.data[off + i])]:
                int(self._clock_seq.data[off + i]) for i in range(n)}

    def max_op(self, row):
        self._check(row)
        return int(self._max_op.data[row])

    def n_changes(self, row):
        self._check(row)
        return int(self._n_changes.data[row])

    def _build_prefixes(self):
        """Vectorized fold of the heads arena (EVERY head ever
        appended, dead rows' included — stale entries only cost a
        fall-through to the exact scan) into one sorted uint64 array:
        ~8 B/head of accountable numpy memory instead of a Python set,
        and a few hundred ms at 10M heads instead of a per-head loop."""
        n = (self._heads_arena.n // 32) * 32
        if n == 0:
            self._head_prefixes = np.zeros(0, dtype=np.uint64)
        else:
            raw = np.frombuffer(self._heads_arena.data, dtype=np.uint8,
                                count=n)
            self._head_prefixes = np.unique(
                raw.reshape(-1, 32)[:, :8].copy().view(np.uint64).ravel())
        self._prefix_overflow = set()

    def contains_head(self, row, hash_hex):
        """Sync-membership probe against the columnar heads arena —
        no chunk decode, no Python per-head strings on the hot path.
        Past PREFIX_MIN_ROWS rows, a store-wide 8-byte head-prefix
        index (sorted uint64 array + recent-adds overflow set)
        short-circuits misses in O(log heads) (discards leave stale
        prefixes behind — a false HIT only falls through to the exact
        row scan, never a wrong answer; vacuum rebuilds it clean)."""
        self._check(row)
        needle = bytes.fromhex(hash_hex)
        if self._seg.n > self.PREFIX_MIN_ROWS:
            if self._head_prefixes is None:
                self._build_prefixes()
            elif len(self._prefix_overflow) > 4096:
                self._build_prefixes()      # fold recent adds back in
            p = int.from_bytes(needle[:8], sys.byteorder)
            if p not in self._prefix_overflow:
                i = int(np.searchsorted(self._head_prefixes, p))
                if i >= len(self._head_prefixes) or \
                        int(self._head_prefixes[i]) != p:
                    return False
        off = int(self._heads_off.data[row])
        n = int(self._heads_n.data[row])
        arena = self._heads_arena.data
        return any(arena[off + 32 * i:off + 32 * (i + 1)] == needle
                   for i in range(n))

    def covers_heads(self, row, their_heads):
        """True when every hash in `their_heads` is one of row's heads —
        the parked-doc 'already in sync' fast path."""
        return all(self.contains_head(row, h) for h in their_heads)

    def discard(self, row):
        """Drop a row; returns its chunk (for disk arenas a still-valid
        view — the bytes stay in the segment until vacuum). Disk-backed
        stores record a tombstone frame; the StorageEngine flushes it at
        the end of the batched operation (process-kill safe), and
        ``sync()`` closes the OS-crash window."""
        self._check(row)
        off = int(self._off.data[row])
        ln = int(self._len.data[row])
        if isinstance(self._arena, RamArena):
            chunk = self._arena._items[off]
            self._arena.discard_slot(off)
        else:
            chunk = self._arena.view(int(self._seg.data[row]), off, ln)
        self._arena.tombstone(int(self._tag.data[row]), ln)
        self._seg.data[row] = -1
        self._dead_head_bytes += 32 * int(self._heads_n.data[row])
        self._dead_clock_rows += int(self._clock_n.data[row])
        self._live -= 1
        return chunk

    @property
    def dead_fraction(self):
        total = self._seg.n
        return (total - self._live) / total if total else 0.0

    @property
    def chunk_bytes(self):
        return self._arena.data_bytes

    def vacuum(self):
        """Compact: rewrite live chunks into a fresh arena epoch and
        rebuild the causal lanes, dropping discarded rows. For disk
        stores this is the segment rewrite + ATOMIC manifest swap —
        crash-safe at every byte, and views held across the swap stay
        valid (fleet/segment.py). Returns {old_row: new_row}."""
        writer = self._arena.rewrite_begin()
        fresh = MainStore(_arena=writer)
        fresh.path = self.path
        fresh.actors = self.actors
        fresh._actor_index = self._actor_index
        remap = {}
        for row in range(self._seg.n):
            if self._seg.data[row] < 0:
                continue
            remap[row] = fresh.add(
                self.chunk(row), self.heads(row), self.clock(row),
                self.max_op(row), self.n_changes(row), tag=self.tag(row))
        self._arena.rewrite_commit(writer)
        next_tag = max(self._next_tag, fresh._next_tag)
        for name in ('_seg', '_off', '_len', '_tag', '_heads_arena',
                     '_heads_off', '_heads_n', '_clock_actor', '_clock_seq',
                     '_clock_off', '_clock_n', '_max_op', '_n_changes',
                     '_live', '_dead_head_bytes', '_dead_clock_rows',
                     '_arena'):
            setattr(self, name, getattr(fresh, name))
        self._next_tag = next_tag
        self._head_prefixes = None      # rebuilt on demand, now clean
        self._prefix_overflow = set()
        _live_stores.discard(fresh)     # its lanes moved into self
        return remap

    def flush(self):
        self._arena.flush()

    def sync(self):
        self._arena.sync()

    def close(self):
        self._arena.close()
        _live_stores.discard(self)

    def memory_stats(self):
        """Byte accounting: chunk payload vs per-doc overhead. For disk
        stores `chunk_bytes`/`disk_bytes` are MAPPED, not resident — the
        acceptance signal is resident_per_doc: what RSS pays per parked
        doc (the causal index), with the chunk bytes a disk number."""
        lanes = (self._seg.nbytes + self._off.nbytes + self._len.nbytes +
                 self._tag.nbytes + self._heads_off.nbytes +
                 self._heads_n.nbytes + self._clock_off.nbytes +
                 self._clock_n.nbytes + self._max_op.nbytes +
                 self._n_changes.nbytes)
        arenas = (self._heads_arena.nbytes + self._clock_actor.nbytes +
                  self._clock_seq.nbytes)
        ram_arena = isinstance(self._arena, RamArena)
        # RAM arena: list slot (8 B pointer) + bytes-object header (~33 B)
        obj_overhead = (8 * self.n_rows + 33 * self._live) if ram_arena \
            else 0
        overhead = lanes + arenas + obj_overhead
        resident = overhead + self._arena.resident_bytes()
        return {
            'n_docs': self._live,
            'chunk_bytes': self._arena.data_bytes,
            'disk_bytes': self.disk_bytes(),
            'garbage_bytes': self._arena.garbage_bytes,
            'causal_arena_bytes': arenas,
            'lane_bytes': lanes,
            'overhead_bytes': overhead,
            'overhead_per_doc': overhead / self._live if self._live else 0.0,
            'resident_bytes': resident,
            'resident_per_doc': resident / self._live if self._live else 0.0,
            'total_bytes': self._arena.data_bytes + overhead,
            'dead_fraction': self.dead_fraction,
            'n_actors': len(self.actors),
        }


class StorageEngine:
    """Delta (live DocFleet) + main (MainStore) with park/revive policy
    and compute-on-compressed reads for the parked tier.

    Doc ids handed out by ``park``/``ingest_chunks`` are STABLE: an
    id→row indirection lets the engine vacuum the main store underneath
    its callers without invalidating anything a caller holds. Vacuum
    POLICY is pluggable: the default standalone trigger is the classic
    ``vacuum_dead_fraction`` byte threshold; pass ``cost_model`` (a
    fleet/tiering.py ``CostModel``) to replace it with the write-amp vs
    read-latency vs recovery-debt decision, or ``vacuum_dead_fraction=
    None`` to drive ``vacuum_now`` by hand / from a TieringController.

    ``path=`` puts the chunk arena on disk (mmap-backed, crash-safe —
    see MainStore); ``StorageEngine.open(path)`` recovers engine ids and
    causal lanes after a crash."""

    # don't churn tiny stores: below this row count a vacuum saves noise
    VACUUM_MIN_ROWS = 8

    def __init__(self, fleet=None, vacuum_dead_fraction=0.5, path=None,
                 segment_bytes=None, cost_model=None):
        from .backend import DocFleet
        self.fleet = fleet if fleet is not None else DocFleet()
        self.main = MainStore(path=path, segment_bytes=segment_bytes)
        self.vacuum_dead_fraction = vacuum_dead_fraction
        self.cost_model = cost_model
        # brownout pressure stage for the cost model's write-cost
        # multiplier — kept current by the TieringController's tick, so
        # discard-churn vacuums BETWEEN ticks defer under pressure too
        self.pressure_stage = 0
        self.vacuums = 0
        self._row_of = _IdMap()      # stable doc id -> main-store row
        self._next_id = 0

    @classmethod
    def open(cls, path, fleet=None, segment_bytes=None,
             vacuum_dead_fraction=0.5, cost_model=None, check=False):
        """Recover a disk-backed engine: the arena's live records become
        parked docs under their original stable ids."""
        eng = cls(fleet=fleet, vacuum_dead_fraction=vacuum_dead_fraction,
                  cost_model=cost_model)
        eng.main.close()
        eng.main, tags = MainStore.open(path, segment_bytes=segment_bytes,
                                        check=check)
        eng._row_of = _IdMap()
        eng._row_of.update((tag, row) for row, tag in enumerate(tags))
        eng._next_id = max(eng._row_of, default=-1) + 1
        return eng

    def adopt_main(self, other):
        """MOVE another engine's main store and its stable-id space here
        (e.g. rebinding parked docs to a durable fleet's engine): ids the
        other engine handed out stay valid on THIS engine, and the donor
        resets to empty. Ownership transfers whole — two engines sharing
        one store would race their id maps the first time either
        auto-vacuums (the vacuum rebinds the map it knows about and
        strands the other's rows) — and only into an EMPTY engine: the
        adopter's own id space would otherwise silently alias the
        donor's."""
        if self._row_of or self.main.n_rows:
            raise ValueError('adopt_main requires an empty adopter: this '
                             'engine already holds parked docs whose ids '
                             'would alias the adopted ones')
        self.main.close()
        self.main = other.main
        self._row_of = other._row_of.copy()
        self._next_id = other._next_id
        other.main = MainStore(path=None)
        other._row_of = _IdMap()
        other._next_id = 0

    def _claim_id(self, doc_id=None):
        if doc_id is None:
            doc_id = self._next_id
        self._next_id = max(self._next_id, doc_id + 1)
        return doc_id

    def _row(self, doc_id):
        row = self._row_of.get(doc_id)
        if row is None:
            raise KeyError(f'no parked doc {doc_id}')
        return row

    def _discard(self, doc_ids):
        for doc_id in doc_ids:
            self.main.discard(self._row_of.pop(doc_id))
        # tombstones leave the user-space buffer NOW: a process kill
        # after this batch cannot resurrect the discarded docs (the
        # OS-crash window stays open until sync(), like the journal's
        # group-commit loss window)
        self.main.flush()
        self._maybe_vacuum()

    def vacuum_now(self):
        """Compact the main store (segment rewrite + atomic swap for
        disk arenas), preserving every outstanding doc id."""
        with _span('storage_vacuum', docs=len(self.main)):
            remap = self.main.vacuum()
        rebound = _IdMap()
        rebound.update((doc_id, remap[row])
                       for doc_id, row in self._row_of.items())
        self._row_of = rebound
        self.vacuums += 1
        _stats.inc('storage_auto_vacuums')
        return True

    def _maybe_vacuum(self, stage=None):
        if self.main.n_rows < self.VACUUM_MIN_ROWS:
            return False
        model = self.cost_model
        if model is not None:
            if stage is None:
                stage = self.pressure_stage
            if not model.vacuum_due(self.main, stage=stage):
                return False
            return self.vacuum_now()
        threshold = self.vacuum_dead_fraction
        if threshold is None or self.main.dead_fraction < threshold:
            return False
        return self.vacuum_now()

    # -- demotion -------------------------------------------------------

    def park(self, handles, ids=None):
        """Demote fleet documents into the main store: canonical chunk
        (round-trip-validated — a doc whose history cannot reproduce
        from its chunk stays live), causal state into the columnar
        arrays, chunk bytes appended to the arena, device slots freed in
        one batched call. Returns a list aligned with `handles`: the
        doc's main-store id, or None where the doc was skipped (queued
        changes, non-fleet, failed validation). Skipped handles stay
        live and usable. `ids` (internal) parks each doc under a caller-
        chosen id — the repark path."""
        from . import backend as fleet_backend
        from .backend import FleetDoc, _validate_doc_chunks

        out = [None] * len(handles)
        to_free = []
        ready = []          # (input index, handle, state, chunk, n)
        pending = []        # (input index, handle, state, chunk) to batch
        with _span('storage_park', docs=len(handles)):
            for i, handle in enumerate(handles):
                state = handle.get('state')
                if handle.get('frozen') or not isinstance(state, FleetDoc) \
                        or not state.is_fleet:
                    continue
                impl = state._impl
                if impl.queue:
                    continue
                if impl._doc_pending is not None and not impl._changes:
                    # already parked in-fleet with no delta tail: the
                    # chunk is the validated canonical form
                    ready.append((i, handle, state, impl._doc_pending,
                                  impl._parked_n))
                else:
                    pending.append((i, handle, state, bytes(state.save())))
            # ONE batched validation (native pool fan-out) for every doc
            # that needs it
            counts = _validate_doc_chunks([c for _i, _h, _s, c in pending])
            for (i, handle, state, chunk), n in zip(pending, counts):
                if n is not None:
                    ready.append((i, handle, state, chunk, n))
            for i, handle, state, chunk, n in ready:
                doc_id = self._claim_id(None if ids is None else ids[i])
                self._row_of[doc_id] = self.main.add(
                    chunk, state.heads, state.clock, state.max_op, n,
                    tag=doc_id)
                out[i] = doc_id
                to_free.append(handle)
            # On a JOURNALED fleet, free_docs will emit FREE records the
            # journal fsyncs on its own cadence — the chunk bytes must
            # be AT LEAST as durable before that can happen, or an OS
            # crash between the two loses the doc from both tiers. So:
            # fsync when a journal is attached, flush (process-kill
            # safety) otherwise.
            if to_free and getattr(self.fleet, 'journal', None) is not None:
                self.main.sync()
            else:
                self.main.flush()
            if to_free:
                fleet_backend.free_docs(to_free)
        return out

    def ingest_chunks(self, chunks, check=True, rows=None):
        """Admit saved document chunks straight into the main store —
        no fleet slot, no engine, no decode of op columns: causal state
        comes from the chunk itself (DocChunkView), or from `rows`
        (pre-computed ``(heads, clock, max_op, n_changes)`` tuples — the
        bulk-ingest fast path when the caller already knows them).
        Returns main-store ids. Raises MalformedDocument for undecodable
        bytes (the batch up to that point is kept)."""
        if rows is not None and len(rows) != len(chunks):
            # a short rows list would append every chunk to the durable
            # arena but install only len(rows) — orphan records that
            # recovery would resurrect; fail loudly instead
            raise ValueError(f'rows ({len(rows)}) and chunks '
                             f'({len(chunks)}) must align')
        with _span('storage_ingest', docs=len(chunks)):
            err = None
            if rows is None:
                rows = []
                for c in chunks:
                    try:
                        v = DocChunkView(c, check=check)
                    except MalformedDocument as exc:
                        err = exc
                        break
                    rows.append((v.heads, v.clock, v.max_op, v.n_changes))
                chunks = chunks[:len(rows)]
            ids = [self._claim_id() for _ in chunks]
            row_ids = self.main.add_many(chunks, rows, tags=ids)
            self._row_of.update(zip(ids, row_ids))
            self.main.flush()       # process-kill safe once we return
            if err is not None:
                raise err
            return ids

    # -- promotion ------------------------------------------------------

    def revive(self, ids, durable=None):
        """Promote parked docs back into the live fleet through the bulk
        loader (one native parse straight off the arena's mapped views +
        batched dispatches; history stays lazily parked on the revived
        engines). `durable` is an optional DurableFleet manager —
        revived docs journal their chunk as a baseline through its
        load_docs. Returns backend handles in id order; the docs leave
        the main store (the vacuum policy may compact the arenas
        afterwards — ids held for OTHER docs stay valid)."""
        chunks = [self.main.chunk(self._row(i)) for i in ids]
        with _span('storage_revive', docs=len(ids)):
            if durable is not None:
                handles = durable.load_docs(chunks)
            else:
                from .loader import load_docs
                handles = load_docs(chunks, self.fleet)
            del chunks      # release the arena views before any vacuum
            self._discard(ids)
        return handles

    def discard(self, ids):
        """Drop parked docs outright (no revive); returns their chunks
        (copied — the rows are gone, so views would dangle across the
        next vacuum). Vacuum policy applies."""
        chunks = [bytes(self.main.chunk(self._row(i))) for i in ids]
        self._discard(ids)
        return chunks

    def repark(self, handles, ids):
        """Return just-revived docs to the store under their ORIGINAL
        ids — the abort path of a round that revived docs and then
        raised before serving them (mixed sync deadline/decode aborts):
        the caller's ids must stay valid because the caller never sees
        the handles. Freshly revived docs re-park through the
        already-parked fast path (chunk verbatim, no re-validation), and
        the arena frames carry the original ids (crash-consistent)."""
        self.park(handles, ids=ids)

    # -- compute-on-compressed reads -----------------------------------

    def chunk(self, doc_id):
        return self.main.chunk(self._row(doc_id))

    def heads(self, doc_id):
        return self.main.heads(self._row(doc_id))

    def clock(self, doc_id):
        return self.main.clock(self._row(doc_id))

    def max_op(self, doc_id):
        return self.main.max_op(self._row(doc_id))

    def n_changes(self, doc_id):
        return self.main.n_changes(self._row(doc_id))

    def contains_head(self, doc_id, hash_hex):
        return self.main.contains_head(self._row(doc_id), hash_hex)

    def covers_heads(self, doc_id, their_heads):
        return self.main.covers_heads(self._row(doc_id), their_heads)

    def needs_sync(self, doc_id, their_heads):
        """Parked-doc sync gate: False when the peer's heads equal ours
        (nothing to exchange — the doc can stay parked); True otherwise
        (revive before running a real sync round)."""
        ours = set(self.main.heads(self._row(doc_id)))
        return set(their_heads) != ours

    def close(self):
        self.main.close()

    def memory_stats(self):
        return self.main.memory_stats()
