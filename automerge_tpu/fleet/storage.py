"""Delta+main storage engine: park a million cold documents per host.

The fleet's in-memory footprint has two very different tenants. LIVE
documents (the write-optimized **delta**) need device rows, causal state,
host change logs, and journal hooks. COLD documents need none of that:
their entire identity is one compressed document chunk plus a few dozen
bytes of causal state — and every read those docs actually get (heads,
clock, maxOp, change count, "are we in sync?") is answerable straight
from the chunk header and metadata columns (LSM-OPD: compute on
compressed data; `columnar.DocChunkView`).

This module is the read-optimized **main** for those cold documents:

- ``MainStore`` — a columnar arena of parked chunks. Per-doc causal
  state lives in fleet-level arrays (heads in one byte arena + offset
  arrays, clocks as flat (actor, seq) runs against an interned actor
  table, maxOp/n_changes as int64 lanes), NOT per-doc Python objects —
  the ~3.3 KB/doc of engine/handle/dict overhead a fleet-resident parked
  doc costs (BASELINE.md host-memory accounting) collapses to the chunk
  bytes plus ~100-200 B/doc of arrays. One host comfortably holds 1M
  parked docs (tests/test_storage.py, slow-marked, asserts the ceiling).
- ``StorageEngine`` — the policy layer binding a live ``DocFleet`` to a
  ``MainStore``: ``park`` demotes cold fleet docs (canonical chunk via
  ``save()``, round-trip-validated by the native extractor, device slots
  freed), ``revive`` promotes them back through the bulk loader (one
  native parse + batched dispatches, history stays parked-lazy on the
  revived engine), and the causal-state reads route to the columnar
  arrays without touching chunk bytes at all.

Durability composition: parking a journaled doc frees it from the
journal's registry (the standard FREE record) — its bytes now live in
the main store; reviving through a ``DurableFleet``'s ``load_docs``
re-journals the chunk as the doc's baseline. The incremental per-doc
compaction that keeps checkpoint cost proportional to churn lives in
fleet/durability.py; this module is the RAM-resident tier.
"""

import weakref

import numpy as np

from ..columnar import DocChunkView
from ..errors import MalformedDocument
from ..observability.metrics import Counters, register_health_source
from ..observability.perf import register_mem_source
from ..observability.spans import span as _span

__all__ = ['MainStore', 'StorageEngine']

_stats = Counters({
    'storage_auto_vacuums': 0,   # dead_fraction-policy vacuums triggered
    'storage_parked_syncs_skipped': 0,   # sync rounds served parked
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])

# memory-watermark tier: every live MainStore's chunk arena + causal
# lanes, the signal the cost-based-tiering ROADMAP item consumes
_live_stores = weakref.WeakSet()
register_mem_source(
    'mainstore_bytes',
    lambda: sum(s.resident_bytes() for s in list(_live_stores)))


class _I64:
    """Growable int64 lane (amortized-doubling numpy array)."""

    __slots__ = ('data', 'n')

    def __init__(self, dtype=np.int64):
        self.data = np.zeros(16, dtype=dtype)
        self.n = 0

    def append(self, value):
        if self.n == len(self.data):
            grown = np.zeros(len(self.data) * 2, dtype=self.data.dtype)
            grown[:self.n] = self.data
            self.data = grown
        self.data[self.n] = value
        self.n += 1

    def extend(self, values):
        need = self.n + len(values)
        if need > len(self.data):
            cap = len(self.data)
            while cap < need:
                cap *= 2
            grown = np.zeros(cap, dtype=self.data.dtype)
            grown[:self.n] = self.data
            self.data = grown
        self.data[self.n:need] = values
        self.n = need

    @property
    def nbytes(self):
        return int(self.data.nbytes)


class MainStore:
    """Columnar store of parked compressed document chunks.

    Row ids are dense ints assigned by ``add`` and never recycled until
    ``vacuum`` (discarded rows leave arena garbage that vacuum reclaims;
    ``dead_fraction`` exposes the trigger signal). All causal reads are
    O(row) array lookups — no chunk bytes are touched."""

    def __init__(self):
        self._chunks = []               # row -> bytes | None (discarded)
        self._chunk_bytes = 0
        self._heads_arena = bytearray()  # 32 B per head, concatenated
        self._heads_off = _I64()
        self._heads_n = _I64(np.int32)
        self._clock_actor = _I64(np.int32)   # interned actor index
        self._clock_seq = _I64()
        self._clock_off = _I64()
        self._clock_n = _I64(np.int32)
        self._max_op = _I64()
        self._n_changes = _I64()
        self.actors = []                # interned actor hex strings
        self._actor_index = {}
        self._live = 0
        self._dead_head_bytes = 0
        self._dead_clock_rows = 0
        _live_stores.add(self)          # memory-watermark tier (perf.py)

    def __len__(self):
        return self._live

    def resident_bytes(self):
        """Resident bytes of this store: the compressed chunk arena plus
        the columnar causal lanes (heads arena + index arrays) — the
        number the cost-based-tiering ROADMAP item budgets against."""
        total = self._chunk_bytes + len(self._heads_arena)
        for col in (self._heads_off, self._heads_n, self._clock_actor,
                    self._clock_seq, self._clock_off, self._clock_n,
                    self._max_op, self._n_changes):
            total += col.nbytes
        return total

    def _intern_actor(self, hexa):
        idx = self._actor_index.get(hexa)
        if idx is None:
            idx = len(self.actors)
            self.actors.append(hexa)
            self._actor_index[hexa] = idx
        return idx

    def add(self, chunk, heads, clock, max_op, n_changes):
        """Store one parked doc; returns its row id. `heads` are hex
        strings, `clock` {actor_hex: seq}."""
        row = len(self._chunks)
        chunk = bytes(chunk)
        self._chunks.append(chunk)
        self._chunk_bytes += len(chunk)
        self._heads_off.append(len(self._heads_arena))
        self._heads_n.append(len(heads))
        for h in sorted(heads):
            self._heads_arena += bytes.fromhex(h)
        self._clock_off.append(self._clock_actor.n)
        self._clock_n.append(len(clock))
        for hexa in sorted(clock):
            self._clock_actor.append(self._intern_actor(hexa))
            self._clock_seq.append(int(clock[hexa]))
        self._max_op.append(int(max_op))
        self._n_changes.append(int(n_changes))
        self._live += 1
        return row

    def add_chunk(self, chunk, check=True):
        """Store a chunk deriving its causal row compute-on-compressed
        (DocChunkView: header heads + change-meta columns only). Raises
        MalformedDocument on undecodable bytes."""
        view = DocChunkView(chunk, check=check)
        return self.add(chunk, view.heads, view.clock, view.max_op,
                        view.n_changes)

    def _check(self, row):
        if not (0 <= row < len(self._chunks)) or self._chunks[row] is None:
            raise KeyError(f'no parked doc at row {row}')

    def chunk(self, row):
        self._check(row)
        return self._chunks[row]

    def heads(self, row):
        self._check(row)
        off = int(self._heads_off.data[row])
        n = int(self._heads_n.data[row])
        return [self._heads_arena[off + 32 * i:off + 32 * (i + 1)].hex()
                for i in range(n)]

    def clock(self, row):
        self._check(row)
        off = int(self._clock_off.data[row])
        n = int(self._clock_n.data[row])
        return {self.actors[int(self._clock_actor.data[off + i])]:
                int(self._clock_seq.data[off + i]) for i in range(n)}

    def max_op(self, row):
        self._check(row)
        return int(self._max_op.data[row])

    def n_changes(self, row):
        self._check(row)
        return int(self._n_changes.data[row])

    def contains_head(self, row, hash_hex):
        """Sync-membership probe against the columnar heads arena —
        no chunk decode, no Python per-head strings on the hot path."""
        self._check(row)
        off = int(self._heads_off.data[row])
        n = int(self._heads_n.data[row])
        needle = bytes.fromhex(hash_hex)
        arena = self._heads_arena
        return any(arena[off + 32 * i:off + 32 * (i + 1)] == needle
                   for i in range(n))

    def covers_heads(self, row, their_heads):
        """True when every hash in `their_heads` is one of row's heads —
        the parked-doc 'already in sync' fast path."""
        return all(self.contains_head(row, h) for h in their_heads)

    def discard(self, row):
        self._check(row)
        chunk = self._chunks[row]
        self._chunks[row] = None
        self._chunk_bytes -= len(chunk)
        self._dead_head_bytes += 32 * int(self._heads_n.data[row])
        self._dead_clock_rows += int(self._clock_n.data[row])
        self._live -= 1
        return chunk

    @property
    def dead_fraction(self):
        total = len(self._chunks)
        return (total - self._live) / total if total else 0.0

    def vacuum(self):
        """Compact arenas and row lanes, dropping discarded rows.
        Returns {old_row: new_row} so callers can remap their ids."""
        remap = {}
        fresh = MainStore()
        fresh.actors = self.actors
        fresh._actor_index = self._actor_index
        for row, chunk in enumerate(self._chunks):
            if chunk is None:
                continue
            remap[row] = fresh.add(chunk, self.heads(row), self.clock(row),
                                   self.max_op(row), self.n_changes(row))
        for name in ('_chunks', '_chunk_bytes', '_heads_arena', '_heads_off',
                     '_heads_n', '_clock_actor', '_clock_seq', '_clock_off',
                     '_clock_n', '_max_op', '_n_changes', '_live',
                     '_dead_head_bytes', '_dead_clock_rows'):
            setattr(self, name, getattr(fresh, name))
        return remap

    def memory_stats(self):
        """Byte accounting: chunk payload vs per-doc overhead (the
        columnar causal state + row lanes + list slots). The acceptance
        signal is overhead_per_doc — what the HOST pays per parked doc
        on top of its compressed bytes."""
        lanes = (self._heads_off.nbytes + self._heads_n.nbytes +
                 self._clock_off.nbytes + self._clock_n.nbytes +
                 self._max_op.nbytes + self._n_changes.nbytes)
        arenas = (len(self._heads_arena) + self._clock_actor.nbytes +
                  self._clock_seq.nbytes)
        # list slot (8 B pointer) + bytes-object header (~33 B) per chunk
        obj_overhead = 8 * len(self._chunks) + 33 * self._live
        overhead = lanes + arenas + obj_overhead
        return {
            'n_docs': self._live,
            'chunk_bytes': self._chunk_bytes,
            'causal_arena_bytes': arenas,
            'lane_bytes': lanes,
            'overhead_bytes': overhead,
            'overhead_per_doc': overhead / self._live if self._live else 0.0,
            'total_bytes': self._chunk_bytes + overhead,
            'dead_fraction': self.dead_fraction,
            'n_actors': len(self.actors),
        }


class StorageEngine:
    """Delta (live DocFleet) + main (MainStore) with park/revive policy
    and compute-on-compressed reads for the parked tier.

    Doc ids handed out by ``park``/``ingest_chunks`` are STABLE: an
    id→row indirection lets the engine vacuum the main store underneath
    its callers (``vacuum_dead_fraction`` policy — after discard churn
    pushes ``MainStore.dead_fraction`` past the threshold, the arenas
    compact automatically, counted in the ``storage_auto_vacuums``
    health counter) without invalidating anything a caller holds. Pass
    ``vacuum_dead_fraction=None`` to disable the policy and vacuum by
    hand via ``self.main``."""

    # don't churn tiny stores: below this row count a vacuum saves noise
    VACUUM_MIN_ROWS = 8

    def __init__(self, fleet=None, vacuum_dead_fraction=0.5):
        from .backend import DocFleet
        self.fleet = fleet if fleet is not None else DocFleet()
        self.main = MainStore()
        self.vacuum_dead_fraction = vacuum_dead_fraction
        self.vacuums = 0
        self._row_of = {}            # stable doc id -> main-store row
        self._next_id = 0

    def adopt_main(self, other):
        """MOVE another engine's main store and its stable-id space here
        (e.g. rebinding parked docs to a durable fleet's engine): ids the
        other engine handed out stay valid on THIS engine, and the donor
        resets to empty. Ownership transfers whole — two engines sharing
        one store would race their id maps the first time either
        auto-vacuums (the vacuum rebinds the map it knows about and
        strands the other's rows) — and only into an EMPTY engine: the
        adopter's own id space would otherwise silently alias the
        donor's."""
        if self._row_of or len(self.main._chunks):
            raise ValueError('adopt_main requires an empty adopter: this '
                             'engine already holds parked docs whose ids '
                             'would alias the adopted ones')
        self.main = other.main
        self._row_of = dict(other._row_of)
        self._next_id = other._next_id
        other.main = MainStore()
        other._row_of = {}
        other._next_id = 0

    def _admit(self, row):
        doc_id = self._next_id
        self._next_id += 1
        self._row_of[doc_id] = row
        return doc_id

    def _row(self, doc_id):
        row = self._row_of.get(doc_id)
        if row is None:
            raise KeyError(f'no parked doc {doc_id}')
        return row

    def _discard(self, doc_ids):
        for doc_id in doc_ids:
            self.main.discard(self._row_of.pop(doc_id))
        self._maybe_vacuum()

    def _maybe_vacuum(self):
        threshold = self.vacuum_dead_fraction
        if threshold is None:
            return False
        if len(self.main._chunks) < self.VACUUM_MIN_ROWS or \
                self.main.dead_fraction < threshold:
            return False
        with _span('storage_vacuum', docs=len(self.main)):
            remap = self.main.vacuum()
        self._row_of = {doc_id: remap[row]
                        for doc_id, row in self._row_of.items()}
        self.vacuums += 1
        _stats.inc('storage_auto_vacuums')
        return True

    # -- demotion -------------------------------------------------------

    def park(self, handles):
        """Demote fleet documents into the main store: canonical chunk
        (round-trip-validated — a doc whose history cannot reproduce
        from its chunk stays live), causal state into the columnar
        arrays, device slots freed in one batched call. Returns a list
        aligned with `handles`: the doc's main-store id, or None where
        the doc was skipped (queued changes, non-fleet, failed
        validation). Skipped handles stay live and usable."""
        from . import backend as fleet_backend
        from .backend import FleetDoc, _validate_doc_chunks

        ids = [None] * len(handles)
        to_free = []
        ready = []          # (input index, handle, state, chunk, n)
        pending = []        # (input index, handle, state, chunk) to batch
        with _span('storage_park', docs=len(handles)):
            for i, handle in enumerate(handles):
                state = handle.get('state')
                if handle.get('frozen') or not isinstance(state, FleetDoc) \
                        or not state.is_fleet:
                    continue
                impl = state._impl
                if impl.queue:
                    continue
                if impl._doc_pending is not None and not impl._changes:
                    # already parked in-fleet with no delta tail: the
                    # chunk is the validated canonical form
                    ready.append((i, handle, state, impl._doc_pending,
                                  impl._parked_n))
                else:
                    pending.append((i, handle, state, bytes(state.save())))
            # ONE batched validation (native pool fan-out) for every doc
            # that needs it
            counts = _validate_doc_chunks([c for _i, _h, _s, c in pending])
            for (i, handle, state, chunk), n in zip(pending, counts):
                if n is not None:
                    ready.append((i, handle, state, chunk, n))
            for i, handle, state, chunk, n in ready:
                ids[i] = self._admit(self.main.add(
                    chunk, state.heads, state.clock, state.max_op, n))
                to_free.append(handle)
            if to_free:
                fleet_backend.free_docs(to_free)
        return ids

    def ingest_chunks(self, chunks, check=True):
        """Admit saved document chunks straight into the main store —
        no fleet slot, no engine, no decode of op columns: causal state
        comes from the chunk itself (DocChunkView). This is the 1M-doc
        bulk-park path. Returns main-store ids. Raises MalformedDocument
        for undecodable bytes (the batch up to that point is kept)."""
        with _span('storage_ingest', docs=len(chunks)):
            return [self._admit(self.main.add_chunk(c, check=check))
                    for c in chunks]

    # -- promotion ------------------------------------------------------

    def revive(self, ids, durable=None):
        """Promote parked docs back into the live fleet through the bulk
        loader (one native parse + batched dispatches; history stays
        lazily parked on the revived engines). `durable` is an optional
        DurableFleet manager — revived docs journal their chunk as a
        baseline through its load_docs. Returns backend handles in id
        order; the docs leave the main store (auto-vacuum may compact
        the arenas afterwards — ids held for OTHER docs stay valid)."""
        chunks = [self.main.chunk(self._row(i)) for i in ids]
        with _span('storage_revive', docs=len(ids)):
            if durable is not None:
                handles = durable.load_docs(chunks)
            else:
                from .loader import load_docs
                handles = load_docs(chunks, self.fleet)
            self._discard(ids)
        return handles

    def discard(self, ids):
        """Drop parked docs outright (no revive); returns their chunks.
        Auto-vacuum policy applies."""
        chunks = [self.main.chunk(self._row(i)) for i in ids]
        self._discard(ids)
        return chunks

    def repark(self, handles, ids):
        """Return just-revived docs to the store under their ORIGINAL
        ids — the abort path of a round that revived docs and then
        raised before serving them (mixed sync deadline/decode aborts):
        the caller's ids must stay valid because the caller never sees
        the handles. Freshly revived docs re-park through the
        already-parked fast path (chunk verbatim, no re-validation)."""
        got = self.park(handles)
        for orig, new in zip(ids, got):
            if new is not None and new != orig:
                self._row_of[orig] = self._row_of.pop(new)

    # -- compute-on-compressed reads -----------------------------------

    def chunk(self, doc_id):
        return self.main.chunk(self._row(doc_id))

    def heads(self, doc_id):
        return self.main.heads(self._row(doc_id))

    def clock(self, doc_id):
        return self.main.clock(self._row(doc_id))

    def max_op(self, doc_id):
        return self.main.max_op(self._row(doc_id))

    def n_changes(self, doc_id):
        return self.main.n_changes(self._row(doc_id))

    def contains_head(self, doc_id, hash_hex):
        return self.main.contains_head(self._row(doc_id), hash_hex)

    def covers_heads(self, doc_id, their_heads):
        return self.main.covers_heads(self._row(doc_id), their_heads)

    def needs_sync(self, doc_id, their_heads):
        """Parked-doc sync gate: False when the peer's heads equal ours
        (nothing to exchange — the doc can stay parked); True otherwise
        (revive before running a real sync round)."""
        ours = set(self.main.heads(self._row(doc_id)))
        return set(their_heads) != ours

    def memory_stats(self):
        return self.main.memory_stats()
