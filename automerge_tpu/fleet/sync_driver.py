"""Fleet-scale batched sync driver.

The host protocol (``backend/sync.py``, ref backend/sync.js:234-306) builds
one Bloom filter per peer and probes each candidate change hash one at a
time — fine for two peers, quadratic pain for a fleet syncing with thousands.
Here the same control flow runs over N (document, peer-state) pairs with the
two filter-heavy steps batched into ONE device dispatch each per round —
O(1) in the peer count AND in the per-peer filter-size skew (the flat
packed layout in fleet/bloom.py gives every filter its exact wire-format
byte span inside one concatenated vector, so differing entry counts no
longer split the batch into per-size-class dispatches, and batch memory
stays proportional to real filter bytes). `dispatch_count()` exposes the
round's device-call count for bench.py and the regression tests:

- ``generate_sync_messages_docs``: every doc's Bloom build (over its
  changes since sharedHeads) lands in one ``build_bloom_filters_batch``
  dispatch, and every doc's changes-to-send scan probes the peer's filter
  in one ``probe_bloom_filters_batch`` dispatch. Both dispatches are
  issued async (begin/finish pairs) so the device build and the packed
  filter-byte transfers overlap the host-side graph scans, and filters
  cross the link bit-packed (see fleet/bloom.py). Messages are
  byte-identical to the host ``generate_sync_message`` outputs.
- ``receive_sync_messages_docs``: all received changes apply through
  ``apply_changes_docs`` (one device merge dispatch on the fleet backend's
  turbo path), then the sharedHeads algebra runs per doc.

Wire format, resets, and the dependents-closure repair of Bloom false
positives are unchanged — graph traversal stays host-side (SURVEY.md §2.11).
"""

import hashlib

from ..backend import (
    get_heads, get_missing_deps, get_change_by_hash, get_change_hashes,
)
from ..columnar import CHUNK_TYPE_CHANGE, MAGIC_BYTES as _MAGIC
from ..backend.sync import (
    _cached_meta, advance_heads, changes_to_send_finish,
    changes_to_send_prescan, decode_sync_message, encode_sync_message,
)
from ..errors import DocError, MalformedSyncMessage, as_wire_error
from ..observability import recorder as _flight
from ..observability import tracecontext as _trace
from ..observability.metrics import Counters, register_health_source
from ..observability.spans import span as _span
from .backend import FleetDoc, apply_changes_docs, quarantine_stats
from .bloom import (
    build_bloom_filters_batch_begin, build_bloom_filters_batch_finish,
    dispatch_count, probe_bloom_filters_batch_begin,
    probe_bloom_filters_batch_finish,
)

__all__ = ['generate_sync_messages_docs', 'receive_sync_messages_docs',
           'generate_sync_messages_mixed', 'receive_sync_messages_mixed',
           'dispatch_count']


# the enable flag lives in hashindex so the single-doc protocol path
# (backend/sync.py -> _FlatEngine.probe_hashes) honors the same toggle
from .hashindex import (  # noqa: E402,F401
    PeerSentSet, frontier_enabled, probe_peer_sets, release_sync_state,
    set_frontier_enabled,
)

_stats = Counters({
    'sync_frontier_member_docs': 0,     # docs probed via the hashindex
    'sync_frontier_straggler_docs': 0,  # docs routed classic in a
                                        # frontier-served round
    'sync_peer_space_links': 0,         # links whose sentHashes rode a
})                                      # peer-space this round
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])


def _frontier_of(backends):
    """(FleetFrontierIndex, {i: engine}) over the FLEET SUBSET of a
    batch — the docs whose membership probes (theirHave lastSync
    reconciliation, received-heads lookup, incoming-change dedup) ride
    the device-resident frontier index as batched dispatches instead of
    per-doc host-dict probes (fleet/hashindex.py). Host backends,
    promoted docs, and docs of a second fleet are STRAGGLERS: absent
    from the map, they keep the classic dict path — one promoted doc no
    longer reverts the whole round (the mixed-batch routing ROADMAP
    follow-up). None when the index is disabled or no doc qualifies."""
    if not frontier_enabled():
        return None
    members = {}
    fleet = None
    for i, backend in enumerate(backends):
        state = backend.get('state') if isinstance(backend, dict) else None
        if not isinstance(state, FleetDoc) or not state.is_fleet:
            continue
        engine = state._impl
        if fleet is None:
            fleet = engine.fleet
        elif engine.fleet is not fleet:
            continue        # a second fleet's docs route classic
        members[i] = engine
    if not members:
        return None
    return fleet.frontier_index(), members


def _probe_pairs_grouped(fidx, members, hashes_by_doc):
    """Batch the member docs' membership questions into ONE index probe:
    hashes_by_doc[i] is a (possibly empty) list of hex hashes for member
    doc i. Returns {i: [bool, ...]} aligned with each doc's list (docs
    with no hashes are omitted)."""
    flat_e, flat_h, owners = [], [], []
    for i, hashes in hashes_by_doc.items():
        engine = members[i]
        for h in hashes:
            flat_e.append(engine)
            flat_h.append(h)
            owners.append(i)
    if not flat_h:
        return {}
    hits = fidx.probe_pairs(flat_e, flat_h)
    out = {}
    for i, hit in zip(owners, hits):
        out.setdefault(i, []).append(bool(hit))
    return out


def _batched_generate_probes(frontier, sync_states):
    """The generate round's TWO membership questions — get_missing_deps
    candidates (the peer's advertised heads plus deps of causally-queued
    changes) and the theirHave lastSync reconciliation — merged into ONE
    index dispatch for the member docs. Returns (our_need, reset_known),
    both keyed by doc index: our_need[i] exactly matches
    backend.get_missing_deps (the equivalence tests pin it);
    reset_known[i] is all-lastSync-hashes-known, defaulting True for
    docs with nothing to check. Straggler docs appear in neither."""
    fidx, members = frontier
    cands, queued, last_syncs = {}, {}, {}
    for i, engine in members.items():
        state = sync_states[i]
        all_deps = set(state['theirHeads'] or [])
        in_queue = set()
        for change in engine.queue:
            in_queue.add(change['hash'])
            all_deps.update(change['deps'])
        cands[i] = sorted(all_deps)
        queued[i] = in_queue
        their_have = state['theirHave']
        last_syncs[i] = their_have[0]['lastSync'] if their_have else []
    hits = _probe_pairs_grouped(
        fidx, members,
        {i: cands[i] + last_syncs[i] for i in members})
    our_need, reset_known = {}, {}
    for i in members:
        flags = hits.get(i, [])
        need_flags = flags[:len(cands[i])]
        our_need[i] = [h for h, known in zip(cands[i], need_flags)
                       if not known and h not in queued[i]]
        if last_syncs[i]:
            reset_known[i] = all(flags[len(cands[i]):])
    return our_need, reset_known


def _fused_sent_filter(sync_states, changes_to_send_by_doc):
    """{i: [bool]} "already sent on this link?" flags for every doc
    whose sentHashes rides a peer-space (``PeerSentSet``): ALL such
    links' questions fuse into at most one staged-flush insert plus one
    probe dispatch for the round (hashindex.probe_peer_sets). Plain-set
    links are absent — their check is a host set hit, and a member link
    only promotes to a peer-space the first time it actually sends."""
    idxs = [i for i, ch in changes_to_send_by_doc.items()
            if ch and isinstance(sync_states[i]['sentHashes'],
                                 PeerSentSet)]
    if not idxs:
        return {}
    flags = probe_peer_sets(
        [sync_states[i]['sentHashes'] for i in idxs],
        [[_cached_meta(c)['hash'] for c in changes_to_send_by_doc[i]]
         for i in idxs])
    _stats.inc('sync_peer_space_links', len(idxs))
    return dict(zip(idxs, flags))


def generate_sync_messages_docs(backends, sync_states, deadline=None,
                                trace_ctx=None):
    """Batched ``generate_sync_message`` over N (backend, syncState) pairs.
    Returns (new_sync_states, messages) with messages[i] = bytes or None,
    byte-identical to the host function applied per doc. All Bloom builds
    share one device dispatch; all peer-filter probes share another.
    `deadline` is checked before the build dispatch is issued (generation
    mutates no document state, so the check is purely a latency bound).

    `trace_ctx` OPTS the round into cross-peer trace stitching: every
    produced message is prepended with the trace envelope
    (observability/tracecontext.py), so the receiving peer's spans join
    this trace. Without it the wire bytes are untouched (the
    byte-identity contract above holds) — an AMBIENT context
    (``tracecontext.use``) only decorates this round's spans with the
    trace id, it never changes the wire."""
    n = len(backends)
    if len(sync_states) != n:
        raise ValueError('backends and sync_states must align')
    if deadline is not None:
        deadline.check(what='generate_sync_messages_docs')
    with _span('sync_generate', docs=n,
               **_trace.trace_attr(trace_ctx)):
        new_states, messages = _generate_inner(backends, sync_states, n)
    if trace_ctx is not None:
        messages = [m if m is None else _trace.wrap(m, trace_ctx)
                    for m in messages]
    return new_states, messages


def _generate_inner(backends, sync_states, n):
    our_heads = [get_heads(b) for b in backends]
    frontier = _frontier_of(backends)
    # With a frontier index, the member docs' membership questions —
    # get_missing_deps candidates AND each doc's theirHave lastSync
    # reconciliation — merge into ONE batched dispatch here, replacing
    # per-doc get_change_by_hash dict probes: O(1) dispatches regardless
    # of peer count or history depth, and no hash-graph dict build for
    # docs that are otherwise quiet. Stragglers (host backends, promoted
    # docs, a second fleet) take the classic path WITHOUT demoting the
    # member subset.
    if frontier is not None:
        member_need, reset_known = _batched_generate_probes(frontier,
                                                            sync_states)
        _stats.inc('sync_frontier_member_docs', len(frontier[1]))
        _stats.inc('sync_frontier_straggler_docs', n - len(frontier[1]))
    else:
        member_need, reset_known = {}, None
    our_need = [member_need[i] if i in member_need
                else get_missing_deps(b, s['theirHeads'] or [])
                for i, (b, s) in enumerate(zip(backends, sync_states))]

    # Phase 1 — which docs attach a filter, and over which hashes. The
    # build dispatch is issued here but not materialized until after the
    # probe dispatch: the device builds (and the link moves packed filter
    # bytes) while phase 2's host-side graph scans run.
    bloom_hash_lists = [None] * n
    for i, (backend, state) in enumerate(zip(backends, sync_states)):
        their_heads = state['theirHeads']
        if their_heads is None or all(h in their_heads for h in our_need[i]):
            bloom_hash_lists[i] = get_change_hashes(
                backend, state['sharedHeads'])
    build_handle = build_bloom_filters_batch_begin(
        [row if row is not None else [] for row in bloom_hash_lists])

    # Phase 2 — full-resync resets, and the changes-to-send pre-scan
    # (the lastSync reconciliation answers come from the merged phase-1
    # probe when the frontier index is on)
    results = [None] * n          # i -> (new_state, message or None)
    probe_rows = []               # flattened (doc, filter) probe requests
    probe_meta = []               # i -> ('probe', changes, first_row, n_filters)
    for i, (backend, state) in enumerate(zip(backends, sync_states)):
        their_have, their_need = state['theirHave'], state['theirNeed']
        if their_have:
            last_sync = their_have[0]['lastSync']
            known = reset_known.get(i, True) if i in member_need \
                else all(get_change_by_hash(backend, h) is not None
                         for h in last_sync)
            if not known:
                reset = {'heads': our_heads[i], 'need': [],
                         'have': [{'lastSync': [], 'bloom': b''}],
                         'changes': []}
                results[i] = (state, encode_sync_message(reset))
                continue
        if not (isinstance(their_have, list) and
                isinstance(their_need, list)):
            probe_meta.append(None)
            continue
        mode, payload = changes_to_send_prescan(backend, their_have,
                                                their_need)
        if mode == 'need-only':
            probe_meta.append(('done', i, payload))
        else:
            changes, filter_bytes = payload
            first = len(probe_rows)
            hashes = [c['hash'] for c in changes]
            for fb in filter_bytes:
                probe_rows.append((fb, hashes))
            probe_meta.append(('probe', i, changes, first,
                               len(filter_bytes)))

    probe_handle = probe_bloom_filters_batch_begin(
        [r[0] for r in probe_rows], [r[1] for r in probe_rows])
    built = build_bloom_filters_batch_finish(build_handle)
    our_have = [[{'lastSync': s['sharedHeads'], 'bloom': built[i]}]
                if bloom_hash_lists[i] is not None else []
                for i, s in enumerate(sync_states)]
    hits = probe_bloom_filters_batch_finish(probe_handle)

    # Phase 3 — assemble messages exactly as the host does
    changes_to_send_by_doc = {}
    for entry in probe_meta:
        if entry is None:
            continue
        if entry[0] == 'done':
            _, i, changes_list = entry
            changes_to_send_by_doc[i] = changes_list
        else:
            _, i, changes, first, n_filters = entry
            bloom_hits = [hits[first + f] for f in range(n_filters)]
            changes_to_send_by_doc[i] = changes_to_send_finish(
                backends[i], changes, bloom_hits,
                sync_states[i]['theirNeed'])

    # Fused sentHashes filter: every peer-space link's already-sent?
    # questions ride one flush insert + one probe dispatch for the whole
    # round, regardless of link count (tentpole of the sync fabric)
    sent_flags = _fused_sent_filter(sync_states, changes_to_send_by_doc)
    member_docs = frontier[1] if frontier is not None else {}

    new_states, messages = [], []
    with _span('sync_encode', docs=n):
        for i, (backend, state) in enumerate(zip(backends, sync_states)):
            if results[i] is not None:
                new_states.append(results[i][0])
                messages.append(results[i][1])
                continue
            changes_to_send = changes_to_send_by_doc.get(i, [])
            heads_unchanged = isinstance(state['lastSentHeads'], list) and \
                our_heads[i] == state['lastSentHeads']
            heads_equal = isinstance(state['theirHeads'], list) and \
                our_heads[i] == state['theirHeads']
            if heads_unchanged and heads_equal and not changes_to_send:
                new_states.append(state)
                messages.append(None)
                continue
            sent_hashes = state['sentHashes']
            if i in sent_flags:
                changes_to_send = [c for c, hit in zip(changes_to_send,
                                                       sent_flags[i])
                                   if not hit]
            else:
                changes_to_send = [
                    c for c in changes_to_send
                    if _cached_meta(c)['hash'] not in sent_hashes]
            message = {'heads': our_heads[i], 'have': our_have[i],
                       'need': our_need[i], 'changes': changes_to_send}
            if changes_to_send:
                new_hashes = [_cached_meta(c)['hash']
                              for c in changes_to_send]
                if isinstance(sent_hashes, PeerSentSet):
                    # staged host-side; next round's fused filter (or
                    # flush_peer_sets) lands the whole shard's backlog
                    # in ONE insert
                    sent_hashes.stage_many(new_hashes)
                elif i in member_docs:
                    # first send on a member link: promote the plain set
                    # to a peer-space of the fleet's table — the
                    # promotion snapshot IS the copy-on-write the
                    # classic path performed
                    sent_hashes = PeerSentSet(frontier[0].table,
                                              seed=sent_hashes)
                    sent_hashes.stage_many(new_hashes)
                else:
                    sent_hashes = set(sent_hashes)
                    sent_hashes.update(new_hashes)
            new_states.append(dict(state, lastSentHeads=our_heads[i],
                                   sentHashes=sent_hashes))
            messages.append(encode_sync_message(message))
    return new_states, messages


def receive_sync_messages_docs(backends, sync_states, binary_messages,
                               mirror=True, on_error='raise',
                               deadline=None, _decoded=None):
    """Batched ``receive_sync_message`` over N docs. messages[i] may be None
    (no-op for that doc). All received changes apply through ONE
    apply_changes_docs call (device turbo batch with mirror=False on fleet
    backends). Returns (new_backends, new_sync_states, patches) — or, with
    on_error='quarantine', (new_backends, new_sync_states, patches,
    errors): an undecodable message or a poisoned change quarantines ONLY
    its own doc (errors[i] is a DocError; that doc's backend and sync
    state stay untouched) while the other N-1 docs commit in the same
    fused dispatch. on_error='raise' aborts the round on the first bad
    input (classic contract), with a typed exception carrying the doc
    index. Messages are decoded per doc EITHER way, so the exception
    names the offender instead of dying mid-list.

    `deadline` is checked at entry and again AFTER the (host-side,
    non-mutating) decode, immediately before the fused apply dispatch —
    a deadline that fires leaves every doc and sync state untouched
    (typed DeadlineExceeded, all-or-nothing).

    Messages carrying the trace ENVELOPE (a tracing peer generated with
    ``trace_ctx``) are transparently stripped before decode, and the
    round's spans adopt the first stripped trace id — the receive side
    of cross-peer trace stitching. Plain messages pass through the
    (one-byte) probe untouched."""
    n = len(backends)
    if len(sync_states) != n or len(binary_messages) != n:
        raise ValueError('backends, sync_states, and messages must align')
    if deadline is not None:
        deadline.check(what='receive_sync_messages_docs')
    wire_ctx, binary_messages = _strip_trace_envelopes(binary_messages)
    with _span('sync_receive', docs=n,
               **_trace.trace_attr(wire_ctx)):
        return _receive_inner(backends, sync_states, binary_messages,
                              mirror, on_error, deadline, _decoded, n)


def _strip_trace_envelopes(binary_messages):
    """(first stripped TraceContext or None, messages with every trace
    envelope removed). The input list is untouched (copied on first
    strip); plain messages cost a one-byte probe. Every receive entry
    point — batched AND mixed — must strip before any decode, or an
    enveloped message from a tracing peer reads as hostile bytes."""
    wire_ctx = None
    stripped = None
    for i, message_bytes in enumerate(binary_messages):
        if message_bytes is not None and len(message_bytes) and \
                message_bytes[0] == _trace.TRACE_MAGIC:
            ctx, payload = _trace.unwrap(bytes(message_bytes))
            if ctx is not None:
                if stripped is None:
                    stripped = list(binary_messages)
                stripped[i] = payload
                if wire_ctx is None:
                    wire_ctx = ctx
    return wire_ctx, (binary_messages if stripped is None else stripped)


def _quick_change_hash(buf):
    """Hex hash of a SINGLE well-formed change chunk without any header
    decode: the change hash is SHA-256 over the chunk from the type byte
    on, and the wire checksum is its first four bytes — so one hashlib
    pass whose digest matches the stored checksum proves both that the
    buffer is exactly one chunk (no trailing bytes shifted the span) and
    that the digest IS the change's hash. Anything else (deflated,
    multi-chunk, corrupt) returns None: the caller must keep the buffer
    for the apply path, which types those cases properly."""
    b = bytes(buf)
    if len(b) > 9 and b[:4] == _MAGIC and b[8] == CHUNK_TYPE_CHANGE:
        digest = hashlib.sha256(b[8:]).digest()
        if digest[:4] == b[4:8]:
            return digest.hex()
    return None


def _dedup_known_changes(frontier, per_doc_changes):
    """Drop incoming changes already in their doc's applied history —
    ONE batched frontier-index probe for the round's MEMBER docs
    (stragglers keep their changes: the causal gate dedups them at
    general-gate prices). A resent known change (Bloom false negative,
    replayed wire) breaks the turbo chain shape and demotes its doc to
    the per-change path. Buffers whose hash has no cheap provable lane
    are kept (never wrong)."""
    fidx, members = frontier
    flat_e, flat_h, where = [], [], []
    for i, changes in enumerate(per_doc_changes):
        if i not in members:
            continue
        for j, buf in enumerate(changes):
            h = _quick_change_hash(buf)
            if h is not None:
                flat_e.append(members[i])
                flat_h.append(h)
                where.append((i, j))
    if not flat_h:
        return
    hits = fidx.probe_pairs(flat_e, flat_h)
    drop = {}
    for (i, j), hit in zip(where, hits):
        if hit:
            drop.setdefault(i, set()).add(j)
    for i, gone in drop.items():
        per_doc_changes[i] = [c for j, c in enumerate(per_doc_changes[i])
                              if j not in gone]


def _receive_inner(backends, sync_states, binary_messages, mirror,
                   on_error, deadline, _decoded, n):
    quarantine = on_error == 'quarantine'
    if not quarantine and on_error != 'raise':
        raise ValueError(f"on_error must be 'raise' or 'quarantine', "
                         f"got {on_error!r}")
    errors = [None] * n
    decoded = [None] * n
    with _span('sync_decode', docs=n):
        for i, message_bytes in enumerate(binary_messages):
            if message_bytes is None:
                continue
            if _decoded is not None and _decoded[i] is not None:
                # the mixed parked gate already decoded this message to
                # decide revive-vs-fast; don't parse the bytes twice
                decoded[i] = _decoded[i]
                continue
            try:
                decoded[i] = decode_sync_message(message_bytes)
            except Exception as exc:
                err = as_wire_error(exc, MalformedSyncMessage,
                                    'receive_sync_messages_docs',
                                    doc_index=i)
                if not quarantine:
                    raise err
                errors[i] = DocError(i, 'decode', err)
                quarantine_stats.inc('quarantined_docs')
                state = backends[i].get('state') \
                    if isinstance(backends[i], dict) else None
                _flight.record_event(
                    'quarantine', doc=i, stage='decode',
                    error=type(err).__name__, message=str(err)[:200],
                    durable_id=getattr(state, '_dur_id', None),
                    change_bytes=len(message_bytes))
    if any(e is not None for e in errors):
        # undecodable sync messages: forensic dump now — the apply path
        # below only dumps for ITS rejects, and never sees these docs
        _flight.dump_flight_record('quarantine', detail={'errors': [
            e.describe(durable_id=getattr(
                backends[i].get('state') if isinstance(backends[i], dict)
                else None, '_dur_id', None))
            for i, e in enumerate(errors) if e is not None]})
    before_heads = [get_heads(b) for b in backends]

    frontier = _frontier_of(backends)
    per_doc_changes = [list(d['changes']) if d else [] for d in decoded]
    if frontier is not None and any(per_doc_changes):
        _dedup_known_changes(frontier, per_doc_changes)
    if any(per_doc_changes):
        # the decode above was pure host-side reading; this is the last
        # point before the fused dispatch mutates anything (apply checks
        # the deadline again at its own entry)
        if quarantine:
            new_backends, patches, apply_errors = apply_changes_docs(
                backends, per_doc_changes, mirror=mirror,
                on_error='quarantine', deadline=deadline)
            for i, err in enumerate(apply_errors):
                if err is not None and errors[i] is None:
                    errors[i] = err
        else:
            new_backends, patches = apply_changes_docs(
                backends, per_doc_changes, mirror=mirror,
                deadline=deadline)
    else:
        new_backends, patches = list(backends), [None] * n

    # Received-heads membership for the member docs in ONE index
    # dispatch (post-apply: the commit staged this round's hashes, the
    # probe's flush lands them first). Quarantined docs probe nothing.
    # Derived from the POST-apply backends, not the pre-apply engine
    # list: an apply can PROMOTE a doc to the host engine (unsupported
    # ops), freeing its slot — a stale engine reference would crash the
    # probe mid-round; a freshly promoted doc simply drops out of the
    # member map and answers via the classic dict probe below.
    heads_known = None
    post_members = {}
    post_frontier = _frontier_of(new_backends)
    if post_frontier is not None:
        post_members = post_frontier[1]
        heads_known = _probe_pairs_grouped(
            post_frontier[0], post_members,
            {i: decoded[i]['heads'] for i in post_members
             if decoded[i] is not None and errors[i] is None})

    new_states = []
    for i, (backend, state) in enumerate(zip(new_backends, sync_states)):
        message = decoded[i]
        if message is None or errors[i] is not None:
            # quarantined docs keep their pre-round sync state: the peer
            # retries from the last good handshake, nothing is half-advanced
            new_states.append(state)
            continue
        shared_heads = state['sharedHeads']
        last_sent_heads = state['lastSentHeads']
        sent_hashes = state['sentHashes']
        if message['changes']:
            shared_heads = advance_heads(before_heads[i], get_heads(backend),
                                         shared_heads)
        if not message['changes'] and message['heads'] == before_heads[i]:
            last_sent_heads = message['heads']
        if heads_known is not None and i in post_members:
            flags = heads_known.get(i, [])
            known_heads = [h for h, known in zip(message['heads'], flags)
                           if known]
        else:
            known_heads = [h for h in message['heads']
                           if get_change_by_hash(backend, h) is not None]
        if len(known_heads) == len(message['heads']):
            shared_heads = message['heads']
            if len(message['heads']) == 0:
                last_sent_heads = []
                # peer lost all data: its sent set must not survive —
                # hand a peer-space back deterministically
                release_sync_state(state)
                sent_hashes = set()
        else:
            shared_heads = sorted(set(known_heads) | set(shared_heads))
        new_states.append({
            'sharedHeads': shared_heads,
            'lastSentHeads': last_sent_heads,
            'theirHave': message['have'],
            'theirHeads': message['heads'],
            'theirNeed': message['need'],
            'sentHashes': sent_hashes,
        })
    if quarantine:
        return new_backends, new_states, patches, errors
    return new_backends, new_states, patches


# ----------------------------------------------------------------------
# Mixed live+parked rounds: the StorageEngine.needs_sync gate
# ----------------------------------------------------------------------
#
# A host serving 1M parked docs cannot revive its whole main store to
# answer sync rounds; these variants accept a MIXED population — element
# i of `docs` is either an ordinary live backend handle or an int doc id
# parked in `storage` (a fleet/storage.py StorageEngine) — and revive
# ONLY the docs a peer actually needs, in one batched revive, before
# running the ordinary fused round over the live subset. Parked docs
# whose handshake is provably quiet are answered compute-on-compressed
# (the columnar heads lane; zero chunk decode, zero device work) and
# counted in the 'storage_parked_syncs_skipped' health counter.

def _parked_stats():
    from .storage import _stats
    return _stats


def generate_sync_messages_mixed(storage, docs, sync_states,
                                 deadline=None):
    """Batched generate over a mixed live/parked population. A parked
    doc stays parked (message None, state unchanged) when the handshake
    is QUIET: the peer's advertised heads equal ours, our last sent
    heads equal ours, and the peer needs nothing — exactly the state in
    which the live protocol answers None. Every other parked doc is
    revived (one batched revive for the round) and joins the fused
    generate. Returns (docs_out, new_states, messages): docs_out[i] is
    the live handle (possibly freshly revived) or the untouched parked
    id."""
    n = len(docs)
    if len(sync_states) != n:
        raise ValueError('docs and sync_states must align')
    if deadline is not None:
        # before the gate revives anything: an already-expired deadline
        # must abort with storage untouched (all-or-nothing)
        deadline.check(what='generate_sync_messages_mixed')
    docs_out = list(docs)
    revive = []
    with _span('sync_parked_gate', docs=n):
        for i, doc in enumerate(docs):
            if not isinstance(doc, int):
                continue
            state = sync_states[i]
            their = state['theirHeads']
            last_sent = state['lastSentHeads']
            their_have = state['theirHave']
            # the live reset branch fires when the peer's lastSync names
            # history we don't hold; the heads lane can only prove
            # membership for our heads themselves, so anything else
            # revives (conservative, never wrong)
            last_sync_known = not their_have or all(
                storage.contains_head(doc, h)
                for h in their_have[0]['lastSync'])
            quiet = isinstance(their, list) and \
                not storage.needs_sync(doc, their) and \
                isinstance(last_sent, list) and \
                sorted(last_sent) == storage.heads(doc) and \
                not state['theirNeed'] and last_sync_known
            if quiet:
                _parked_stats().inc('storage_parked_syncs_skipped')
            else:
                revive.append(i)
    if revive:
        for i, handle in zip(revive,
                             storage.revive([docs[i] for i in revive])):
            docs_out[i] = handle
    live = [i for i in range(n) if not isinstance(docs_out[i], int)]
    new_states = list(sync_states)
    messages = [None] * n
    if live:
        try:
            sub_states, sub_msgs = generate_sync_messages_docs(
                [docs_out[i] for i in live],
                [sync_states[i] for i in live], deadline=deadline)
        except Exception:
            # the round raised after the gate revived docs (e.g. a
            # deadline expiring mid-round): the caller gets no docs_out,
            # so the revived handles would leak and the caller's parked
            # ids would dangle — re-park them under their original ids
            if revive:
                storage.repark([docs_out[i] for i in revive],
                               [docs[i] for i in revive])
            raise
        for i, state, message in zip(live, sub_states, sub_msgs):
            new_states[i] = state
            messages[i] = message
    return docs_out, new_states, messages


def receive_sync_messages_mixed(storage, docs, sync_states,
                                binary_messages, mirror=True,
                                on_error='raise', deadline=None):
    """Batched receive over a mixed live/parked population (see
    ``generate_sync_messages_mixed``). A parked doc stays parked when
    its message carries NO changes and every advertised head is already
    one of ours (the columnar heads-lane membership probe — then the
    sharedHeads algebra needs no history lookup and the doc mutates
    nothing); anything else revives it first. Returns
    (docs_out, new_states, patches[, errors])."""
    n = len(docs)
    if len(sync_states) != n or len(binary_messages) != n:
        raise ValueError('docs, sync_states, and messages must align')
    if deadline is not None:
        # before the gate revives anything (see generate_..._mixed)
        deadline.check(what='receive_sync_messages_mixed')
    # strip trace envelopes BEFORE the parked gate's decode — an
    # enveloped message from a tracing peer would otherwise read as
    # hostile bytes and quarantine a perfectly valid sync
    _wire_ctx, binary_messages = _strip_trace_envelopes(binary_messages)
    quarantine = on_error == 'quarantine'
    docs_out = list(docs)
    fast = {}                   # i -> decoded message served parked
    pre_decoded = [None] * n    # parked-gate decodes, reused by the
    revive = []                 # live path (no double message parse)
    with _span('sync_parked_gate', docs=n,
               **_trace.trace_attr(_wire_ctx)):
        for i, doc in enumerate(docs):
            if not isinstance(doc, int) or binary_messages[i] is None:
                continue
            try:
                message = decode_sync_message(binary_messages[i])
            except Exception as exc:
                # an undecodable message mutates nothing, so the doc can
                # stay parked while its error is reported
                err = as_wire_error(exc, MalformedSyncMessage,
                                    'receive_sync_messages_mixed',
                                    doc_index=i)
                if not quarantine:
                    raise err
                fast[i] = err
                continue
            if message['changes'] or not storage.covers_heads(
                    doc, message['heads']):
                pre_decoded[i] = message
                revive.append(i)
            else:
                fast[i] = message
    if revive:
        for i, handle in zip(revive,
                             storage.revive([docs[i] for i in revive])):
            docs_out[i] = handle
    live = [i for i in range(n) if not isinstance(docs_out[i], int)]

    new_states = list(sync_states)
    patches = [None] * n
    errors = [None] * n
    if live:
        try:
            # the messages were already stripped above, so the inner
            # receive's own probe finds no envelope — hand it the wire
            # context as AMBIENT instead (trace_attr falls back to it),
            # so the round's spans still adopt the peer's trace id
            with _trace.use(_wire_ctx or _trace.current()):
                out = receive_sync_messages_docs(
                    [docs_out[i] for i in live],
                    [sync_states[i] for i in live],
                    [binary_messages[i] for i in live], mirror=mirror,
                    on_error=on_error, deadline=deadline,
                    _decoded=[pre_decoded[i] for i in live])
        except Exception:
            # round aborted after the gate revived docs (deadline at the
            # apply seam, or a raise-mode decode failure — both fire
            # BEFORE any doc mutates): re-park under the original ids so
            # nothing leaks and the caller's ids stay valid
            if revive:
                storage.repark([docs_out[i] for i in revive],
                               [docs[i] for i in revive])
            raise
        if quarantine:
            sub_docs, sub_states, sub_patches, sub_errors = out
        else:
            sub_docs, sub_states, sub_patches = out
            sub_errors = [None] * len(live)
        for k, i in enumerate(live):
            docs_out[i] = sub_docs[k]
            new_states[i] = sub_states[k]
            patches[i] = sub_patches[k]
            if sub_errors[k] is not None:
                # the sublist call indexed its errors in ITS coordinate
                # space; re-scope the record to the caller's mixed array
                # so both error populations share one index space
                sub_errors[k].index = i
                if sub_errors[k].error is not None and \
                        getattr(sub_errors[k].error, 'doc_index',
                                None) is not None:
                    sub_errors[k].error.doc_index = i
            errors[i] = sub_errors[k]

    fast_errors = []
    for i, decoded in fast.items():
        if isinstance(decoded, Exception):
            errors[i] = DocError(i, 'decode', decoded)
            quarantine_stats.inc('quarantined_docs')
            # same forensic trail as the live decode path: this fault
            # class must not go invisible just because the doc is parked
            _flight.record_event(
                'quarantine', doc=i, stage='decode',
                error=type(decoded).__name__,
                message=str(decoded)[:200], durable_id=None,
                change_bytes=len(binary_messages[i]))
            fast_errors.append(errors[i])
            continue
        # the live sharedHeads algebra, specialized to the case the gate
        # proved: no changes, every message head one of ours — so every
        # 'known head' check is a heads-lane membership hit
        state = sync_states[i]
        ours = storage.heads(docs[i])
        last_sent = state['lastSentHeads']
        sent_hashes = state['sentHashes']
        if list(decoded['heads']) == ours:
            last_sent = decoded['heads']
        shared_heads = decoded['heads']
        if len(decoded['heads']) == 0:
            last_sent = []
            release_sync_state(state)
            sent_hashes = set()
        new_states[i] = {
            'sharedHeads': shared_heads,
            'lastSentHeads': last_sent,
            'theirHave': decoded['have'],
            'theirHeads': decoded['heads'],
            'theirNeed': decoded['need'],
            'sentHashes': sent_hashes,
        }
        _parked_stats().inc('storage_parked_syncs_skipped')
    if fast_errors:
        _flight.dump_flight_record('quarantine', detail={
            'errors': [e.describe() for e in fast_errors]})
    if quarantine:
        return docs_out, new_states, patches, errors
    return docs_out, new_states, patches
