"""Bulk document load: saved containers straight to device state.

This is the native batch-load path (round-2 VERDICT item 8, SURVEY §7 north
star "decode straight into padded device tensors"): one C++ call
(`native.parse_documents`, ref columnar.js:1006-1047) parses every saved
document in the fleet to flat op columns, and the FINAL CRDT state — the
succ-derived visible sets of ref new.js:1204-1217 — is scattered into the
device registers in a handful of batched dispatches. Nothing is replayed:
where the reference's load walks every op through seekToOp (new.js:1604-1635
documentPatch after decode), this loader reconstructs the end state directly
from the document columns, because the document format already stores ops in
final document order with their successors.

The change *log* is not materialized at all (the deferred-hash-graph load of
ref new.js:1709-1749): the original chunk parks on the engine and per-change
buffers/hashes are decoded lazily the first time history is genuinely read
(sync, getChanges, save-after-edit, mirror fallback). An unedited loaded
document's save() returns the loaded bytes verbatim — a byte-identical
round-trip; note this skips save()'s usual canonical re-encode, so two
replicas bulk-loaded from *different* foreign encodings of the same state
can save different bytes until their first edit.

Documents outside the fleet subset (link ops, unknown columns, op counters
past the 2^23 packing window, >256 actors) fall back per-doc to the
ordinary load path — the loader is an accelerator, never a semantic fork.
Objects inside sequences (rows-in-lists) bulk-load natively: make element
rows install as links (round 4).
"""

import numpy as np

from .. import native
from ..columnar import (decode_value, split_containers,
                        CHUNK_TYPE_DOCUMENT, MAGIC_BYTES as _MAGIC)
from .tensor_doc import CTR_LIMIT, MAX_ACTORS
from ..observability.spans import spanned as _spanned

# Wire action numbers (ref columnar.js:51-52)
_A_MAKE_MAP, _A_SET, _A_MAKE_LIST, _A_MAKE_TEXT = 0, 1, 2, 4
_A_INC, _A_MAKE_TABLE = 5, 6
_MAKES = (_A_MAKE_MAP, _A_MAKE_LIST, _A_MAKE_TEXT, _A_MAKE_TABLE)
_SEQ_MAKES = (_A_MAKE_LIST, _A_MAKE_TEXT)
_TYPE_NAMES = {_A_MAKE_MAP: 'map', _A_MAKE_TABLE: 'table',
               _A_MAKE_LIST: 'list', _A_MAKE_TEXT: 'text'}


class _DocDeferredBatch:
    """Adapter giving the hash graph lazy access to a bulk-loaded doc's
    change metadata (resolved through the engine's parked chunk)."""

    __slots__ = ('engine',)

    def __init__(self, engine):
        self.engine = engine

    def resolve(self, i):
        return self.engine._doc_resolve(i)


def _okey(doc, ctr, actor):
    """Doc-scoped object/op key: collision-free int64 for (doc, ctr, actor)
    with ctr < 2^23 and actor < 256 (root encodes as ctr=0, actor=-1)."""
    return doc.astype(np.int64) * (1 << 33) + ctr * 512 + (actor + 1)


def _isin_sorted(values, sorted_arr):
    if len(sorted_arr) == 0:
        return np.zeros(len(values), dtype=bool)
    pos = np.clip(np.searchsorted(sorted_arr, values), 0,
                  len(sorted_arr) - 1)
    return sorted_arr[pos] == values


@_spanned('bulk_load')
def load_docs(buffers, fleet=None):
    """Load N saved documents into fleet-resident handles in one native
    parse + a few batched device dispatches. Returns handles in input
    order. Docs the fast path can't represent load through the ordinary
    per-doc path transparently."""
    from . import backend as fleet_backend

    fleet = fleet or fleet_backend.default_fleet()
    n_in = len(buffers)
    handles = [None] * n_in

    chunks = [None] * n_in
    if native.available():
        for i, buf in enumerate(buffers):
            # keep memoryviews (mmap'd parked chunks on the revive
            # path) unowned: the probe below and the native parse both
            # read through the buffer protocol without materializing
            if not isinstance(buf, (bytes, memoryview)):
                buf = bytes(buf)
            # fast single-container probe: magic + document type byte —
            # the native parser re-verifies framing, checksum, and
            # trailing bytes, so a false positive only round-trips
            # through its per-doc ok=0 fallback. The full Python
            # container walk runs only for multi-chunk/odd inputs.
            if len(buf) > 11 and buf[:4] == _MAGIC and \
                    buf[8] == CHUNK_TYPE_DOCUMENT:
                chunks[i] = buf
                continue
            try:
                parts = split_containers(buf)
            except Exception:
                parts = []
            if len(parts) == 1 and parts[0][8] == CHUNK_TYPE_DOCUMENT:
                chunks[i] = parts[0]

    native_idx = [i for i, c in enumerate(chunks) if c is not None]
    out = native.parse_documents([chunks[i] for i in native_idx]) \
        if native_idx else None
    installed = set()
    if out is not None and native_idx:
        installed = _install_parsed(fleet, out, native_idx, chunks, handles,
                                    fleet_backend)
    for i in range(n_in):
        if i not in installed:
            handles[i] = fleet_backend.load(bytes(buffers[i]), fleet)
    return handles


def _install_parsed(fleet, out, native_idx, chunks, handles, fleet_backend):
    """Vectorized end-state assembly for every natively parsed doc; returns
    the set of input indexes successfully installed."""
    from .backend import FleetDoc, _FlatEngine

    ok = out['ok'].astype(bool)

    # Fleet actor registration (one insert_many + remap for the batch)
    perm = fleet.actors.insert_many(out['actors'])
    if perm is not None:
        if fleet.exact_device:
            fleet._remap_reg_actors(perm)
        else:
            fleet._remap_actors(perm)
        fleet._remap_seq_actors(perm)
    amap = np.array([fleet.actors.index.get(a, -1) for a in out['actors']],
                    dtype=np.int64) if out['actors'] else np.zeros(1, np.int64)

    doc = out['doc'].astype(np.int64)
    id_ctr = out['id_ctr']
    id_actor = amap[out['id_actor']]
    obj_ctr = out['obj_ctr']
    obj_actor = np.where(out['obj_actor'] >= 0, amap[out['obj_actor']], -1)
    key_ctr = out['key_ctr']
    key_actor = np.where(out['key_actor'] >= 0, amap[out['key_actor']], -1)
    key_str = out['key_str']
    action = out['action'].astype(np.int64)
    insert = out['insert'].astype(bool)
    vtype = out['vtype']
    val_int = out['val_int']
    succ_off = out['succ_off']
    succ_ctr = out['succ_ctr']
    succ_actor = amap[out['succ_actor']] if len(out['succ_actor']) else \
        np.zeros(0, dtype=np.int64)
    n_ops = len(doc)

    # ---- per-doc viability ----------------------------------------------
    # Overflow badness FIRST: _okey packing assumes ctr < 2^23 and
    # actor < 256, so rows of overflowing (fallback-bound) docs must be
    # excluded from classification keys before they can alias another
    # doc's object identities
    bad = ~ok.copy()
    ctr_over = (id_ctr >= CTR_LIMIT) | (key_ctr >= CTR_LIMIT) | \
        (obj_ctr >= CTR_LIMIT)
    actor_over = (id_actor >= MAX_ACTORS) | (id_actor < 0) | \
        (key_actor >= MAX_ACTORS) | (obj_actor >= MAX_ACTORS)
    for mask in (ctr_over, actor_over):
        if mask.any():
            bad[np.unique(doc[mask])] = True
    n_succ = len(succ_ctr)
    srow = np.repeat(np.arange(n_ops), np.diff(succ_off)) if n_succ else \
        np.zeros(0, dtype=np.int64)
    if n_succ:
        sc_over = (succ_ctr >= CTR_LIMIT) | (succ_actor >= MAX_ACTORS) | \
            (succ_actor < 0)
        if sc_over.any():
            bad[np.unique(doc[srow[sc_over]])] = True

    row_ok = ~bad[doc]
    okey = _okey(doc, obj_ctr, obj_actor)           # op's containing object
    rid = _okey(doc, id_ctr, id_actor)              # op's own id
    make_mask = np.isin(action, _MAKES)
    seq_make = np.isin(action, _SEQ_MAKES)
    seq_objs = np.sort(rid[make_mask & seq_make & row_ok])
    map_objs = np.sort(rid[make_mask & ~seq_make & row_ok])
    row_is_seq = _isin_sorted(okey, seq_objs)
    row_in_map = (obj_actor < 0) | _isin_sorted(okey, map_objs)
    orphan = row_ok & ~row_is_seq & ~row_in_map
    # map rows must carry a string key and cannot be inserts (a crafted
    # chunk can pass the column-level checks with an elemId on a map row —
    # out['keys'][-1] must never be dereferenced). Makes inside sequences
    # are legal element rows (rows-in-lists): their value lane becomes a
    # link to the child object, handled in _install_seq_rows.
    map_malformed = row_ok & ~row_is_seq & ((key_str < 0) | insert)
    for mask in (orphan, map_malformed):
        if mask.any():
            bad[np.unique(doc[mask])] = True

    # ---- alive / counter-fold (succNum==0 visibility; inc successors
    # accumulate instead of killing, ref new.js:937-965). The inc lookup
    # table takes good-doc rows ONLY: a fallback-bound doc's un-packable
    # op ids alias into other docs' _okey space and would corrupt their
    # alive/counter computation -------------------------------------------
    inc_mask = action == _A_INC
    inc_sel = inc_mask & ~bad[doc]
    inc_rid = rid[inc_sel]
    inc_order = np.argsort(inc_rid)
    inc_sorted = inc_rid[inc_order]
    inc_vals = val_int[inc_sel][inc_order]
    n_succ_per = np.diff(succ_off)
    counter_add = np.zeros(n_ops, dtype=np.int64)
    if n_succ and len(inc_sorted):
        skey = _okey(doc[srow], succ_ctr, succ_actor)
        pos = np.clip(np.searchsorted(inc_sorted, skey), 0,
                      len(inc_sorted) - 1)
        succ_is_inc = inc_sorted[pos] == skey
        # Counter attribution (new.js:942-945): an inc shared as succ by
        # multiple counter sets (conflicted counter) is consumed and
        # folded ONLY by the Lamport-max set; the other sets keep an
        # unconsumed succ, so they fail the all-succs-are-incs rule below
        # and stay invisible — matching the reference's counterStates
        # overwrite (round-4 50x-chaos find)
        succ_ok = np.zeros(len(srow), dtype=bool)
        # good-doc rows only: a fallback-bound doc's overflow-aliased succ
        # rows must not steal a good doc's winner group (same defense as
        # the inc lookup table above)
        idx = np.flatnonzero(succ_is_inc & ~bad[doc[srow]])
        if len(idx):
            packed32_pre = ((id_ctr << 8) | id_actor).astype(np.int64)
            sk = skey[idx]
            order2 = np.lexsort((packed32_pre[srow[idx]], sk))
            sk_s = sk[order2]
            last = np.r_[sk_s[1:] != sk_s[:-1], True]
            keep = np.zeros(len(idx), dtype=bool)
            keep[order2[last]] = True
            succ_ok[idx[keep]] = True
        inc_per = np.bincount(srow, weights=succ_ok.astype(np.float64),
                              minlength=n_ops).astype(np.int64)
        fold = np.where(succ_ok, inc_vals[pos], 0)
        counter_add = np.bincount(srow, weights=fold.astype(np.float64),
                                  minlength=n_ops).astype(np.int64)
    else:
        inc_per = np.zeros(n_ops, dtype=np.int64)
    alive = ~inc_mask & (inc_per == n_succ_per)

    # ---- engines + per-doc metadata --------------------------------------
    packed32 = ((id_ctr << 8) | id_actor).astype(np.int64)
    oid_str = {}                       # rid key -> 'ctr@actor' string
    obj_type = {}                      # rid key -> wire make action
    # good-doc rows only: a fallback-bound doc's overflowing ids must not
    # alias (and overwrite) another doc's object identities
    for j in np.flatnonzero(make_mask & ~bad[doc]):
        oid_str[int(rid[j])] = \
            f'{int(id_ctr[j])}@{fleet.actors.actors[int(id_actor[j])]}'
        obj_type[int(rid[j])] = int(action[j])

    good_docs = np.flatnonzero(~bad)
    slot_of = np.full(len(ok), -1, dtype=np.int64)
    engines = {}
    # one batched allocation for the whole load (init_docs' bookkeeping);
    # engines come from the allocation-only bulk constructor and the GC
    # stays paused across the loop — the per-doc constructor chain +
    # gen-0 scans were a measurable slice of recovery's snapshot load
    # at 10k docs (same reasoning as init_docs)
    slots = fleet.alloc_slots(len(good_docs))
    bulk_new = _FlatEngine._bulk_new
    fleet_actors = fleet.actors.actors
    heads_off = out['heads_off']
    actor_off = out['actor_off']
    doc_actors = out['doc_actors']
    max_op_arr = out['max_op']
    n_changes_arr = out['n_changes']
    heads_hex = out['heads'].tobytes().hex() if len(out['heads']) else ''
    from .backend import _gc_paused
    with _gc_paused():
        for d, slot in zip(good_docs.tolist(), slots):
            eng = bulk_new(fleet, slot)
            slot_of[d] = slot
            # The loaded ops feed the applied-op index below
            # (_install_map_cells), so the turbo dangling-pred check stays
            # armed for bulk-loaded slots — the reference detects invalid
            # op references during the merge regardless of how the doc
            # arrived (new.js:1219-1220; closes round-5 VERDICT weak #6).
            a0, a1 = int(actor_off[d]), int(actor_off[d + 1])
            if a1 - a0 == 1:                 # the common single-actor doc
                eng.actor_ids = [fleet_actors[int(amap[doc_actors[a0]])]]
            else:
                eng.actor_ids = [fleet_actors[int(amap[g])]
                                 for g in doc_actors[a0:a1]]
            h0, h1 = int(heads_off[d]), int(heads_off[d + 1])
            if h1 - h0 == 1:                 # the common single-head doc
                eng.heads = [heads_hex[64 * h0:64 * h1]]
            else:
                eng.heads = sorted(heads_hex[64 * h:64 * (h + 1)]
                                   for h in range(h0, h1))
            eng.max_op = int(max_op_arr[d])
            chunk = bytes(chunks[native_idx[d]])
            eng._install_parked_chunk(chunk, int(n_changes_arr[d]))
            engines[d] = eng
        # clock: per (doc, actor) max seq, accumulated per doc and
        # assigned WHOLE (engine.clock is a columnar-backed property:
        # in-place writes on the materialized dict would be lost)
        c_doc = out['c_doc'].astype(np.int64)
        c_actor = amap[out['c_actor']] if len(out['c_actor']) else \
            np.zeros(0, dtype=np.int64)
        c_seq = out['c_seq']
        clocks = {}
        for d, a, s in zip(c_doc.tolist(), c_actor.tolist(),
                           c_seq.tolist()):
            if d in engines:
                clock = clocks.setdefault(d, {})
                hexa = fleet_actors[a]
                if clock.get(hexa, 0) < s:
                    clock[hexa] = s
        for d, clock in clocks.items():
            engines[d].clock = clock
    fleet.metrics.docs_bulk_loaded += len(engines)
    # object registries
    for j in np.flatnonzero(make_mask):
        d = int(doc[j])
        if d not in engines:
            continue
        a = int(action[j])
        oid = oid_str[int(rid[j])]
        if a in _SEQ_MAKES:
            engines[d].seq_objects[oid] = _TYPE_NAMES[a]
        else:
            engines[d].map_objects[oid] = _TYPE_NAMES[a]

    max_slot = int(slot_of.max()) if len(slot_of) else -1
    if max_slot >= 0:
        _ensure_caps(fleet, max_slot + 1)

    keep = ~bad[doc] & (slot_of[doc] >= 0)
    _install_map_cells(fleet, out, keep & ~row_is_seq & ~inc_mask & alive,
                       keep & ~row_is_seq,
                       doc, slot_of, okey, oid_str, key_str, packed32,
                       id_actor, vtype, val_int, counter_add, action,
                       make_mask, rid)
    # sequence counter lanes bit-pack (sum << 2) | count-bits, where the
    # count bits are 0, 1, or 3 (3 = two or more incs consumed) — the
    # patch walk replays the reference's counterStates edit shapes, which
    # depend on whether 0, 1, or >= 2 incs were consumed. Sums past the
    # +/-2^29 envelope cannot pack; those rows go inexact in
    # _install_seq_rows (mirror-served) instead of wrapping.
    seq_counter = counter_add * 4 + np.minimum(inc_per, 2) + (inc_per >= 2)
    seq_counter_over = np.abs(counter_add) >= (1 << 29)
    _install_seq_rows(fleet, out, keep & row_is_seq, doc, slot_of, okey,
                      oid_str, obj_type, insert, alive, inc_mask,
                      packed32, id_actor, key_ctr, key_actor, vtype, val_int,
                      make_mask, rid, seq_counter, seq_counter_over)

    installed = set()
    for d, eng in engines.items():
        handles[native_idx[d]] = {'state': FleetDoc(fleet, eng),
                                  'heads': eng.heads}
        installed.add(native_idx[d])
    return installed


def _ensure_caps(fleet, n_docs):
    if fleet.exact_device:
        fleet._ensure_reg_capacity(n_docs=max(n_docs, fleet.n_slots),
                                   n_keys=len(fleet.keys))
    else:
        # materialize (not just size): the loader writes fleet.state in
        # place below, so the deferred fresh-fleet allocation must land
        fleet._materialize_grid(n_docs=max(n_docs, fleet.n_slots),
                                n_keys=len(fleet.keys))


def _decode_cell_value(fleet, out, j, vtype_j, val_int_j, exact):
    """One op's value -> int32 register/grid lane value (inline or value
    table ref). Exact mode uses fleet._intern_typed — THE datatype-boxing
    rule; the LWW grid boxes raw (its reader folds counters onto plain
    ints and never unwraps TypedValue)."""
    if vtype_j == 4 and 0 <= val_int_j < (1 << 31):
        return int(val_int_j)
    off = int(out['val_off'][j])
    ln = int(out['val_len'][j])
    decoded = decode_value((ln << 4) | int(vtype_j),
                           out['val_blob'][off:off + ln])
    value, datatype = decoded['value'], decoded.get('datatype')
    if exact:
        return fleet._intern_typed(value, datatype)
    return fleet._intern_value(value)


def _install_map_cells(fleet, out, sel, index_sel, doc, slot_of, okey,
                       oid_str, key_str, packed32, id_actor, vtype, val_int,
                       counter_add, action, make_mask, rid):
    """Scatter alive map-cell ops into the register state (exact mode) or
    the LWW winners grid, one batched device write per array.

    `index_sel` selects EVERY map-key op row of the loaded docs — alive,
    overwritten, and inc rows alike (the document format stores no del
    rows, so nothing here is del material). They all feed the slot's
    applied-op index in one `_index_ops` batch: the turbo dangling-pred
    oracle then covers bulk-loaded history exactly like applied history
    (an overwritten op is still a valid pred target for a concurrent op
    that saw it)."""
    import jax.numpy as jnp

    idx_rows = np.flatnonzero(index_sel)
    if not len(idx_rows):
        return
    # Intern cell keys once over every indexed row: root keys as plain
    # strings, nested as (oid, key)
    key_ids_all = np.zeros(len(idx_rows), dtype=np.int64)
    cache = {}
    for i, j in enumerate(idx_rows):
        ks = out['keys'][int(key_str[j])]
        ok_ = int(okey[j])
        ck = (ok_, ks)
        kid = cache.get(ck)
        if kid is None:
            parent = oid_str.get(ok_)
            kid = fleet.keys.intern(ks if parent is None else (parent, ks))
            cache[ck] = kid
        key_ids_all[i] = kid
    fleet._index_ops(slot_of[doc[idx_rows]], key_ids_all,
                     packed32[idx_rows])

    rows = np.flatnonzero(sel)
    if not len(rows):
        return
    # install subset: positions of the alive cells inside the index rows
    # (sel is a subset of index_sel by construction)
    key_ids = key_ids_all[np.searchsorted(idx_rows, rows)]

    values = np.zeros(len(rows), dtype=np.int64)
    for i, j in enumerate(rows):
        jj = int(j)
        if make_mask[jj]:
            # fleet._make_link_value — THE shared make-op link rule
            # (allocates an empty child sequence's device row too)
            values[i] = fleet._make_link_value(
                int(slot_of[doc[jj]]), oid_str[int(rid[jj])],
                _TYPE_NAMES[int(action[jj])])
        else:
            values[i] = _decode_cell_value(fleet, out, jj, int(vtype[jj]),
                                           int(val_int[jj]),
                                           fleet.exact_device)

    slots = slot_of[doc[rows]]
    lanes = id_actor[rows]
    packed = packed32[rows]
    counters = counter_add[rows]
    _ensure_caps(fleet, int(slots.max()) + 1)
    if fleet.exact_device:
        from .registers import RegisterState
        # one live op per (slot, key, lane); duplicates flag the doc inexact
        cell = slots * (1 << 33) + key_ids * 512 + lanes
        uniq, counts = np.unique(cell, return_counts=True)
        dup_docs = np.unique(slots[np.isin(cell, uniq[counts > 1])]) \
            if (counts > 1).any() else np.zeros(0, dtype=np.int64)
        rs = fleet.reg_state
        idx = (jnp.asarray(slots), jnp.asarray(key_ids), jnp.asarray(lanes))
        inexact = rs.inexact
        if len(dup_docs):
            inexact = inexact.at[jnp.asarray(dup_docs)].set(True)
        fleet.reg_state = RegisterState(
            rs.reg.at[idx].set(jnp.asarray(packed.astype(np.int32))),
            rs.killed.at[idx].set(False),
            rs.value.at[idx].set(jnp.asarray(values.astype(np.int32))),
            rs.counter.at[idx].set(jnp.asarray(counters.astype(np.int32))),
            inexact)
    else:
        from .tensor_doc import FleetState
        # LWW grid: winner per (slot, key) by max packed opId
        cell = slots * (1 << 33) + key_ids
        order = np.lexsort((packed, cell))
        cs = cell[order]
        last = np.r_[cs[1:] != cs[:-1], True]     # winner = last per group
        w = order[last]
        idx = (jnp.asarray(slots[w]), jnp.asarray(key_ids[w]))
        st = fleet.state
        fleet.state = FleetState(
            st.winners.at[idx].set(jnp.asarray(packed[w].astype(np.int32))),
            st.values.at[idx].set(jnp.asarray(values[w].astype(np.int32))),
            st.counters.at[idx].set(
                jnp.asarray(counters[w].astype(np.int32))))
        if (counters[w] != 0).any():
            # loaded accumulators pin the fleet to the general merge
            # kernel (see DocFleet._counters_touched)
            fleet._counters_touched = True
        if fleet.host_winners is not None:
            # Seed the host winner mirror (counter-attribution checks for
            # later incs run against these loaded winners)
            np.maximum.at(fleet.host_winners, (slots[w], key_ids[w]),
                          packed[w].astype(np.int32))
    fleet.metrics.dispatches += 1
    fleet.metrics.device_ops += len(rows)


def _install_seq_rows(fleet, out, sel, doc, slot_of, okey, oid_str, obj_type,
                      insert, alive, inc_mask, packed32, id_actor,
                      key_ctr, key_actor, vtype, val_int, make_mask, rid,
                      counter_add, counter_over):
    """Reconstruct SeqState rows from document-order sequence ops: element
    encounter order IS final RGA order, so the linked list is a straight
    chain — no pointer walking, no replay. Make rows (objects nested inside
    sequences) become link-valued elements, matching the ordinary apply
    path (backend._pack_seq_op)."""
    import jax.numpy as jnp
    from .sequence import SeqState, END, HEAD, SLOT0

    rows = np.flatnonzero(sel)
    if not len(rows):
        return
    # (doc, obj) groups; rows of one object are contiguous in doc order
    gkey = okey[rows]
    uniq, inv = np.unique(gkey, return_inverse=True)
    fleet_row = np.zeros(len(uniq), dtype=np.int64)
    is_text = np.zeros(len(uniq), dtype=bool)
    first_of_group = np.full(len(uniq), len(rows), dtype=np.int64)
    np.minimum.at(first_of_group, inv, np.arange(len(rows)))
    for u, ok_ in enumerate(uniq):
        oid = oid_str[int(ok_)]
        d = int(doc[rows[int(first_of_group[u])]])
        slot = int(slot_of[d])
        typ = 'text' if obj_type[int(ok_)] == _A_MAKE_TEXT else 'list'
        # alive makes already allocated their row in _install_map_cells;
        # killed/overwritten objects' rows allocate here
        existing = fleet.slot_seq.get(slot, {}).get(oid)
        fleet_row[u] = existing if existing is not None else \
            fleet._alloc_seq_row(slot, oid, typ)
        is_text[u] = typ == 'text'

    ins = insert[rows]
    # element ordinal per insert row within its group (stable group sort
    # preserves document order inside each group)
    order = np.argsort(inv, kind='stable')
    inv_s = inv[order]
    ins_s = ins[order].astype(np.int64)
    cum = np.cumsum(ins_s)
    grp_start = np.searchsorted(inv_s, np.arange(len(uniq)), side='left')
    grp_sizes = np.diff(np.r_[grp_start, len(ins_s)])
    base = cum - np.repeat(cum[grp_start] - ins_s[grp_start], grp_sizes)
    elem_ord = np.zeros(len(rows), dtype=np.int64)
    elem_ord[order] = base - 1                 # valid where ins
    n_elems = np.bincount(inv, weights=ins.astype(np.float64),
                          minlength=len(uniq)).astype(np.int64)

    # update rows: find the target element by its insert op id
    ins_idx = np.flatnonzero(ins)
    ikey = inv[ins_idx] * (1 << 33) + packed32[rows][ins_idx]
    ins_sorted = np.argsort(ikey)
    ins_keys = ikey[ins_sorted]
    tgt_packed = (key_ctr[rows] << 8) | np.maximum(key_actor[rows], 0)
    tkey = inv * (1 << 33) + tgt_packed
    if len(ins_keys):
        pos = np.clip(np.searchsorted(ins_keys, tkey), 0, len(ins_keys) - 1)
        matched = ins_keys[pos] == tkey
        tgt_ord = elem_ord[ins_idx[ins_sorted[pos]]]
    else:
        matched = np.zeros(len(rows), dtype=bool)
        tgt_ord = np.zeros(len(rows), dtype=np.int64)
    bad_upd = ~ins & ~matched       # update to unknown element -> inexact
    node = SLOT0 + np.where(ins, elem_ord, tgt_ord)

    # value lanes (text: single codepoints inline; lists: ints inline;
    # everything else boxes; counters flag the row, ref new.js:937-965)
    txt = is_text[inv]
    values = np.zeros(len(rows), dtype=np.int64)
    flag_counter = np.zeros(len(rows), dtype=bool)
    for i, j in enumerate(rows):
        jj = int(j)
        if inc_mask[jj]:
            continue   # consumed via succ attribution into counter lanes
        if make_mask[jj]:
            # Nested object as a sequence element: fleet._make_link_value
            # is THE shared make-op link rule (links the child, allocates
            # an empty child sequence's device row)
            values[i] = fleet._make_link_value(
                int(slot_of[int(doc[jj])]), oid_str[int(rid[jj])],
                _TYPE_NAMES[obj_type[int(rid[jj])]])
            if txt[i]:
                # object elements inside Text render as spans: mirror
                # serves those reads (same rule as _pack_seq_op)
                flag_counter[i] = True
            continue
        vt, vi = int(vtype[jj]), int(val_int[jj])
        if txt[i] and vt == 6 and vi >= 0:
            values[i] = vi
            continue
        elif not txt[i] and vt == 4 and 0 <= vi < (1 << 31):
            values[i] = vi
            continue
        off, ln = int(out['val_off'][jj]), int(out['val_len'][jj])
        decoded = decode_value((ln << 4) | vt, out['val_blob'][off:off + ln])
        dt = decoded.get('datatype')
        if isinstance(dt, str) and dt != 'int':
            # fleet._intern_typed — THE datatype-boxing rule (shared with
            # every other ingest path; it normalizes int wire tags itself)
            values[i] = fleet._intern_typed(decoded['value'], dt)
        else:
            # plain payloads box raw here (NOT _intern_typed): sequence
            # lanes reserve inline ints for text code points, and the list
            # inline-int fast path already ran above
            values[i] = fleet._intern_value_boxed(decoded['value'])

    live = alive[rows] & ~inc_mask[rows] & ~bad_upd
    live_mask = np.zeros(len(rows), dtype=bool)
    live_mask[np.flatnonzero(live)] = True

    # inexact flags: unmatched update targets, counter sums past the
    # packable envelope, object elements in Text rows, and duplicate
    # (element, lane) live ops (outside one-op-per-actor) — computed on
    # op rows, applied per placement below
    inex_obj = np.zeros(len(uniq), dtype=bool)
    np.logical_or.at(
        inex_obj, inv[flag_counter | bad_upd | counter_over[rows]], True)
    lane_cell = inv[live_mask] * (1 << 42) + node[live_mask] * 512 + \
        id_actor[rows][live_mask]
    uq, cnt = np.unique(lane_cell, return_counts=True)
    if (cnt > 1).any():
        dup = np.isin(lane_cell, uq[cnt > 1])
        np.logical_or.at(inex_obj, inv[live_mask][dup], True)

    # place each object in its size class (host-tracked lengths), then
    # install per class: one chain/element/lane scatter set per class
    place = [fleet._place_seq_row(int(fleet_row[u]), int(n_elems[u]))
             for u in range(len(uniq))]
    cls_arr = np.array([p[0] for p in place], dtype=np.int64)
    idx_arr = np.array([p[1] for p in place], dtype=np.int64)
    idx_of_op = idx_arr[inv]

    for cls in np.unique(cls_arr):
        cls = int(cls)
        objs = np.flatnonzero(cls_arr == cls)
        st = fleet.seq_pools.state(cls)
        nodes = st.elem_id.shape[1]

        # linked chain per pool row: HEAD -> SLOT0 .. SLOT0+n-1 -> END
        nxt_host = np.full((len(objs), nodes), END, dtype=np.int32)
        n_host = np.zeros(len(objs), dtype=np.int32)
        for i, u in enumerate(objs):
            n_k = int(n_elems[u])
            n_host[i] = n_k
            if n_k:
                nxt_host[i, HEAD] = SLOT0
                if n_k > 1:
                    nxt_host[i, SLOT0:SLOT0 + n_k - 1] = \
                        np.arange(SLOT0 + 1, SLOT0 + n_k, dtype=np.int32)
                nxt_host[i, SLOT0 + n_k - 1] = END
        tr = jnp.asarray(idx_arr[objs])
        new_nxt = st.nxt.at[tr].set(jnp.asarray(nxt_host))
        new_n = st.n.at[tr].set(jnp.asarray(n_host))

        in_cls = np.isin(inv, objs)
        ins_sel = np.flatnonzero(ins & in_cls)
        eidx = (jnp.asarray(idx_of_op[ins_sel]),
                jnp.asarray(node[ins_sel]))
        new_elem = st.elem_id.at[eidx].set(
            jnp.asarray(packed32[rows][ins_sel].astype(np.int32)))

        live_sel = np.flatnonzero(live_mask & in_cls)
        lidx = (jnp.asarray(idx_of_op[live_sel]),
                jnp.asarray(node[live_sel]),
                jnp.asarray(id_actor[rows][live_sel]))
        new_reg = st.reg.at[lidx].set(
            jnp.asarray(packed32[rows][live_sel].astype(np.int32)))
        new_killed = st.killed.at[lidx].set(False)
        new_val = st.val.at[lidx].set(
            jnp.asarray(values[live_sel].astype(np.int32)))
        new_counter = st.counter.at[lidx].set(
            jnp.asarray(counter_add[rows][live_sel].astype(np.int32)))
        # Dead counter sets that consumed incs install as KILLED lanes
        # with their counter bits: the patch walk needs them to emit the
        # reference's phantom remove / remove->update edits for deleted
        # or overwritten inc'd counters
        dead_sel = np.flatnonzero(
            in_cls & ~live_mask & ~inc_mask[rows] & ~bad_upd &
            ((counter_add[rows] & 3) != 0))
        if len(dead_sel):
            # A dead inc'd counter whose lane was reclaimed by the same
            # actor cannot be represented (sequence.py flags the same
            # shape reclaim_incd): route the object to the mirror rather
            # than clobber the live lane
            lane_key = (idx_of_op.astype(np.int64) * (1 << 40) +
                        node.astype(np.int64) * 512 +
                        id_actor[rows].astype(np.int64))
            taken = np.isin(lane_key[dead_sel], lane_key[live_sel])
            if taken.any():
                np.logical_or.at(inex_obj, inv[dead_sel[taken]], True)
                dead_sel = dead_sel[~taken]
        if len(dead_sel):
            didx = (jnp.asarray(idx_of_op[dead_sel]),
                    jnp.asarray(node[dead_sel]),
                    jnp.asarray(id_actor[rows][dead_sel]))
            new_reg = new_reg.at[didx].set(
                jnp.asarray(packed32[rows][dead_sel].astype(np.int32)))
            new_killed = new_killed.at[didx].set(True)
            new_val = new_val.at[didx].set(
                jnp.asarray(values[dead_sel].astype(np.int32)))
            new_counter = new_counter.at[didx].set(
                jnp.asarray(counter_add[rows][dead_sel].astype(np.int32)))

        new_inexact = st.inexact
        inex = objs[inex_obj[objs]]
        if len(inex):
            new_inexact = new_inexact.at[jnp.asarray(idx_arr[inex])].set(
                True)
        fleet.seq_pools.pools[cls] = SeqState(
            new_elem, new_nxt, new_reg, new_killed, new_val, new_counter,
            new_n, new_inexact)
        fleet.metrics.dispatches += 1
    fleet.metrics.device_ops += len(rows)
