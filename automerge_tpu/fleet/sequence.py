"""Batched RGA sequence engine: list/text CRDTs as device tensors.

This is the tensorized equivalent of the reference's list-insertion path
(ref backend/new.js:50-192 seekWithinBlock, :145-163 concurrent-insert skip;
host mirror: automerge_tpu/backend/op_set.py ObjState.insert_rga): a fleet of
N sequence documents (one Text or list object each) lives as padded [N, S]
slot tensors plus a linked-list `nxt` pointer array encoding RGA order. Slots
are allocated in op-arrival order and never move; an insert splices pointers,
so per-op work is O(S) vector compares (the referent lookup) + an O(skip)
pointer walk, with NO data movement of the sequence itself — the analogue of
the reference editing a block in place instead of reshuffling the array.

Application is a `vmap` over docs of a `lax.scan` over each doc's op stream:
ops within one doc apply in causal order (as the reference's per-change op
loop does), while the fleet axis is embarrassingly parallel — the SURVEY §7
"vmap'd masked scan" formulation. Extraction back to sequence order
(`linearize`) is pointer-doubling list ranking: O(log S) rounds of gathers,
fully parallel, replacing the reference's visibleCount block walk
(new.js:225-240).

Packed opIds: (counter << ACTOR_BITS) | actorNum, as in tensor_doc. For the
integer comparisons here to agree with the host engine's Lamport order
(counter, actorId-hex-string) — used both for the RGA concurrent-insert skip
and per-element LWW — actor numbers MUST be assigned in ascending
lexicographic order of the actor hex ids (the reference's columnar format
sorts its actor table the same way, ref backend/columnar.js:133-170).

Per-element overwrite state is an exact multi-value register (the
fleet/registers.py design applied to sequence elements): each element keeps
an actor-slotted visible set — packed opId + payload per actor lane, with a
`killed` bit marking ops that have a successor (ref new.js:1204-1217's
succNum == 0 visibility rule). A SET/DEL kills exactly its preds, never
concurrent ops, so the two shapes where single-winner LWW diverges from the
reference — concurrent set-vs-set (conflict sets) and set-vs-delete
(element resurrection, ref test/new_backend_test.js:1660) — are exact on
device, and counters inside sequences accumulate exactly in per-lane
counter registers with the reference's Lamport-max attribution
(new.js:942-945). The remaining host-only shapes (same-actor overwrites
that don't pred their own op, pred lists past SEQ_PRED_LANES) flag the row
`inexact` and route reads to the host mirror.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..observability.perf import instrument_kernel
from .tensor_doc import ACTOR_BITS, MAX_ACTORS, pack_op_id, register_pytrees

# Op kinds in a SeqOpBatch
PAD, INSERT, SET, DEL, INC = 0, 1, 2, 3, 4

HEAD_REF = 0  # `ref == 0` means insert at the head ('_head' in the reference)

INT32_MAX = np.int32(2**31 - 1)

ACTOR_MASK = MAX_ACTORS - 1

# Static pred-lane width: ops with more preds flag their row inexact. A pred
# list wider than the element's current conflict set cannot occur, so lanes
# bound the *representable* conflict width, matching registers.RegisterOpBatch.
SEQ_PRED_LANES = 4

# Default actor-lane width for new states; grows on demand (pow2) with the
# fleet's actor table.
DEFAULT_ACTOR_SLOTS = 4


# Node-id layout, front-anchored so every per-node array shares one shape
# [N, capacity + 3] and capacity can grow (or pad for sharding) by appending
# at the tail without moving the sentinels:
#
#   0        HEAD sentinel (its nxt is the first element)
#   1        END sentinel / pointer-scratch (masked pointer writes land here;
#            its outgoing pointer is never followed)
#   2        slot-scratch (masked writes of per-slot arrays land here)
#   3..S+2   real slots, allocated in op-arrival order
HEAD, END, SCRATCH, SLOT0 = 0, 1, 2, 3


class SeqState:
    """Pytree of per-doc sequence tensors.

    Element identity / order (node-id indexed, [N, S+3]):
      elem_id  packed elemId per slot (0 = unallocated)
      nxt      linked-list next pointers over node ids

    Per-element multi-value registers ([N, S+3, A], actor-lane indexed by the
    op's packed actor number — at most one live op per actor per element in
    causally well-formed histories, since the frontend always preds its own
    visible op, ref frontend/context.js:576-586):
      reg      packed opId of actor lane a's op on this element (0 = none)
      killed   that op has a successor (overwritten / deleted)
      val      the op's payload (char code / value-table ref)
      counter  accumulated inc deltas for the lane's op, bit-packed as
               (sum << 2) | count-bits, where the count bits are 0, 1,
               or 3 (3 = two or more incs consumed) — the reference defers
               a counter element's whole-doc patch through its counter
               state, and the edit shape depends on the count (0 or 1 inc
               emits `insert`, >= 2 emits `update` via the transient
               remove->update conversion) — so the patch walk replays a
               shape-equivalent row sequence; display value =
               val + (counter >> 2), ref new.js:937-965

    Plus [N] allocation cursors `n` and [N] `inexact` flags (device state
    diverged from reference semantics — self conflicts, pred overflow,
    unknown referents — so reads must come from the host mirror, cf.
    registers.RegisterState)."""

    def __init__(self, elem_id, nxt, reg, killed, val, counter, n,
                 inexact=None):
        self.elem_id = elem_id
        self.nxt = nxt
        self.reg = reg
        self.killed = killed
        self.val = val
        self.counter = counter
        self.n = n              # slots allocated per doc
        if inexact is None:
            # .shape is static even on tracers, so this default is jit-safe
            inexact = np.zeros((n.shape[0],), dtype=bool)
        self.inexact = inexact  # row needs the host mirror for reads

    @property
    def capacity(self):
        return self.elem_id.shape[1] - 3

    @property
    def actor_slots(self):
        return self.reg.shape[2]

    @classmethod
    def empty(cls, n_docs, capacity, actor_slots=DEFAULT_ACTOR_SLOTS, xp=np):
        nodes = (n_docs, capacity + 3)
        lanes = (n_docs, capacity + 3, actor_slots)
        nxt = xp.full(nodes, END, dtype=np.int32)
        return cls(
            xp.zeros(nodes, dtype=np.int32),
            nxt,
            xp.zeros(lanes, dtype=np.int32),
            xp.zeros(lanes, dtype=bool),
            xp.zeros(lanes, dtype=np.int32),
            xp.zeros(lanes, dtype=np.int32),
            xp.zeros((n_docs,), dtype=np.int32),
            xp.zeros((n_docs,), dtype=bool))

    def tree_flatten(self):
        return ((self.elem_id, self.nxt, self.reg, self.killed, self.val,
                 self.counter, self.n, self.inexact), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def grow_seq_state(state, n_rows, capacity, actor_slots=None):
    """Host-side resize to at least (n_rows rows, capacity slots,
    actor_slots lanes): new rows/slots/lanes are zeroed/END-filled; existing
    node ids and actor lanes never move (the sentinels are front-anchored
    precisely so capacity can grow by appending at the tail). Returns
    `state` unchanged if already big enough."""
    old_r, old_nodes = state.elem_id.shape
    old_cap = old_nodes - 3
    old_a = state.reg.shape[2]
    want_a = old_a if actor_slots is None else actor_slots
    if n_rows <= old_r and capacity <= old_cap and want_a <= old_a:
        return state
    r, cap = max(n_rows, old_r), max(capacity, old_cap)
    a = max(want_a, old_a)

    def pad(arr, fill, dtype):
        out = jnp.full((r, cap + 3), fill, dtype=dtype)
        return out.at[:old_r, :old_nodes].set(arr)

    def pad_lane(arr, fill, dtype):
        out = jnp.full((r, cap + 3, a), fill, dtype=dtype)
        return out.at[:old_r, :old_nodes, :old_a].set(arr)

    def pad_vec(arr, dtype):
        out = jnp.zeros((r,), dtype=dtype)
        return out.at[:old_r].set(arr)

    return SeqState(
        pad(state.elem_id, 0, jnp.int32),
        pad(state.nxt, END, jnp.int32),
        pad_lane(state.reg, 0, jnp.int32),
        pad_lane(state.killed, False, bool),
        pad_lane(state.val, 0, jnp.int32),
        pad_lane(state.counter, 0, jnp.int32),
        pad_vec(state.n, jnp.int32),
        pad_vec(state.inexact, bool))


class SeqOpBatch:
    """One batch of sequence ops, parallel columns [N, P].

    - kind   int32: PAD / INSERT / SET / DEL
    - ref    int32: INSERT → packed elemId to insert after (0 = head);
                    SET/DEL → packed elemId of the target element
    - packed int32: the op's own packed opId (INSERT: the new elemId)
    - value  int32: INSERT/SET payload
    - preds  int32 [N, P, SEQ_PRED_LANES]: packed opIds this op supersedes
      (0 = unused lane, negative = pred naming an actor unknown to the
      fleet). The device kills exactly these lanes in the target element's
      register; concurrent ops survive (multi-value / resurrection
      semantics, ref new.js:1204-1217).
    - kind INC increments a counter element: ref targets the element,
      value carries the delta, preds name the counter set op(s) — the
      Lamport-max pred is the attribution target (new.js:942-945).
    - flag   bool: host-detected inexactness for this row (pred-lane
      overflow, object elements in Text rows): applied unconditionally.
    """

    def __init__(self, kind, ref, packed, value, preds=None, flag=None):
        self.kind = kind
        self.ref = ref
        self.packed = packed
        self.value = value
        if preds is None:
            preds = np.zeros(np.asarray(kind).shape + (SEQ_PRED_LANES,),
                             dtype=np.int32)
        self.preds = preds
        self.flag = np.zeros(np.asarray(kind).shape, dtype=bool) \
            if flag is None else flag

    def tree_flatten(self):
        return ((self.kind, self.ref, self.packed, self.value, self.preds,
                 self.flag), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


register_pytrees(SeqState, SeqOpBatch)


def _apply_one_doc(carry, op, capacity, n_actor_slots):
    """One op against one doc.
    carry = (elem_id, nxt, reg, killed, val, counter, n, inexact)."""
    elem_id, nxt, reg, killed, val, counter, n, inexact = carry
    kind, ref, packed, value, preds, flag = op

    is_ins = kind == INSERT
    is_upd = (kind == SET) | (kind == DEL)
    is_inc = kind == INC

    # Referent / target node: packed elemIds are unique and non-zero, so an
    # equality one-hot over the node axis finds it (sentinel and scratch
    # entries keep elem_id 0). A miss (op referencing an elemId not in the
    # doc, e.g. one dropped by a capacity overflow) must not resolve to an
    # arbitrary slot.
    hits = elem_id == ref
    found = jnp.any(hits)
    match = jnp.argmax(hits).astype(jnp.int32)

    # ---- INSERT: RGA splice -------------------------------------------
    # Start after the referent (HEAD sentinel for ref==0), then skip any
    # following elements whose insertion opId is greater than ours — the
    # concurrent-insert rule (ref new.js:145-163; op_set.insert_rga).
    r0 = jnp.where(ref == HEAD_REF, jnp.int32(HEAD), match)
    # Non-insert ops must not walk: an impossible comparison key stalls the
    # loop immediately.
    my_key = jnp.where(is_ins, packed, INT32_MAX)

    def skip_cond(state):
        r, j, h = state
        # Sentinels/scratch hold elem_id 0, which can never exceed a real
        # packed opId, so the walk stops at END (or list end) by itself; the
        # hop counter is a termination backstop so a corrupted/cyclic nxt
        # chain cannot hang the device kernel (a well-formed list has at
        # most capacity+3 nodes).
        return (elem_id[j] > my_key) & (h < capacity + 3)

    def skip_body(state):
        r, j, h = state
        return j, nxt[j], h + 1

    r, j, _ = lax.while_loop(skip_cond, skip_body,
                             (r0, nxt[r0], jnp.int32(0)))

    # Inserts past capacity or after an unknown referent are dropped
    # (reported via the per-op applied flag) rather than silently corrupting
    # state: scratch and the sentinels must never be written by a live
    # insert, and a missed referent lookup must not splice after node 0.
    can_ins = is_ins & (n < capacity) & ((ref == HEAD_REF) | found)
    slot = SLOT0 + jnp.minimum(n, capacity - 1)  # allocation cursor, clamped
    ins_slot = jnp.where(can_ins, slot, jnp.int32(SCRATCH))
    ins_ptr_from = jnp.where(can_ins, r, jnp.int32(END))
    ins_ptr_new = jnp.where(can_ins, slot, jnp.int32(END))

    nxt = nxt.at[ins_ptr_new].set(jnp.where(can_ins, j, nxt[ins_ptr_new]))
    nxt = nxt.at[ins_ptr_from].set(jnp.where(can_ins, slot, nxt[ins_ptr_from]))
    # All masked writes preserve the scratch node's elem_id = 0 — the
    # invariant the one-hot referent match depends on. (Scratch's register
    # lanes absorb masked lane writes; their contents are never read.)
    elem_id = elem_id.at[ins_slot].set(jnp.where(can_ins, packed,
                                                 elem_id[ins_slot]))
    n = n + can_ins.astype(jnp.int32)

    # Own actor lane (the insert op IS the element's first set op; a SET
    # occupies its actor's lane the same way, ref registers.py design note)
    a = (packed & ACTOR_MASK).astype(jnp.int32)
    a_ok = a < n_actor_slots
    a_c = jnp.minimum(a, n_actor_slots - 1)

    ins_lane_tgt = jnp.where(can_ins & a_ok, slot, jnp.int32(SCRATCH))
    w_ins = can_ins & a_ok
    reg = reg.at[ins_lane_tgt, a_c].set(
        jnp.where(w_ins, packed, reg[ins_lane_tgt, a_c]))
    killed = killed.at[ins_lane_tgt, a_c].set(
        jnp.where(w_ins, False, killed[ins_lane_tgt, a_c]))
    val = val.at[ins_lane_tgt, a_c].set(
        jnp.where(w_ins, value, val[ins_lane_tgt, a_c]))
    counter = counter.at[ins_lane_tgt, a_c].set(
        jnp.where(w_ins, 0, counter[ins_lane_tgt, a_c]))

    # ---- SET / DEL / INC: exact multi-value register update -------------
    # ref == HEAD_REF (0) marks a malformed update (no target): it would
    # "match" every unallocated slot's zero elem_id, so reject it explicitly.
    upd_ok = is_upd & found & (ref != HEAD_REF)
    inc_ok = is_inc & found & (ref != HEAD_REF)
    tgt = jnp.where(upd_ok | inc_ok, match, jnp.int32(SCRATCH))
    reg_row = reg[tgt]          # [A]
    killed_row = killed[tgt]
    val_row = val[tgt]
    counter_row = counter[tgt]

    # Kill preds: each pred lane targets its actor's lane; the kill lands
    # only if that lane still holds exactly the pred'd op (a pred naming an
    # already-superseded op is a legitimate no-op succ entry, which the
    # reference also accepts). Concurrent ops are never killed — that is
    # the multi-value / resurrection rule (new.js:1204-1217).
    lane_oob = jnp.bool_(False)
    d_lanes = preds.shape[0]
    for d in range(d_lanes):
        p = preds[d]
        s = (p & ACTOR_MASK).astype(jnp.int32)
        s_ok = (s < n_actor_slots) & (p > 0)
        s_c = jnp.minimum(s, n_actor_slots - 1)
        lane_oob |= (upd_ok | inc_ok) & (p != 0) & ~s_ok
        hit = upd_ok & s_ok & (reg_row[s_c] == p)
        killed_row = killed_row.at[s_c].set(killed_row[s_c] | hit)

    # INC: counter attribution follows the reference (new.js:942-945):
    # the inc is consumed by its LAMPORT-MAX pred (even a dead one); it
    # accumulates into that lane iff the lane still holds the op live, and
    # every OTHER live pred'd lane hides forever (its counter state never
    # completes). Same rule as registers._apply_step.
    max_pred = jnp.int32(0)
    any_live_hit = jnp.bool_(False)
    for d in range(d_lanes):
        p = preds[d]
        s = (p & ACTOR_MASK).astype(jnp.int32)
        s_ok = (s < n_actor_slots) & (p > 0)
        s_c = jnp.minimum(s, n_actor_slots - 1)
        max_pred = jnp.where(is_inc & (p > 0),
                             jnp.maximum(max_pred, p), max_pred)
        any_live_hit |= inc_ok & s_ok & (reg_row[s_c] == p) & \
            ~killed_row[s_c]
    s_max = (max_pred & ACTOR_MASK).astype(jnp.int32)
    s_max_ok = (s_max < n_actor_slots) & (max_pred != 0)
    s_max_c = jnp.minimum(s_max, n_actor_slots - 1)
    max_live = inc_ok & s_max_ok & (reg_row[s_max_c] == max_pred) & \
        ~killed_row[s_max_c]
    # (sum << 2) | count-bits packing (bits 0 -> 1 -> 3, 3 = "two or
    # more", saturating) — see the SeqState docstring. The shifted add
    # leaves the count bits alone. The ingest-side guards bound each
    # DELTA to +/-2^29, but the accumulated SUM can still leave the
    # packed envelope (two +2^28 incs): flag the row inexact when it
    # does, mirroring the bulk loader's counter_over rule, so live-applied
    # and bulk-loaded replicas agree instead of wrapping silently.
    old_cnt = counter_row[s_max_c]
    new_sum = (old_cnt >> 2) + value
    bad_sum = max_live & (jnp.abs(new_sum) >= jnp.int32(1 << 29))
    stepped = (old_cnt & ~3) + (value << 2)
    stepped = stepped | jnp.where((old_cnt & 3) == 0, 1, 3)
    counter_row = counter_row.at[s_max_c].set(
        jnp.where(max_live, stepped, old_cnt))
    for d in range(d_lanes):
        p = preds[d]
        s = (p & ACTOR_MASK).astype(jnp.int32)
        s_ok = (s < n_actor_slots) & (p > 0)
        s_c = jnp.minimum(s, n_actor_slots - 1)
        lose = inc_ok & s_ok & (reg_row[s_c] == p) & ~killed_row[s_c] & \
            (p != max_pred)
        killed_row = killed_row.at[s_c].set(killed_row[s_c] | lose)
    bad_inc = inc_ok & ~any_live_hit & ~max_live

    # SET: occupy own actor lane. If the lane already holds a live op this
    # op did NOT pred, the reference would keep both visible — outside the
    # one-op-per-actor shape (only constructible by hand-built changes), so
    # flag the doc instead of losing data.
    is_set_live = upd_ok & (kind == SET)
    own_prev = reg_row[a_c]
    own_pred = jnp.bool_(False)
    for d in range(d_lanes):
        own_pred |= preds[d] == own_prev
    self_conflict = is_set_live & a_ok & (own_prev != 0) & \
        ~killed_row[a_c] & ~own_pred & (own_prev != packed)
    set_actor_oob = is_set_live & ~a_ok

    w_set = is_set_live & a_ok
    # Reclaiming a lane whose previous op consumed incs loses the dead
    # counter's phantom-remove patch trace (the reference's dangling inc
    # rows still emit edits for it): flag the row inexact instead
    reclaim_incd = w_set & ((counter_row[a_c] & 3) != 0)
    reg_row = reg_row.at[a_c].set(jnp.where(w_set, packed, reg_row[a_c]))
    killed_row = killed_row.at[a_c].set(
        jnp.where(w_set, False, killed_row[a_c]))
    val_row = val_row.at[a_c].set(jnp.where(w_set, value, val_row[a_c]))
    counter_row = counter_row.at[a_c].set(
        jnp.where(w_set, 0, counter_row[a_c]))

    reg = reg.at[tgt].set(reg_row)
    killed = killed.at[tgt].set(killed_row)
    val = val.at[tgt].set(val_row)
    counter = counter.at[tgt].set(counter_row)

    # Dropped ops (over-capacity or unknown-referent inserts, SET/DELs on
    # unknown targets) report as not-applied so callers can detect loss from
    # the stats instead of getting silent truncation.
    applied = jnp.where(is_ins, can_ins, jnp.where(is_inc, inc_ok, upd_ok))
    ins_actor_oob = can_ins & ~a_ok
    # Inexactness: host-flagged ops (pred overflow), any dropped live op,
    # actor numbers past the lane width, self conflicts, preds naming
    # unknown/out-of-range actors, and incs with no consumable target
    inexact = inexact | flag | self_conflict | lane_oob | set_actor_oob | \
        ins_actor_oob | bad_inc | bad_sum | reclaim_incd | \
        ((kind > PAD) & ~applied)
    return (elem_id, nxt, reg, killed, val, counter, n, inexact), applied


def _apply_seq_batch_impl(state, ops):
    capacity = state.elem_id.shape[1] - 3
    n_actor_slots = state.reg.shape[2]

    def per_doc(elem_id, nxt, reg, killed, val, counter, n, inexact,
                kind, ref, packed, value, preds, flag):
        carry = (elem_id, nxt, reg, killed, val, counter, n, inexact)
        xs = (kind, ref, packed, value, preds, flag)
        carry, applied = lax.scan(
            lambda c, x: _apply_one_doc(c, x, capacity, n_actor_slots),
            carry, xs)
        return carry, jnp.sum(applied, dtype=jnp.int32)

    carry, applied = jax.vmap(per_doc)(
        state.elem_id, state.nxt, state.reg, state.killed, state.val,
        state.counter, state.n, state.inexact, ops.kind, ops.ref,
        ops.packed, ops.value, ops.preds, ops.flag)
    return SeqState(*carry), jnp.sum(applied)


apply_seq_batch = instrument_kernel(
    'apply_seq_batch', jax.jit(_apply_seq_batch_impl))
# In-place variant for the fleet's own dispatch paths (see
# apply.apply_op_batch_donated)
apply_seq_batch_donated = instrument_kernel(
    'apply_seq_batch_donated',
    jax.jit(_apply_seq_batch_impl, donate_argnums=(0,)))


def _visible_impl(state):
    """Per-element visibility and Lamport winner from the registers:
    (vis [N, S+3] bool, winner [N, S+3] int32 packed, value [N, S+3],
    counter [N, S+3] — the winning lane's accumulated inc deltas)."""
    live = (state.reg != 0) & ~state.killed
    vis = jnp.any(live, axis=-1)
    masked = jnp.where(live, state.reg, -1)
    w = jnp.argmax(masked, axis=-1)
    winner = jnp.max(jnp.where(live, state.reg, 0), axis=-1)
    value = jnp.take_along_axis(state.val, w[..., None], axis=-1)[..., 0]
    cnt = jnp.take_along_axis(state.counter, w[..., None], axis=-1)[..., 0]
    return vis, winner, value, cnt


element_visibility = instrument_kernel(
    'element_visibility', jax.jit(_visible_impl))


def _linearize_impl(state):
    """List-rank every node: returns (pos [N, S+3], length [N]).

    pos is node-indexed (sentinels at 0..2, real slots from SLOT0=3, in
    op-arrival order): pos[d, SLOT0 + k] is the 0-based sequence index of
    doc d's k-th allocated slot; sentinel and unallocated entries are
    garbage — mask with SLOT0 <= node < SLOT0 + n.
    Pointer doubling (Wyllie's list ranking): dist[i] = hops from node i to
    END, accumulated over ceil(log2(nodes)) rounds of jumps. Then
    pos = dist[HEAD] - dist - 1.
    """
    nodes = state.nxt.shape[1]

    def per_doc(nxt):
        dist = jnp.ones((nodes,), dtype=jnp.int32).at[END].set(0)
        ptr = nxt.at[END].set(END)

        def round_(i, s):
            dist, ptr = s
            return dist + dist[ptr], ptr[ptr]

        steps = int(np.ceil(np.log2(nodes)))
        dist, ptr = lax.fori_loop(0, steps, round_, (dist, ptr))
        return dist[HEAD] - dist - 1

    pos = jax.vmap(per_doc)(state.nxt)
    return pos, state.n


linearize = instrument_kernel('linearize', jax.jit(_linearize_impl))


def _materialize_impl(state):
    """Return (vals [N, S], cnts [N, S], vis [N, S], length [N]) in
    sequence order.

    vals/cnts/vis are scattered into order positions; entries at index >=
    length are zeros. Visible-only extraction (for text strings / patch
    indexes) is a host-side compress over the vis mask. Values are the
    per-element Lamport winners over the visible register set (conflict
    sets render their winner, like the reference's applyProperties rule,
    frontend/apply_patch.js:57-79); cnts carry the winning lane's
    accumulated counter deltas (display value = val + cnt for counter
    payloads)."""
    capacity = state.elem_id.shape[1] - 3
    pos, n = _linearize_impl(state)
    e_vis, _winner, e_val, e_cnt = _visible_impl(state)

    def per_doc(pos, vis, val, cnt, n):
        node_ids = jnp.arange(capacity + 3, dtype=jnp.int32)
        alloc = (node_ids >= SLOT0) & (node_ids < SLOT0 + n)
        # Scatter into sequence order; masked lanes land on a trailing
        # scratch column that the [:capacity] slice drops
        tgt = jnp.where(alloc, jnp.clip(pos, 0, capacity), capacity)
        out_val = jnp.zeros((capacity + 1,), val.dtype).at[tgt].set(
            jnp.where(alloc, val, 0))
        out_cnt = jnp.zeros((capacity + 1,), cnt.dtype).at[tgt].set(
            jnp.where(alloc, cnt, 0))
        out_vis = jnp.zeros((capacity + 1,), jnp.bool_).at[tgt].set(
            jnp.where(alloc, vis, False))
        return out_val[:capacity], out_cnt[:capacity], out_vis[:capacity]

    vals, cnts, vis = jax.vmap(per_doc)(pos, e_vis, e_val, e_cnt, state.n)
    return vals, cnts, vis, state.n


materialize = instrument_kernel('materialize', jax.jit(_materialize_impl))


def visible_text(state):
    """Host helper: decode each doc's visible values as a Python string
    (values interpreted as Unicode code points)."""
    vals, _cnts, vis, n = jax.device_get(materialize(state))
    out = []
    for d in range(vals.shape[0]):
        row_vis = vis[d]
        out.append(''.join(chr(int(c)) for c in vals[d][row_vis]))
    return out


def element_conflicts(state, row):
    """Host read of one doc's per-element conflict sets: {packed elemId:
    {packed opId: value}} for every element whose visible register holds
    more than one op (the raw-engine view of what
    fleet.backend._FlatEngine._device_patch_diffs serves as patch edits)."""
    reg = np.asarray(jax.device_get(state.reg[row]))
    killed = np.asarray(jax.device_get(state.killed[row]))
    val = np.asarray(jax.device_get(state.val[row]))
    elem = np.asarray(jax.device_get(state.elem_id[row]))
    live = (reg != 0) & ~killed
    out = {}
    for node in np.flatnonzero(live.sum(axis=-1) > 1):
        lanes = np.flatnonzero(live[node])
        out[int(elem[node])] = {int(reg[node, s]): int(val[node, s])
                                for s in lanes}
    return out


class SeqEncoder:
    """Host-side helper turning 'ctr@actor' string ops into SeqOpBatch
    columns for one fleet. Actor numbers are assigned by ascending hex order
    over a fixed, pre-registered actor set (required for packed-opId
    comparisons to match host Lamport order). SET/DEL ops default their
    pred to the target elemId (the element's insert op) when none is given —
    the common shape for linear edit traces."""

    def __init__(self, actors):
        self.actor_num = {a: i for i, a in enumerate(sorted(actors))}

    def pack(self, op_id):
        if op_id in ('_head', None):
            return HEAD_REF
        ctr_s, _, actor = op_id.partition('@')
        return pack_op_id(int(ctr_s), self.actor_num[actor])

    def batch(self, per_doc_ops, pad_to=None):
        """per_doc_ops: list (per doc) of op dicts
        {kind: 'insert'|'set'|'del', ref/target: opId str, id: opId str,
         value: int, pred: [opId str, ...]}. Returns a SeqOpBatch of numpy
        columns [N, P]."""
        n_docs = len(per_doc_ops)
        width = max((len(ops) for ops in per_doc_ops), default=0)
        if pad_to is not None:
            width = max(width, pad_to)
        kind = np.zeros((n_docs, width), dtype=np.int32)
        ref = np.zeros((n_docs, width), dtype=np.int32)
        packed = np.zeros((n_docs, width), dtype=np.int32)
        value = np.zeros((n_docs, width), dtype=np.int32)
        preds = np.zeros((n_docs, width, SEQ_PRED_LANES), dtype=np.int32)
        flag = np.zeros((n_docs, width), dtype=bool)
        kinds = {'insert': INSERT, 'set': SET, 'del': DEL,
                 'inc': INC}
        for d, ops in enumerate(per_doc_ops):
            for i, op in enumerate(ops):
                kind[d, i] = kinds[op['kind']]
                target = op.get('ref') or op.get('target')
                ref[d, i] = self.pack(target)
                packed[d, i] = self.pack(op['id'])
                value[d, i] = op.get('value', 0)
                pred_ids = op.get('pred')
                if pred_ids is None and op['kind'] in ('set', 'del'):
                    pred_ids = [target]
                pred_ids = pred_ids or []
                if len(pred_ids) > SEQ_PRED_LANES:
                    flag[d, i] = True
                    pred_ids = pred_ids[:SEQ_PRED_LANES]
                for l, p in enumerate(pred_ids):
                    preds[d, i, l] = self.pack(p)
                if op.get('flag'):
                    flag[d, i] = True
        return SeqOpBatch(kind, ref, packed, value, preds, flag)


class SeqPools:
    """Size-class pools of sequence rows.

    A single SeqState is rectangular: one 10k-element document would force
    every row in the fleet to 10k slots × A actor lanes — the long-document
    analogue of padding a whole batch to its longest member. Pools bucket
    rows by pow2 capacity class (class c holds rows of capacity
    `base << c`), so memory follows each document's own length; a row that
    outgrows its class migrates up by a prefix copy (front-anchored
    sentinels make the tail padding inert, see the node-layout note above).
    The per-flush cost is one apply dispatch per ACTIVE class instead of
    one total — bounded by log2(longest/base) — which is the same
    size-class trick the sync driver uses for variable Bloom filter sizes
    (fleet/bloom.py).

    Addressing: callers hold (cls, idx) placements; this object owns the
    per-class SeqStates, free lists, and growth/migration. It is
    deliberately host-side bookkeeping — all device work stays in the
    SeqState kernels."""

    def __init__(self, base_capacity=64):
        self.base = base_capacity
        self.pools = {}     # cls -> SeqState
        self.free = {}      # cls -> [idx, ...]
        self.used = {}      # cls -> high-water row count
        self.grow_events = 0   # device-copy growths (reserve() keeps this
                               # at ~1 per class per dispatch, not per row)

    def cls_for(self, capacity):
        c = 0
        while (self.base << c) < capacity:
            c += 1
        return c

    def capacity(self, cls):
        return self.base << cls

    def state(self, cls):
        return self.pools.get(cls)

    def _ensure(self, cls, n_rows, actor_slots):
        import jax.numpy as jnp
        pow2 = 1
        while pow2 < n_rows:
            pow2 *= 2
        st = self.pools.get(cls)
        if st is None:
            self.pools[cls] = SeqState.empty(
                pow2, self.capacity(cls), actor_slots=actor_slots, xp=jnp)
            self.grow_events += 1
        else:
            grown = grow_seq_state(st, pow2, self.capacity(cls),
                                   actor_slots)
            if grown is not st:
                self.grow_events += 1
            self.pools[cls] = grown
        return self.pools[cls]

    def ensure_lanes(self, actor_slots):
        """Grow every pool's actor-lane axis (before a lane permutation)."""
        for cls in list(self.pools):
            grown = grow_seq_state(self.pools[cls], 0, 0, actor_slots)
            if grown is not self.pools[cls]:
                self.grow_events += 1
            self.pools[cls] = grown

    def alloc(self, cls, actor_slots):
        free = self.free.setdefault(cls, [])
        if free:
            # a pool built under a narrower actor table must still widen
            # its lane axis before the recycled row is written
            self._ensure(cls, self.used.get(cls, 1), actor_slots)
            return free.pop()
        idx = self.used.get(cls, 0)
        self.used[cls] = idx + 1
        self._ensure(cls, idx + 1, actor_slots)
        return idx

    def reserve(self, cls, count, actor_slots):
        """Pre-size a pool for `count` upcoming alloc() calls in one
        growth: growing inside each alloc re-pads the whole pool's arrays
        eagerly on device per pow2 step (~log2(rows) growths of 8 arrays
        each for a batch of fresh rows — a dispatch storm on a real TPU).
        Reservation is capacity-only; alloc() still does the bookkeeping,
        it just finds the pool already big enough."""
        fresh = count - len(self.free.get(cls, ()))
        if fresh > 0:
            self._ensure(cls, self.used.get(cls, 0) + fresh, actor_slots)

    def release(self, cls, idx):
        """Zero a row and return it to its class's free list."""
        self.release_rows({cls: [idx]})

    def release_rows(self, by_cls):
        """Zero rows and return them to their free lists; one batched
        indexed update per touched class ({cls: [idx, ...]})."""
        import jax.numpy as jnp
        for cls, idxs in by_cls.items():
            st = self.pools.get(cls)
            live = [i for i in idxs if st is not None and
                    i < st.elem_id.shape[0]]
            if live:
                i = jnp.asarray(np.array(live, dtype=np.int32))
                self.pools[cls] = SeqState(
                    st.elem_id.at[i].set(0),
                    st.nxt.at[i].set(END),
                    st.reg.at[i].set(0),
                    st.killed.at[i].set(False),
                    st.val.at[i].set(0),
                    st.counter.at[i].set(0),
                    st.n.at[i].set(0),
                    st.inexact.at[i].set(False))
            self.free.setdefault(cls, []).extend(idxs)

    def copy_row(self, src, dst):
        """Copy row (cls, idx) -> (cls2, idx2); dst class must be >= src
        (prefix copy; END-filled tail stays inert)."""
        self.copy_rows(src[0], [src[1]], dst[0], [dst[1]])

    def copy_rows(self, src_cls, src_idxs, dst_cls, dst_idxs):
        """Batched row copies between two classes (dst capacity >= src);
        one indexed gather/scatter per array."""
        import jax.numpy as jnp
        width = max(self.pools[src_cls].reg.shape[2],
                    self.pools[dst_cls].reg.shape[2])
        if self.pools[src_cls].reg.shape[2] != \
                self.pools[dst_cls].reg.shape[2]:
            self.ensure_lanes(width)
        s = self.pools[src_cls]
        d = self.pools[dst_cls]
        nodes = s.elem_id.shape[1]
        si = jnp.asarray(np.array(src_idxs, dtype=np.int32))
        di = jnp.asarray(np.array(dst_idxs, dtype=np.int32))

        def put(darr, sarr):
            if darr.ndim == 2:
                return darr.at[di, :nodes].set(sarr[si])
            return darr.at[di, :nodes, :].set(sarr[si])

        self.pools[dst_cls] = SeqState(
            put(d.elem_id, s.elem_id), put(d.nxt, s.nxt),
            put(d.reg, s.reg), put(d.killed, s.killed), put(d.val, s.val),
            put(d.counter, s.counter),
            d.n.at[di].set(s.n[si]),
            d.inexact.at[di].set(s.inexact[si]))

    def migrate(self, cls, idx, new_cls, actor_slots):
        """Move a row to a bigger class; returns its new idx."""
        new_idx = self.alloc(new_cls, actor_slots)
        self.copy_row((cls, idx), (new_cls, new_idx))
        self.release(cls, idx)
        return new_idx
