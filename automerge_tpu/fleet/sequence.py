"""Batched RGA sequence engine: list/text CRDTs as device tensors.

This is the tensorized equivalent of the reference's list-insertion path
(ref backend/new.js:50-192 seekWithinBlock, :145-163 concurrent-insert skip;
host mirror: automerge_tpu/backend/op_set.py ObjState.insert_rga): a fleet of
N sequence documents (one Text or list object each) lives as padded [N, S]
slot tensors plus a linked-list `nxt` pointer array encoding RGA order. Slots
are allocated in op-arrival order and never move; an insert splices pointers,
so per-op work is O(S) vector compares (the referent lookup) + an O(skip)
pointer walk, with NO data movement of the sequence itself — the analogue of
the reference editing a block in place instead of reshuffling the array.

Application is a `vmap` over docs of a `lax.scan` over each doc's op stream:
ops within one doc apply in causal order (as the reference's per-change op
loop does), while the fleet axis is embarrassingly parallel — the SURVEY §7
"vmap'd masked scan" formulation. Extraction back to sequence order
(`linearize`) is pointer-doubling list ranking: O(log S) rounds of gathers,
fully parallel, replacing the reference's visibleCount block walk
(new.js:225-240).

Packed opIds: (counter << ACTOR_BITS) | actorNum, as in tensor_doc. For the
integer comparisons here to agree with the host engine's Lamport order
(counter, actorId-hex-string) — used both for the RGA concurrent-insert skip
and per-element LWW — actor numbers MUST be assigned in ascending
lexicographic order of the actor hex ids (the reference's columnar format
sorts its actor table the same way, ref backend/columnar.js:133-170).

Semantics note: per-element overwrite resolution here is greatest-opId LWW,
which matches the host engine for causally-ordered edits; concurrent
set-vs-delete multi-value conflict shapes route through the host OpSet engine
(same caveat as the map engine, see tensor_doc.py).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .tensor_doc import ACTOR_BITS, pack_op_id, register_pytrees

# Op kinds in a SeqOpBatch
PAD, INSERT, SET, DEL = 0, 1, 2, 3

HEAD_REF = 0  # `ref == 0` means insert at the head ('_head' in the reference)

INT32_MAX = np.int32(2**31 - 1)


# Node-id layout, front-anchored so every per-node array shares one shape
# [N, capacity + 3] and capacity can grow (or pad for sharding) by appending
# at the tail without moving the sentinels:
#
#   0        HEAD sentinel (its nxt is the first element)
#   1        END sentinel / pointer-scratch (masked pointer writes land here;
#            its outgoing pointer is never followed)
#   2        slot-scratch (masked writes of per-slot arrays land here)
#   3..S+2   real slots, allocated in op-arrival order
HEAD, END, SCRATCH, SLOT0 = 0, 1, 2, 3


class SeqState:
    """Pytree of per-doc sequence tensors: five [N, S+3] per-node arrays
    (shared node-id indexing, sentinels at the front) + [N] allocation
    cursors + [N] inexact flags (device state diverged from reference
    semantics — concurrent set-vs-delete, counters, unknown referents — so
    reads must come from the host mirror, cf. registers.RegisterState)."""

    def __init__(self, elem_id, nxt, winner, vis, val, n, inexact=None):
        self.elem_id = elem_id  # packed elemId per slot (0 = unallocated)
        self.nxt = nxt          # linked-list next pointers over node ids
        self.winner = winner    # packed opId of the LWW winner op per element
        self.vis = vis          # element visible (winner is not a delete)
        self.val = val          # winner's value (char code / value-table idx)
        self.n = n              # slots allocated per doc
        if inexact is None:
            # .shape is static even on tracers, so this default is jit-safe
            inexact = np.zeros((n.shape[0],), dtype=bool)
        self.inexact = inexact  # row needs the host mirror for reads

    @property
    def capacity(self):
        return self.elem_id.shape[1] - 3

    @classmethod
    def empty(cls, n_docs, capacity, xp=np):
        nodes = (n_docs, capacity + 3)
        nxt = xp.full(nodes, END, dtype=np.int32)
        return cls(
            xp.zeros(nodes, dtype=np.int32),
            nxt,
            xp.zeros(nodes, dtype=np.int32),
            xp.zeros(nodes, dtype=bool),
            xp.zeros(nodes, dtype=np.int32),
            xp.zeros((n_docs,), dtype=np.int32),
            xp.zeros((n_docs,), dtype=bool))

    def tree_flatten(self):
        return ((self.elem_id, self.nxt, self.winner, self.vis, self.val,
                 self.n, self.inexact), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def grow_seq_state(state, n_rows, capacity):
    """Host-side resize to at least (n_rows rows, capacity slots): new rows
    and tail slots are zeroed/END-filled; existing node ids never move (the
    sentinels are front-anchored precisely so capacity can grow by appending
    at the tail). Returns `state` unchanged if already big enough."""
    old_r, old_nodes = state.elem_id.shape
    old_cap = old_nodes - 3
    if n_rows <= old_r and capacity <= old_cap:
        return state
    r, cap = max(n_rows, old_r), max(capacity, old_cap)

    def pad(arr, fill, dtype):
        out = jnp.full((r, cap + 3), fill, dtype=dtype)
        return out.at[:old_r, :old_nodes].set(arr)

    def pad_vec(arr, dtype):
        out = jnp.zeros((r,), dtype=dtype)
        return out.at[:old_r].set(arr)

    return SeqState(
        pad(state.elem_id, 0, jnp.int32),
        pad(state.nxt, END, jnp.int32),
        pad(state.winner, 0, jnp.int32),
        pad(state.vis, False, bool),
        pad(state.val, 0, jnp.int32),
        pad_vec(state.n, jnp.int32),
        pad_vec(state.inexact, bool))


class SeqOpBatch:
    """One batch of sequence ops, parallel columns [N, P].

    - kind   int32: PAD / INSERT / SET / DEL
    - ref    int32: INSERT → packed elemId to insert after (0 = head);
                    SET/DEL → packed elemId of the target element
    - packed int32: the op's own packed opId (INSERT: the new elemId)
    - value  int32: INSERT/SET payload
    - pred   int32: SET/DEL → greatest packed pred opId (0 = none). The
      device compares it against the element's current winner: a mismatch
      means the op was concurrent with another overwrite — the one shape
      where LWW diverges from the reference's multi-value/resurrection
      semantics — and flags the row inexact.
    - flag   bool: host-detected inexactness for this row (counter ops in
      sequences, pred overflow): applied unconditionally.
    """

    def __init__(self, kind, ref, packed, value, pred=None, flag=None):
        self.kind = kind
        self.ref = ref
        self.packed = packed
        self.value = value
        self.pred = np.zeros_like(np.asarray(kind)) if pred is None else pred
        self.flag = np.zeros(np.asarray(kind).shape, dtype=bool) \
            if flag is None else flag

    def tree_flatten(self):
        return ((self.kind, self.ref, self.packed, self.value, self.pred,
                 self.flag), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


register_pytrees(SeqState, SeqOpBatch)


def _apply_one_doc(carry, op, capacity):
    """One op against one doc.
    carry = (elem_id, nxt, winner, vis, val, n, inexact)."""
    elem_id, nxt, winner, vis, val, n, inexact = carry
    kind, ref, packed, value, pred, flag = op

    is_ins = kind == INSERT
    is_upd = (kind == SET) | (kind == DEL)

    # Referent / target node: packed elemIds are unique and non-zero, so an
    # equality one-hot over the node axis finds it (sentinel and scratch
    # entries keep elem_id 0). A miss (op referencing an elemId not in the
    # doc, e.g. one dropped by a capacity overflow) must not resolve to an
    # arbitrary slot.
    hits = elem_id == ref
    found = jnp.any(hits)
    match = jnp.argmax(hits).astype(jnp.int32)

    # ---- INSERT: RGA splice -------------------------------------------
    # Start after the referent (HEAD sentinel for ref==0), then skip any
    # following elements whose insertion opId is greater than ours — the
    # concurrent-insert rule (ref new.js:145-163; op_set.insert_rga).
    r0 = jnp.where(ref == HEAD_REF, jnp.int32(HEAD), match)
    # Non-insert ops must not walk: an impossible comparison key stalls the
    # loop immediately.
    my_key = jnp.where(is_ins, packed, INT32_MAX)

    def skip_cond(state):
        r, j, h = state
        # Sentinels/scratch hold elem_id 0, which can never exceed a real
        # packed opId, so the walk stops at END (or list end) by itself; the
        # hop counter is a termination backstop so a corrupted/cyclic nxt
        # chain cannot hang the device kernel (a well-formed list has at
        # most capacity+3 nodes).
        return (elem_id[j] > my_key) & (h < capacity + 3)

    def skip_body(state):
        r, j, h = state
        return j, nxt[j], h + 1

    r, j, _ = lax.while_loop(skip_cond, skip_body,
                             (r0, nxt[r0], jnp.int32(0)))

    # Inserts past capacity or after an unknown referent are dropped
    # (reported via the per-op applied flag) rather than silently corrupting
    # state: scratch and the sentinels must never be written by a live
    # insert, and a missed referent lookup must not splice after node 0.
    can_ins = is_ins & (n < capacity) & ((ref == HEAD_REF) | found)
    slot = SLOT0 + jnp.minimum(n, capacity - 1)  # allocation cursor, clamped
    ins_slot = jnp.where(can_ins, slot, jnp.int32(SCRATCH))
    ins_ptr_from = jnp.where(can_ins, r, jnp.int32(END))
    ins_ptr_new = jnp.where(can_ins, slot, jnp.int32(END))

    nxt = nxt.at[ins_ptr_new].set(jnp.where(can_ins, j, nxt[ins_ptr_new]))
    nxt = nxt.at[ins_ptr_from].set(jnp.where(can_ins, slot, nxt[ins_ptr_from]))
    # All four masked writes preserve the scratch node's contents so that
    # elem_id[SCRATCH] stays 0 — the invariant the one-hot referent match
    # depends on.
    elem_id = elem_id.at[ins_slot].set(jnp.where(can_ins, packed,
                                                 elem_id[ins_slot]))
    winner = winner.at[ins_slot].set(jnp.where(can_ins, packed,
                                               winner[ins_slot]))
    vis = vis.at[ins_slot].set(jnp.where(can_ins, True, vis[ins_slot]))
    val = val.at[ins_slot].set(jnp.where(can_ins, value, val[ins_slot]))
    n = n + can_ins.astype(jnp.int32)

    # ---- SET / DEL: per-element LWW ------------------------------------
    # ref == HEAD_REF (0) marks a malformed update (no target): it would
    # "match" every unallocated slot's zero elem_id, so reject it explicitly.
    # The concurrency check must read the PRE-update winner: an op whose
    # pred is not the op it actually supersedes was concurrent with another
    # overwrite — the shape where LWW diverges from the reference's
    # multi-value / set-vs-delete-resurrection semantics (new.js:1204-1217).
    concurrent = is_upd & found & (ref != HEAD_REF) & (pred != winner[match])
    lww = is_upd & found & (ref != HEAD_REF) & (packed > winner[match])
    upd_slot = jnp.where(lww, match, jnp.int32(SCRATCH))
    winner = winner.at[upd_slot].set(jnp.where(lww, packed, winner[upd_slot]))
    vis = vis.at[upd_slot].set(jnp.where(lww, kind == SET, vis[upd_slot]))
    val = val.at[upd_slot].set(jnp.where(lww & (kind == SET), value,
                                         val[upd_slot]))

    # Dropped ops (over-capacity or unknown-referent inserts, SET/DELs on
    # unknown targets) report as not-applied so callers can detect loss from
    # the stats instead of getting silent truncation.
    applied = jnp.where(is_ins, can_ins,
                        (kind > PAD) & found & (ref != HEAD_REF))
    # Inexactness: host-flagged ops (counters, pred overflow), any dropped
    # live op, and concurrent overwrites (computed above, pre-update)
    inexact = inexact | flag | concurrent | ((kind > PAD) & ~applied)
    return (elem_id, nxt, winner, vis, val, n, inexact), applied


def _apply_seq_batch_impl(state, ops):
    capacity = state.elem_id.shape[1] - 3

    def per_doc(elem_id, nxt, winner, vis, val, n, inexact,
                kind, ref, packed, value, pred, flag):
        carry = (elem_id, nxt, winner, vis, val, n, inexact)
        xs = (kind, ref, packed, value, pred, flag)
        carry, applied = lax.scan(
            lambda c, x: _apply_one_doc(c, x, capacity), carry, xs)
        return carry, jnp.sum(applied, dtype=jnp.int32)

    carry, applied = jax.vmap(per_doc)(
        state.elem_id, state.nxt, state.winner, state.vis, state.val, state.n,
        state.inexact, ops.kind, ops.ref, ops.packed, ops.value, ops.pred,
        ops.flag)
    return SeqState(*carry), jnp.sum(applied)


apply_seq_batch = jax.jit(_apply_seq_batch_impl)


def _linearize_impl(state):
    """List-rank every node: returns (pos [N, S+3], length [N]).

    pos is node-indexed (sentinels at 0..2, real slots from SLOT0=3, in
    op-arrival order): pos[d, SLOT0 + k] is the 0-based sequence index of
    doc d's k-th allocated slot; sentinel and unallocated entries are
    garbage — mask with SLOT0 <= node < SLOT0 + n.
    Pointer doubling (Wyllie's list ranking): dist[i] = hops from node i to
    END, accumulated over ceil(log2(nodes)) rounds of jumps. Then
    pos = dist[HEAD] - dist - 1.
    """
    nodes = state.nxt.shape[1]

    def per_doc(nxt):
        dist = jnp.ones((nodes,), dtype=jnp.int32).at[END].set(0)
        ptr = nxt.at[END].set(END)

        def round_(i, s):
            dist, ptr = s
            return dist + dist[ptr], ptr[ptr]

        steps = int(np.ceil(np.log2(nodes)))
        dist, ptr = lax.fori_loop(0, steps, round_, (dist, ptr))
        return dist[HEAD] - dist - 1

    pos = jax.vmap(per_doc)(state.nxt)
    return pos, state.n


linearize = jax.jit(_linearize_impl)


def _materialize_impl(state):
    """Return (vals [N, S], vis [N, S], length [N]) in sequence order.

    vals/vis are scattered into order positions; entries at index >= length
    are zeros. Visible-only extraction (for text strings / patch indexes) is
    a host-side compress over the vis mask.
    """
    capacity = state.elem_id.shape[1] - 3
    pos, n = _linearize_impl(state)

    def per_doc(pos, vis, val, n):
        node_ids = jnp.arange(capacity + 3, dtype=jnp.int32)
        alloc = (node_ids >= SLOT0) & (node_ids < SLOT0 + n)
        # Scatter into sequence order; masked lanes land on a trailing
        # scratch column that the [:capacity] slice drops
        tgt = jnp.where(alloc, jnp.clip(pos, 0, capacity), capacity)
        out_val = jnp.zeros((capacity + 1,), val.dtype).at[tgt].set(
            jnp.where(alloc, val, 0))
        out_vis = jnp.zeros((capacity + 1,), jnp.bool_).at[tgt].set(
            jnp.where(alloc, vis, False))
        return out_val[:capacity], out_vis[:capacity]

    vals, vis = jax.vmap(per_doc)(pos, state.vis, state.val, state.n)
    return vals, vis, state.n


materialize = jax.jit(_materialize_impl)


def visible_text(state):
    """Host helper: decode each doc's visible values as a Python string
    (values interpreted as Unicode code points)."""
    vals, vis, n = jax.device_get(materialize(state))
    out = []
    for d in range(vals.shape[0]):
        row_vis = vis[d]
        out.append(''.join(chr(int(c)) for c in vals[d][row_vis]))
    return out


class SeqEncoder:
    """Host-side helper turning 'ctr@actor' string ops into SeqOpBatch
    columns for one fleet. Actor numbers are assigned by ascending hex order
    over a fixed, pre-registered actor set (required for packed-opId
    comparisons to match host Lamport order)."""

    def __init__(self, actors):
        self.actor_num = {a: i for i, a in enumerate(sorted(actors))}

    def pack(self, op_id):
        if op_id in ('_head', None):
            return HEAD_REF
        ctr_s, _, actor = op_id.partition('@')
        return pack_op_id(int(ctr_s), self.actor_num[actor])

    def batch(self, per_doc_ops, pad_to=None):
        """per_doc_ops: list (per doc) of op dicts
        {kind: 'insert'|'set'|'del', ref/target: opId str, id: opId str,
         value: int}. Returns a SeqOpBatch of numpy columns [N, P]."""
        n_docs = len(per_doc_ops)
        width = max((len(ops) for ops in per_doc_ops), default=0)
        if pad_to is not None:
            width = max(width, pad_to)
        kind = np.zeros((n_docs, width), dtype=np.int32)
        ref = np.zeros((n_docs, width), dtype=np.int32)
        packed = np.zeros((n_docs, width), dtype=np.int32)
        value = np.zeros((n_docs, width), dtype=np.int32)
        pred = np.zeros((n_docs, width), dtype=np.int32)
        flag = np.zeros((n_docs, width), dtype=bool)
        kinds = {'insert': INSERT, 'set': SET, 'del': DEL}
        for d, ops in enumerate(per_doc_ops):
            for i, op in enumerate(ops):
                kind[d, i] = kinds[op['kind']]
                ref[d, i] = self.pack(op.get('ref') or op.get('target'))
                packed[d, i] = self.pack(op['id'])
                value[d, i] = op.get('value', 0)
                preds = op.get('pred') or []
                if preds:
                    pred[d, i] = max(self.pack(p) for p in preds)
                flag[d, i] = bool(op.get('flag'))
        return SeqOpBatch(kind, ref, packed, value, pred, flag)
