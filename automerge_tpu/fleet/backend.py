"""The device-routed backend: Automerge's Backend contract over the TPU fleet.

This is the `setDefaultBackend` drop-in (ref src/automerge.js:147-149,
test/wasm.js:24-25): documents created through this module keep their bulk
CRDT state — per-key LWW winners, values, counter accumulators — in the
shared device fleet (automerge_tpu.fleet.tensor_doc.FleetState), where change
application is a batched scatter-max/scatter-add dispatch over every document
at once. The host keeps only what is inherently host work:

- the hash graph + causal gate (HashGraph — same machinery as the host OpSet,
  ref new.js:1550-1597),
- a per-document *mirror* of visible ops per key, from which exact reference
  patches (conflict sets, counter accumulation, ref new.js:884-1040) are
  produced without touching the device,
- wire encode/decode.

Map trees (nested maps/tables, keyed by two-level (objectId, key) interned
grid columns), sequence objects (Text/lists, as device RGA rows), and
objects nested inside sequences (rows-in-lists: the element value links to
the child object, which interns like any registered object) all stay
fleet-resident. Documents whose changes leave that subset (packed-counter
overflow on sequence paths, oversized actor populations) transparently
*promote*: their change log replays into the host OpSet engine and every
later call delegates to it, so the full reference semantics are always
available — the fleet path is an accelerator, never a semantic fork.
`link` ops reject loudly in the pre-scan (see PARITY.md).

Scale notes: one fleet packs up to 256 actors (tensor_doc.ACTOR_BITS); actor
numbers are kept in actor-hex sort order so the device's packed-opId
scatter-max resolves Lamport ties identically to the reference's
lamportCompare (frontend/apply_patch.js:33-42) — when a new actor lands
between existing ones, the fleet renumbers by remapping the low bits of the
winners tensor in one dispatch.
"""

import contextlib
import copy
import gc
import hashlib
import queue
import threading
import time
import weakref

import numpy as np

from .. import native
from ..backend.hash_graph import HashGraph, decode_change_buffers
from ..errors import (AutomergeError, DanglingPred, DocError, DuplicateOpId,
                      InvalidChange, MalformedChange, as_wire_error)
from ..observability import (Counters, Metrics, register_health_source,
                             register_mem_source)
from ..observability import hist as _hist
from ..observability import recorder as _flight
from ..observability.spans import (span as _span, span_seq as _span_seq,
                                   spanned as _spanned)

# live fleets for the memory-watermark tier (see _fleet_bytes below,
# which must stay below this line since DocFleet.__init__ registers
# here); a WeakSet so an abandoned fleet leaves the gauge with the fleet
_live_fleets = weakref.WeakSet()
from ..backend.op_set import OpSet
from ..columnar import decode_change, OBJECT_TYPE
from .tensor_doc import (ACTOR_BITS, CTR_LIMIT, FleetState, MAX_ACTORS,
                         TOMBSTONE, pack_op_id)
from .ingest import KeyInterner

_FLAT_ACTIONS = ('set', 'del', 'inc')
_SEQ_MAKE = ('makeText', 'makeList')

# Turbo commits park their log appends as lazily-folded _SeamSegs; past
# this many outstanding records the fleet folds everything (bounds the
# rowmap overhead on write-only workloads that never read history).
_SEAM_FOLD_LIMIT = 64



class _Unsupported(Exception):
    """An op outside the fleet-resident subset: promote to the host engine."""


class _SeqLink:
    """Value-table entry marking a root-map key whose value is a sequence
    object (Text/list) living in the fleet's SeqState rows. Bulk reads
    resolve it to the rendered sequence; the host mirror remains the exact
    source for patches."""

    __slots__ = ('object_id',)

    def __init__(self, object_id):
        self.object_id = object_id

    def __repr__(self):
        return f'_SeqLink({self.object_id})'

    def __eq__(self, other):
        return isinstance(other, _SeqLink) and \
            other.object_id == self.object_id

    def __hash__(self):
        return hash(('_SeqLink', self.object_id))


_MAP_MAKE = ('makeMap', 'makeTable')

# Deferred host-winner-mirror backlog cap (rows) before a forced fold; see
# DocFleet._pending_winner_rows
_WINNER_FOLD_LIMIT = 1 << 20


class _ValueTable(list):
    """Boxed-value store with dedup interning: the table grows with the
    number of DISTINCT values, not with op count (repeated strings across a
    long change log were an unbounded leak). Unhashable payloads append
    without dedup."""

    def __init__(self):
        super().__init__()
        self.index = {}

    def intern(self, value):
        # Key by (type, value): Python equality conflates True/1/1.0 etc.,
        # and a boxed 1.0 must not read back as an earlier doc's True
        key = (type(value), value)
        try:
            idx = self.index.get(key)
            hashable = True
        except TypeError:
            idx = None
            hashable = False
        if idx is not None:
            return idx
        idx = len(self)
        self.append(value)
        if hashable:
            self.index[key] = idx
        return idx


class _MapLink:
    """Value-table entry marking a key whose value is a nested map/table
    object. The nested object's own keys live in the same [docs, keys] grid
    under composite (objectId, key) interned columns (the two-level
    interning of the reference's objectMeta ancestry, ref new.js:1461-1528),
    so map trees stay fleet-resident."""

    __slots__ = ('object_id', 'kind')

    def __init__(self, object_id, kind='map'):
        self.object_id = object_id
        self.kind = kind

    def __repr__(self):
        return f'_MapLink({self.object_id}, {self.kind})'

    def __eq__(self, other):
        return isinstance(other, _MapLink) and \
            other.object_id == self.object_id and other.kind == self.kind

    def __hash__(self):
        return hash(('_MapLink', self.object_id, self.kind))


def _leaf_value(leaf):
    """Render a whole-doc patch leaf to a plain Python value: value leaves
    unwrap; list/text object patches replay their edits (whole-doc patches
    contain only insert/multi-insert/update/remove shapes); map patches
    resolve per-key Lamport winners."""
    if not isinstance(leaf, dict):
        return leaf
    if leaf.get('type') == 'value':
        return leaf.get('value')
    if 'objectId' not in leaf:
        return leaf
    if leaf.get('type') in ('list', 'text'):
        out = []
        for edit in leaf.get('edits', []):
            action = edit['action']
            if action == 'insert':
                out.insert(edit['index'], _leaf_value(edit['value']))
            elif action == 'multi-insert':
                out[edit['index']:edit['index']] = list(edit['values'])
            elif action == 'update':
                out[edit['index']] = _leaf_value(edit['value'])
            elif action == 'remove':
                del out[edit['index']:edit['index'] + edit.get('count', 1)]
        if leaf['type'] == 'text':
            return ''.join(str(v) for v in out)
        return out
    from ..common import lamport_key
    doc = {}
    for key, candidates in leaf.get('props', {}).items():
        if candidates:
            winner = max(candidates.keys(), key=lamport_key)
            doc[key] = _leaf_value(candidates[winner])
    return doc


class _SortedActorTable:
    """Actor interning that keeps numbers equal to the actor-hex sort rank,
    so packed opIds order exactly like the reference's Lamport comparison.
    Inserting an actor that sorts before existing ones renumbers; the caller
    applies the returned permutation to any device state."""

    def __init__(self):
        self.actors = []          # sorted actor hex strings
        self.index = {}           # actor -> current number

    def __len__(self):
        return len(self.actors)

    def intern(self, actor):
        num = self.index.get(actor)
        if num is None:
            raise KeyError(f'actor {actor} not pre-registered with the fleet')
        return num

    def insert_many(self, new_actors):
        """Insert actors; returns an old->new permutation array if existing
        numbers changed, else None."""
        fresh = sorted(set(a for a in new_actors if a not in self.index))
        if not fresh:
            return None
        if len(self.actors) + len(fresh) > MAX_ACTORS:
            raise ValueError(
                f'fleet actor table overflow (> {MAX_ACTORS} actors); '
                f'use separate fleets or the host backend')
        old_order = list(self.actors)
        self.actors = sorted(self.actors + fresh)
        self.index = {a: i for i, a in enumerate(self.actors)}
        if not old_order:
            return None
        perm = np.array([self.index[a] for a in old_order], dtype=np.int32)
        if np.array_equal(perm, np.arange(len(old_order), dtype=np.int32)):
            return None
        return perm


def _pow2(n):
    cap = 1
    while cap < n:
        cap *= 2
    return cap


class DocFleet:
    """The shared device state for a fleet of flat documents.

    Capacity (doc slots, key-grid width) grows in powers of two so XLA
    recompiles O(log n) times as the fleet grows. Change buffers enqueue per
    slot and land on the device in one batched ingest + one merge dispatch
    per flush (lazy: reads flush first)."""

    def __init__(self, doc_capacity=64, key_capacity=64,
                 exact_device=False, actor_slot_capacity=8, d_preds=4,
                 mesh=None):
        # Optional jax.sharding.Mesh with a 'docs' axis: the fleet's
        # grid/register state and every merge batch shard data-parallel
        # over the docs axis, so the turbo/exact merge dispatches run SPMD
        # across the mesh (SURVEY.md §2.12 — documents are independent, the
        # batch axis is the dp axis). Sequence pools stay device-local: the
        # RGA pointer walk is a per-document scan and their row axis is not
        # slot-aligned. mesh=None (default) keeps everything single-device.
        self.mesh = mesh
        self.keys = KeyInterner()
        self.actors = _SortedActorTable()
        self.value_table = _ValueTable()   # non-inline values, -(i + 2) refs
        # Packed-opId counter rebasing (round-2 VERDICT item 9): the int32
        # packing holds counters < 2^23, but a slot's counters may grow
        # without bound. ctr_base[slot] is subtracted before packing; when
        # a slot's window fills, _rebase_slot shifts its live winners down
        # in one device op. Slots whose LIVE counter spread exceeds the
        # window (or that receive sub-window stragglers after a rebase)
        # land in grid_overflow: their grid rows stop being authoritative
        # and bulk reads fall back to the host mirror.
        self.ctr_base = {}        # slot -> int counter base (default 0)
        self.grid_overflow = set()
        self.state = None         # FleetState, allocated on first flush
        # Host mirror of the grid's scatter-max winners (LWW mode only,
        # same packing basis per path). The device counter cell cannot
        # attribute an inc to its pred (apply.py's documented corner: an
        # inc whose pred lost the key is credited to the winner), so every
        # flush checks each inc's pred against the post-batch winner here
        # and flags mismatching slots into grid_overflow — reads for those
        # slots fall back to the exact host mirror instead of serving the
        # over-counted cell. Exact-device mode needs none of this (the
        # register engine applies pred kills exactly).
        self.host_winners = None  # np.int32 [doc_cap, key_cap + 1]
        # Slots whose history contains any delete: bulk reads route to
        # the exact host mirror. The single-winner grid cannot resurrect
        # a concurrent LOSER it never stored, and per-cell visible-op
        # accounting is unsound under shared preds (two concurrent ops
        # may pred the same target) and same-batch supersession chains —
        # so ANY kill lane flags its slot here, bluntly and soundly.
        # Unlike grid_overflow this does NOT block the turbo apply path:
        # packing stays trustworthy, only reads fall back.
        self.del_fallback = set()
        # Per-slot index of every map-key op row ever applied, as sorted
        # int64 combos (key_id << 32) | packed — the turbo path's
        # dangling-pred oracle (ref op_set.py: a pred must name a non-del
        # row on its key; ref new.js rejects invalid op references during
        # the merge). Fed by every ingest path; slots whose ops landed
        # without indexing (bulk document loads) are marked incomplete
        # and skip validation rather than risk a false reject — their
        # dangling preds surface at the next mirror rebuild as before.
        # ~8 bytes/op of host memory, vs the ~60+ bytes/op change log.
        self._op_index = {}            # slot -> sorted np.int64 combos
        self._op_index_pending = []    # [(slots, combos)] flat batches
        self._op_index_incomplete = set()
        # Set rows fold into host_winners lazily: inc-free batches (the
        # common case) just append their arrays here, and the scatter-max
        # replays only when an inc needs checking, a maintenance op
        # (rebase/remap/clone/free/load) touches the mirror, or the
        # backlog passes _WINNER_FOLD_LIMIT rows
        # [(kill_doc, kill_key, kill_packed, set_doc, set_key,
        #   set_packed) array 6-tuples], one entry per dispatched batch
        self._pending_winner_rows = []
        self._pending_winner_count = 0
        # exact_device=True stores the device state in the multi-value
        # register engine (fleet/registers.py) instead of the LWW
        # scatter-max grid: conflict sets, set-vs-delete resurrection, and
        # counter semantics become exact on device, at ordered-scan cost
        self.exact_device = exact_device
        self.reg_state = None     # RegisterState, allocated on first flush
        self.actor_slot_cap = actor_slot_capacity
        self.d_preds = d_preds
        self.doc_cap = doc_capacity
        self.key_cap = key_capacity
        self.n_slots = 0
        self.free_slots = []
        # bumped by every free_slots_batch: slot-indexed caches outside
        # the fleet (the subscription hub's frontier-scan plan) key on it
        # so a freed/recycled slot can never serve a stale row
        self.free_epoch = 0
        self.pending = []         # (slot, [change buffers])
        self.pending_actors = set()
        # Struct-of-arrays doc state (heads/clock/max_op/stale/...): the
        # turbo commit scatters whole batches into these columns; the
        # engines' attributes are property views onto their slot row.
        self.doc_cols = _DocCols(doc_capacity)
        # slot -> live engine — lets the seam-cap fold reach pending
        # docs without a handle. A PLAIN dict (a WeakValueDictionary
        # measured 40x slower per store, ~40 ms per 10k-doc init):
        # entries are popped by every slot-free path (free_docs /
        # free_slot / promote), so an engine outlives its handles only
        # until its slot is freed or reused — and an abandoned FLEET
        # takes the whole registry down with it.
        self._engines = {}
        # Lazily-folded turbo-commit log segments (see _SeamSegs) and
        # the clock-actor registry backing the _DocCols clock lanes
        self._pend_seams = []
        self._ck_reg = {}         # actor hex -> clock-actor id
        self._ck_names = []       # clock-actor id -> actor hex
        # False until any inc lane (or bulk-loaded counter cell) lands:
        # while False, set-only batches take the specialized no-inc
        # merge kernel (apply.py) that skips the counter grid passes
        self._counters_touched = False
        self.metrics = Metrics()  # per-dispatch counters (observability.py)
        _live_fleets.add(self)    # memory-watermark tier (perf.py)
        # Sequence-object fleet: one device row per (doc slot, objectId).
        # Text/list CRDT state lives in pow2 size-class pools of SeqStates
        # (fleet/sequence.py SeqPools) so memory follows each document's
        # own length — one long document no longer pads the whole fleet.
        from .sequence import SeqPools
        self.seq_elem_cap = 64    # base (smallest) class capacity
        self.seq_pools = SeqPools(self.seq_elem_cap)
        self.seq_rows = []        # row -> {'slot','object_id','type'} | None
        self.seq_place = []       # row -> (cls, idx) | None (unwritten)
        self.seq_len = []         # row -> host upper bound on elements
        self.seq_free = []
        self.slot_seq = {}        # slot -> {objectId: row}
        # Optional durability hook (fleet/durability.py ChangeJournal):
        # when attached, the mutation seams — FleetDoc.apply_changes, the
        # turbo batch commit, free/clone — journal accepted change bytes
        # through it, so sync rounds and batched applies are crash-durable
        # without callers doing anything per call.
        self.journal = None
        # Device-resident frontier index (fleet/hashindex.py): exact
        # (slot, change-hash) membership for the sync plane. Created
        # lazily by the first batched sync round (frontier_index());
        # while None the commit seams pay a single attribute check.
        self._hash_index = None

    def frontier_index(self, create=True, **kwargs):
        """The fleet's FleetFrontierIndex (fleet/hashindex.py), created
        on first use. The commit seams stage every accepted change hash
        into it host-side once it exists; sync rounds flush + probe in
        one dispatch each."""
        if self._hash_index is None and create:
            from .hashindex import FleetFrontierIndex
            self._hash_index = FleetFrontierIndex(self, **kwargs)
        return self._hash_index

    def _cap_docs(self, n_docs):
        """Doc-capacity sizing shared by the grid and register allocators:
        pow2 growth, raised to a multiple of the mesh docs axis so sharded
        device_put divides evenly (a bare pow2 fails on e.g. a 6-device
        axis). An already-sufficient mesh-aligned capacity is returned
        unchanged: on a non-pow2 mesh the stored doc_cap is itself non-pow2
        (e.g. 66 on a 6-device axis), and re-deriving pow2 from it
        (128 -> 132) would regrow state ~2x on every call. A constructor
        doc_capacity that is NOT yet a mesh multiple still rounds up."""
        m = self.mesh.shape.get('docs', 1) if self.mesh is not None else 1
        if n_docs <= self.doc_cap and self.doc_cap % m == 0:
            return self.doc_cap
        need = max(_pow2(max(n_docs, 1)), self.doc_cap)
        return ((need + m - 1) // m) * m

    def _shard_docs(self, tree):
        """Place a pytree of [docs, ...] arrays sharded over the mesh's
        docs axis (identity when the fleet has no mesh). Used for state
        allocation/growth and for op batches entering a dispatch, so the
        jitted merge runs SPMD with XLA inserting any needed collectives."""
        if self.mesh is None:
            return tree
        import jax
        import jax.tree_util as tree_util
        from jax.sharding import NamedSharding, PartitionSpec as P

        def put(x):
            spec = P('docs', *([None] * (x.ndim - 1)))
            return jax.device_put(x, NamedSharding(self.mesh, spec))
        return tree_util.tree_map(put, tree)

    @property
    def dispatches(self):
        return self.metrics.dispatches

    def attach_journal(self, journal):
        """Attach (or detach, with None) a durability journal; the
        mutation-seam hooks consult it on every accepted batch."""
        self.journal = journal

    def memory_stats(self):
        """Device-state byte accounting per component: the LWW grid or
        register state, and each sequence size-class pool (observability
        for capacity planning; host-side shapes only, no transfers)."""
        def nbytes(arrs):
            return int(sum(int(np.prod(a.shape)) * a.dtype.itemsize
                           for a in arrs))

        out = {'total': 0}
        if self.state is not None:
            out['lww_grid'] = nbytes(self.state.tree_flatten()[0])
        if self.host_winners is not None:
            # host-RAM mirror for counter-attribution checks (not device)
            out['host_winner_mirror'] = int(self.host_winners.nbytes)
        if self._op_index or self._op_index_pending:
            # host-RAM dangling-pred oracle: 8 bytes per applied op row
            out['op_index'] = int(
                sum(a.nbytes for a in self._op_index.values()) +
                sum(p[1].nbytes for p in self._op_index_pending))
        if self.reg_state is not None:
            out['registers'] = nbytes(self.reg_state.tree_flatten()[0])
        pools = {}
        for cls, st in sorted(self.seq_pools.pools.items()):
            pools[cls] = {'capacity': st.capacity,
                          'rows': int(st.elem_id.shape[0]),
                          'actor_lanes': int(st.actor_slots),
                          'bytes': nbytes(st.tree_flatten()[0])}
        if pools:
            out['seq_pools'] = pools
        if self.journal is not None:
            # durability accounting: what is buffered in RAM awaiting the
            # next group commit, and what the OS holds but has not yet
            # fsynced (the crash-loss window)
            out['journal'] = self.journal.stats()
        out['total'] = out.get('lww_grid', 0) + out.get('registers', 0) + \
            sum(p['bytes'] for p in pools.values())
        out['value_table_entries'] = len(self.value_table)
        return out

    # -- slot management ------------------------------------------------

    def alloc_slot(self):
        if self.free_slots:
            slot = self.free_slots.pop()
        else:
            slot = self.n_slots
            self.n_slots += 1
        self.doc_cols.ensure(self.n_slots)
        self.doc_cols.reset_rows([slot])
        return slot

    def alloc_slots(self, n):
        """Allocate n slots in one call (recycled slots first, in the same
        LIFO order alloc_slot would hand them out, then fresh ones) —
        init_docs' O(1) bookkeeping instead of n alloc_slot calls."""
        if n <= 0:
            return []
        out = []
        if self.free_slots:
            k = min(len(self.free_slots), n)
            out = self.free_slots[-k:][::-1]
            del self.free_slots[-k:]
        rest = n - len(out)
        if rest:
            base = self.n_slots
            out.extend(range(base, base + rest))
            self.n_slots = base + rest
        self.doc_cols.ensure(self.n_slots)
        self.doc_cols.reset_rows(out)
        return out

    def free_slot(self, slot):
        self.free_slots_batch([slot])

    def free_slots_batch(self, slots):
        """Release a batch of slots: all host-side bookkeeping in one pass
        and the device rows zeroed in ONE dispatch per engine kind
        (`_zero_rows`) — freeing n docs used to rewrite the whole grid n
        times over (the per-doc `.at[slot].set(0)` chain)."""
        if not slots:
            return
        if self.pending:
            gone = set(slots)
            self.pending = [(s, b) for (s, b) in self.pending
                            if s not in gone]
        if self._pend_seams:
            # un-folded turbo appends die with the doc: a recycled slot
            # must never fold a previous tenant's segments
            for seg in self._pend_seams:
                for slot in slots:
                    seg.rowmap.pop(slot, None)
            self._pend_seams = [s for s in self._pend_seams if s.rowmap]
        self._index_consolidate()
        if self._hash_index is not None:
            # release the slots' membership spaces (and purge staged
            # rows) so a recycled slot never inherits its previous
            # tenant's change hashes
            self._hash_index.drop_slots(slots)
        seq_zero = []
        for slot in slots:
            eng = self._engines.pop(slot, None)
            if eng is not None:
                # sever the dead engine from the shared columns: every
                # freeing path nulls its handle's _impl, so nothing
                # legitimate touches it again — but a leaked raw
                # reference must fail LOUDLY (a non-integer slot makes
                # every column index raise) rather than alias the
                # slot's next tenant. slot=None would be WORSE than
                # stale: numpy None-indexing broadcasts, so a setter
                # would overwrite whole columns.
                eng.slot = 'freed'
        for slot in slots:
            self.ctr_base.pop(slot, None)
            self.grid_overflow.discard(slot)
            self.del_fallback.discard(slot)
            self._op_index.pop(slot, None)
            self._op_index_incomplete.discard(slot)
            rows = self.slot_seq.pop(slot, {})
            if rows:
                seq_zero.extend(rows.values())
                for row in rows.values():
                    self.seq_rows[row] = None
                    self.seq_free.append(row)
        self._zero_rows(slots)
        if seq_zero:
            self._zero_seq_rows(seq_zero)
        self.free_slots.extend(slots)
        self.free_epoch += 1

    def _fold_all_pending(self):
        """Fold every doc's pending turbo-commit segments into the real
        logs — the amortized eager path bounding seam-record memory on
        write-heavy workloads that never read history (the hot path
        stays O(1); this runs once per _SEAM_FOLD_LIMIT commits)."""
        for seg in list(self._pend_seams):
            for slot in list(seg.rowmap):
                eng = self._engines.get(slot)
                if eng is None:
                    seg.rowmap.pop(slot, None)
                else:
                    eng._fold_pending()
        self._pend_seams = [s for s in self._pend_seams if s.rowmap]

    def clone_slot(self, src):
        self.flush()
        dst = self.alloc_slot()
        # Counter-window state travels with the row copy: without it a
        # clone of a rebased/overflowed slot would read its grid row with
        # the wrong base (or as authoritative when it is not)
        if src in self.ctr_base:
            self.ctr_base[dst] = self.ctr_base[src]
        if src in self.grid_overflow:
            self.grid_overflow.add(dst)
        if src in self.del_fallback:
            self.del_fallback.add(dst)
        if src in self._op_index_incomplete:
            self._op_index_incomplete.add(dst)
        self._index_consolidate()
        src_idx = self._op_index.get(src)
        if src_idx is not None:
            self._op_index[dst] = src_idx.copy()
        copies = {}    # cls -> ([src idx], [dst idx])
        lanes = self._seq_lane_width()
        for oid, row in list(self.slot_seq.get(src, {}).items()):
            info = self.seq_rows[row]
            dst_row = self._alloc_seq_row(dst, oid, info['type'])
            place = self.seq_place[row]
            if place is not None:
                idx = self.seq_pools.alloc(place[0], lanes)
                self.seq_place[dst_row] = (place[0], idx)
                self.seq_len[dst_row] = self.seq_len[row]
                srcs, dsts = copies.setdefault(place[0], ([], []))
                srcs.append(place[1])
                dsts.append(idx)
        for cls, (srcs, dsts) in copies.items():
            self.seq_pools.copy_rows(cls, srcs, cls, dsts)
        if self.state is not None and src < self.state.winners.shape[0]:
            self._ensure_capacity(n_docs=dst + 1, n_keys=len(self.keys))
            st = self.state
            self.state = FleetState(
                st.winners.at[dst].set(st.winners[src]),
                st.values.at[dst].set(st.values[src]),
                st.counters.at[dst].set(st.counters[src]))
            if self.host_winners is not None:
                self._fold_pending_winners()
                self.host_winners[dst] = self.host_winners[src]
        if self.reg_state is not None and src < self.reg_state.reg.shape[0]:
            from .registers import RegisterState
            self._ensure_reg_capacity(n_docs=dst + 1, n_keys=len(self.keys))
            rs = self.reg_state
            self.reg_state = RegisterState(
                rs.reg.at[dst].set(rs.reg[src]),
                rs.killed.at[dst].set(rs.killed[src]),
                rs.value.at[dst].set(rs.value[src]),
                rs.counter.at[dst].set(rs.counter[src]),
                rs.inexact.at[dst].set(rs.inexact[src]))
        return dst

    def _zero_rows(self, slots):
        """Zero a batch of slots' device rows in ONE fused donated kernel
        per engine kind (grid and/or registers), counted in
        metrics.dispatches. The index vector is padded to a power of two
        with repeats of its first slot (zeroing is idempotent) so the JIT
        recompiles O(log batch) times, not once per batch size."""
        arr = np.asarray(list(slots), dtype=np.int64)
        if not len(arr):
            return
        import jax.numpy as jnp

        def padded(sel):
            n_pad = _pow2(len(sel))
            return jnp.asarray(np.concatenate(
                [sel, np.full(n_pad - len(sel), sel[0], dtype=sel.dtype)]))

        if self.state is not None:
            sel = arr[arr < self.state.winners.shape[0]]
            if len(sel):
                from .apply import zero_doc_rows_donated
                self.state = zero_doc_rows_donated(self.state, padded(sel))
                self.metrics.dispatches += 1
                if self.host_winners is not None:
                    self._fold_pending_winners()
                    self.host_winners[sel] = 0
        if self.reg_state is not None:
            sel = arr[arr < self.reg_state.reg.shape[0]]
            if len(sel):
                from .registers import zero_register_rows_donated
                self.reg_state = zero_register_rows_donated(
                    self.reg_state, padded(sel))
                self.metrics.dispatches += 1

    # -- sequence rows ---------------------------------------------------

    def _alloc_seq_row(self, slot, object_id, type_):
        info = {'slot': slot, 'object_id': object_id, 'type': type_}
        if self.seq_free:
            row = self.seq_free.pop()
            self.seq_rows[row] = info
            self.seq_place[row] = None
            self.seq_len[row] = 0
        else:
            row = len(self.seq_rows)
            self.seq_rows.append(info)
            self.seq_place.append(None)
            self.seq_len.append(0)
        self.slot_seq.setdefault(slot, {})[object_id] = row
        return row

    def _seq_lane_width(self):
        return _pow2(max(len(self.actors), 4))

    def _seq_need(self, row, need_len):
        """(size class, performs-a-fresh-pool-alloc) for placing `row` at
        need_len elements — the ONE sizing policy driving both the
        reserve() pre-pass and _place_seq_row, so they cannot drift."""
        need_cls = self.seq_pools.cls_for(
            max(self.seq_len[row], need_len, 1))
        place = self.seq_place[row]
        return need_cls, place is None or need_cls > place[0]

    def _place_seq_row(self, row, need_len):
        """Ensure row has a device placement with capacity >= need_len,
        migrating up a size class when it outgrows its current one.
        Returns (cls, idx)."""
        need_cls, _ = self._seq_need(row, need_len)
        self.seq_len[row] = max(self.seq_len[row], need_len, 1)
        pools = self.seq_pools
        place = self.seq_place[row]
        lanes = self._seq_lane_width()
        if place is None:
            idx = pools.alloc(need_cls, lanes)
            place = (need_cls, idx)
        elif need_cls > place[0]:
            idx = pools.migrate(place[0], place[1], need_cls, lanes)
            place = (need_cls, idx)
        self.seq_place[row] = place
        return place

    def seq_row_inexact(self, row):
        """Host read of one device row's inexact flag (False when the row
        was never written)."""
        place = self.seq_place[row] if row < len(self.seq_place) else None
        if place is None:
            return False
        st = self.seq_pools.state(place[0])
        return bool(np.asarray(st.inexact[place[1]]))

    def _zero_seq_rows(self, rows):
        by_cls = {}
        for row in rows:
            place = self.seq_place[row] if row < len(self.seq_place) \
                else None
            if place is not None:
                by_cls.setdefault(place[0], []).append(place[1])
                self.seq_place[row] = None
            if row < len(self.seq_len):
                self.seq_len[row] = 0
        if by_cls:
            self.seq_pools.release_rows(by_cls)

    @_spanned('actor_remap')
    def _remap_seq_actors(self, perm):
        """Renumber the actor bits of packed elemIds/register opIds in every
        sequence pool after a sorted-order actor insertion, permuting the
        actor-lane axis the same way (lanes are indexed by actor number,
        like _remap_reg_actors; machinery shared via _lane_permutation)."""
        if not self.seq_pools.pools:
            return
        import jax.numpy as jnp
        from .sequence import SeqState
        # Grow every pool's lane axis FIRST (same rationale as
        # _remap_reg_actors)
        self.seq_pools.ensure_lanes(self._seq_lane_width())
        self.metrics.remaps += 1
        for cls, st in list(self.seq_pools.pools.items()):
            move, renum = self._lane_permutation(perm, st.reg.shape[2])
            self.seq_pools.pools[cls] = SeqState(
                renum(st.elem_id), jnp.asarray(st.nxt),
                renum(move(st.reg, 0)), move(st.killed, False),
                move(st.val, 0), move(st.counter, 0), jnp.asarray(st.n),
                jnp.asarray(st.inexact))

    def _intern_value(self, value):
        """Inline int32 in [0, 2^31) or a value-table ref -(i + 2)."""
        if isinstance(value, int) and not isinstance(value, bool) and \
                0 <= value < (1 << 31):
            return value
        return self._intern_value_boxed(value)

    def _intern_seq_value(self, type_, op):
        """Sequence-element payload: text rows store single-char codepoints
        inline (table refs are negative, so the two never collide); list
        rows store plain non-negative int32s inline; everything else goes
        through the value table. uint/counter/timestamp/float64 payloads
        box with their datatype (TypedValue) so device-served patches keep
        exact datatype leaves — the same rule as the map register paths."""
        value = op.get('value')
        datatype = op.get('datatype')
        if type_ == 'text' and datatype is None and \
                isinstance(value, str) and len(value) == 1:
            return ord(value)
        if type_ == 'text' and datatype in (None, 'int'):
            # non-char text payloads box raw (never inline: a text lane's
            # non-negative ints mean code points)
            return self._intern_value_boxed(value)
        return self._intern_typed(value, datatype)

    def _intern_value_boxed(self, value):
        return -(self.value_table.intern(value) + 2)

    def _make_link_value(self, slot, oid, type_name):
        """THE make-op link rule, shared by the apply and bulk-load ingest
        paths: a child object created by a make op is represented as a
        boxed link value; sequence children (text/list) allocate their
        device row immediately — an empty child would otherwise push every
        read of the doc to the mirror via an unresolved link."""
        if type_name in ('text', 'list'):
            if oid not in self.slot_seq.get(slot, {}):
                self._alloc_seq_row(slot, oid, type_name)
            return self._intern_value_boxed(_SeqLink(oid))
        return self._intern_value_boxed(_MapLink(oid, type_name))

    def _intern_typed(self, value, datatype):
        """THE datatype-boxing rule for device value lanes (one source of
        truth for the per-op, turbo, and loader ingest paths): payloads
        whose wire datatype an int32 lane can't carry ('uint', 'counter',
        'timestamp', 'float64', …) box as TypedValue so device-served
        patches keep exact datatype leaves; plain ints in range stay
        inline; everything else boxes raw."""
        from .registers import TypedValue
        if not isinstance(datatype, str):
            # int datatype tags (bytes / unknown wire types,
            # columnar.decode_value) box raw: their patch leaves are
            # mirror territory, not TypedValue material
            datatype = None
        if datatype not in (None, 'int'):
            return self._intern_value_boxed(TypedValue(value, datatype))
        if isinstance(value, int) and not isinstance(value, bool) and \
                0 <= value < (1 << 31):
            return value
        return self._intern_value_boxed(value)

    def _pack_seq_op(self, row, info, op, packed, op_id=None):
        """One decoded sequence op -> (row, kind, ref, packed, value,
        pred0..predD-1, flag) with packed opIds in fleet actor numbering."""
        from .sequence import INSERT, SET, DEL, PAD, SEQ_PRED_LANES
        from .tensor_doc import pack_op_id
        from ..common import parse_op_id

        def pack_ref(eid):
            if eid in (None, '_head'):
                return 0
            ctr, actor = parse_op_id(eid)
            return pack_op_id(ctr, self.actors.intern(actor))

        action = op['action']
        flag = False
        lanes = [0] * SEQ_PRED_LANES
        pred_ids = op.get('pred', [])
        if len(pred_ids) > SEQ_PRED_LANES:
            flag = True
            pred_ids = pred_ids[:SEQ_PRED_LANES]
        for i, p in enumerate(pred_ids):
            lanes[i] = pack_ref(p)
        if action == 'inc':
            # Exact on device: the INC kind accumulates into the pred'd
            # counter lane with Lamport-max attribution (new.js:937-965).
            # The lane bit-packs (sum << 2) | count-bits, so deltas are
            # bounded at +/-2^29 — larger ones flag the row inexact
            # instead of wrapping.
            from .sequence import INC
            kind = INC
            delta = op.get('value', 0)
            if isinstance(delta, int) and not isinstance(delta, bool) and \
                    -(1 << 29) < delta < (1 << 29):
                value = delta
            else:
                kind, value, flag = PAD, 0, True   # unencodable delta
        elif action == 'del':
            kind, value = DEL, 0
        elif action in _SEQ_MAKE or action in _MAP_MAKE:
            # Nested object as a sequence element (rows-in-lists, lists in
            # lists; ref new.js:1461-1528 objectMeta ancestry): the element
            # value is a link to the child object, which registers like any
            # fleet object — (objectId, key) grid columns for maps/tables,
            # its own SeqState row for text/lists.
            kind = INSERT if op.get('insert') else SET
            value = self._make_link_value(info['slot'], op_id,
                                          OBJECT_TYPE[action])
            if info['type'] == 'text':
                # Object elements inside Text render as spans, which stay
                # mirror territory: flag the row so reads route there
                flag = True
        else:
            kind = INSERT if op.get('insert') else SET
            value = self._intern_seq_value(info['type'], op)
        return (row, kind, pack_ref(op.get('elemId')), packed, value,
                *lanes, flag)

    @_spanned('dispatch_seq')
    def _dispatch_seq(self, seq_ops):
        """Place every touched row in a size-class pool with enough
        capacity (migrating rows that outgrew their class) and batch-apply
        all pending sequence ops — ONE dispatch per active size class.
        seq_ops rows are (row, kind, ref, packed, value, pred0..D-1, flag)."""
        from .sequence import SeqOpBatch, apply_seq_batch_donated, \
            INSERT, \
            SEQ_PRED_LANES
        if len(self.seq_rows) == 0 or len(seq_ops) == 0:
            return
        # Widen every pool's lane axis FIRST: a new actor whose hex sorts
        # after all existing ones produces no remap (identity perm), yet
        # its lane must exist before its ops apply
        self.seq_pools.ensure_lanes(self._seq_lane_width())
        D = SEQ_PRED_LANES
        arr = np.asarray(seq_ops, dtype=np.int64)   # [M, 6 + D] op tuples
        row_a = arr[:, 0]
        n_rows = len(self.seq_rows)
        counts = np.bincount(row_a, minlength=n_rows)
        ins = np.bincount(row_a[arr[:, 1] == INSERT], minlength=n_rows)
        # Placement pass: host-tracked element counts give each row's
        # needed capacity class without any device reads. Reserve each
        # pool's capacity ONCE for all rows landing in it this dispatch
        # (the round-5 on-chip mixed-seam dispatch storm: per-alloc pow2
        # growth cost 72 device copies at 500 fresh docs).
        pools = self.seq_pools
        lanes = self._seq_lane_width()
        uniq_rows = [int(r) for r in np.unique(row_a)]
        new_by_cls = {}
        for row in uniq_rows:
            need_cls, fresh = self._seq_need(
                row, self.seq_len[row] + int(ins[row]))
            if fresh:
                new_by_cls[need_cls] = new_by_cls.get(need_cls, 0) + 1
        for cls, count in new_by_cls.items():
            pools.reserve(cls, count, lanes)
        cls_of = {}
        for row in uniq_rows:
            cls_of[row], _ = self._place_seq_row(
                row, self.seq_len[row] + int(ins[row]))
        # One batch per active class, rows addressed by pool index
        by_cls = {}
        for row, cls in cls_of.items():
            by_cls.setdefault(cls, []).append(row)
        order = np.argsort(row_a, kind='stable')
        row_sorted = row_a[order]
        pos_in_row = np.arange(len(row_sorted)) - \
            np.searchsorted(row_sorted, row_sorted, side='left')
        for cls, rows in by_cls.items():
            st = self.seq_pools.state(cls)
            r_cap = st.elem_id.shape[0]
            sel = np.isin(row_sorted, rows)
            sub = order[sel]
            idx_of = np.zeros(n_rows, dtype=np.int64)
            for row in rows:
                idx_of[row] = self.seq_place[row][1]
            rows_idx = idx_of[row_sorted[sel]]
            pos = pos_in_row[sel]
            width = max(int(counts[rows].max()), 1)
            cols = {name: np.zeros((r_cap, width), dtype=np.int32)
                    for name in ('kind', 'ref', 'packed', 'value')}
            preds = np.zeros((r_cap, width, D), dtype=np.int32)
            flag = np.zeros((r_cap, width), dtype=bool)
            for j, name in enumerate(('kind', 'ref', 'packed', 'value')):
                cols[name][rows_idx, pos] = arr[sub, j + 1]
            for d in range(D):
                preds[rows_idx, pos, d] = arr[sub, 5 + d]
            flag[rows_idx, pos] = arr[sub, 5 + D] != 0
            batch = SeqOpBatch(cols['kind'], cols['ref'], cols['packed'],
                               cols['value'], preds, flag)
            new_state, _stats = apply_seq_batch_donated(st, batch)
            self.seq_pools.pools[cls] = new_state
            self.metrics.dispatches += 1
        self.metrics.device_ops += len(seq_ops)

    def render_seq_all(self):
        """Render every live sequence row: {row: str/list}, with None for
        rows whose device state is inexact (host mirror must serve those
        reads). One materialize + transfer per ACTIVE size class."""
        import jax
        from .sequence import materialize as seq_materialize
        from .registers import TypedValue
        out = {}
        per_cls = {}
        for row, info in enumerate(self.seq_rows):
            if info is None:
                continue
            place = self.seq_place[row]
            if place is None:
                out[row] = '' if info['type'] == 'text' else []
            else:
                per_cls.setdefault(place[0], []).append(row)
        mats = {}
        for cls in per_cls:
            st = self.seq_pools.state(cls)
            vals, cnts, vis, _n = (np.asarray(x) for x in
                                   jax.device_get(seq_materialize(st)))
            mats[cls] = (vals, cnts, vis, np.asarray(st.inexact))

        def unbox(v, c):
            boxed = self.value_table[-v - 2]
            if isinstance(boxed, TypedValue):
                # counter display = set base + accumulated inc deltas
                # (ref new.js:937-965)
                return boxed.value + c if boxed.datatype == 'counter' \
                    else boxed.value
            return boxed

        for cls, rows in per_cls.items():
            vals, cnts, vis, inexact = mats[cls]
            for row in rows:
                idx = self.seq_place[row][1]
                if inexact[idx]:
                    out[row] = None
                    continue
                # counter lanes bit-pack (sum << 2) | count-bits
                items = [(int(v), int(c) >> 2) for v, c in
                         zip(vals[idx][vis[idx]], cnts[idx][vis[idx]])]
                if self.seq_rows[row]['type'] == 'text':
                    out[row] = ''.join(
                        chr(v) if v >= 0 else str(unbox(v, c))
                        for v, c in items)
                else:
                    out[row] = [v if v >= 0 else unbox(v, c)
                                for v, c in items]
        return out

    # -- ingest ---------------------------------------------------------

    def enqueue(self, slot, buffers, actors):
        if buffers:
            self.pending.append((slot, list(buffers)))
            self.pending_actors.update(actors)

    def _grid_cap(self):
        """Doc capacity of the grid state — materialized or (fresh
        fleet, allocation deferred into the first dispatch) recorded."""
        return self.state.winners.shape[0] if self.state is not None \
            else self.doc_cap

    def _materialize_grid(self, n_docs, n_keys):
        """Eagerly materialize the grid state at capacity — for callers
        that write `self.state` IN PLACE (the bulk loader's direct
        installs) rather than through a dispatch, where the deferred
        fresh-fleet allocation (see _ensure_capacity/_dispatch_grid)
        would leave state None."""
        self._ensure_capacity(n_docs=n_docs, n_keys=n_keys)
        if self.state is None:
            import jax.numpy as jnp
            self.state = self._shard_docs(
                FleetState.empty(self.doc_cap, self.key_cap, xp=jnp))

    def _ensure_capacity(self, n_docs, n_keys):
        need_docs = self._cap_docs(n_docs)
        need_keys = _pow2(max(n_keys + 1, self.key_cap))
        if self.state is None:
            self.doc_cap, self.key_cap = need_docs, need_keys
            self.host_winners = np.zeros((need_docs, need_keys + 1),
                                         dtype=np.int32)
            if self.mesh is not None:
                # sharded fleets keep the eager device allocation (the
                # fused fresh-dispatch path would need out_shardings)
                import jax.numpy as jnp
                self.state = self._shard_docs(
                    FleetState.empty(need_docs, need_keys, xp=jnp))
            # else: the first _dispatch_grid builds the zero state INSIDE
            # its jit (apply.apply_op_batch_fresh) — the fill fuses with
            # the first scatter instead of being its own ~whole-grid
            # memset dispatch
            return
        old_n, old_k = self.state.winners.shape
        if need_docs <= old_n and need_keys + 1 <= old_k:
            return
        import jax.numpy as jnp
        self.metrics.grows += 1
        n, k = max(need_docs, old_n), max(need_keys + 1, old_k)
        # The old scratch column (index old_k - 1) holds garbage from padded
        # scatter lanes; it must not become a real key slot when widening
        grown = []
        for arr in (self.state.winners, self.state.values, self.state.counters):
            out = jnp.zeros((n, k), dtype=arr.dtype)
            out = out.at[:old_n, :old_k - 1].set(arr[:, :old_k - 1])
            grown.append(out)
        hw = np.zeros((n, k), dtype=np.int32)
        if self.host_winners is not None:
            hw[:old_n, :old_k - 1] = self.host_winners[:, :old_k - 1]
        self.host_winners = hw
        self.doc_cap, self.key_cap = n, k - 1
        self.state = self._shard_docs(FleetState(*grown))

    @_spanned('actor_remap')
    def _remap_actors(self, perm):
        """Renumber the actor bits of every packed opId on the device."""
        perm_full = np.arange(MAX_ACTORS, dtype=np.int32)
        perm_full[:len(perm)] = perm
        self._index_remap_actors(perm_full)
        if self.state is None:
            return
        import jax.numpy as jnp
        mask = MAX_ACTORS - 1
        self.metrics.remaps += 1
        w = self.state.winners
        remapped = (w & ~mask) | jnp.asarray(perm_full)[w & mask]
        self.state = FleetState(jnp.where(w != 0, remapped, 0),
                                self.state.values, self.state.counters)
        if self.host_winners is not None:
            self._fold_pending_winners()
            hw = self.host_winners
            hw_new = (hw & ~mask) | perm_full[hw & mask]
            self.host_winners = np.where(hw != 0, hw_new, 0) \
                .astype(np.int32)

    def _ensure_reg_capacity(self, n_docs, n_keys):
        from .registers import RegisterState
        import jax.numpy as jnp
        need_docs = self._cap_docs(n_docs)
        need_keys = _pow2(max(n_keys + 1, self.key_cap))
        need_slots = _pow2(max(len(self.actors), self.actor_slot_cap))
        if self.reg_state is None:
            self.doc_cap, self.key_cap = need_docs, need_keys
            self.actor_slot_cap = need_slots
            self.reg_state = self._shard_docs(
                RegisterState.empty(need_docs, need_keys - 1,
                                    need_slots, xp=jnp))
            return
        old_n, old_k, old_a = self.reg_state.reg.shape
        if need_docs <= old_n and need_keys <= old_k and \
                need_slots <= old_a:
            return
        self.metrics.grows += 1
        n = max(need_docs, old_n)
        k = max(need_keys, old_k)
        a = max(need_slots, old_a)
        grown = []
        for arr in (self.reg_state.reg, self.reg_state.killed,
                    self.reg_state.value, self.reg_state.counter):
            out = jnp.zeros((n, k, a), dtype=arr.dtype)
            # old scratch column (old_k - 1) holds garbage: drop it
            out = out.at[:old_n, :old_k - 1, :old_a].set(arr[:, :old_k - 1])
            grown.append(out)
        inexact = jnp.zeros((n,), dtype=bool)
        inexact = inexact.at[:old_n].set(self.reg_state.inexact)
        self.doc_cap, self.key_cap = n, k - 1
        self.actor_slot_cap = a
        self.reg_state = self._shard_docs(RegisterState(*grown, inexact))

    @staticmethod
    def _lane_permutation(perm, n_lanes):
        """Shared actor-lane permutation machinery for the register and
        sequence engines: lanes are indexed by actor number, so a
        sorted-order actor insertion (perm: old actor num -> new actor num)
        both renumbers packed-id actor bits and moves every lane.

        Returns (move, renum): move(arr, fill) permutes the trailing lane
        axis of a [..., n_lanes] array — every pre-existing actor appears in
        perm; lanes not fed by any old actor (newly inserted actors, plus
        the unused tail) start as `fill` — and renum(arr) rewrites the
        actor bits of non-zero packed opIds."""
        import jax.numpy as jnp
        old_of_new = np.zeros(n_lanes, dtype=np.int32)
        fresh = np.ones(n_lanes, dtype=bool)
        for old_i, new_i in enumerate(np.asarray(perm)):
            if new_i < n_lanes:
                old_of_new[new_i] = old_i
                fresh[new_i] = False
        gather = jnp.asarray(old_of_new)
        zero_new = jnp.asarray(fresh)
        mask = MAX_ACTORS - 1
        perm_full = np.arange(MAX_ACTORS, dtype=np.int32)
        perm_full[:len(perm)] = perm
        bits = jnp.asarray(perm_full)

        def move(arr, fill):
            out = jnp.asarray(arr)[..., gather]
            return jnp.where(zero_new, jnp.full_like(out, fill), out)

        def renum(arr):
            arr = jnp.asarray(arr)
            return jnp.where(arr != 0, (arr & ~mask) | bits[arr & mask], 0)

        return move, renum

    @_spanned('actor_remap')
    def _remap_reg_actors(self, perm):
        """Renumber actor bits AND permute the actor-slot axis of the
        register state after a sorted-order actor insertion."""
        perm_full = np.arange(MAX_ACTORS, dtype=np.int32)
        perm_full[:len(perm)] = perm
        self._index_remap_actors(perm_full)
        if self.reg_state is None:
            return
        from .registers import RegisterState
        # Grow the slot axis FIRST: the freshly inserted actors may push an
        # existing actor's new slot index past the current width, and the
        # permutation below would silently drop its registers
        self._ensure_reg_capacity(n_docs=self.n_slots, n_keys=len(self.keys))
        self.metrics.remaps += 1
        rs = self.reg_state
        move, renum = self._lane_permutation(perm, rs.reg.shape[2])
        self.reg_state = RegisterState(
            renum(move(rs.reg, 0)), move(rs.killed, False),
            move(rs.value, 0), move(rs.counter, 0), rs.inexact)

    def _rebase_slot(self, slot, new_ctr, floor_ctr=None):
        """Shift a slot's packing window so counters up to `new_ctr` fit:
        new base = min(live winner counters, incoming batch floor) - 1, with
        the slot's live winners shifted down in one device update. When the
        spread itself exceeds the window the slot lands in grid_overflow
        (reads fall back to the host mirror; history stays unbounded)."""
        old = self.ctr_base.get(slot, 0)
        min_live = None
        if self.state is not None and slot < self.state.winners.shape[0]:
            row = np.asarray(self.state.winners[slot])
            live = row[row != 0]
            if len(live):
                min_live = int((live >> ACTOR_BITS).min()) + old
        floor = new_ctr if floor_ctr is None else floor_ctr
        if min_live is not None:
            floor = min(floor, min_live)
        new_base = floor - 1
        if new_ctr - new_base >= CTR_LIMIT or new_base <= old:
            self.grid_overflow.add(slot)
            return old
        if min_live is not None:
            import jax.numpy as jnp
            self._fold_pending_winners()
            delta = (new_base - old) << ACTOR_BITS
            w = self.state.winners
            shifted = jnp.where(w[slot] != 0, w[slot] - delta, 0)
            self.state = FleetState(w.at[slot].set(shifted),
                                    self.state.values, self.state.counters)
            self.metrics.dispatches += 1
            if self.host_winners is not None and \
                    slot < self.host_winners.shape[0]:
                hw = self.host_winners[slot]
                self.host_winners[slot] = np.where(hw != 0, hw - delta, 0)
        self._index_rebase(slot, (new_base - old) << ACTOR_BITS)
        self.ctr_base[slot] = new_base
        return new_base

    def _pack_pred(self, slot, op):
        """Pack an inc op's attribution pred against the slot's current
        window WITHOUT rebase side effects. Multi-pred incs (conflicted
        counters) attribute to the LAMPORT-MAX pred, matching the
        reference's counterStates overwrite (new.js:942-945). Returns -1
        when no pred can be packed (absent, unregistered actor, outside
        the window) — which _note_grid_batch treats as a mismatch."""
        from ..common import parse_op_id
        from .tensor_doc import pack_op_id
        preds = op.get('pred') or []
        if not preds:
            return -1
        packed = []
        for pr in preds:
            try:
                ctr, actor = parse_op_id(pr)
                num = self.actors.intern(actor)
            except (KeyError, ValueError):
                return -1
            rel = ctr - self.ctr_base.get(slot, 0)
            if rel <= 0 or rel >= CTR_LIMIT:
                return -1
            packed.append(pack_op_id(rel, num))
        return max(packed)

    # -- dangling-pred oracle (see _op_index in __init__) ---------------

    def _index_ops(self, slots, key_ids, packeds):
        """Record applied map-key op rows (sets, incs, makes — never
        dels) for later pred-existence checks. slots/key_ids/packeds are
        parallel arrays in fleet numbering. O(1) per batch: the per-slot
        split is deferred to consolidation (lookup/clone/rebase time), so
        pred-free bulk workloads pay only the combo pack."""
        if not len(slots):
            return
        combos = (np.asarray(key_ids, dtype=np.int64) << 32) | \
            np.asarray(packeds, dtype=np.int64)
        self._op_index_pending.append(
            (np.asarray(slots, dtype=np.int64), combos))

    def _index_consolidate(self):
        """Drain the flat pending batches into per-slot sorted arrays."""
        if not self._op_index_pending:
            return
        slots = np.concatenate([p[0] for p in self._op_index_pending])
        combos = np.concatenate([p[1] for p in self._op_index_pending])
        self._op_index_pending = []
        order = np.argsort(slots, kind='stable')
        ss = slots[order]
        cs = combos[order]
        bounds = np.flatnonzero(np.r_[True, ss[1:] != ss[:-1]])
        ends = np.r_[bounds[1:], len(ss)]
        for b, e in zip(bounds, ends):
            slot = int(ss[b])
            old = self._op_index.get(slot)
            if old is None:
                self._op_index[slot] = np.sort(cs[b:e])
            else:
                self._op_index[slot] = np.sort(
                    np.concatenate([old, cs[b:e]]))

    def _index_lookup(self, slot, combos):
        """Membership of (key << 32 | packed) combos in the slot's
        applied-op index (consolidates the pending backlog first)."""
        self._index_consolidate()
        arr = self._op_index.get(slot)
        if arr is None or not len(arr):
            return np.zeros(len(combos), dtype=bool)
        pos = np.searchsorted(arr, combos)
        pos = np.clip(pos, 0, len(arr) - 1)
        return arr[pos] == combos

    def _index_remap_actors(self, perm_full):
        """Renumber the actor bits of every indexed packed opId (actor
        table re-sort) — consolidated arrays and pending batches alike."""
        mask = np.int64(MAX_ACTORS - 1)
        perm64 = perm_full.astype(np.int64)

        def remap(arr):
            return (arr & ~mask) | perm64[arr & mask]

        for slot, arr in self._op_index.items():
            self._op_index[slot] = np.sort(remap(arr))
        self._op_index_pending = [(s, remap(c))
                                  for s, c in self._op_index_pending]

    def _index_rebase(self, slot, delta_packed):
        """Shift a slot's indexed packed ids down by a counter rebase."""
        self._index_consolidate()
        arr = self._op_index.get(slot)
        if arr is None or not len(arr):
            return
        low = arr & 0xffffffff
        shifted = np.maximum(low - delta_packed, 0)
        self._op_index[slot] = np.sort(
            (arr & ~np.int64(0xffffffff)) | shifted)

    @_spanned('dispatch_grid')
    def _dispatch_grid(self, batch, kills=None):
        """One LWW-grid merge dispatch. With `kills` (a (kill_key,
        kill_packed) [N, Q] pair from delete preds), the kills-aware
        kernel runs so deletes only kill the ops they pred
        (apply.apply_op_batch_kills — ref new.js:1204-1217); without, the
        plain scatter kernel. The batch must already be padded to the
        state's doc capacity; kills are padded here."""
        from .apply import (apply_op_batch_donated, apply_op_batch_fresh,
                            apply_op_batch_kills_donated,
                            apply_op_batch_kills_fresh,
                            apply_op_batch_noinc_donated,
                            apply_op_batch_noinc_fresh)
        fresh = self.state is None      # deferred fresh-fleet allocation
        has_inc = bool(batch.is_inc.any())
        if has_inc:
            self._counters_touched = True
        if kills is None:
            if not has_inc and not self._counters_touched:
                # set-only batch on a counter-free grid: the specialized
                # kernel skips ~3 whole-grid memory passes (see apply.py)
                if fresh:
                    self.state, _stats = apply_op_batch_noinc_fresh(
                        batch, self.doc_cap, self.key_cap)
                else:
                    self.state, _stats = apply_op_batch_noinc_donated(
                        self.state, self._shard_docs(batch))
            elif fresh:
                self.state, _stats = apply_op_batch_fresh(
                    batch, self.doc_cap, self.key_cap)
            else:
                self.state, _stats = apply_op_batch_donated(
                    self.state, self._shard_docs(batch))
        else:
            kill_key, kill_packed = kills
            n_cap = self._grid_cap()
            if kill_key.shape[0] < n_cap:
                pad = n_cap - kill_key.shape[0]
                kill_key = np.pad(kill_key, ((0, pad), (0, 0)))
                kill_packed = np.pad(kill_packed, ((0, pad), (0, 0)))
            if fresh:
                self.state, _stats = apply_op_batch_kills_fresh(
                    batch, kill_key, kill_packed, self.doc_cap,
                    self.key_cap)
            else:
                self.state, _stats = apply_op_batch_kills_donated(
                    self.state, self._shard_docs(batch),
                    self._shard_docs(kill_key),
                    self._shard_docs(kill_packed))
        self.metrics.dispatches += 1

    def _note_grid_batch(self, set_doc, set_key, set_packed,
                         inc_doc, inc_key, inc_pred,
                         kill_doc=(), kill_key=(), kill_packed=()):
        """Advance the host winner mirror with a batch's set rows (same
        scatter-max the device applies, minus sets a same-batch kill
        names — the device masks those lanes) and kill rows (delete preds
        — clear the mirrored winner iff it holds exactly the pred'd opId,
        matching apply.apply_op_batch_kills), route every kill-touched
        slot's reads to the exact mirror (del_fallback), then verify
        every inc op's pred against the post-batch winner. An inc whose
        pred is not the winner would be credited to the wrong counter by
        the device cell (apply.py's documented corner), so its slot goes
        mirror-authoritative via grid_overflow. inc_pred == -1 marks
        preds that could not be packed (absent, multiple, or outside the
        window) and always flags."""
        if len(kill_doc):
            # Blunt-but-sound delete rule (see del_fallback): the grid's
            # winner view after kills is best-effort only. Runs BEFORE the
            # mirror guard — read-routing soundness must not depend on the
            # optional winner mirror being allocated.
            self.del_fallback.update(int(d) for d in np.unique(kill_doc))
        hw = self.host_winners
        if hw is None:
            return
        if len(set_doc) or len(kill_doc):
            self._pending_winner_rows.append(
                (np.asarray(kill_doc, dtype=np.int64),
                 np.asarray(kill_key, dtype=np.int64),
                 np.asarray(kill_packed, dtype=np.int32),
                 np.asarray(set_doc, dtype=np.int64),
                 np.asarray(set_key, dtype=np.int64),
                 np.asarray(set_packed, dtype=np.int32)))
            self._pending_winner_count += len(set_doc) + len(kill_doc)
        if len(inc_doc):
            self._fold_pending_winners()
            inc_doc = np.asarray(inc_doc, dtype=np.int64)
            inc_key = np.asarray(inc_key, dtype=np.int64)
            inc_pred = np.asarray(inc_pred, dtype=np.int64)
            bad = inc_pred != hw[inc_doc, inc_key]
            for d in np.unique(inc_doc[bad]):
                self.grid_overflow.add(int(d))
        elif self._pending_winner_count > _WINNER_FOLD_LIMIT or \
                len(self._pending_winner_rows) > 4096:
            # Two caps: total rows (bounds the fold's work) and batch
            # count (bounds per-batch numpy/tuple overhead under many
            # tiny inc-free flushes)
            self._fold_pending_winners()

    def _fold_pending_winners(self):
        """Replay the deferred batches into the host winner mirror. Per
        batch, preserving the device dispatch order: (1) kills clear a
        cell iff it holds exactly the pred'd opId (the device's
        standing-winner kill); (2) set rows scatter-max — EXCLUDING sets
        a same-batch kill names, which the device masks at the lane
        level. Kill-touched slots are already read-routed to the mirror
        (del_fallback), so this winner view is only consumed by the
        counter-attribution check on delete-free slots."""
        if not self._pending_winner_rows:
            return
        hw = self.host_winners
        for (kill_doc, kill_key, kill_packed,
             set_doc, set_key, set_packed) in self._pending_winner_rows:
            if len(kill_doc):
                m = hw[kill_doc, kill_key] == kill_packed
                hw[kill_doc[m], kill_key[m]] = 0
            if len(set_doc):
                keep = np.ones(len(set_doc), dtype=bool)
                if len(kill_doc):
                    kill_combo = kill_doc * (1 << 32) + kill_packed
                    keep = ~np.isin(set_doc * (1 << 32) + set_packed,
                                    kill_combo)
                np.maximum.at(hw, (set_doc[keep], set_key[keep]),
                              set_packed[keep])
        self._pending_winner_rows = []
        self._pending_winner_count = 0

    def _slot_pack(self, slot, ctr, actor_num):
        """Pack a grid op's (counter, actor) against the slot's rebased
        window; overflowing slots still get a clamped packing (their grid
        rows are no longer authoritative — reads use the mirror)."""
        base = self.ctr_base.get(slot, 0)
        if ctr - base >= CTR_LIMIT and slot not in self.grid_overflow:
            # (an overflowed slot must NOT rebase mid-batch: earlier ops in
            # this batch already packed against the old base)
            base = self._rebase_slot(slot, ctr)
        rel = ctr - base
        if rel <= 0 or rel >= CTR_LIMIT:
            # Sub-window straggler after a rebase, or irreducible spread:
            # mark and clamp (the mirror is authoritative for this slot)
            self.grid_overflow.add(slot)
            rel = min(max(rel, 1), CTR_LIMIT - 1)
        return pack_op_id(rel, actor_num)

    @_spanned('fleet_flush')
    def flush(self):
        """Land all pending change buffers on the device: one batched ingest
        and one merge dispatch for the whole fleet."""
        if not self.pending:
            return
        perm = self.actors.insert_many(self.pending_actors)
        if perm is not None:
            if self.exact_device:
                self._remap_reg_actors(perm)
            else:
                self._remap_actors(perm)
            self._remap_seq_actors(perm)
        n_docs = self.n_slots
        per_doc = [[] for _ in range(n_docs)]
        for slot, buffers in self.pending:
            per_doc[slot].extend(buffers)
            self.metrics.changes_ingested += len(buffers)
            self.metrics.bytes_ingested += sum(len(b) for b in buffers)
        self.pending = []
        self.pending_actors = set()
        if self.exact_device:
            self._flush_exact(per_doc, n_docs)
            return
        batch = None
        rebased_touched = any(
            d < n_docs and per_doc[d]
            for d in set(self.ctr_base) | self.grid_overflow)
        hazard = []
        kills = []
        index_rows = []
        if native.available() and not rebased_touched:
            # (rebased slots pack against per-slot bases the native batch
            # does not know about: only flushes touching such slots take
            # the Python decode — the rest of the fleet keeps the C++ path)
            from .ingest import changes_to_op_batch_native
            batch = changes_to_op_batch_native(per_doc, self.keys,
                                               self.actors,
                                               hazard_out=hazard,
                                               kills_out=kills,
                                               index_out=index_rows)
        if batch is None:
            # Sequence ops, non-inline values, or no native codec: Python
            # decode once, routing flat rows to the grid and sequence ops
            # to the SeqState fleet
            self._flush_mixed(per_doc, n_docs)
            return
        self._ensure_capacity(n_docs=n_docs, n_keys=len(self.keys))
        if batch.key_id.shape[0] < self._grid_cap():
            pad = self._grid_cap() - batch.key_id.shape[0]
            batch = type(batch)(*(np.pad(col, ((0, pad), (0, 0)))
                                  for col in batch.tree_flatten()[0]))
        if index_rows:
            self._index_ops(*index_rows[0])
        self._dispatch_grid(batch, kills[0] if kills else None)
        self.metrics.device_ops += int(batch.valid.sum())
        if hazard:
            self._note_grid_batch(*hazard[0])

    def _flush_exact(self, per_doc, n_docs):
        """Exact-device flush: flat rows (with preds) into the multi-value
        register engine, one ordered-scan dispatch. Batches containing
        sequence ops route through the mixed Python parse."""
        from .ingest import changes_to_op_rows
        from .registers import (apply_register_batch_donated,
                                rows_to_register_batch)
        try:
            rows = changes_to_op_rows(per_doc, self.keys, self.actors,
                                      value_table=self.value_table)
        except ValueError:
            self._flush_exact_mixed(per_doc, n_docs)
            return
        self._ensure_reg_capacity(n_docs=n_docs, n_keys=len(self.keys))
        n_cap = self.reg_state.reg.shape[0]
        idx_sel = ((rows['flags'] == 1) & (rows['value'] != TOMBSTONE)) | \
            (rows['flags'] == 2)
        self._index_ops(rows['doc'][idx_sel], rows['key'][idx_sel],
                        rows['packed'][idx_sel])
        batch = rows_to_register_batch(
            rows['doc'], rows['flags'], rows['key'], rows['packed'],
            rows['value'], rows['pred_off'], rows['pred'],
            n_docs=n_cap, d_preds=self.d_preds)
        self.reg_state, _stats = apply_register_batch_donated(
            self.reg_state, self._shard_docs(batch))
        self.metrics.dispatches += 1
        self.metrics.device_ops += len(rows['doc'])

    def _flush_mixed(self, per_doc, n_docs):
        """Python-decode flush splitting flat root-map rows (LWW grid) from
        sequence-object ops (SeqState fleet). per_doc is indexed by slot."""
        from .tensor_doc import OpBatch, pack_op_id
        from .ingest import changes_to_decoded_ops
        from ..common import parse_op_id

        ops_list = list(changes_to_decoded_ops(per_doc))
        # Rebase pre-pass: shift any slot whose incoming grid counters
        # overflow its packing window BEFORE building rows, so one batch
        # packs against one base per slot
        slot_max, slot_min = {}, {}
        for d, op_id, op in ops_list:
            if op['obj'] == '_root' or \
                    op['obj'] not in self.slot_seq.get(d, {}):
                ctr = parse_op_id(op_id)[0]
                if ctr > slot_max.get(d, 0):
                    slot_max[d] = ctr
                if ctr < slot_min.get(d, ctr + 1):
                    slot_min[d] = ctr
        for d, ctr in slot_max.items():
            if ctr - self.ctr_base.get(d, 0) >= CTR_LIMIT:
                self._rebase_slot(d, ctr, floor_ctr=slot_min[d])

        rows = []       # (slot, key_id, packed, value, is_set, is_inc)
        seq_ops = []
        inc_checks = []  # (slot, key_id, pred packed | -1)
        kill_rows = []   # (slot, key_id, pred packed): delete kill lanes
        for d, op_id, op in ops_list:
            ctr, actor = parse_op_id(op_id)
            obj = op['obj']
            action = op['action']
            if obj != '_root' and obj in self.slot_seq.get(d, {}):
                row = self.slot_seq[d][obj]
                packed = pack_op_id(ctr, self.actors.intern(actor))
                seq_ops.append(self._pack_seq_op(row, self.seq_rows[row],
                                                 op, packed, op_id=op_id))
                continue
            packed = self._slot_pack(d, ctr, self.actors.intern(actor))
            # Root keys intern as bare strings (shared with the native
            # path); nested map/table keys as (objectId, key) tuples —
            # the two never collide
            key_id = self.keys.intern(
                op['key'] if obj == '_root' else (obj, op['key']))
            if action in _SEQ_MAKE:
                self._alloc_seq_row(
                    d, op_id, 'text' if action == 'makeText' else 'list')
                rows.append((d, key_id, packed,
                             self._intern_value_boxed(_SeqLink(op_id)),
                             True, False))
            elif action in _MAP_MAKE:
                rows.append((d, key_id, packed,
                             self._intern_value_boxed(_MapLink(
                                 op_id, OBJECT_TYPE[action])),
                             True, False))
            elif action == 'del':
                # Pred-scoped delete (ref new.js:1204-1217): each pred
                # becomes a kill lane; the del writes no winner of its
                # own, so concurrent sets it never saw stay visible. An
                # unpackable pred (outside the slot's counter window,
                # unknown actor) can't kill exactly — the mirror goes
                # authoritative for that slot instead.
                for pr in op.get('pred') or []:
                    try:
                        pctr, pactor = parse_op_id(pr)
                        num = self.actors.intern(pactor)
                    except (KeyError, ValueError):
                        self.grid_overflow.add(d)
                        continue
                    rel = pctr - self.ctr_base.get(d, 0)
                    if rel <= 0 or rel >= CTR_LIMIT:
                        self.grid_overflow.add(d)
                        continue
                    kill_rows.append((d, key_id, pack_op_id(rel, num)))
            elif action == 'inc':
                rows.append((d, key_id, packed, op.get('value', 0),
                             False, True))
                inc_checks.append((d, key_id, self._pack_pred(d, op)))
            else:
                rows.append((d, key_id, packed,
                             self._intern_value(op.get('value')),
                             True, False))
        if rows or kill_rows:
            counts = np.zeros(n_docs, dtype=np.int64)
            for r in rows:
                counts[r[0]] += 1
            width = max(int(counts.max()), 1)
            self._ensure_capacity(n_docs=n_docs, n_keys=len(self.keys))
            n_cap = self._grid_cap()
            shape = (n_cap, width)
            cols = {name: np.zeros(shape, dtype=np.int32)
                    for name in ('key_id', 'packed', 'value')}
            is_set = np.zeros(shape, dtype=bool)
            is_inc = np.zeros(shape, dtype=bool)
            valid = np.zeros(shape, dtype=bool)
            pos = np.zeros(n_docs, dtype=np.int64)
            for (d, k, p, v, s, inc) in rows:
                j = pos[d]
                pos[d] += 1
                cols['key_id'][d, j] = k
                cols['packed'][d, j] = p
                cols['value'][d, j] = v
                is_set[d, j] = s
                is_inc[d, j] = inc
                valid[d, j] = True
            batch = OpBatch(cols['key_id'], cols['packed'], cols['value'],
                            is_set, is_inc, valid)
            # every rows entry is a map-key set/inc/make (dels became
            # kill lanes): feed the dangling-pred oracle
            self._index_ops([r[0] for r in rows], [r[1] for r in rows],
                            [r[2] for r in rows])
            kills = None
            if kill_rows:
                from .ingest import layout_doc_rows
                kd = np.array([k[0] for k in kill_rows], dtype=np.int64)
                kk = np.array([k[1] for k in kill_rows], dtype=np.int64)
                kp = np.array([k[2] for k in kill_rows], dtype=np.int64)
                (kk_arr, kp_arr), _ = layout_doc_rows(
                    kd, n_cap, (kk, kp), (np.int32, np.int32))
                kills = (kk_arr, kp_arr)
            self._dispatch_grid(batch, kills)
            self.metrics.device_ops += len(rows) + len(kill_rows)
            sets = [(r[0], r[1], r[2]) for r in rows if r[4]]
            self._note_grid_batch([s[0] for s in sets], [s[1] for s in sets],
                                  [s[2] for s in sets],
                                  [c[0] for c in inc_checks],
                                  [c[1] for c in inc_checks],
                                  [c[2] for c in inc_checks],
                                  [k[0] for k in kill_rows],
                                  [k[1] for k in kill_rows],
                                  [k[2] for k in kill_rows])
        self._dispatch_seq(seq_ops)

    def _flush_exact_mixed(self, per_doc, n_docs):
        """Mixed-content flush for exact-device mode: flat rows (with pred
        lists) into the register engine, sequence ops into the SeqState
        fleet."""
        from .registers import (apply_register_batch_donated,
                                rows_to_register_batch)
        from .tensor_doc import pack_op_id
        from .ingest import changes_to_decoded_ops
        from ..common import parse_op_id

        def pack(opid):
            ctr, actor = parse_op_id(opid)
            return pack_op_id(ctr, self.actors.intern(actor))

        out_doc, out_key, out_packed, out_val, out_flags = [], [], [], [], []
        pred_off, preds = [0], []
        seq_ops = []
        for d, op_id, op in changes_to_decoded_ops(per_doc):
            obj = op['obj']
            action = op['action']
            packed = pack(op_id)
            if obj != '_root' and obj in self.slot_seq.get(d, {}):
                row = self.slot_seq[d][obj]
                seq_ops.append(self._pack_seq_op(row, self.seq_rows[row],
                                                 op, packed, op_id=op_id))
                continue
            if action in _SEQ_MAKE:
                self._alloc_seq_row(
                    d, op_id, 'text' if action == 'makeText' else 'list')
                val_idx, flags = \
                    self._intern_value_boxed(_SeqLink(op_id)), 1
            elif action in _MAP_MAKE:
                val_idx, flags = self._intern_value_boxed(
                    _MapLink(op_id, OBJECT_TYPE[action])), 1
            elif action == 'del':
                val_idx, flags = TOMBSTONE, 1
            elif action == 'inc':
                val_idx, flags = op.get('value', 0), 2
            else:
                # _intern_typed is THE datatype-boxing rule: uint/counter/
                # timestamp/float64 sets box with their datatype so
                # device-served patches stay exact
                val_idx, flags = self._intern_typed(
                    op.get('value'), op.get('datatype')), 1
            out_doc.append(d)
            out_key.append(self.keys.intern(
                op['key'] if obj == '_root' else (obj, op['key'])))
            out_packed.append(packed)
            out_val.append(val_idx)
            out_flags.append(flags)
            for p in op.get('pred', []):
                preds.append(pack(p))
            pred_off.append(len(preds))
        if out_doc:
            self._ensure_reg_capacity(n_docs=n_docs, n_keys=len(self.keys))
            n_cap = self.reg_state.reg.shape[0]
            doc_a = np.array(out_doc, dtype=np.int64)
            key_a = np.array(out_key, dtype=np.int32)
            packed_a = np.array(out_packed, dtype=np.int32)
            flags_a = np.array(out_flags, dtype=np.uint8)
            val_a = np.array(out_val, dtype=np.int32)
            idx_sel = ((flags_a == 1) & (val_a != TOMBSTONE)) | \
                (flags_a == 2)
            self._index_ops(doc_a[idx_sel], key_a[idx_sel],
                            packed_a[idx_sel])
            batch = rows_to_register_batch(
                doc_a, flags_a, key_a, packed_a, val_a,
                np.array(pred_off, dtype=np.int64),
                np.array(preds, dtype=np.int32),
                n_docs=n_cap, d_preds=self.d_preds)
            self.reg_state, _stats = apply_register_batch_donated(
                self.reg_state, self._shard_docs(batch))
            self.metrics.dispatches += 1
            self.metrics.device_ops += len(out_doc)
        self._dispatch_seq(seq_ops)

    def inexact_slots(self):
        """Slots whose histories fell outside the register engine's exact
        shape (self-conflicts, pred overflow, …) — reads for these route to
        the host mirror."""
        self.flush()
        if self.reg_state is None:
            return set()
        return set(np.flatnonzero(np.asarray(self.reg_state.inexact)))

    # -- reads ----------------------------------------------------------

    def materialize_all(self):
        """Whole-fleet state readback in one device->host transfer:
        slot -> {key: value} with LWW winners, tombstones dropped, and
        counter accumulators added to their base value. In exact-device
        mode the read comes from the multi-value registers instead (winner
        per key from the visible set, per-op counter folds)."""
        self.flush()
        if self.exact_device:
            return self._materialize_registers()
        if self.state is None:
            return [{} for _ in range(self.n_slots)]
        winners = np.asarray(self.state.winners)
        values = np.asarray(self.state.values)
        counters = np.asarray(self.state.counters)
        out = []
        free = set(self.free_slots)
        rendered = None
        for slot in range(self.n_slots):
            if slot in free:
                out.append({})
                continue
            root_cells = {}      # root key -> value
            nested = {}          # objectId -> {key: value}
            any_seq = False
            live = np.flatnonzero(winners[slot, :len(self.keys)])
            for k in live:
                v = int(values[slot, k])
                if v == TOMBSTONE:
                    continue
                value = self.value_table[-v - 2] if v <= -2 else v
                if isinstance(value, _SeqLink):
                    any_seq = True
                elif not isinstance(value, _MapLink):
                    c = int(counters[slot, k])
                    if c and isinstance(value, int) and \
                            not isinstance(value, bool):
                        value += c
                key = self.keys.keys[k]
                if isinstance(key, tuple):
                    nested.setdefault(key[0], {})[key[1]] = value
                else:
                    root_cells[key] = value
            if any_seq and rendered is None:
                rendered = self.render_seq_all()
            out.append({key: self._resolve_value(slot, v, rendered or {},
                                                 nested)
                        for key, v in root_cells.items()})
        return out

    def _resolve_value(self, slot, value, rendered, nested, depth=0):
        """Resolve link values into rendered subtrees with slot context:
        _MapLink -> nested dict assembled from the (objectId, key) grid
        cells; _SeqLink -> the rendered device sequence row, with list
        elements resolved recursively so objects nested inside sequences
        materialize straight from device state (the two-level interning of
        the reference's objectMeta ancestry, ref new.js:1461-1528).
        Unresolved links (device-inexact rows, recursion backstop) stay in
        place, which routes bulk readers to the host mirror."""
        if depth > 128:
            return value
        if isinstance(value, _SeqLink):
            row = self.slot_seq.get(slot, {}).get(value.object_id)
            if row is None:
                return value
            r = rendered.get(row)
            if r is None:
                return value
            if isinstance(r, list):
                return [self._resolve_value(slot, v, rendered, nested,
                                            depth + 1) for v in r]
            return r
        if isinstance(value, _MapLink):
            return {k: self._resolve_value(slot, v, rendered, nested,
                                           depth + 1)
                    for k, v in nested.get(value.object_id, {}).items()}
        return value

    def materialize(self, slot):
        return self.materialize_all()[slot]

    def _materialize_registers(self):
        from .registers import materialize_registers
        if self.reg_state is None:
            return [{} for _ in range(self.n_slots)]
        docs = materialize_registers(self.reg_state, self.keys.keys,
                                     value_table=self.value_table)
        free = set(self.free_slots)
        out = []
        rendered = None
        for slot in range(self.n_slots):
            if slot in free or slot >= len(docs):
                out.append({})
            else:
                # Keys legitimately set to null keep their None value (the
                # LWW grid and host mirror both report them; only absent /
                # fully-deleted keys are omitted)
                root_cells, nested = {}, {}
                any_seq = False
                for k, (v, _conflicts) in docs[slot].items():
                    if isinstance(v, _SeqLink):
                        any_seq = True
                    if isinstance(k, tuple):
                        nested.setdefault(k[0], {})[k[1]] = v
                    else:
                        root_cells[k] = v
                if any_seq and rendered is None:
                    rendered = self.render_seq_all()
                out.append({k: self._resolve_value(slot, v, rendered or {},
                                                   nested)
                            for k, v in root_cells.items()})
        return out

    def conflicts_all(self):
        """Exact-device only: slot -> {key: {packed opId: value}} for every
        key with a multi-value conflict (>1 visible op)."""
        self.flush()
        from .registers import materialize_registers
        if not self.exact_device:
            raise ValueError('conflicts_all requires exact_device=True')
        if self.reg_state is None:
            return [{} for _ in range(self.n_slots)]
        docs = materialize_registers(self.reg_state, self.keys.keys,
                                     value_table=self.value_table)
        return [{k: conflicts for k, (_v, conflicts) in doc.items()
                 if conflicts} for doc in docs[:self.n_slots]]


class _DocCols:
    """Struct-of-arrays doc state for every fleet engine, indexed by slot.

    The turbo commit's per-doc Python loop (heads / max_op / stale /
    binary_doc writes, clock advance, log bookkeeping) is replaced by
    vectorized scatters into these columns; `_FlatEngine` exposes the
    same attributes as properties reading its row, so every slow path
    keeps its exact semantics against ONE source of truth. Grows pow2
    with the fleet's slot count; recycled slots are reset at allocation
    time in one vectorized pass (`reset_rows`).

    Head frontier: ``head_n`` is the head count when the frontier is
    columnar-representable (0 = empty, 1 = ``head32`` holds the raw
    hash, ``head_hex``/``head_obj`` memoize the hex string / list) and
    -1 when the authoritative list lives in ``head_obj`` (multi-head
    docs — the gate falls back to the host hex compare for those).

    Clock: up to ``CLOCK_LANES`` (actor, seq) lanes per doc
    (``ck_actor`` holds ids into the fleet's clock-actor registry,
    -1 = unused), ``ck_n`` the lane count — or -1 when the
    authoritative dict lives in ``ck_obj`` (actor populations past the
    lane width). The gate's per-(doc, actor) base lookup and the
    commit's clock advance are vectorized over the lanes; dict-mode
    docs take the counted fallback loop.

    Change log: per-doc buffer lists stay on the engines (``_log``),
    but turbo commits append LAZILY — each batch parks one
    `_SeamSegs` record on the fleet and bumps ``pend_n``; an engine
    folds its pending segments into ``_log``/``_defer`` only when
    something actually reads its history (`_fold_pending`). ``pend_doc``
    / ``parked_n`` mirror ``_doc_pending`` / ``_parked_n`` so the
    commit computes parked-prefix bases without touching engines."""

    CLOCK_LANES = 4

    __slots__ = ('cap', 'maxop', 'stale', 'bindoc', 'head_n', 'head32',
                 'head_hex', 'head_obj', 'ck_n', 'ck_actor', 'ck_seq',
                 'ck_obj', 'pend_doc', 'parked_n', 'pend_n')

    def __init__(self, cap=64):
        self._alloc(max(int(cap), 1))

    def _alloc(self, cap):
        L = self.CLOCK_LANES
        self.cap = cap
        self.maxop = np.zeros(cap, dtype=np.int64)
        self.stale = np.zeros(cap, dtype=bool)
        self.bindoc = np.full(cap, None, dtype=object)
        self.head_n = np.zeros(cap, dtype=np.int32)
        self.head32 = np.zeros((cap, 32), dtype=np.uint8)
        self.head_hex = np.full(cap, None, dtype=object)
        self.head_obj = np.full(cap, None, dtype=object)
        self.ck_n = np.zeros(cap, dtype=np.int32)
        self.ck_actor = np.full((cap, L), -1, dtype=np.int32)
        self.ck_seq = np.zeros((cap, L), dtype=np.int64)
        self.ck_obj = np.full(cap, None, dtype=object)
        self.pend_doc = np.full(cap, None, dtype=object)
        self.parked_n = np.zeros(cap, dtype=np.int64)
        self.pend_n = np.zeros(cap, dtype=np.int64)

    def ensure(self, n):
        """Grow (pow2) so rows [0, n) are addressable."""
        if n <= self.cap:
            return
        old = {name: getattr(self, name) for name in self.__slots__
               if name != 'cap'}
        k = self.cap
        self._alloc(_pow2(n))
        for name, arr in old.items():
            getattr(self, name)[:k] = arr

    def reset_rows(self, rows):
        """Vectorized per-row defaults (fresh-engine state) — the single
        choke point recycled slots pass through at allocation."""
        if not len(rows):
            return
        rows = np.asarray(rows, dtype=np.int64)
        self.maxop[rows] = 0
        self.stale[rows] = False
        self.bindoc[rows] = None
        self.head_n[rows] = 0
        self.head_hex[rows] = None
        self.head_obj[rows] = None
        self.ck_n[rows] = 0
        self.ck_actor[rows] = -1
        self.ck_seq[rows] = 0
        self.ck_obj[rows] = None
        self.pend_doc[rows] = None
        self.parked_n[rows] = 0
        self.pend_n[rows] = 0


class _SeamSegs:
    """One turbo commit's lazily-folded log/deferred-graph appends: the
    flat buffer list + parse metadata, and per-slot (start, stop, base)
    segments. `_FlatEngine._fold_pending` pops its slot's segment and
    splices `buffers[start:stop]` into the log (and one deferred-graph
    record at `base`) — until then the commit cost for the log is one
    dict build for the whole batch."""

    __slots__ = ('buffers', 'meta', 'rowmap')

    def __init__(self, buffers, meta, rowmap):
        self.buffers = buffers
        self.meta = meta
        self.rowmap = rowmap


class _FlatEngine(HashGraph):
    """Host-side mirror + patch generator for one fleet document.

    The mirror is a real OpSet (the host conformance engine, op_set.py) with
    the causal gate bypassed — this engine's own HashGraph does the gating,
    and ready changes stream into the mirror's op store. Patches, conflict
    sets, counter accumulation, and error conditions are therefore identical
    to the host backend *by construction*: it is the same code. The heavy
    merge state lives on the device; the mirror exists for exact patches,
    reads, and serialization — and after turbo (metadata-only) applies it is
    dropped and rebuilt lazily, like the reference's deferred hash graph
    (new.js:1887-1912)."""

    # 'changes' is inherited as a HashGraph slot but shadowed by the
    # property below; storage lives in _changes (see the property note).
    # The hot doc-state fields (heads/clock/max_op/stale/binary_doc/
    # _doc_pending/_parked_n) live in the fleet's _DocCols columns —
    # shadowed here as properties reading this engine's slot row — so
    # the turbo commit updates a whole batch of docs with vectorized
    # scatters instead of per-engine attribute writes.
    # _doc_hashes/_doc_maxops carry the native extractor's per-change
    # hashes/maxOps after a native materialize (in place of the decoded
    # dicts the Python path keeps in _doc_decoded).
    __slots__ = ('fleet', 'slot', 'mirror', 'seq_objects', 'map_objects',
                 '_doc_decoded', '_log', '_defer', '_doc_hashes',
                 '_doc_maxops')

    def __init__(self, fleet, slot):
        # fleet/slot FIRST: every col-backed property setter below (and
        # in HashGraph.__init__) resolves through them
        self.fleet = fleet
        self.slot = slot
        fleet._engines[slot] = self
        self._log = []
        self._defer = []
        super().__init__()
        self.mirror = None        # OpSet, built lazily on first exact use
        self.binary_doc = None
        self.seq_objects = {}     # objectId -> 'text' | 'list'
        self.map_objects = {}     # objectId -> 'map' | 'table'
        # True after a turbo apply (or failed exact apply): the hash graph
        # and device state are current but the mirror is not; reads rebuild
        self.stale = False
        # Bulk document load (fleet/loader.py) installs device state without
        # touching the change log: the original document chunk parks here and
        # the per-change buffers materialize only when history is actually
        # read (the deferred-hash-graph load of ref new.js:1709-1749)
        self._doc_pending = None

    @classmethod
    def _bulk_new(cls, fleet, slot):
        """Allocation-only constructor for init_docs: __new__ + the same
        attribute sets as __init__, skipping the constructor call chain
        (measurable at 10k+ docs). MUST stay equivalent to
        __init__/HashGraph.__init__ — test_bulk_init_matches_constructor
        pins the attribute-set equivalence. Column-backed fields
        (heads/clock/max_op/stale/binary_doc/_doc_pending) are NOT set
        here: the caller's alloc_slots already reset their rows in one
        vectorized pass (`_DocCols.reset_rows`)."""
        e = cls.__new__(cls)
        e.fleet = fleet
        e.slot = slot
        fleet._engines[slot] = e
        # HashGraph.__init__ body (column-backed fields via reset_rows)
        e.actor_ids = []
        e.queue = []
        e._log = []
        e.changes_meta = []
        e.change_index_by_hash = {}
        e.dependencies_by_hash = {}
        e.dependents_by_hash = {}
        e.hashes_by_actor = {}
        e._defer = []
        # _FlatEngine.__init__ body
        e.mirror = None
        e.seq_objects = {}
        e.map_objects = {}
        return e

    # -- column-backed doc state (struct-of-arrays; see _DocCols) -------

    @property
    def max_op(self):
        return int(self.fleet.doc_cols.maxop[self.slot])

    @max_op.setter
    def max_op(self, v):
        self.fleet.doc_cols.maxop[self.slot] = v

    @property
    def stale(self):
        return bool(self.fleet.doc_cols.stale[self.slot])

    @stale.setter
    def stale(self, v):
        self.fleet.doc_cols.stale[self.slot] = v

    @property
    def binary_doc(self):
        return self.fleet.doc_cols.bindoc[self.slot]

    @binary_doc.setter
    def binary_doc(self, v):
        self.fleet.doc_cols.bindoc[self.slot] = v

    @property
    def _doc_pending(self):
        return self.fleet.doc_cols.pend_doc[self.slot]

    @_doc_pending.setter
    def _doc_pending(self, v):
        self.fleet.doc_cols.pend_doc[self.slot] = v

    @property
    def _parked_n(self):
        return int(self.fleet.doc_cols.parked_n[self.slot])

    @_parked_n.setter
    def _parked_n(self, v):
        self.fleet.doc_cols.parked_n[self.slot] = v

    @property
    def heads(self):
        """The head frontier as the usual sorted-hex list. Materialized
        lazily from the binary column (memoized per generation); treat
        the returned list as read-only — replace it via assignment, as
        every existing writer does."""
        cols = self.fleet.doc_cols
        r = self.slot
        n = cols.head_n[r]
        if n == -1:
            return cols.head_obj[r]
        memo = cols.head_obj[r]
        if memo is None:
            if n == 0:
                memo = []
            else:
                hx = cols.head_hex[r]
                if hx is None:
                    hx = cols.head32[r].tobytes().hex()
                    cols.head_hex[r] = hx
                memo = [hx]
            cols.head_obj[r] = memo
        return memo

    @heads.setter
    def heads(self, v):
        cols = self.fleet.doc_cols
        r = self.slot
        if type(v) is not list:
            v = list(v)
        if len(v) == 1 and len(v[0]) == 64:
            try:
                cols.head32[r] = np.frombuffer(bytes.fromhex(v[0]),
                                               dtype=np.uint8)
            except ValueError:
                cols.head_n[r] = -1       # not a hex hash: attr-mode
                cols.head_obj[r] = v
                return
            cols.head_n[r] = 1
            cols.head_hex[r] = v[0]
            cols.head_obj[r] = v
        elif not v:
            cols.head_n[r] = 0
            cols.head_obj[r] = v
        else:
            cols.head_n[r] = -1           # multi-head: attr-mode
            cols.head_obj[r] = v

    @property
    def clock(self):
        """The vector clock as a dict. Lane-mode rows materialize a
        FRESH dict per read — mutate via whole-dict assignment (the
        pattern every writer uses), never in place."""
        cols = self.fleet.doc_cols
        r = self.slot
        n = cols.ck_n[r]
        if n == -1:
            return cols.ck_obj[r]
        if n == 0:
            return {}
        names = self.fleet._ck_names
        ck_actor = cols.ck_actor
        ck_seq = cols.ck_seq
        return {names[ck_actor[r, l]]: int(ck_seq[r, l]) for l in range(n)}

    @clock.setter
    def clock(self, d):
        cols = self.fleet.doc_cols
        r = self.slot
        n = len(d)
        if 0 < n <= cols.CLOCK_LANES:
            reg = self.fleet._ck_reg
            names = self.fleet._ck_names
            for l, (a, s) in enumerate(d.items()):
                aid = reg.get(a)
                if aid is None:
                    aid = len(names)
                    reg[a] = aid
                    names.append(a)
                cols.ck_actor[r, l] = aid
                cols.ck_seq[r, l] = s
            # clear the tail lanes: the gate/commit lane scans read all
            # CLOCK_LANES, so a SHRINKING assignment (e.g. restore_all
            # rolling back a failed drain) must not leave a stale lane
            # that would hand the gate a phantom seq base
            cols.ck_actor[r, n:] = -1
            cols.ck_n[r] = n
            cols.ck_obj[r] = None
        elif n == 0:
            cols.ck_actor[r, :] = -1
            cols.ck_n[r] = 0
            cols.ck_obj[r] = None
        else:
            cols.ck_n[r] = -1
            cols.ck_obj[r] = d

    # -- lazily-folded change log (see _SeamSegs) -----------------------

    def _fold_pending(self):
        """Splice this doc's pending turbo-commit segments into the real
        log + deferred-graph records (commit order preserved). Runs only
        when something genuinely reads or extends history — the hot
        write path never pays it."""
        fleet = self.fleet
        r = self.slot
        if not fleet.doc_cols.pend_n[r]:
            return
        log = self._log
        defer = self._defer
        compact = False
        for seg in fleet._pend_seams:
            ent = seg.rowmap.pop(r, None)
            if ent is None:
                continue
            start, stop, base = ent
            log.extend(seg.buffers[start:stop])
            defer.append((base, seg.meta, range(start, stop)))
            if not seg.rowmap:
                compact = True
        fleet.doc_cols.pend_n[r] = 0
        if compact:
            fleet._pend_seams = [s for s in fleet._pend_seams if s.rowmap]

    @property
    def _changes(self):
        if self.fleet.doc_cols.pend_n[self.slot]:
            self._fold_pending()
        return self._log

    @_changes.setter
    def _changes(self, value):
        # fold-then-replace: an overwrite must never silently drop
        # pending accepted appends (every real caller reads first, so
        # the fold is a no-op there; this is belt-and-braces)
        if self.fleet.doc_cols.pend_n[self.slot]:
            self._fold_pending()
        self._log = value

    @property
    def _deferred(self):
        if self.fleet.doc_cols.pend_n[self.slot]:
            self._fold_pending()
        return self._defer

    @_deferred.setter
    def _deferred(self, value):
        self._defer = value

    # The change log is a property so a bulk-loaded document's history can
    # stay unmaterialized until something genuinely reads or extends it
    # (sync, save-after-edit, mirror rebuilds, clone, further applies).
    @property
    def changes(self):
        if self._doc_pending is not None:
            self._materialize_doc()
        return self._changes

    @changes.setter
    def changes(self, value):
        self._changes = value

    def _materialize_doc(self):
        """Expand the parked document chunk into the real change log
        prefix (runs at most once per parked generation, and only when
        history is genuinely read). The native extractor (codec.cpp
        am_extract_changes) splits the chunk into canonical per-change
        buffers + hashes directly — byte-identical to the Python
        decode_document + encode_change round trip it replaces, ~5-10x
        faster (the delta+main materialize kernel); docs outside the
        native subset fall back to the Python path transparently. Changes
        appended while parked (the delta tail — see apply_changes_docs'
        commit loop) stay in _changes and the extracted prefix splices in
        front of them. Attributed three ways: a `doc_materialize` span,
        `metrics.seconds['doc_materializations']`, and the
        `doc_materialize_s` histogram."""
        chunk = self._doc_pending
        if chunk is None:
            return
        self._doc_pending = None
        metrics = self.fleet.metrics
        metrics.doc_materializations += 1
        start = time.perf_counter()
        tail = self._changes
        used_native = False
        with _span('doc_materialize', slot=self.slot,
                   durable_id=getattr(self, '_dur_id', None),
                   chunk_bytes=len(chunk)):
            extracted = native.extract_changes([chunk]) \
                if native.available() else None
            if extracted is not None and extracted[0] is not None:
                buffers, hashes, max_ops = extracted[0]
                self._changes = buffers + tail
                self._doc_decoded = None
                self._doc_hashes = hashes
                self._doc_maxops = max_ops
                used_native = True
            else:
                from ..columnar import decode_document, encode_change
                decoded = decode_document(chunk)
                self._changes = [encode_change(ch) for ch in decoded] + tail
                self._doc_decoded = decoded
        elapsed = time.perf_counter() - start
        metrics.seconds['doc_materializations'] = \
            metrics.seconds.get('doc_materializations', 0.0) + elapsed
        if used_native:
            metrics.seconds['doc_materializations_native'] = \
                metrics.seconds.get('doc_materializations_native', 0.0) + \
                elapsed
        _hist.record_value('doc_materialize_s', elapsed, scale=1e9,
                           unit='s')

    def _install_parked_chunk(self, chunk, n_changes):
        """THE parked form, in one place (loader bulk-load and park_docs
        both install it): host history collapses to the document chunk —
        change log empty, graph dicts empty, one full-range deferred
        record resolving through the chunk, mirrors and any previously
        decoded history dropped. Causal state (heads/clock/max_op/
        actor_ids) is NOT touched; callers own it."""
        from .loader import _DocDeferredBatch
        ix = self.fleet._hash_index
        if ix is not None:
            # the slot's history representation is being replaced
            # wholesale; drop its membership space (a later sync round
            # re-registers and backfills from the chunk's hash lanes)
            ix.drop_slots([self.slot])
        self._changes = []
        self._doc_pending = chunk
        self._doc_decoded = None
        self._doc_hashes = None
        self._doc_maxops = None
        self._parked_n = n_changes
        self.binary_doc = chunk
        self.changes_meta = []
        self.change_index_by_hash = {}
        self.dependencies_by_hash = {}
        self.dependents_by_hash = {}
        self.hashes_by_actor = {}
        self._deferred = [(0, _DocDeferredBatch(self), range(n_changes))] \
            if n_changes else []
        self.mirror = None
        self.stale = True

    def _doc_resolve(self, i):
        """(hash, deps, actor, meta) for _ensure_graph over a bulk-loaded
        document's i-th change. After a NATIVE materialize the decoded
        dicts don't exist; the hash/maxOp come from the extractor's
        arrays and the rest from a header-only decode of the canonical
        change buffer (cheap: no op columns are touched)."""
        self._materialize_doc()
        if self._doc_decoded is None:
            # header + raw column slicing only — no op decode (and
            # extraBytes, which the header-only decode_change_meta
            # doesn't reach, survives into changes_meta)
            from ..columnar import decode_change_columns
            m = decode_change_columns(self._changes[i])
            meta = {
                'actor': m['actor'], 'seq': m['seq'],
                'maxOp': self._doc_maxops[i],
                'time': m.get('time', 0),
                'message': m.get('message') or '',
                'deps': list(m['deps']),
                'extraBytes': m.get('extraBytes'),
            }
            return self._doc_hashes[i], meta['deps'], meta['actor'], meta
        ch = self._doc_decoded[i]
        meta = {
            'actor': ch['actor'], 'seq': ch['seq'],
            'maxOp': ch['startOp'] + len(ch['ops']) - 1,
            'time': ch.get('time', 0), 'message': ch.get('message') or '',
            'deps': list(ch['deps']), 'extraBytes': ch.get('extraBytes'),
        }
        return ch['hash'], meta['deps'], meta['actor'], meta

    @_spanned('mirror_rebuild')
    def _rebuild_mirror(self):
        """Replay the committed log into a fresh OpSet, bypassing the causal
        gate (the log is already in applied order, so no per-change SHA-256
        or dep checks are needed)."""
        mirror = OpSet()
        for buffer in self.changes:
            change = decode_change(bytes(buffer))
            mirror._apply_decoded_change(
                {'_root': {'objectId': '_root', 'type': 'map', 'props': {}}},
                change, set())
        self.mirror = mirror

    def _ensure_mirror(self):
        """Rebuild the mirror after turbo applies. Raises if the committed
        log contains a change turbo could not validate (dangling pred) — see
        apply_changes_docs' trust note."""
        if self.mirror is None and not self.stale and not self.changes:
            self.mirror = OpSet()
            return
        if not self.stale and self.mirror is not None:
            return
        self.fleet.metrics.mirror_rebuilds += 1
        self._rebuild_mirror()
        self.seq_objects = {oid: obj.type
                            for oid, obj in self.mirror.objects.items()
                            if oid != '_root' and obj.is_seq}
        self.map_objects = {oid: obj.type
                            for oid, obj in self.mirror.objects.items()
                            if oid != '_root' and not obj.is_seq}
        # Turbo queue entries carry only metadata; re-decode so the exact
        # drain path can apply their ops when deps arrive
        self.queue = [dict(decode_change(bytes(c['buffer'])), buffer=c['buffer'])
                      if not isinstance(c.get('ops'), list) else c
                      for c in self.queue]
        self.stale = False

    # -- change application --------------------------------------------

    def _ensure_graph(self):
        if self._deferred:
            self.fleet.metrics.graph_builds += 1
        super()._ensure_graph()

    # Frontier-index maintenance (fleet/hashindex.py): every path that
    # lands an APPLIED change on this engine stages its hash — the
    # general/exact paths per change here, the turbo fast path as one
    # vectorized batch in the commit. One attribute check when no index
    # exists.

    def _record_applied(self, change):
        super()._record_applied(change)
        ix = self.fleet._hash_index
        if ix is not None:
            ix.stage_one(self.slot, change['hash'])

    def _defer_record(self, change):
        super()._defer_record(change)
        ix = self.fleet._hash_index
        if ix is not None:
            ix.stage_one(self.slot, change['hash'])

    def probe_hashes(self, hashes):
        """Exact membership flags for `hashes` from the fleet's frontier
        index, or None when this doc has no WARM index space (the
        single-doc protocol path must not pay a surprise history
        backfill — the batched driver registers; until then the caller's
        dict path serves) or routing is disabled
        (AUTOMERGE_TPU_FRONTIER_INDEX=0 must pin the classic path on
        EVERY consumer, not just the batched driver)."""
        from .hashindex import frontier_enabled
        ix = self.fleet._hash_index
        if ix is None or not ix.registered(self) or not frontier_enabled():
            return None
        return ix.probe_pairs([self] * len(hashes), list(hashes))

    def apply_changes(self, change_buffers, is_local=False):
        self.fleet.metrics.exact_calls += 1
        decoded = decode_change_buffers(change_buffers)

        # Pre-scan for the supported subset before mutating anything, so
        # promotion to the host engine happens from an untouched state.
        # `made_seq`/`made_map` track objects created earlier in the same
        # batch so ops on them pass the scan.
        made_seq = set(self.seq_objects)
        made_map = set(self.map_objects)
        for change in decoded:
            start, actor = change['startOp'], change['actor']
            for i, op in enumerate(change['ops']):
                self._check_supported(op, made_seq, made_map, ctr=start + i)
                if op['obj'] == '_root' or op['obj'] in made_map or \
                        op['obj'] in made_seq:
                    if op['action'] in _SEQ_MAKE:
                        made_seq.add(f'{start + i}@{actor}')
                    elif op['action'] in _MAP_MAKE:
                        made_map.add(f'{start + i}@{actor}')
        self._ensure_mirror()

        from ..backend.op_set import empty_object_patch
        patches = {'_root': empty_object_patch('_root', 'map')}
        object_ids = set()
        backup = (dict(self.clock), list(self.heads), list(self.queue))
        try:
            all_applied, queue = self._drain_queue(
                decoded,
                lambda change: self.mirror._apply_decoded_change(
                    patches, change, object_ids))
        except Exception:
            self._rollback(backup)
            raise
        self.mirror._setup_patches(patches, object_ids)

        for change in all_applied:
            self._record_applied(change)
            for i, op in enumerate(change['ops']):
                if op['obj'] == '_root' or op['obj'] in self.map_objects \
                        or op['obj'] in self.seq_objects:
                    oid = f"{change['startOp'] + i}@{change['actor']}"
                    if op['action'] in _SEQ_MAKE:
                        self.seq_objects[oid] = OBJECT_TYPE[op['action']]
                    elif op['action'] in _MAP_MAKE:
                        self.map_objects[oid] = OBJECT_TYPE[op['action']]
        self.queue = queue
        self.max_op = max(self.max_op, self.mirror.max_op)
        self.binary_doc = None
        self.fleet.enqueue(self.slot, [c['buffer'] for c in all_applied],
                           [c['actor'] for c in all_applied])

        patch = {'maxOp': self.max_op, 'clock': dict(self.clock),
                 'deps': list(self.heads), 'pendingChanges': len(self.queue),
                 'diffs': patches['_root']}
        if is_local and len(decoded) == 1:
            patch['actor'] = decoded[0]['actor']
            patch['seq'] = decoded[0]['seq']
        return patch

    def _check_supported(self, op, made_seq, made_map, ctr=None):
        """Fleet-resident subset: keyed set/del/inc plus nested
        makeMap/makeTable/makeText/makeList on the root map or any
        registered map/table object (map trees intern as (objectId, key)
        grid columns), and element ops on registered sequence objects.
        Anything else (objects inside sequences, link ops) promotes to the
        host engine.

        Counter headroom: the LWW grid rebases its packing window per slot
        (unbounded history), but the sequence rows and the exact-device
        register engine pack raw counters — ops at or past CTR_LIMIT on
        those paths promote cleanly here, BEFORE any state mutates."""
        action = op['action']
        if action == 'link':
            # Reserved wire-table action the reference never applies
            # (new.js:893 TODO). Reject here in the pre-scan — before the
            # _Unsupported promotion path — so a bogus change cannot cost
            # the document its device slot (see PARITY.md).
            raise ValueError('link operations are not supported')
        if op['obj'] == '_root' or op['obj'] in made_map:
            if op.get('insert') or op.get('key') is None:
                raise _Unsupported()
            if ctr is not None and ctr >= CTR_LIMIT and \
                    (self.fleet.exact_device or action in _SEQ_MAKE):
                raise _Unsupported()
            if action in _SEQ_MAKE or action in _MAP_MAKE:
                return
            if action not in _FLAT_ACTIONS:
                raise _Unsupported()
            if action == 'inc':
                # The device value column carries inc deltas inline as int32
                delta = op.get('value', 0)
                if not isinstance(delta, int) or isinstance(delta, bool) or \
                        not -(1 << 31) < delta < (1 << 31):
                    raise _Unsupported()
            return
        if op['obj'] not in made_seq:
            raise _Unsupported()
        if action in _SEQ_MAKE or action in _MAP_MAKE:
            # Nested object as a sequence element: the element value links
            # to the child, which interns like any registered object
            if op.get('key') is not None:
                raise _Unsupported()
        elif action not in ('set', 'del', 'inc') or op.get('key') is not None:
            raise _Unsupported()
        if ctr is not None and ctr >= CTR_LIMIT:
            raise _Unsupported()      # sequence rows pack raw counters

    def _rollback(self, backup):
        """Restore gate state; the partially-mutated mirror rebuilds lazily
        from the (unmodified) committed log. The device never saw the failed
        call; enqueue happens only on success."""
        self.clock, self.heads, self.queue = backup
        self.stale = True

    # -- reads ----------------------------------------------------------

    def get_patch(self):
        diffs = self._register_patch_diffs()
        if diffs is not None:
            return {'maxOp': self.max_op, 'clock': dict(self.clock),
                    'deps': list(self.heads),
                    'pendingChanges': len(self.queue), 'diffs': diffs}
        self._ensure_mirror()
        patch = self.mirror.get_patch()
        patch['maxOp'] = max(self.max_op, self.mirror.max_op)
        patch['clock'] = dict(self.clock)
        patch['deps'] = list(self.heads)
        patch['pendingChanges'] = len(self.queue)
        return patch

    def _register_patch_diffs(self):
        """Whole-doc patch diffs straight from the device state (exact
        mode; round-2 VERDICT item 10, extended round 3 to map trees and
        sequences) — no mirror rebuild. The device's visible register
        lanes become pseudo op rows fed through the host engine's OWN
        patch machinery (`op_set._update_patch_property`, ref
        new.js:884-1040 / documentPatch :1604-1635), so the patch grammar
        is identical by construction. Returns None when the mirror must
        serve instead: non-register fleets, device-inexact rows, or
        payloads the device lanes can't represent."""
        fleet = self.fleet
        if not fleet.exact_device:
            return None
        fleet.flush()
        empty = {'objectId': '_root', 'type': 'map', 'props': {}}
        # emptiness check must not touch the changes property: on a
        # bulk-loaded doc that would materialize the whole parked chunk
        # just to answer a question the device state answers anyway
        if self._doc_pending is None and not self._changes:
            return empty
        if fleet.reg_state is None:
            return empty
        import numpy as _np
        if self.slot >= fleet.reg_state.inexact.shape[0]:
            # Past the register state's doc capacity: a clamped device
            # gather would serve another doc's row — mirror serves instead
            return None
        if bool(_np.asarray(fleet.reg_state.inexact[self.slot])):
            return None
        try:
            return self._device_patch_diffs()
        except _Unsupported:
            return None

    def _device_patch_diffs(self):
        """Assemble the whole-doc diff tree from device register/sequence
        lanes via the host patch machinery. Raises _Unsupported for any
        shape the lanes can't serve exactly (callers use the mirror)."""
        import jax
        import numpy as _np
        from ..backend.op_set import OpSet, ObjState, _utf16_key
        from ..common import lamport_key
        from .registers import _patch_leaf
        from .tensor_doc import unpack_op_id
        fleet = self.fleet
        rs = fleet.reg_state
        slot = self.slot
        reg = _np.asarray(jax.device_get(rs.reg[slot]))
        killed = _np.asarray(jax.device_get(rs.killed[slot]))
        value = _np.asarray(jax.device_get(rs.value[slot]))
        counter = _np.asarray(jax.device_get(rs.counter[slot]))
        visible = (reg != 0) & ~killed

        def op_id_str(packed):
            ctr, num = unpack_op_id(int(packed))
            return f'{ctr}@{fleet.actors.actors[num]}'

        def lane_row(packed, raw, cnt, base, char=None):
            """Pseudo op row for one live register lane. `char` carries an
            inline text code point already decoded (so reads never intern
            into the shared value table)."""
            row = dict(base)
            row['id'] = op_id_str(packed)
            row['succ'] = []
            if char is not None:
                row['action'] = 'set'
                row['value'] = char
                return row, None
            boxed = fleet.value_table[-raw - 2] if raw <= -2 else raw
            if isinstance(boxed, _SeqLink):
                oid = boxed.object_id
                row['action'] = 'makeText' \
                    if self.seq_objects.get(oid) == 'text' else 'makeList'
                return row, oid
            if isinstance(boxed, _MapLink):
                row['action'] = 'makeTable' if boxed.kind == 'table' \
                    else 'makeMap'
                return row, boxed.object_id
            leaf = _patch_leaf(int(raw), int(cnt), fleet.value_table)
            if leaf is None:
                raise _Unsupported('payload outside device lanes')
            row['action'] = 'set'
            row['value'] = leaf['value']
            if 'datatype' in leaf:
                row['datatype'] = leaf['datatype']
            return row, None

        # group this doc's live cells by (object, key)
        cells = {}                  # object_id -> {key: [(packed, lane)]}
        for k in _np.flatnonzero(visible.any(axis=-1)):
            key = fleet.keys.keys[int(k)]
            obj, key_str = key if isinstance(key, tuple) else ('_root', key)
            lanes = sorted((int(reg[k, s]), int(s))
                           for s in _np.flatnonzero(visible[k]))
            cells.setdefault(obj, {})[key_str] = [(p, s, int(k))
                                                  for p, s in lanes]
        # cells are fleet-global: keep only THIS doc's objects (root keys
        # are per-slot because register rows are per-slot; nested keys are
        # (oid, key) and oids are globally unique)
        mine = {'_root'} | set(self.map_objects) | set(self.seq_objects)
        cells = {obj: kv for obj, kv in cells.items() if obj in mine}

        # reachability from root through live make lanes
        shim = OpSet()
        shim.objects = {'_root': ObjState('map')}
        for oid, typ in self.map_objects.items():
            shim.objects[oid] = ObjState(typ)
        for oid, typ in self.seq_objects.items():
            shim.objects[oid] = ObjState(typ)

        seq_rows_data = self._fetch_seq_rows()
        object_order = ['_root'] + sorted(
            set(self.map_objects) | set(self.seq_objects), key=lamport_key)
        from ..backend.op_set import root_meta
        object_meta = {'_root': root_meta()}
        patches = {'_root': {'objectId': '_root', 'type': 'map',
                             'props': {}}}
        for object_id in object_order:
            obj = shim.objects[object_id]
            prop_state = {}
            if obj.is_seq:
                if object_id not in object_meta:
                    continue          # unreachable (overwritten) object
                data = seq_rows_data.get(object_id)
                if data is None:
                    raise _Unsupported('sequence rows unavailable')
                list_index = 0
                for elem_packed, elem_lanes in data:
                    elem_str = op_id_str(elem_packed)
                    vis_elem = False
                    for packed, raw, cnt, char, n_incs, dead in elem_lanes:
                        # object elements (rows-in-lists) flow through the
                        # same make-row path the map cells use: the child
                        # registers in object_meta and its own rows link
                        # in when its (later) object_id is processed
                        base = {'insert': True} if packed == elem_packed \
                            else {'insert': False, 'elemId': elem_str}
                        if n_incs == 0:
                            row, _child = lane_row(packed, raw, cnt, base,
                                                   char)
                            shim._update_patch_property(
                                patches, object_id, row, prop_state,
                                list_index, 0, object_meta, whole_doc=True)
                        else:
                            # Replay the reference's counterStates walk
                            # (new.js:936-965): the counter set with its
                            # inc succs, then the incs — the edit shape
                            # (insert for one consumed inc, the transient
                            # remove->update for two or more, the phantom
                            # remove of a deleted inc'd counter) falls
                            # out of the same ported machinery. A dead
                            # lane gets an extra never-consumed del succ
                            # so its counter state never completes.
                            opid = op_id_str(packed)
                            base_row, _child = lane_row(packed, raw, 0,
                                                        base, char)
                            if base_row.get('datatype') != 'counter':
                                raise _Unsupported('inc on non-counter')
                            succs = [f'{opid}+inc{i}'
                                     for i in range(n_incs)]
                            all_succs = succs + ([f'{opid}+del'] if dead
                                                 else [])
                            base_row['succ'] = all_succs
                            shim._update_patch_property(
                                patches, object_id, base_row, prop_state,
                                list_index, len(all_succs), object_meta,
                                whole_doc=True)
                            for i, sid in enumerate(succs):
                                inc_row = {
                                    'id': sid, 'succ': [], 'action': 'inc',
                                    'insert': False, 'elemId': elem_str,
                                    'value': cnt if i == n_incs - 1 else 0,
                                }
                                shim._update_patch_property(
                                    patches, object_id, inc_row,
                                    prop_state, list_index, 0, object_meta,
                                    whole_doc=True)
                        # a dead inc'd counter lane still counts: its inc
                        # rows are succ-free, so the host walk treats the
                        # element as visible and bumps the index past the
                        # phantom remove
                        vis_elem = True
                    if vis_elem:
                        list_index += 1
            else:
                if object_id != '_root' and object_id not in object_meta:
                    continue          # unreachable (overwritten) object
                for key_str in sorted(cells.get(object_id, {}),
                                      key=_utf16_key):
                    for packed, s, k in cells[object_id][key_str]:
                        row, _child = lane_row(packed, int(value[k, s]),
                                               int(counter[k, s]),
                                               {'key': key_str,
                                                'insert': False})
                        shim._update_patch_property(
                            patches, object_id, row, prop_state, 0, 0,
                            object_meta, whole_doc=True)
        return patches['_root']

    def _fetch_seq_rows(self):
        """Read this doc's sequence rows off the device: {objectId:
        [(elem packed id, [(packed, raw, counter_sum, char, n_incs,
        dead)])] in RGA order}. `char` is the decoded inline text code
        point (None for table-boxed payloads — reads never write the
        shared value table); `n_incs` is the consumed-inc count (0, 1,
        or 2 meaning "two or more"); `dead` marks killed inc'd counter
        lanes, which ride along because the reference's dangling inc
        rows still shape the whole-doc patch. Raises _Unsupported when
        a row is device-inexact."""
        import jax
        import numpy as _np
        from .sequence import HEAD, END, SLOT0
        fleet = self.fleet
        rows_map = fleet.slot_seq.get(self.slot, {})
        out = {}
        if not rows_map:
            return out
        for oid, row in rows_map.items():
            place = fleet.seq_place[row]
            if place is None:
                out[oid] = []          # allocated but never written: empty
                continue
            st = fleet.seq_pools.state(place[0])
            idx = place[1]
            if bool(_np.asarray(st.inexact[idx])):
                raise _Unsupported('sequence row inexact')
            # one transfer for all six arrays (not six round-trips)
            elem_id, nxt, reg, killed, val, cnt = (
                _np.asarray(x) for x in jax.device_get(
                    (st.elem_id[idx], st.nxt[idx], st.reg[idx],
                     st.killed[idx], st.val[idx], st.counter[idx])))
            is_text = self.seq_objects.get(oid) == 'text'
            elems = []
            node = int(nxt[HEAD])
            hops = 0
            limit = elem_id.shape[0]
            while node != END and hops <= limit:
                lanes = []
                live = (reg[node] != 0) & ~killed[node]
                # Dead lanes whose op consumed incs still shape the
                # whole-doc patch: the reference's dangling inc rows emit
                # a phantom remove (converted to update by a surviving
                # lane), so they ride along marked dead
                dead_incd = (reg[node] != 0) & killed[node] & \
                    ((cnt[node] & 3) != 0)
                for s in _np.flatnonzero(live | dead_incd):
                    raw = int(val[node, s])
                    char = chr(raw) if is_text and raw >= 0 else None
                    # counter lanes bit-pack (sum << 2) | count-bits
                    # (0, 1, or 3; 3 = two or more); the count rides along
                    # so the patch walk can replay the reference's
                    # counterStates edit shapes
                    bits = int(cnt[node, s]) & 3
                    lanes.append((int(reg[node, s]), raw,
                                  int(cnt[node, s]) >> 2, char,
                                  2 if bits == 3 else bits,
                                  bool(dead_incd[s])))
                lanes.sort(key=lambda lane: lane[0])
                elems.append((int(elem_id[node]), lanes))
                node = int(nxt[node])
                hops += 1
            if hops > limit:
                raise _Unsupported('corrupt sequence chain')
            out[oid] = elems
        return out

    def materialize(self):
        """Exact current {key: value} view (LWW winner per key,
        ascending-Lamport max, frontend/apply_patch.js:33-42); sequence
        values render to str (text) / list. get_patch serves from the
        device registers when it can and rebuilds the mirror itself when it
        can't, so no mirror work happens here."""
        from ..common import lamport_key
        doc = {}
        for key, candidates in self.get_patch()['diffs'].get('props',
                                                             {}).items():
            if candidates:
                winner = max(candidates.keys(), key=lamport_key)
                doc[key] = _leaf_value(candidates[winner])
        return doc

    def save(self):
        """Canonical document container serialization. The native builder
        (codec.cpp am_build_document) parses the change log, replays it into
        a succ-annotated op store, and emits the chunk entirely in C++ — no
        host mirror; histories it can't represent (link/child ops, unknown
        columns) fall back to the mirror path, which is the same bytes by
        construction (differential-tested)."""
        if self.binary_doc is None:
            if native.available():
                built = native.build_document(
                    [bytes(b) for b in self.changes], self.heads)
                if built is not None:
                    self.binary_doc = built
                    return self.binary_doc
            self._ensure_mirror()
            self._ensure_graph()
            m = self.mirror
            m.changes = self.changes
            m.changes_meta = self.changes_meta
            m.change_index_by_hash = self.change_index_by_hash
            m.heads = list(self.heads)
            m.clock = dict(self.clock)
            m.binary_doc = None
            self.binary_doc = m.save()
        return self.binary_doc

    def clone_engine(self):
        self._ensure_mirror()
        self._ensure_graph()
        other = _FlatEngine(self.fleet, self.fleet.clone_slot(self.slot))
        for field in ('max_op', 'actor_ids', 'heads', 'clock', 'queue',
                      'changes', 'changes_meta', 'change_index_by_hash',
                      'dependencies_by_hash', 'dependents_by_hash',
                      'hashes_by_actor', 'mirror', 'seq_objects',
                      'map_objects'):
            setattr(other, field, copy.deepcopy(getattr(self, field)))
        return other


class FleetDoc:
    """A Backend-contract document handle routed through the device fleet.

    Wraps either a _FlatEngine (fleet mode) or, after promotion, a host
    OpSet. All HashGraph state is exposed as properties so handles stay
    valid across promotion, and so host-backed and fleet-backed documents
    interoperate (merge, sync) freely."""

    # _dur_id: durable doc id assigned by an attached ChangeJournal
    # (fleet/durability.py); set lazily, survives promotion and slot reuse
    __slots__ = ('fleet', '_impl', '_dur_id')

    def __init__(self, fleet, impl=None):
        self.fleet = fleet
        self._impl = impl if impl is not None else \
            _FlatEngine(fleet, fleet.alloc_slot())

    # HashGraph state passthrough (valid across promotion)
    heads = property(lambda self: self._impl.heads)
    clock = property(lambda self: self._impl.clock)
    queue = property(lambda self: self._impl.queue)
    changes = property(lambda self: self._impl.changes)
    max_op = property(lambda self: self._impl.max_op)
    actor_ids = property(lambda self: self._impl.actor_ids)

    def _graph_dict(name):
        # The index dicts materialize lazily after turbo applies
        def get(self):
            self._impl._ensure_graph()
            return getattr(self._impl, name)
        return property(get)

    changes_meta = _graph_dict('changes_meta')
    change_index_by_hash = _graph_dict('change_index_by_hash')
    dependencies_by_hash = _graph_dict('dependencies_by_hash')
    dependents_by_hash = _graph_dict('dependents_by_hash')
    hashes_by_actor = _graph_dict('hashes_by_actor')
    del _graph_dict

    @property
    def is_fleet(self):
        return isinstance(self._impl, _FlatEngine)

    def promote(self):
        """Replay this document into the host OpSet engine and delegate all
        further calls to it (the escape hatch for non-flat documents)."""
        if not self.is_fleet:
            return self._impl
        impl = self._impl
        impl.fleet.metrics.promotions += 1
        ops = OpSet()
        if impl.changes:
            ops.apply_changes([bytes(b) for b in impl.changes])
        for change in impl.queue:
            ops.apply_changes([change['buffer']])
        self.fleet.free_slot(impl.slot)
        self._impl = ops
        return ops

    def apply_changes(self, change_buffers, is_local=False):
        change_buffers = list(change_buffers)
        if self.is_fleet:
            try:
                patch = self._impl.apply_changes(change_buffers, is_local)
                self._journal_accepted(change_buffers)
                return patch
            except _Unsupported:
                self.promote()
        patch = self._impl.apply_changes(change_buffers, is_local)
        self._journal_accepted(change_buffers)
        return patch

    def _journal_accepted(self, buffers):
        """Durability seam hook: record the buffers this call accepted
        (applied or causally queued — replay reproduces either) in the
        fleet's attached change journal. Rejected calls raise before
        reaching here, so the journal never holds refused bytes."""
        journal = self.fleet.journal
        if journal is not None and buffers:
            journal.record_changes(self, buffers)

    def get_patch(self):
        return self._impl.get_patch()

    def get_changes(self, have_deps):
        return self._impl.get_changes(have_deps)

    def get_change_hashes(self, have_deps):
        return self._impl.get_change_hashes(have_deps)

    def get_changes_added(self, other):
        return self._impl.get_changes_added(other)

    def get_change_by_hash(self, hash):
        return self._impl.get_change_by_hash(hash)

    def probe_hashes(self, hashes):
        """Frontier-index membership flags (see _FlatEngine.probe_hashes);
        None after promotion or while the index is cold."""
        probe = getattr(self._impl, 'probe_hashes', None)
        return probe(hashes) if probe is not None else None

    def get_missing_deps(self, heads=()):
        return self._impl.get_missing_deps(heads)

    def save(self):
        return self._impl.save()

    def clone(self):
        if self.is_fleet:
            out = FleetDoc(self.fleet, self._impl.clone_engine())
        else:
            out = FleetDoc(self.fleet, self._impl.clone())
        journal = self.fleet.journal
        if journal is not None:
            # the clone is a NEW durable document whose history predates
            # its first journaled change: baseline it with one document
            # chunk, plus its causally-held-back queue buffers — the
            # original's queue records live under the ORIGINAL's durable
            # id, so the clone must carry its own copies or a crash
            # before the next checkpoint would drop them
            bufs = [bytes(out.save())]
            for entry in out.queue or []:
                if isinstance(entry, dict) and \
                        entry.get('buffer') is not None:
                    bufs.append(bytes(entry['buffer']))
            journal.record_changes(out, bufs)
        return out

    def free(self):
        journal = self.fleet.journal
        if journal is not None:
            journal.record_free(self)
        if self.is_fleet:
            self.fleet.free_slot(self._impl.slot)
        self._impl = None

    def materialize(self):
        """Exact current {key: value} state (host mirror when in fleet mode,
        whole-doc patch walk after promotion); nested objects render to
        plain Python values (str for text, list, dict for maps)."""
        if self.is_fleet:
            return self._impl.materialize()
        patch = self._impl.get_patch()
        return _leaf_value(patch['diffs'])


# ----------------------------------------------------------------------
# Backend-contract module surface (ref backend/index.js:1-8): identical to
# automerge_tpu.backend but init/load build fleet-routed documents. Pass this
# module (or a FleetBackend instance) to automerge_tpu.set_default_backend.
# ----------------------------------------------------------------------

_default_fleet = DocFleet()


def default_fleet():
    return _default_fleet


from ..backend import (  # noqa: E402
    _backend_state, apply_changes, apply_local_change, save,
    load_changes, get_patch, get_heads, get_all_changes, get_changes,
    get_changes_added, get_change_by_hash, get_missing_deps,
    generate_sync_message, receive_sync_message, encode_sync_message,
    decode_sync_message, init_sync_state, encode_sync_state,
    decode_sync_state, BloomFilter,
)


def init(fleet=None):
    return {'state': FleetDoc(fleet or _default_fleet), 'heads': []}


def load(data, fleet=None):
    handle = init(fleet)
    state = handle['state']
    state.apply_changes([data])
    return {'state': state, 'heads': state.heads}


def clone(backend):
    return {'state': _backend_state(backend).clone(),
            'heads': backend['heads']}


def free(backend):
    backend['state'].free()
    backend['state'] = None
    backend['frozen'] = True


class FleetBackend:
    """Object-style backend (equivalent to this module) bound to its own
    DocFleet — for isolating fleets or injecting a custom-capacity one."""

    def __init__(self, fleet=None):
        self.fleet = fleet or DocFleet()

    def init(self):
        return init(self.fleet)

    def load(self, data):
        return load(data, self.fleet)

    def __getattr__(self, name):
        import sys
        return getattr(sys.modules[__name__], name)


# ----------------------------------------------------------------------
# Fleet-level batched API: the TPU-idiomatic entry point
# ----------------------------------------------------------------------

@contextlib.contextmanager
def _gc_paused():
    """CPython's generational GC fires every ~700 net container
    allocations; a 10k-doc bulk init or commit allocates ~10^5 containers,
    paying ~170 gen-0 scans of an ever-growing heap — measured 4-7x the
    useful work of init_docs itself. Pause collection across the bounded
    bulk phase: everything allocated inside is live on exit, so the
    skipped scans could not have freed anything anyway. Reentrant-safe
    (restores the prior state), exception-safe (finally)."""
    was_enabled = gc.isenabled()
    gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()


def init_docs(n, fleet=None):
    """Create n fleet documents sharing one device fleet, with O(1)
    (size-independent) device work.

    Bulk-constructs the engines via _FlatEngine._bulk_new instead of
    going through init(): the per-doc constructor chain (init -> FleetDoc
    -> _FlatEngine -> HashGraph -> alloc_slot) costs ~8us/doc in CPython,
    which at 10k+ docs is a measurable slice of the turbo seam; pausing
    the GC across the loop saves another 4-7x (see _gc_paused). Slot
    numbers come from ONE alloc_slots call, and when the fleet already
    holds device state it is pre-grown to the new slot count in one step
    here — n fresh docs would otherwise regrow the [docs, keys] state
    O(log n) times across their first flushes. (A fleet with no device
    state yet keeps its lazy allocation: the first flush allocates at
    full capacity in one step, and seq-only fleets never pay for a grid.)"""
    fleet = fleet or _default_fleet
    out = []
    append = out.append
    bulk_new = _FlatEngine._bulk_new
    with _gc_paused():
        slots = fleet.alloc_slots(n)
        if fleet.state is not None:
            fleet._ensure_capacity(n_docs=fleet.n_slots,
                                   n_keys=len(fleet.keys))
        if fleet.reg_state is not None:
            fleet._ensure_reg_capacity(n_docs=fleet.n_slots,
                                       n_keys=len(fleet.keys))
        for slot in slots:
            d = FleetDoc.__new__(FleetDoc)
            d.fleet = fleet
            d._impl = bulk_new(fleet, slot)
            append({'state': d, 'heads': []})
    return out


def free_docs(handles):
    """Free n fleet documents with O(1) device dispatches: per owning
    fleet, one batched row-zeroing per engine kind (free_slots_batch)
    instead of the per-doc free() chain, which rewrites the whole device
    grid once per document. Handles are frozen like free()."""
    by_fleet = {}
    journals = {}
    for handle in handles:
        state = handle.get('state')
        if isinstance(state, FleetDoc):
            journal = state.fleet.journal
            if journal is not None:
                journal.record_free(state, commit=False)
                journals[id(journal)] = journal
            if state.is_fleet:
                fleet = state.fleet
                by_fleet.setdefault(id(fleet), (fleet, []))[1].append(
                    state._impl.slot)
            state._impl = None
        handle['state'] = None
        handle['frozen'] = True
    for journal in journals.values():
        journal.commit()          # one group commit for the whole batch
    for fleet, slots in by_fleet.values():
        fleet.free_slots_batch(slots)


def host_memory_stats(handles):
    """Host-RAM accounting for fleet documents (round-5 VERDICT item 8):
    what the HOST keeps per doc alongside the device state. Returns a
    dict of byte totals: change logs (the source of truth), rebuilt host
    mirrors (only docs something has read exactly), parked document
    chunks (bulk loads), plus the owning fleet's host-side structures
    (winner mirror, applied-op index, value table entry count). Device
    bytes live in DocFleet.memory_stats()."""
    log_bytes = queue_bytes = parked_bytes = 0
    mirrors = decoded = 0
    fleet = None
    for handle in handles:
        state = handle.get('state')
        if not isinstance(state, FleetDoc) or not state.is_fleet:
            continue
        impl = state._impl
        fleet = impl.fleet
        if impl._doc_pending is not None:
            parked_bytes += len(impl._doc_pending)
        # a parked doc's _changes holds its delta TAIL (changes accepted
        # since parking); both forms count — they are both host RAM
        log_bytes += sum(len(b) for b in impl._changes)
        for q in impl.queue:
            buf = q.get('buffer') if isinstance(q, dict) else None
            if buf is not None:
                queue_bytes += len(buf)
        if impl.mirror is not None:
            mirrors += 1
        if getattr(impl, '_doc_decoded', None) is not None:
            decoded += 1
    out = {
        'change_log_bytes': log_bytes,
        'parked_doc_bytes': parked_bytes,
        'queue_bytes': queue_bytes,
        'docs_with_host_mirror': mirrors,
        # rematerialized histories pin their decoded change dicts (larger
        # than the binary log) until the next park_docs — visible here so
        # the accounting cannot claim reclaim while they linger
        'docs_with_decoded_history': decoded,
        'n_docs': len(handles),
    }
    if fleet is not None:
        if fleet.host_winners is not None:
            out['host_winner_mirror_bytes'] = int(fleet.host_winners.nbytes)
        out['op_index_bytes'] = int(
            sum(a.nbytes for a in fleet._op_index.values()) +
            sum(p[1].nbytes for p in fleet._op_index_pending))
        out['value_table_entries'] = len(fleet.value_table)
    return out


def park_docs(handles):
    """Demote cold documents to their canonical saved chunk — the
    loader's parked form (`_doc_pending`), made available to LIVE docs:
    the host-side change log, deferred hash-graph records, graph dicts,
    and read mirrors collapse into ONE compressed document chunk per doc
    (BASELINE.md's 100k-doc host-memory plan, operational). Device state
    is untouched and causal state (heads/clock/maxOp/actorIds) stays
    live, so parked docs keep accepting changes through the turbo gate,
    serving sync, and answering bulk device reads; any history read
    rematerializes the log lazily from the chunk (the same machinery
    bulk-loaded documents already exercise, ref new.js:1709-1749 — the
    deferred document-chunk load). A history read or a new change
    REVIVES the host log (appending needs the change list); revived docs
    show up in host_memory_stats (change_log_bytes,
    docs_with_decoded_history) and re-park on the next park_docs call —
    parking is a policy the caller applies to docs it believes are cold,
    not a one-way compression.

    Soundness: the chunk is round-trip-validated once at park time — the
    native extractor reconstructs every change canonically and verifies
    the re-encoded hash frontier against the header heads (codec.cpp
    am_extract_changes; Python `decode_document` does the identical check
    when the native codec is absent or bails) — so a doc whose history
    cannot round-trip (e.g. foreign non-canonically-encoded changes) is
    left live rather than parked. The change COUNT comes from the same
    extraction instead of a full Python decode (the old
    decode-every-change-just-to-record-n cost). Docs with queued changes
    are skipped; an already-parked doc re-parks only when it has accrued
    a delta tail (changes accepted while parked), folding the tail into
    a fresh chunk. Returns the number of docs parked."""
    parked = 0
    flushed = set()
    cands = []                   # (impl, chunk) pending batch validation
    for handle in handles:
        state = handle.get('state')
        if not isinstance(state, FleetDoc) or not state.is_fleet:
            continue
        impl = state._impl
        fleet = impl.fleet
        if id(fleet) not in flushed:
            fleet.flush()
            flushed.add(id(fleet))
        if impl.queue or not impl._changes:
            # held-back queue entries can't be represented in a chunk;
            # no tail means either an empty doc or already parked clean
            continue
        cands.append((impl, bytes(impl.save())))
    # ONE batched validation for the whole park call: the native
    # extractor fans the chunks over its thread pool instead of paying a
    # per-doc FFI round trip
    counts = _validate_doc_chunks([chunk for _impl, chunk in cands])
    for (impl, chunk), n in zip(cands, counts):
        if n is None:
            continue          # cannot round-trip: stays live
        impl._install_parked_chunk(chunk, n)
        parked += 1
    return parked


def _validate_doc_chunks(chunks):
    """Batched round-trip validation: per chunk, its change count or
    None when the history cannot be reproduced from it (the park-time
    soundness gate). Native extraction validates by construction (heads
    verified against re-encoded hashes) over the thread pool; docs it
    bails on get the identical check from the Python decode."""
    if not chunks:
        return []
    native_out = native.extract_changes(chunks) if native.available() \
        else None
    out = [None] * len(chunks)
    from ..columnar import decode_document
    for i, chunk in enumerate(chunks):
        if native_out is not None and native_out[i] is not None:
            out[i] = len(native_out[i][0])
        else:
            try:
                out[i] = len(decode_document(chunk))
            except Exception:
                out[i] = None
    return out


def _validate_doc_chunk(chunk):
    """Single-chunk form of _validate_doc_chunks."""
    return _validate_doc_chunks([chunk])[0]


def rebuild_docs(handles, fleet=None, mirror=False):
    """Recover documents into a fresh fleet from their host-side change
    logs — the donation-failure contract (fleet/apply.py): a failed
    donated dispatch leaves the old fleet's device state unrecoverable,
    but the change logs remain the source of truth, so documents replay
    into new slots. Causally-held-back queue entries re-queue too.
    Returns new handles in input order; the old handles are frozen.

    Durability continuity: each rebuilt document keeps its durable id in
    its OWN source journal's registry (ids are per-journal), so no
    checkpoint ever snapshots the dead pre-rebuild states. When exactly
    one source journal is involved and the target fleet is unjournaled,
    the journal moves across (no baseline records needed — it already
    holds these docs' full accepted-change history, which is exactly
    what the rebuild replayed); with several source journals, or a
    target that already carries its own, the caller must re-home the
    managers explicitly (DurableFleet.adopt_fleet). Source fleets are
    detached either way — they are abandoned by contract."""
    fleet = fleet or DocFleet()
    per_doc, per_doc_queue, src_states, src_journals = [], [], [], []
    journals = {}
    src_fleets = {}
    for handle in handles:
        state = handle['state']
        impl = state._impl if isinstance(state, FleetDoc) else state
        journal = state.fleet.journal if isinstance(state, FleetDoc) \
            else None
        if journal is not None:
            journals[id(journal)] = journal
            src_fleets[id(state.fleet)] = state.fleet
        src_journals.append(journal)
        src_states.append(state)
        per_doc.append([bytes(b) for b in impl.changes])
        per_doc_queue.append([q['buffer'] for q in impl.queue
                              if isinstance(q, dict) and 'buffer' in q])
        handle['frozen'] = True
    for src_fleet in src_fleets.values():
        src_fleet.attach_journal(None)    # abandoned by contract
    new_handles = init_docs(len(handles), fleet)
    new_handles, _ = apply_changes_docs(new_handles, per_doc, mirror=mirror)
    if any(per_doc_queue):
        new_handles, _ = apply_changes_docs(new_handles, per_doc_queue,
                                            mirror=mirror)
    for old, journal, new_handle in zip(src_states, src_journals,
                                        new_handles):
        did = getattr(old, '_dur_id', None)
        if journal is not None and did is not None and \
                journal.docs.get(did) is old:
            new_state = new_handle['state']
            new_state._dur_id = did
            journal.docs[did] = new_state
    if len(journals) == 1 and fleet.journal is None:
        fleet.attach_journal(next(iter(journals.values())))
    return new_handles


# Fault-containment roll-up (observability.health_counts): documents
# rejected by quarantining batch calls, and how many change buffers went
# down with them. Module-level because quarantine also runs over host
# backends with no fleet in sight (the sync driver's receive path).
quarantine_stats = Counters({'quarantined_docs': 0,
                             'rejected_changes': 0})

# ---- memory-watermark tier: fleet-resident state ---------------------------
#
# Every live DocFleet's device grids + register/sequence pools + host
# mirror, summed on demand for the perf observatory's watermark sampler
# (perf.sample_watermarks). The WeakSet itself lives just under the
# import block (a fleet is constructed during module init, before this
# block runs).
def _fleet_bytes(fleet):
    import jax
    total = 0
    for state in (fleet.state, fleet.reg_state):
        if state is not None:
            total += sum(getattr(leaf, 'nbytes', 0)
                         for leaf in jax.tree_util.tree_leaves(state))
    if fleet.host_winners is not None:
        total += fleet.host_winners.nbytes
    pools = getattr(fleet, 'seq_pools', None)
    if pools is not None:
        for state in list(pools.pools.values()):
            total += sum(getattr(leaf, 'nbytes', 0)
                         for leaf in jax.tree_util.tree_leaves(state))
    return total


def fleets_resident_bytes():
    """Resident bytes across every live fleet's device/mirror state."""
    return sum(_fleet_bytes(fleet) for fleet in list(_live_fleets))


register_mem_source('fleet_resident_bytes', fleets_resident_bytes)
register_health_source('quarantined_docs',
                       lambda: quarantine_stats['quarantined_docs'])
register_health_source('rejected_changes',
                       lambda: quarantine_stats['rejected_changes'])


def _journal_of(handles):
    """The attached ChangeJournal of the handles' fleet, or None. Turbo
    batches require a single shared fleet, so the first fleet doc's
    journal is THE journal."""
    for handle in handles:
        state = handle.get('state') if isinstance(handle, dict) else None
        if isinstance(state, FleetDoc) and state.is_fleet:
            return state.fleet.journal
    return None


def apply_changes_docs(handles, per_doc_changes, mirror=True,
                       on_error='raise', deadline=None, _parsed=None):
    """Apply per-document change lists across the fleet. Returns
    (see _apply_changes_docs_impl for the full contract). When
    observability is enabled the whole batch records an `apply_batch`
    span and an `apply_batch_s` latency histogram sample. `deadline` (a
    service.deadline.Deadline) is checked HERE, before any parse or
    mutation: an expired deadline raises typed DeadlineExceeded with the
    batch entirely unapplied — the all-or-nothing half of the service's
    deadline contract (work that expires DURING the batch still commits;
    late useful work beats a torn doc). `_parsed` is the pipelined
    driver's pre-parsed native ingest result (private — see
    apply_changes_docs_pipelined)."""
    if deadline is not None:
        deadline.check(what='apply_changes_docs')
    start = time.perf_counter()
    with _span('apply_batch', docs=len(handles), mirror=mirror,
               on_error=on_error):
        out = _apply_changes_docs_impl(handles, per_doc_changes, mirror,
                                       on_error, _parsed)
    _hist.record_value('apply_batch_s', time.perf_counter() - start,
                       scale=1e9, unit='s')
    return out


def apply_changes_docs_pipelined(handles, per_doc_changes, sub_batches=4,
                                 mirror=False):
    """Pipelined turbo apply: split every document's change run into
    `sub_batches` consecutive sub-runs and overlap the NATIVE PARSE of
    sub-run k+1 with the host gate/commit and (async) device dispatch of
    sub-run k. The parse runs on a background Python thread, but the
    native codec releases the GIL across the whole batch and fans the
    chunks over its thread pool, so the overlap is real CPU concurrency,
    not just dispatch asynchrony — the span rig shows `parse_chunk` /
    `native_parse` spans tiling under the previous sub-batch's
    `turbo_commit`/`turbo_dispatch` phases (bench.py's seam section
    measures the overlap from the exported trace).

    Committed state is byte-identical to `sub_batches` sequential
    apply_changes_docs calls over the same splits (the prefetched parse
    is a pure function of the bytes). Only the turbo path pipelines; a
    sub-batch that falls back to the exact path simply ignores its
    prefetched parse. mirror=True (exact path) has no native parse to
    overlap, so it routes to the plain call."""
    if mirror or sub_batches <= 1:
        return apply_changes_docs(handles, per_doc_changes, mirror=mirror)
    work = [c if isinstance(c, (list, tuple)) else list(c)
            for c in per_doc_changes]
    subs = []
    for s in range(int(sub_batches)):
        sub = [None] * len(work)
        any_changes = False
        for d, changes in enumerate(work):
            step = -(-len(changes) // int(sub_batches))   # ceil
            run = changes[s * step:(s + 1) * step] if step else []
            sub[d] = run
            any_changes = any_changes or bool(run)
        if any_changes:
            subs.append(sub)
    if not subs:
        return apply_changes_docs(handles, per_doc_changes, mirror=False)

    # Producer thread streams parses AHEAD of the consumer (bounded at 2
    # in flight so a long run never accumulates every parsed sub-batch in
    # memory): while the main thread gates/commits/dispatches sub-batch
    # k, the producer is already parsing k+1 — and, once that lands, k+2.
    # The native parse releases the GIL, so this is core-level overlap.
    results = queue.Queue(maxsize=2)
    stop = []

    def producer():
        for sub in subs:
            if stop:
                break
            try:
                flat = [b if type(b) is bytes else bytes(b)
                        for changes in sub for b in changes]
                parsed = (len(flat), native.ingest_changes(
                    flat, None, with_meta=True, with_seq=True))
            except BaseException as exc:
                # the consumer's blocking get() must never wait on a dead
                # producer: ship the failure and let the main thread raise
                results.put(exc)
                return
            results.put(parsed)

    worker = threading.Thread(target=producer, daemon=True)
    worker.start()
    patches = [None] * len(handles)
    try:
        for sub in subs:
            parsed = results.get()
            if isinstance(parsed, BaseException):
                raise parsed
            handles, patches = apply_changes_docs(handles, sub, mirror=False,
                                                  _parsed=parsed)
    finally:
        # On an exception mid-pipeline the producer may be blocked on a
        # full queue: signal it and drain so join() cannot hang.
        stop.append(True)
        try:
            while True:
                results.get_nowait()
        except queue.Empty:
            pass
        worker.join()
    return handles, patches


def _apply_changes_docs_impl(handles, per_doc_changes, mirror, on_error,
                             _parsed=None):
    """Apply per-document change lists across the fleet. Returns
    (new_handles, patches) — or (new_handles, patches, errors) with
    on_error='quarantine', where a bad input rejects ONLY its own doc
    (errors[i] is a DocError; healthy docs commit in the same fused
    dispatch). on_error='raise' keeps the classic batch-fatal contract,
    now with typed exceptions carrying `doc_index`.

    mirror=True (exact): per-doc causal gating and patch mirrors on host,
    then ONE batched ingest + merge dispatch for every document's ops.

    mirror=False (turbo): only change *headers* are decoded on host (hash,
    deps, actor/seq — the causal gate and hash graph stay exact); the op
    columns go straight from the wire through the native C++ parser into the
    device merge, never materializing per-op Python objects. Patches come
    back as None and per-key mirrors are marked stale — reads rebuild them
    lazily. Sync protocol functions need only the hash graph, so they work
    on turbo documents without any rebuild.

    Validation: turbo checks the causal gate (seq contiguity, deps),
    chunk checksums/hashes, intra-batch duplicate opIds, AND map-key pred
    well-formedness — a change whose pred names no existing op row is
    rejected at apply time with the exact path's error and full rollback
    (the per-slot applied-op index, DocFleet._op_index, is the oracle;
    round-5, closing the old trust note). Residual envelope: sequence
    refs/preds drop-and-flag-inexact instead of raising (the mirror
    serves those docs), bulk-loaded docs skip the apply-time check for
    the slot's lifetime (their loaded history never fed the index;
    dangling preds there surface at the next mirror read), and a
    pred-less inc on a non-counter key surfaces at the next mirror read
    rather than at apply."""
    if on_error == 'quarantine':
        return _apply_changes_docs_quarantine(handles, per_doc_changes,
                                              mirror)
    if on_error != 'raise':
        raise ValueError(f"on_error must be 'raise' or 'quarantine', "
                         f"got {on_error!r}")
    if not mirror:
        journal = _journal_of(handles)
        if journal is not None:
            # turbo consumes one-shot iterables into its flat batch;
            # materialize them first so the journal hook sees the bytes.
            # The OUTER sequence materializes before the any() scan — a
            # generator argument would otherwise be consumed by the scan
            # itself and turbo would see an empty batch.
            if not isinstance(per_doc_changes, (list, tuple)):
                per_doc_changes = list(per_doc_changes)
            if any(not isinstance(c, (list, tuple))
                   for c in per_doc_changes):
                per_doc_changes = [c if isinstance(c, (list, tuple))
                                   else list(c) for c in per_doc_changes]
        with _gc_paused():
            turbo = _apply_changes_turbo(handles, per_doc_changes, _parsed)
            if turbo is not None and journal is not None:
                # inside the GC pause: the ~4 small objects per framed
                # record would otherwise re-trigger the gen-0 scans the
                # pause exists to avoid
                journal.record_seam(turbo[0], per_doc_changes)
        if turbo is not None:
            return turbo
        for handle in handles:
            state = handle.get('state')
            if isinstance(state, FleetDoc) and state.is_fleet:
                state.fleet.metrics.fallbacks += 1
                break
    out_handles, patches = [], []
    # per-doc applies journal through FleetDoc.apply_changes; group()
    # folds their commits into ONE write+fsync for the whole batch
    journal = _journal_of(handles)
    with journal.group() if journal is not None else \
            contextlib.nullcontext():
        for handle, changes in zip(handles, per_doc_changes):
            if changes:
                new_handle, patch = apply_changes(handle, changes)
            else:
                new_handle, patch = handle, None
            out_handles.append(new_handle)
            patches.append(patch)
    fleet = None
    for handle in out_handles:
        state = handle['state']
        if isinstance(state, FleetDoc) and state.is_fleet:
            fleet = state.fleet
            break
    if fleet is not None:
        fleet.flush()
    return out_handles, patches


def _screen_malformed_docs(work):
    """Per-doc screen after the batched native parse refused the whole
    flat batch (it cannot name the offender): re-parse each doc's buffers
    ALONE through the native parser — a doc that parses clean is healthy;
    a doc the parser refuses gets the (slow, Python) header decode to
    distinguish CORRUPT bytes (checksum/header damage -> quarantine,
    returned as [(doc, MalformedChange)]) from merely turbo-INELIGIBLE
    content (unsupported ops, document chunks — legal input that belongs
    on the exact path, where deeper corruption is already contained
    per-doc). The native fast path keeps the screen ~parse-speed for the
    N-K healthy docs; only refused docs pay Python decode. Host work
    only; no device dispatch."""
    from ..columnar import (CHUNK_TYPE_CHANGE, CHUNK_TYPE_DEFLATE,
                            decode_change_meta, split_containers)
    bad = []

    def classify(d):
        """Python header decode of one refused doc: corrupt vs ineligible."""
        try:
            for buf in work[d]:
                for chunk in split_containers(bytes(buf)):
                    if chunk[8] in (CHUNK_TYPE_CHANGE, CHUNK_TYPE_DEFLATE):
                        decode_change_meta(chunk, True)
                    elif hashlib.sha256(bytes(chunk[8:])).digest()[:4] != \
                            bytes(chunk[4:8]):
                        # an unknown container TYPE is legal to skip
                        # (forward compatibility) — but only when its
                        # checksum validates; a well-framed chunk whose
                        # checksum fails is corruption wearing an
                        # unknown-type byte (e.g. a bit flip IN the type
                        # byte) and must quarantine typed, not slide
                        # through as "nothing to apply" (found by the
                        # ISSUE-7 chaos client)
                        raise MalformedChange(
                            'container checksum mismatch on unknown '
                            f'chunk type {chunk[8]}', doc_index=d)
        except Exception as exc:
            bad.append((d, as_wire_error(exc, MalformedChange,
                                         'change screen', doc_index=d)))

    nonempty = [d for d, changes in enumerate(work) if changes]
    if not native.available():
        for d in nonempty:
            classify(d)
        return bad

    def scan(indices):
        """Bisect to the refused docs in O(K log N) native parses —
        parse failure is a per-buffer property, so a subset that parses
        clean clears every doc in it."""
        bufs = [bytes(b) for d in indices for b in work[d]]
        if native.ingest_changes(bufs, None, with_meta=True,
                                 with_seq=True) is not None:
            return
        if len(indices) == 1:
            classify(indices[0])
            return
        mid = len(indices) // 2
        scan(indices[:mid])
        scan(indices[mid:])

    scan(nonempty)
    return bad


def _apply_changes_docs_quarantine(handles, per_doc_changes, mirror):
    """Fault-contained batched apply: the blast radius of a bad input is
    ONE document. Returns (new_handles, patches, errors) with errors[i]
    a DocError for each rejected doc (None for healthy ones).

    Containment strategy: the turbo path validates the whole batch BEFORE
    its device dispatch and raises typed, doc-scoped errors with full
    rollback, so quarantine is a host-side retry loop — reject the
    offender's slot, re-run the (host-only) parse+validation over the
    survivors, and let the single fused device dispatch happen only on
    the attempt that passes. Survivors therefore commit in exactly the
    dispatches a clean batch of N-K docs would take (pinned by
    tests/test_quarantine.py); each retry costs one host-side re-parse of
    the surviving buffers, which is the right trade at K << N. When the
    native parser refuses the whole flat batch (it cannot say which
    buffer is corrupt), a per-doc header screen identifies the poisoned
    docs and the batch retries without them. Workloads turbo cannot take
    at all fall to the per-doc exact path, where isolation is free —
    each doc's gate failure is caught and recorded individually."""
    n = len(handles)
    work = []
    for d in range(n):
        changes = per_doc_changes[d] if d < len(per_doc_changes) else []
        work.append(list(changes) if changes else [])
    errors = [None] * n

    def reject(d, exc, stage):
        errors[d] = DocError(d, stage, exc)
        quarantine_stats.inc('quarantined_docs')
        quarantine_stats.inc('rejected_changes', len(work[d]))
        # flight-recorder event: WHICH doc (slot + durable id), WHAT
        # phase, WHAT typed error, plus a digest of the refused bytes so
        # the forensic dump can be matched to a captured wire corpus
        bufs = work[d]
        state = handles[d].get('state') if d < n else None
        _flight.record_event(
            'quarantine', doc=d, stage=stage,
            error=type(exc).__name__, message=str(exc)[:200],
            durable_id=getattr(state, '_dur_id', None),
            change_bytes=sum(len(b) for b in bufs),
            digest=hashlib.sha256(
                b''.join(bytes(b) for b in bufs)).hexdigest()[:16]
            if bufs else None)
        work[d] = []

    if not mirror:
        screened = False
        turbo = None
        # Bounded: every iteration either returns/breaks or rejects >= 1
        # doc, and only n docs exist
        for _ in range(n + 1):
            try:
                with _gc_paused():
                    turbo = _apply_changes_turbo(handles, work)
            except AutomergeError as exc:
                if exc.doc_index is None:
                    raise     # not doc-scoped: genuinely batch-fatal
                reject(exc.doc_index, exc, 'apply')
                continue
            if turbo is not None or screened:
                break
            # Native parse refused the flat batch without naming the
            # offender: screen headers per doc, quarantine the corrupt
            # ones, and give turbo one retry over the survivors
            screened = True
            bad = _screen_malformed_docs(work)
            if not bad:
                break             # turbo-ineligible workload, not corrupt
            for d, exc in bad:
                reject(d, exc, 'decode')
        if turbo is not None:
            out_handles, patches = turbo
            journal = _journal_of(out_handles)
            if journal is not None:
                with _gc_paused():
                    journal.record_seam(out_handles, work, errors)
            _dump_quarantine_record(out_handles, errors)
            return out_handles, patches, errors
        for handle in handles:
            state = handle.get('state')
            if isinstance(state, FleetDoc) and state.is_fleet:
                state.fleet.metrics.fallbacks += 1
                break
    # Exact / fallback path: the per-doc loop below is the SAME loop the
    # non-quarantining exact path runs — device work still lands in ONE
    # flush dispatch at the end (per-doc apply enqueues host-side), so
    # isolation here is free, not a batching forfeit (pinned by
    # test_exact_path_quarantine_isolates_per_doc's dispatch check).
    out_handles, patches = [], []
    # per-doc applies journal through FleetDoc.apply_changes; group()
    # folds their commits into ONE write+fsync for the whole batch
    journal = _journal_of(handles)
    with journal.group() if journal is not None else \
            contextlib.nullcontext():
        for d, handle in enumerate(handles):
            if work[d] and errors[d] is None:
                try:
                    new_handle, patch = apply_changes(handle, work[d])
                except Exception as exc:
                    # normalize so errors[d].error is ALWAYS typed — host
                    # gate ValueErrors arrive bare on this path
                    reject(d, as_wire_error(exc, InvalidChange, 'apply',
                                            doc_index=d), 'apply')
                    new_handle, patch = handle, None
            else:
                new_handle, patch = handle, None
            out_handles.append(new_handle)
            patches.append(patch)
    fleet = None
    for handle in out_handles:
        state = handle['state']
        if isinstance(state, FleetDoc) and state.is_fleet:
            fleet = state.fleet
            break
    if fleet is not None:
        fleet.flush()
    _dump_quarantine_record(out_handles, errors)
    return out_handles, patches, errors


def _dump_quarantine_record(handles, errors):
    """One forensic flight-recorder dump per quarantining batch that
    actually rejected something: every DocError described with its slot,
    stage, typed error, and durable id (when journaled), alongside the
    surrounding event ring. "quarantined_docs moved by K" becomes K
    named documents with context."""
    if not any(e is not None for e in errors):
        return
    detail = {'errors': [
        e.describe(durable_id=getattr(handles[i].get('state'), '_dur_id',
                                      None) if i < len(handles) else None)
        for i, e in enumerate(errors) if e is not None]}
    _flight.dump_flight_record('quarantine', detail)


class _LazyHandle(dict):
    """A backend handle whose 'heads' hexes LAZILY from the head32 row
    captured at commit time (dict ``__missing__``): the turbo fast path
    stopped materializing hex head strings per doc (the residual-floor
    fix), so a handle nobody asks for heads never pays the decode. The
    row is captured by VALUE at commit, so a stale handle still answers
    with its own generation's frontier exactly like the eager dict did.
    Every dict operation real callers use (['state'], ['heads'],
    .get('frozen'), item assignment, isinstance(..., dict)) behaves
    identically."""

    __slots__ = ('_head32',)

    def __missing__(self, key):
        if key == 'heads':
            value = [self._head32.tobytes().hex()]
            self['heads'] = value
            return value
        raise KeyError(key)


class _TurboMetaBatch:
    """Raw per-change metadata from the native parser, with lazy hex/dict
    materialization: the fast path touches only numpy arrays; full dicts are
    built per change only for general-path gating and deferred hash-graph
    resolution."""

    __slots__ = ('m', 'actors', 'buffers')

    def __init__(self, m, actors, buffers):
        self.m = m
        self.actors = actors
        self.buffers = buffers

    def hash_hex(self, i):
        return self.m['hash32'][i].tobytes().hex()

    def deps_hex(self, i):
        off = self.m['deps_off']
        blob = self.m['deps_blob']
        return [blob[32 * j:32 * (j + 1)].hex()
                for j in range(off[i], off[i + 1])]

    def message(self, i):
        off = self.m['msg_off']
        return self.m['msg_blob'][off[i]:off[i + 1]].decode('utf8')

    def meta(self, i):
        """Full change-header dict (general gating path)."""
        m = self.m
        return {
            'actor': self.actors[int(m['actor'][i])], 'seq': int(m['seq'][i]),
            'startOp': int(m['startOp'][i]), 'time': int(m['time'][i]),
            'message': self.message(i), 'deps': self.deps_hex(i),
            'extraBytes': None, 'hash': self.hash_hex(i),
            'buffer': self.buffers[i], 'ops': range(int(m['nops'][i])),
            '_change_index': i,
        }

    def resolve(self, i):
        """(hash, deps, actor, changes_meta entry) for HashGraph._ensure_graph."""
        m = self.m
        meta = {
            'actor': self.actors[int(m['actor'][i])], 'seq': int(m['seq'][i]),
            'maxOp': int(m['startOp'][i] + m['nops'][i] - 1),
            'time': int(m['time'][i]), 'message': self.message(i),
            'deps': self.deps_hex(i), 'extraBytes': None,
        }
        return self.hash_hex(i), meta['deps'], meta['actor'], meta


def _apply_changes_turbo(handles, per_doc_changes, parsed=None):
    """Header-decode + native-ingest batched apply. Returns None when the
    workload can't take the turbo path (no native codec, non-fleet docs,
    multi-chunk buffers, or ops outside the flat subset), in which case the
    caller falls back to the exact path.

    `parsed` is an optional pre-parsed native ingest result
    ``(n_buffers, native.ingest_changes(...) output)`` produced by a
    pipelined caller on a background thread (the native parse releases
    the GIL, so it genuinely overlaps the previous sub-batch's commit +
    device dispatch). It is used only when its buffer count matches this
    call's flat batch; the parse is a pure function of the bytes, so the
    result is identical to parsing inline.

    Control flow: one native parse for every change; chain validation
    (deps == current head, contiguous seqs) vectorized over the whole batch;
    docs that fit the linear-chain shape commit through the deferred hash
    graph with no per-change dict work, the rest go through the general
    causal gate. The call is atomic: any gate error rolls back every doc.

    Phase attribution: when spans are enabled the call tiles into
    contiguous `turbo_setup` / `turbo_parse` / `turbo_gate` /
    `turbo_commit` / `turbo_stage` / `turbo_dispatch` spans (no
    unattributed gap between marks — the coverage contract bench.py's
    observability section checks), with the native parse / device
    dispatch sub-spans nested inside."""
    ps = _span_seq()
    ps.mark('turbo_setup', docs=len(handles))
    try:
        return _apply_changes_turbo_inner(handles, per_doc_changes, ps,
                                          parsed)
    finally:
        ps.done()


def _apply_changes_turbo_inner(handles, per_doc_changes, ps, parsed=None):
    from .. import native
    from .tensor_doc import OpBatch, MAX_ACTORS as _MA

    if not native.available() or not handles:
        return None
    engines = []
    for handle in handles:
        state = handle.get('state')
        if handle.get('frozen') or not isinstance(state, FleetDoc) or \
                not state.is_fleet:
            return None
        if state._impl.queue:
            # Draining held-back changes needs their op rows; the exact path
            # re-ingests them on flush, so route this call there
            return None
        engines.append(state._impl)
    fleet = engines[0].fleet
    if any(e.fleet is not fleet for e in engines):
        return None
    flat_buffers = []
    per_doc_idx = [None] * len(handles)   # (start, stop) contiguous runs
    # zeros, not empty: a per_doc_changes shorter than handles must leave
    # the trailing docs' counts at 0 (the exact path's zip-truncate
    # semantics), not uninitialized garbage feeding np.repeat
    doc_counts = np.zeros(len(handles), dtype=np.int64)
    for d, changes in enumerate(per_doc_changes):
        k = len(flat_buffers)
        if not isinstance(changes, (list, tuple)):
            changes = list(changes)   # one-shot iterables: materialize once
        flat_buffers += changes
        per_doc_idx[d] = (k, len(flat_buffers))
        doc_counts[d] = len(flat_buffers) - k
    if set(map(type, flat_buffers)) - {bytes}:
        # one normalization pass; set(map(type, ...)) runs the scan at C
        # speed instead of a 200k-element genexpr
        flat_buffers = [bytes(b) for b in flat_buffers]
    change_doc = np.repeat(np.arange(len(handles), dtype=np.int64),
                           doc_counts)
    n_changes = len(flat_buffers)
    if not n_changes:
        return handles, [None] * len(handles)
    if (fleet.ctr_base or fleet.grid_overflow) and any(
            (e.slot in fleet.ctr_base or e.slot in fleet.grid_overflow) and
            per_doc_idx[d][0] != per_doc_idx[d][1]
            for d, e in enumerate(engines)):
        # Rebased/overflowed slots pack against per-slot counter bases the
        # native turbo parser does not apply: batches that actually touch
        # such a slot take the exact path; everything else keeps turbo
        return None
    # doc_ids=None: the zero-copy list entry (C walks the bytes objects
    # in place — no blob join, no length array; buffer i IS doc i here)
    ps.mark('turbo_parse', changes=n_changes)
    if parsed is not None and parsed[0] == n_changes:
        out = parsed[1]   # prefetched on a background thread (pipelined)
    else:
        out = native.ingest_changes(flat_buffers, None,
                                    with_meta=True, with_seq=True)
    if out is None:
        return None     # ops outside the fleet subset, or corrupt chunk
    rows, nat_keys, nat_actors, nmeta = out
    batch_meta = _TurboMetaBatch(nmeta, nat_actors, flat_buffers)
    ps.mark('turbo_gate')

    # ---- Batched linear-chain validation: ONE native call ----
    # A doc takes the fast path iff every change deps on exactly the
    # previous change (or the doc's current head for the first) and seqs
    # are contiguous per actor. Everything else gets the general gate.
    # The chain-link memcmps, deps-count checks, heads compare against
    # the columnar head32 rows, and per-(doc, actor) seq-run grouping
    # all run in codec.cpp's am_turbo_gate with the GIL released —
    # replacing the per-doc hex/dict probes AND the numpy argsort pass.
    doc_of = change_doc
    seqs = nmeta['seq']
    hash32 = nmeta['hash32']
    cols = fleet.doc_cols
    erows = np.fromiter((e.slot for e in engines), dtype=np.int64,
                        count=len(engines))
    if len(np.unique(erows)) != len(erows):
        # the same doc twice in one batch: the scatter commit would
        # collapse its two runs; the exact path applies them in order
        return None
    starts_all = np.cumsum(doc_counts) - doc_counts
    doc_off = np.concatenate([starts_all, [n_changes]])
    head_n_d = cols.head_n[erows]
    gate = native.turbo_gate(doc_off, nmeta['actor'], seqs, hash32,
                             nmeta['deps_off'], nmeta['deps_blob'],
                             cols.head32[erows], head_n_d)
    if gate is None:
        return None
    doc_ok, hostcheck, g_doc, g_actor, g_first, g_last = gate
    # Docs whose head frontier is not columnar-representable (multi-head)
    # get the host hex compare for JUST their first change — rare.
    for d in np.flatnonzero(hostcheck).tolist():
        if doc_ok[d] and doc_counts[d]:
            i = int(starts_all[d])
            heads = engines[d].heads
            if int(nmeta['deps_off'][i + 1] - nmeta['deps_off'][i]) != \
                    len(heads) or batch_meta.deps_hex(i) != heads:
                doc_ok[d] = False
    # Seq bases: each (doc, actor) run's first seq must extend the doc's
    # clock. Lane-mode rows check vectorized against the clock columns;
    # dict-mode rows (actor populations past the lane width) probe their
    # dicts per group.
    if len(g_doc):
        g_rows = erows[g_doc]
        ck_n_g = cols.ck_n[g_rows]
        reg = fleet._ck_reg
        reg_ids = np.fromiter((reg.get(a, -1) for a in nat_actors),
                              dtype=np.int64, count=len(nat_actors)) \
            if nat_actors else np.zeros(1, dtype=np.int64)
        g_reg = reg_ids[g_actor]
        base = np.zeros(len(g_doc), dtype=np.int64)
        known = g_reg >= 0
        if known.any():
            for l in range(cols.CLOCK_LANES):
                m = known & (cols.ck_actor[g_rows, l] == g_reg)
                if m.any():
                    base[m] = cols.ck_seq[g_rows[m], l]
        dmode = np.flatnonzero(ck_n_g == -1)
        for gi in dmode.tolist():
            base[gi] = engines[int(g_doc[gi])].clock.get(
                nat_actors[int(g_actor[gi])], 0)
        bad = g_first != base + 1
        if bad.any():
            doc_ok[g_doc[bad]] = False
    fast_mask = doc_ok

    flags_all = rows['flags']
    seq_sel = (flags_all >= 3) & (flags_all <= 6)
    make_sel = (flags_all >= 7) & (flags_all <= 10)
    seq_make_sel = flags_all >= 11      # makes inside sequences (11-14)
    nested_sel = (flags_all <= 2) & (rows['obj'] != 0)
    if seq_sel.any() or make_sel.any() or nested_sel.any() or \
            seq_make_sel.any():
        # RGA application is order-sensitive: if any doc needs the general
        # causal gate (whose applied order can differ from buffer order),
        # route the whole call to the exact path
        if (~fast_mask[doc_of]).any():
            return None
        # Every op's containing object must resolve to a registered object
        # or a make earlier in this batch; dangling objects get exact-path
        # error handling. Seq ops must target seq objects, keyed ops map
        # objects — a type mismatch is an exact-path error too.
        made_seq = [set() for _ in engines]
        made_map = [set() for _ in engines]
        _oid_memo = {}

        def _oid_of(p):
            oid = _oid_memo.get(p)
            if oid is None:
                oid = f'{p >> 8}@{nat_actors[p & (_MA - 1)]}'
                _oid_memo[p] = oid
            return oid

        mk_rows = np.flatnonzero(make_sel | seq_make_sel)
        mk_docs = change_doc[rows['doc'][mk_rows]].tolist()
        mk_packed = rows['packed'][mk_rows].tolist()
        mk_is_seq = np.isin(rows['flags'][mk_rows],
                            (7, 8, 11, 12)).tolist()
        for d, p, isq in zip(mk_docs, mk_packed, mk_is_seq):
            (made_seq if isq else made_map)[d].add(_oid_of(p))
        sq_rows = np.flatnonzero(seq_sel | seq_make_sel)
        sq_combo = np.unique(
            (change_doc[rows['doc'][sq_rows]] << 32) |
            rows['obj'][sq_rows].astype(np.int64))
        for cv in sq_combo.tolist():
            d, obj_nat = cv >> 32, cv & 0xffffffff
            oid = _oid_of(obj_nat)
            if oid not in made_seq[d] and \
                    oid not in engines[d].seq_objects:
                return None
        nm_rows = np.flatnonzero(nested_sel | (
            make_sel & (rows['obj'] != 0)))
        nm_combo = np.unique(
            (change_doc[rows['doc'][nm_rows]] << 32) |
            rows['obj'][nm_rows].astype(np.int64))
        for cv in nm_combo.tolist():
            d, obj_nat = cv >> 32, cv & 0xffffffff
            oid = _oid_of(obj_nat)
            if oid not in made_map[d] and \
                    oid not in engines[d].map_objects:
                return None
    # Decode every arena-boxed payload BEFORE the commit point: a payload
    # decode_value rejects (out-of-range leb, invalid UTF-8, bad float
    # width) must fall back to the exact path, not corrupt state after
    # heads/clock/logs have already advanced
    vlen_all = rows['vlen']
    voff_all = np.cumsum(vlen_all, dtype=np.int64) - vlen_all
    vblob = rows['vblob']
    vtype_all = rows['vtype']
    decode_sel = np.isin(flags_all, (1, 3, 4)) & (rows['value'] != -1) & \
        ((vlen_all > 0) | np.isin(vtype_all, (0, 1, 2)))
    # Distinct-value table for this batch: decoded_vals holds one dict per
    # DISTINCT wire payload, decoded_gid maps op rows into it (-1 = row
    # not decoded). Fleets repeat values heavily, so downstream interning
    # works per distinct value (vectorized scatter back to rows), never
    # per row — the old per-row dict cache cost more than the native parse
    # on the mixed seam.
    decoded_vals = []
    decoded_gid = np.full(len(flags_all), -1, dtype=np.int32)
    if decode_sel.any():
        from ..columnar import decode_value
        sel_idx = np.flatnonzero(decode_sel)
        vb = vblob if isinstance(vblob, np.ndarray) else \
            np.frombuffer(vblob, dtype=np.uint8)
        try:
            # Group rows by (len, vtype), then dedupe payload bytes within
            # each group so every distinct value decodes exactly once.
            combos = (vlen_all[sel_idx].astype(np.int64) << 8) | \
                vtype_all[sel_idx]
            corder = np.argsort(combos, kind='stable')
            csorted = combos[corder]
            starts = np.flatnonzero(np.r_[True, csorted[1:] != csorted[:-1]])
            stops = np.r_[starts[1:], len(csorted)]
            for gi in range(len(starts)):
                combo = int(csorted[starts[gi]])
                grp = sel_idx[corder[starts[gi]:stops[gi]]]
                ln, vt = combo >> 8, combo & 0xff
                if ln == 0:
                    decoded_gid[grp] = len(decoded_vals)
                    decoded_vals.append(decode_value(vt, b''))
                    continue
                mat = vb[voff_all[grp][:, None] + np.arange(ln)[None, :]]
                # one sort of packed rows (void view) instead of
                # np.unique(axis=0)'s per-byte-column lexsort
                packed_rows = np.ascontiguousarray(mat).view(
                    np.dtype((np.void, ln))).ravel()
                uq, inv = np.unique(packed_rows, return_inverse=True)
                decoded_gid[grp] = len(decoded_vals) + inv
                decoded_vals += [decode_value((ln << 4) | vt, u.tobytes())
                                 for u in uq]
        except Exception:
            return None

    # From here on the batch is committed to turbo (counted as such)
    fleet.metrics.turbo_calls += 1

    # Phase 1 — fallible: general causal gate for docs off the chain shape.
    # _drain_queue mutates clock/heads, so engines carry backups and any
    # failure restores all of them: the whole turbo call is atomic (the
    # exact path gets per-doc atomicity from fleet.pending instead).
    ready = fast_mask[doc_of]    # fancy-indexed: a fresh, writable array
    staged = []                  # general-path: (engine, applied, queue)
    backups = []                 # (engine, clock, heads, queue)

    def restore_all():
        for engine, clock, heads, queue in backups:
            engine.clock, engine.heads, engine.queue = clock, heads, queue

    for d in np.flatnonzero(~fast_mask & (doc_counts > 0)).tolist():
        engine = engines[d]
        start, stop = per_doc_idx[d]
        backups.append((engine, dict(engine.clock), list(engine.heads),
                        list(engine.queue)))
        try:
            applied, queue = engine._drain_queue(
                [batch_meta.meta(i) for i in range(start, stop)],
                lambda change: None)
        except Exception as exc:
            restore_all()
            # Gate errors are doc-scoped by construction (the drain loop
            # runs one doc's changes): type them so a quarantining caller
            # can reject slot d and retry the batch without it
            if isinstance(exc, AutomergeError):
                if exc.doc_index is None:
                    exc.doc_index = d
                raise
            if isinstance(exc, ValueError):
                raise InvalidChange(str(exc), doc_index=d) from exc
            raise
        staged.append((engine, applied, queue))
        for change in applied:
            ready[change['_change_index']] = True

    keep = ready[rows['doc']]
    # Validation from the native rows: duplicate opIds *within* the
    # applied batch are detectable per doc without decoding op objects.
    kept_change = rows['doc'][keep]      # native 'doc' is the change index
    kept_packed_nat = rows['packed'][keep]
    if len(kept_packed_nat):
        kept_doc = change_doc[kept_change]
        pairs = kept_doc * (1 << 32) + kept_packed_nat
        # run-boundary dup check (the trick staging uses): one sort and
        # an adjacent-equality scan — np.unique(return_counts=True) paid
        # for the unique array and a reduceat nobody read
        pairs_sorted = np.sort(pairs)
        dup = pairs_sorted[1:] == pairs_sorted[:-1]
        if dup.any():
            restore_all()
            bad_doc = int(pairs_sorted[1:][dup][0] >> 32)
            raise DuplicateOpId('duplicate operation ID in turbo batch',
                                doc_index=bad_doc)

    # Dangling-pred validation (map-key rows): every pred must name an op
    # ROW on its key — in the slot's applied-op index (_op_index) or
    # earlier in this batch — exactly the exact path's rule
    # (op_set.py `no matching operation for pred`; the reference rejects
    # invalid op references during the merge, new.js:1219-1220). Sequence
    # refs/preds keep their existing envelope (unknown targets drop and
    # flag inexact; the mirror serves). Bulk-loaded docs' indexes are
    # incomplete, so their rows skip the check rather than false-reject —
    # for them a dangling pred still surfaces at the next mirror rebuild.
    _validate_turbo_preds(fleet, engines, rows, keep, seq_sel, seq_make_sel,
                          change_doc, nat_keys, nat_actors, _MA,
                          restore_all)

    # Count only causally-applied changes: queued ones are re-counted when
    # the exact path drains and flushes them later. Byte counts come from
    # the parser's buf_len meta column — no Python len() pass.
    buf_len = nmeta['buf_len']
    fleet.metrics.changes_ingested += int(ready.sum())
    if ready.all():
        fleet.metrics.bytes_ingested += int(buf_len.sum())
    else:
        fleet.metrics.bytes_ingested += int(buf_len[ready].sum())

    # Phase 2 — infallible: record logs, queues, staleness
    ps.mark('turbo_commit', ready=int(ready.sum()))
    start_op = nmeta['startOp']
    nops = nmeta['nops']
    last_op = start_op + nops - 1
    # Per-doc max of last_op in one reduceat over the batch (a linear
    # chain does not guarantee the LAST change has the max op id, so the
    # old code took a numpy .max() per doc — ~27ms at 10k docs)
    nonempty = doc_counts > 0
    if _hist.on() and nonempty.any():
        # per-doc change bytes, one vectorized pass (reduceat over the
        # contiguous per-doc runs). Recorded HERE — past every validation
        # raise — so a quarantining caller's retry loop records each
        # batch's survivors exactly once, on the attempt that commits.
        _hist.histogram('doc_change_bytes', unit='B').record_many(
            np.add.reduceat(buf_len, starts_all[nonempty]))
    doc_max = np.zeros(len(handles), dtype=np.int64)
    if nonempty.any():
        doc_max[nonempty] = np.maximum.reduceat(
            last_op, starts_all[nonempty])
    fast_ne = np.flatnonzero(fast_mask & nonempty)
    # ---- Columnar commit: the whole fast-doc batch lands as vectorized
    # scatters into the _DocCols struct-of-arrays — no per-doc Python.
    # Head frontier: binary rows straight from the parser's hash lanes;
    # hex strings are NOT materialized here (the residual-floor fix) —
    # the heads property's per-row memo hexes on first genuine access,
    # and the returned handles capture their head32 row for the same
    # lazy treatment (_LazyHandle).
    frows = erows[fast_ne]
    last_idx = (starts_all + doc_counts - 1)[fast_ne]
    head_rows = hash32[last_idx]
    cols.head32[frows] = head_rows
    cols.head_n[frows] = 1
    cols.head_hex[frows] = None
    cols.head_obj[frows] = None
    cols.maxop[frows] = np.maximum(cols.maxop[frows], doc_max[fast_ne])
    cols.stale[frows] = True
    cols.bindoc[frows] = None
    # Log append, lazily: one _SeamSegs record for the whole batch; each
    # doc's (start, stop, base) segment folds into its real log only when
    # something reads history. Parked docs' bases account for the parked
    # prefix (the delta+main write path) — all from columns, no engine
    # attribute reads.
    log_lens = np.fromiter((len(e._log) for e in engines),
                           dtype=np.int64, count=len(engines))
    bases = log_lens[fast_ne] + cols.pend_n[frows]
    if cols.parked_n[frows].any():
        # only fleets that actually hold parked docs pay the object-
        # column scan for the parked-prefix bases
        parked = np.array([chunk is not None
                           for chunk in cols.pend_doc[frows]], dtype=bool)
        bases += np.where(parked, cols.parked_n[frows], 0)
    starts_f = starts_all[fast_ne]
    stops_f = starts_f + doc_counts[fast_ne]
    seg = _SeamSegs(flat_buffers, batch_meta,
                    dict(zip(frows.tolist(),
                             zip(starts_f.tolist(), stops_f.tolist(),
                                 bases.tolist()))))
    cols.pend_n[frows] += doc_counts[fast_ne]
    fleet._pend_seams.append(seg)
    if len(fleet._pend_seams) > _SEAM_FOLD_LIMIT:
        fleet._fold_all_pending()
    if fleet._hash_index is not None and len(fast_ne):
        # frontier-index staging for the whole fast batch: a host-side
        # numpy append of the parser's hash lanes (no dispatch here —
        # the next sync probe flushes). Staged/slow docs stage per
        # change via the _defer_record override below.
        fsel = fast_mask[doc_of]
        fleet._hash_index.stage_rows(erows[doc_of[fsel]], hash32[fsel])
    # Clock advance: the gate kernel's per-(doc, actor) groups scatter
    # their final seqs into the clock lanes. Rows already in dict mode,
    # or overflowing the lane width this batch, take the counted
    # fallback loop below (the regression guard pins it at zero for
    # fast-path workloads).
    fallback_docs = set()
    if len(g_doc):
        gsel = np.flatnonzero(fast_mask[g_doc])
        if len(gsel):
            s_rows = g_rows[gsel]
            s_reg = g_reg[gsel]
            s_last = g_last[gsel]
            dict_mode = cols.ck_n[s_rows] == -1
            lanes = np.full(len(gsel), -1, dtype=np.int64)
            for l in range(cols.CLOCK_LANES):
                lanes = np.where((cols.ck_actor[s_rows, l] == s_reg) &
                                 (s_reg >= 0), l, lanes)
            new = (lanes < 0) & ~dict_mode
            if new.any():
                # intern actors the clock registry hasn't seen
                for a in np.unique(np.asarray(g_actor)[gsel][new]).tolist():
                    hexa = nat_actors[a]
                    if hexa not in fleet._ck_reg:
                        fleet._ck_reg[hexa] = len(fleet._ck_names)
                        fleet._ck_names.append(hexa)
                reg_ids = np.fromiter(
                    (fleet._ck_reg.get(a, -1) for a in nat_actors),
                    dtype=np.int64, count=len(nat_actors))
                s_reg = reg_ids[np.asarray(g_actor)[gsel]]
                # per-row rank among this batch's new actors (groups of
                # one doc are contiguous in kernel order)
                ni = np.flatnonzero(new)
                rw = s_rows[ni]
                run_first = np.r_[True, rw[1:] != rw[:-1]]
                rank = np.arange(len(ni)) - \
                    np.repeat(np.flatnonzero(run_first),
                              np.diff(np.r_[np.flatnonzero(run_first),
                                            len(ni)]))
                lanes[ni] = cols.ck_n[rw] + rank
            over = lanes >= cols.CLOCK_LANES
            good = ~dict_mode & ~over
            if good.any():
                gi = np.flatnonzero(good)
                cols.ck_actor[s_rows[gi], lanes[gi]] = s_reg[gi]
                cols.ck_seq[s_rows[gi], lanes[gi]] = s_last[gi]
                newly = good & new
                if newly.any():
                    np.add.at(cols.ck_n, s_rows[newly], 1)
            if (dict_mode | over).any():
                fallback_docs.update(
                    np.asarray(g_doc)[gsel[dict_mode | over]].tolist())
    if fallback_docs:
        # Dict-mode / lane-overflow docs: per-doc dict merge — correct
        # for any actor population, counted so the guard can pin the
        # fast path at zero iterations.
        fleet.metrics.turbo_commit_fallback_docs += len(fallback_docs)
        gd = np.asarray(g_doc)
        ga = np.asarray(g_actor)
        for d in fallback_docs:
            engine = engines[d]
            clock = dict(engine.clock)
            for gi in np.flatnonzero(gd == d).tolist():
                clock[nat_actors[int(ga[gi])]] = int(g_last[gi])
            engine.clock = clock
    for engine, applied, queue in staged:
        # Slow/staged docs: the exact per-doc tail loop (counted — this
        # is the fallback path the columnar commit replaces for fast
        # docs).
        fleet.metrics.turbo_commit_fallback_docs += 1
        for change in applied:
            engine.changes.append(change['buffer'])
            engine._defer_record(change)
            engine.max_op = max(engine.max_op,
                                change['startOp'] + len(change['ops']) - 1)
            engine.stale = True
            engine.binary_doc = None
        engine.queue = queue
        if queue:
            # Queue entries from this pass carry only headers; flag the
            # mirror so the exact path re-decodes them before draining
            engine.stale = True

    for handle in handles:
        handle['frozen'] = True
    # Fast docs' handles capture their post-commit head32 ROW and hex it
    # only when someone reads 'heads' (_LazyHandle.__missing__) — the
    # commit fast path serves the handle contract with zero hex
    # materializations; slow/empty docs consult their engines eagerly
    # (few, and their memos are already warm).
    fast_pos = {int(d): k for k, d in enumerate(fast_ne.tolist())}
    out_handles = []
    for d, handle in enumerate(handles):
        k = fast_pos.get(d)
        if k is None:
            out_handles.append({'state': handle['state'],
                                'heads': engines[d].heads})
        else:
            lazy = _LazyHandle(state=handle['state'])
            lazy._head32 = head_rows[k]
            out_handles.append(lazy)
    result = out_handles, [None] * len(handles)
    if not keep.any():
        return result            # everything queued: no device work

    # Land any lazily-enqueued earlier changes first: the register engine
    # is order-sensitive (pred kills), and even the LWW grid's counter
    # reset bases on the pre-batch winner
    ps.mark('turbo_stage', kept=int(keep.sum()))
    fleet.flush()

    # Device batch: remap the native parser's key/actor numbering into the
    # fleet tables (interning only keys that actually land on the device)
    applied_actor_ids = np.unique(nmeta['actor'][ready])
    perm = fleet.actors.insert_many([nat_actors[int(a)]
                                     for a in applied_actor_ids])
    if perm is not None:
        if fleet.exact_device:
            fleet._remap_reg_actors(perm)
        else:
            fleet._remap_actors(perm)
        fleet._remap_seq_actors(perm)
    # -1 marks actors the fleet has never registered: ops' own actors are
    # always registered (applied_actor_ids above), so -1 can only surface
    # through pred/ref columns, where it flags the doc/row inexact instead
    # of silently renumbering to actor 0
    actor_map = np.array([fleet.actors.index.get(a, -1) for a in nat_actors],
                         dtype=np.int32) if nat_actors else np.zeros(1, np.int32)
    slot_of_doc = np.array([e.slot for e in engines], dtype=np.int64)

    keep_root = keep & ~seq_sel & ~seq_make_sel
    keep_seq = keep & (seq_sel | seq_make_sel)

    # Make ops: register the object with its engine (plus its device row
    # for sequences) and substitute the grid value with a link table ref.
    # Fleets repeat the same objectIds across docs, so the oid string and
    # the boxed link value (value-table interned by equality — slots
    # share it) memoize per packed id; only the per-slot seq-row
    # allocation and engine registration stay per doc.
    kept_vals_all = rows['value'].astype(np.int32, copy=True)
    kept_flags_all = rows['flags'].copy()
    _typ_lut = {7: 'text', 8: 'list', 9: 'map', 10: 'table',
                11: 'text', 12: 'list', 13: 'map', 14: 'table'}
    _mk_memo = {}    # (packed, make kind) -> (oid, typ, boxed link value)
    for ri in np.flatnonzero((make_sel | seq_make_sel) & keep).tolist():
        p = int(rows['packed'][ri])
        mk = int(rows['flags'][ri])
        # keyed on (p, mk): the same packed opId can be a different make
        # KIND on different docs in one batch (independent docs share
        # actor numbering), so type must not leak across docs
        memo = _mk_memo.get((p, mk))
        if memo is None:
            oid = f'{p >> 8}@{nat_actors[p & (_MA - 1)]}'
            typ = _typ_lut[mk]
            if typ in ('text', 'list'):
                boxed = fleet._intern_value_boxed(_SeqLink(oid))
            else:
                boxed = fleet._intern_value_boxed(_MapLink(oid, typ))
            memo = (oid, typ, boxed)
            _mk_memo[(p, mk)] = memo
        oid, typ, boxed = memo
        d = change_doc[int(rows['doc'][ri])]
        if typ in ('text', 'list'):
            engines[d].seq_objects[oid] = typ
            slot = engines[d].slot
            if oid not in fleet.slot_seq.get(slot, {}):
                fleet._alloc_seq_row(slot, oid, typ)
        else:
            engines[d].map_objects[oid] = typ
        # kept_vals_all carries the boxed link for BOTH make kinds; makes
        # inside sequences (mk >= 11) keep their wire insert bit in
        # rows['value'] and route to the seq dispatch, while map-key makes
        # become grid/register cell rows (flag 1)
        kept_vals_all[ri] = boxed
        if mk <= 10:
            kept_flags_all[ri] = 1
    if fleet.exact_device:
        # uint/counter/timestamp sets box with their wire datatype so
        # device-served patches keep exact datatypes and counter folds
        # (same rule as ingest.changes_to_op_rows; dels carry value -1 and
        # no typed vtype, so they never box)
        from .registers import typed_wire_tags
        _tags = typed_wire_tags()
        typed_sel = keep & (rows['flags'] == 1) & (rows['value'] != -1) & \
            (vlen_all == 0) & np.isin(rows['vtype'], list(_tags))
        typed_memo = {}
        for ri in np.flatnonzero(typed_sel).tolist():
            tk = (int(rows['value'][ri]), int(rows['vtype'][ri]))
            vid = typed_memo.get(tk)
            if vid is None:
                vid = fleet._intern_typed(tk[0], _tags[tk[1]])
                typed_memo[tk] = vid
            kept_vals_all[ri] = vid
    # arena-boxed map-cell payloads (strings/bools/None/floats/bytes,
    # out-of-lane ints): decode and intern by the shared rule (exact mode
    # keeps TypedValue datatypes; the LWW grid boxes raw). One table walk
    # per DISTINCT value per batch, scattered back to rows in one indexed
    # assign via the decoded_gid grouping.
    boxed_sel = keep & (rows['flags'] == 1) & (rows['value'] != -1) & \
        ((vlen_all > 0) | np.isin(rows['vtype'], (0, 1, 2)))
    boxed_idx = np.flatnonzero(boxed_sel)
    if len(boxed_idx):
        gids = decoded_gid[boxed_idx]
        if gids.min(initial=0) < 0:
            # boxed_sel ⊆ decode_sel; a -1 here is a parser-contract break
            # and must fail loudly, not index decoded_vals[-1]
            raise AssertionError('undecoded arena payload in turbo batch')
        uniq_g = np.unique(gids)
        if fleet.exact_device:
            vids = [fleet._intern_typed(decoded_vals[g]['value'],
                                        decoded_vals[g].get('datatype'))
                    for g in uniq_g.tolist()]
        else:
            vids = [fleet._intern_value(decoded_vals[g]['value'])
                    for g in uniq_g.tolist()]
        kept_vals_all[boxed_idx] = np.asarray(vids, dtype=np.int32)[
            np.searchsorted(uniq_g, gids)]

    def dispatch_seq_rows():
        """Kept sequence rows -> one SeqState dispatch (fleet numbering)."""
        if not keep_seq.any():
            return
        from .sequence import INC, INSERT, SET, DEL, PAD, SEQ_PRED_LANES
        sflags = rows['flags'][keep_seq]
        svtype = rows['vtype'][keep_seq]
        is_mk = sflags >= 11            # make element rows (11-14)
        s_insert = rows['value'][keep_seq] != 0   # wire insert bit (makes)
        svalue = rows['value'][keep_seq].astype(np.int64)
        if is_mk.any():
            # make rows carry their boxed link value, not the insert bit
            svalue[is_mk] = kept_vals_all[keep_seq][is_mk]
        sdoc = change_doc[rows['doc'][keep_seq]]
        sobj = rows['obj'][keep_seq].astype(np.int64)

        def remap_ids(p):
            # Unknown-actor refs/preds map to -1: never matches an element,
            # so the op drops and the row flags inexact (mirror serves it)
            a = actor_map[p & (_MA - 1)].astype(np.int64)
            return np.where(p != 0,
                            np.where(a >= 0, (p >> 8 << 8) | a, -1),
                            0).astype(np.int64)

        spacked = remap_ids(rows['packed'][keep_seq].astype(np.int64))
        sref = remap_ids(rows['ref'][keep_seq].astype(np.int64))
        pred_counts = np.diff(rows['pred_off'])
        n_seq = int(keep_seq.sum())
        D = SEQ_PRED_LANES
        counts_seq = pred_counts[keep_seq]
        off_seq = rows['pred_off'][:-1][keep_seq]
        pred_lanes = np.zeros((n_seq, D), dtype=np.int64)
        pred_col = rows['pred']
        for d in range(D):
            has = counts_seq > d
            if has.any():
                # gather THEN remap: only the kept seq rows' lanes, not the
                # whole batch's pred column
                pred_lanes[has, d] = remap_ids(
                    pred_col[off_seq[has] + d].astype(np.int64))
        pred_overflow = counts_seq > D
        # resolve device rows per unique (doc, objectId) — packed into one
        # int64 so the unique is a 1D sort, not np.unique(axis=0)'s
        # void-view compare (doc < 2^31, packed objectId < 2^31)
        combo = (sdoc << 32) | sobj
        uniq, inv = np.unique(combo, return_inverse=True)
        urow = np.empty(len(uniq), dtype=np.int64)
        oid_memo = {}
        for i, cv in enumerate(uniq.tolist()):
            d, obj_nat = cv >> 32, cv & 0xffffffff
            oid = oid_memo.get(obj_nat)
            if oid is None:
                oid = f'{obj_nat >> 8}@{nat_actors[obj_nat & (_MA - 1)]}'
                oid_memo[obj_nat] = oid
            urow[i] = fleet.slot_seq[int(slot_of_doc[d])][oid]
        srow = urow[inv]
        kind_lut = np.zeros(15, dtype=np.int64)
        kind_lut[3], kind_lut[4] = INSERT, SET
        kind_lut[5], kind_lut[6] = DEL, INC
        skind = kind_lut[sflags]
        if is_mk.any():
            skind[is_mk] = np.where(s_insert[is_mk], INSERT, SET)
        is_text = np.array([info is not None and info['type'] == 'text'
                            for info in fleet.seq_rows], dtype=bool)
        txt = is_text[srow]
        # host-side inexact flags: pred lists past the lane width, object
        # elements inside Text rows (span rendering is mirror territory —
        # same rule as _pack_seq_op), and inc deltas past the bit-packed
        # counter lane's +/-2^29 envelope; counters in sequences are
        # otherwise exact (INC kind + per-lane counter registers)
        val_op = (sflags == 3) | (sflags == 4)
        hflag = pred_overflow | (is_mk & txt) | \
            ((sflags == 6) & (np.abs(svalue) >= (1 << 29)))
        # Re-intern every payload the device lane can't carry inline
        # through _intern_seq_value — THE shared sequence-value rule:
        # text rows inline single code points, lists inline plain ints,
        # everything else (arena-boxed strings/bools/floats, datatyped
        # ints) boxes into the value table
        svlen = vlen_all[keep_seq]
        seq_ri = np.flatnonzero(keep_seq)
        tag_names = {3: 'uint', 4: 'int', 8: 'counter', 9: 'timestamp'}
        inline_ok = (svlen == 0) & np.where(txt, svtype == 6, svtype == 4)
        rebox = np.flatnonzero(val_op & ~hflag & ~inline_ok)
        seq_memo = {}
        for i in rebox.tolist():
            ln, vt = int(svlen[i]), int(svtype[i])
            if ln > 0 or vt in (0, 1, 2):
                # pre-validated: decode_sel covers every arena row here
                gid = int(decoded_gid[int(seq_ri[i])])
                if gid < 0:
                    raise AssertionError(
                        'undecoded arena payload in turbo seq batch')
                decoded = decoded_vals[gid]
                mk = (gid, bool(txt[i]))
            else:
                decoded = {'value': int(svalue[i]),
                           'datatype': tag_names.get(vt)}
                mk = (decoded['value'], decoded['datatype'], bool(txt[i]))
            vid = seq_memo.get(mk)
            if vid is None:
                vid = fleet._intern_seq_value(
                    'text' if txt[i] else 'list',
                    {'value': decoded['value'],
                     'datatype': decoded.get('datatype')})
                seq_memo[mk] = vid
            svalue[i] = vid
        fleet._dispatch_seq(np.stack(
            [srow, skind, sref, spacked, svalue,
             *(pred_lanes[:, d] for d in range(D)),
             hflag.astype(np.int64)], axis=1))

    n_kept_root = int(keep_root.sum())
    doc_arr = change_doc[rows['doc'][keep_root]].astype(np.int32)
    slots = slot_of_doc.astype(np.int32)[doc_arr]
    kept_packed_root = rows['packed'][keep_root]
    # Key interning: root keys as bare strings; nested map/table cells as
    # composite (objectId, key) — shared with the register ingest
    from .ingest import intern_composite_keys
    key = intern_composite_keys(rows['obj'][keep_root],
                                rows['key'][keep_root], nat_keys,
                                nat_actors, fleet.keys)
    ctr = kept_packed_root >> 8
    actor = actor_map[kept_packed_root & (_MA - 1)]
    packed = (ctr << 8) | actor
    # Feed the dangling-pred oracle: kept map-key rows that create op
    # rows (sets incl. makes folded to flags 1 with non-TOMBSTONE
    # values, and incs — never dels)
    _f = kept_flags_all[keep_root]
    _v = kept_vals_all[keep_root]
    _idx_sel = ((_f == 1) & (_v != TOMBSTONE)) | (_f == 2)
    fleet._index_ops(slots[_idx_sel], key[_idx_sel], packed[_idx_sel])

    if fleet.exact_device:
        from .registers import (apply_register_batch_donated,
                                rows_to_register_batch)
        if n_kept_root:
            # Slice the kept rows' pred segments and remap their actor bits
            pred_counts = np.diff(rows['pred_off'])
            entry_keep = np.repeat(keep_root, pred_counts)
            preds_kept = rows['pred'][entry_keep]
            pred_actor = actor_map[preds_kept & (_MA - 1)]
            bad_pred = (preds_kept != 0) & (pred_actor < 0)
            preds_kept = np.where(
                preds_kept != 0,
                (preds_kept >> 8 << 8) | pred_actor,
                0).astype(np.int32)
            preds_kept[bad_pred] = 0   # unknown-actor preds never reach device
            off_kept = np.zeros(n_kept_root + 1, dtype=np.int64)
            np.cumsum(pred_counts[keep_root], out=off_kept[1:])
            # Rows whose preds named an unregistered actor go inexact (host
            # replay re-validates them) rather than killing actor 0's slot
            bad_rows = np.zeros(n_kept_root, dtype=bool)
            if bad_pred.any():
                row_of_entry = np.repeat(np.arange(n_kept_root),
                                         pred_counts[keep_root])
                bad_rows[row_of_entry[bad_pred]] = True
            fleet._ensure_reg_capacity(n_docs=fleet.n_slots,
                                       n_keys=len(fleet.keys))
            n_cap = fleet.reg_state.reg.shape[0]
            reg_batch = rows_to_register_batch(
                slots.astype(np.int64), kept_flags_all[keep_root], key,
                packed, kept_vals_all[keep_root], off_kept, preds_kept,
                n_docs=n_cap, d_preds=fleet.d_preds,
                force_overflow=bad_rows)
            ps.mark('turbo_dispatch')
            fleet.reg_state, _stats = apply_register_batch_donated(
                fleet.reg_state, fleet._shard_docs(reg_batch))
            fleet.metrics.dispatches += 1
        dispatch_seq_rows()
        fleet.metrics.device_ops += int(keep.sum())
        return result

    if n_kept_root:
        n_slots = fleet.n_slots
        # Fused staging: size the device state FIRST and scatter the op
        # columns straight into capacity-shaped arrays — the old
        # stage-then-np.pad sequence copied every column a second time on
        # every turbo call (part of the round-5 "turbo-commit Python"
        # budget).
        fleet._ensure_capacity(n_docs=n_slots, n_keys=len(fleet.keys))
        n_cap = fleet._grid_cap()
        # Pred-scoped deletes (ref new.js:1204-1217): del rows (flags 1,
        # TOMBSTONE value — boxed values are <= -2, so -1 is del-only)
        # write no winner; their preds become kill lanes for the
        # kills-aware grid kernel. A pred naming an actor the fleet never
        # registered can't kill exactly — that slot's reads go
        # mirror-authoritative instead of mis-killing actor 0.
        vals_root = kept_vals_all[keep_root]
        flags_root = kept_flags_all[keep_root]
        del_sel = (flags_root == 1) & (vals_root == TOMBSTONE)
        # Lane layout without the old argsort pass: kept root rows are
        # already doc-contiguous (the parser emits rows in change order,
        # changes in doc order), so each row's lane is its rank within
        # its doc run — run boundaries + one repeat, no permutation.
        n_root = len(slots)
        run_starts = np.r_[0, np.flatnonzero(doc_arr[1:] != doc_arr[:-1])
                           + 1] if n_root else np.zeros(0, dtype=np.int64)
        run_lens = np.diff(np.r_[run_starts, n_root])
        pos = np.arange(n_root) - np.repeat(run_starts, run_lens)
        max_ops = max(int(run_lens.max()) if n_root else 0, 1)
        shape = (n_cap, max_ops)
        grid_cols = {name: np.zeros(shape, dtype=np.int32)
                     for name in ('key_id', 'packed', 'value')}
        is_set = np.zeros(shape, dtype=bool)
        is_inc = np.zeros(shape, dtype=bool)
        valid = np.zeros(shape, dtype=bool)
        grid_cols['key_id'][slots, pos] = key
        grid_cols['packed'][slots, pos] = packed
        grid_cols['value'][slots, pos] = vals_root
        flags_laid = np.where(del_sel, 0, flags_root)
        is_set[slots, pos] = flags_laid == 1
        is_inc[slots, pos] = flags_laid == 2
        valid[slots, pos] = flags_laid != 0
        batch = OpBatch(grid_cols['key_id'], grid_cols['packed'],
                        grid_cols['value'], is_set, is_inc, valid)

        kills = None
        kill_doc = kill_key_f = kill_packed_f = ()
        pred_counts = np.diff(rows['pred_off'])
        counts_root = pred_counts[keep_root]
        off_root = rows['pred_off'][:-1][keep_root]
        if del_sel.any():
            from .ingest import build_kill_lanes, layout_doc_rows
            # full-batch del mask (keep_root-aligned del_sel scattered
            # back) selects the del rows' pred runs out of the
            # full-batch pred_off layout
            del_all = np.zeros(len(pred_counts), dtype=bool)
            del_all[np.flatnonzero(keep_root)[del_sel]] = True
            kill_doc, kill_key_f, kill_packed_f = build_kill_lanes(
                slots[del_sel].astype(np.int64),
                key[del_sel].astype(np.int64), counts_root[del_sel],
                rows['pred'][np.repeat(del_all, pred_counts)], actor_map,
                on_bad_actor=lambda ds: fleet.grid_overflow.update(
                    int(s) for s in ds))
            # laid out at capacity so _dispatch_grid skips its pad copy
            (kk_arr, kp_arr), _ = layout_doc_rows(
                kill_doc, n_cap, (kill_key_f, kill_packed_f),
                (np.int32, np.int32))
            kills = (kk_arr, kp_arr)

        ps.mark('turbo_dispatch')
        fleet._dispatch_grid(batch, kills)
        # Counter-attribution check (see _note_grid_batch): advance the
        # host winner mirror with this batch's set and kill rows and
        # verify each inc's pred against the post-batch winner
        set_sel = (flags_root == 1) & ~del_sel
        inc_sel = flags_root == 2
        if set_sel.any() or inc_sel.any() or del_sel.any():
            inc_preds = _max_pred_per_inc(
                rows['pred'], off_root[inc_sel], counts_root[inc_sel],
                actor_map)
            fleet._note_grid_batch(slots[set_sel], key[set_sel],
                                   packed[set_sel], slots[inc_sel],
                                   key[inc_sel], inc_preds,
                                   kill_doc, kill_key_f, kill_packed_f)
    dispatch_seq_rows()
    fleet.metrics.device_ops += int(keep.sum())
    return result


def _validate_turbo_preds(fleet, engines, rows, keep, seq_sel, seq_make_sel,
                          change_doc, nat_keys, nat_actors, _MA,
                          restore_all):
    """Reject kept map-key rows whose preds name no existing op row —
    the turbo-path equivalent of op_set.py's per-op pred check. A pred
    exists iff it is (a) an earlier kept non-del map-key row of the same
    (doc, object, key) in THIS batch (ops arrive causally, so a valid
    pred's packed id is strictly below its op's), or (b) in the slot's
    standing applied-op index. Raises ValueError (after restore_all)
    with the exact path's message on the first dangling pred. The fast
    path — no preds, or every pred resolved batch-internally — is fully
    vectorized; only genuinely-missing candidates take the per-pred
    standing-index walk (they either resolve via the index or raise)."""
    pc = np.diff(rows['pred_off'])
    root_rows = keep & ~seq_sel & ~seq_make_sel
    check_rows = root_rows & (pc > 0)
    if not check_rows.any():
        return
    row_doc = change_doc[rows['doc']]
    slot_arr = np.fromiter((e.slot for e in engines), dtype=np.int64,
                           count=len(engines))
    if fleet._op_index_incomplete:
        inc = np.fromiter(
            (s in fleet._op_index_incomplete for s in slot_arr),
            dtype=bool, count=len(slot_arr))
        check_rows &= ~inc[row_doc]
        if not check_rows.any():
            return
    # Batch-internal pred targets: kept, non-seq, non-del rows (dels have
    # no rows in the reference representation; incs and makes do). Dense
    # collision-free ids for (doc, obj, key) triples — restricted to the
    # relevant rows (targets + rows under check), and built with two
    # 1D-packed uniques instead of np.unique(axis=0)'s void compare.
    tgt = root_rows & ~((rows['flags'] == 1) & (rows['value'] == TOMBSTONE))
    rel = np.flatnonzero(tgt | check_rows)
    objkey_rel = (rows['obj'][rel].astype(np.int64) << 32) | \
        rows['key'][rel].astype(np.int64)
    _u1, ok_inv = np.unique(objkey_rel, return_inverse=True)
    combo2_rel = (row_doc[rel].astype(np.int64) << 32) | \
        ok_inv.astype(np.int64)
    _u2, rel_inv = np.unique(combo2_rel, return_inverse=True)
    inv = np.zeros(len(row_doc), dtype=np.int64)
    inv[rel] = rel_inv
    tgt_combo = np.sort(inv[tgt] * (1 << 32) + rows['packed'][tgt])
    # Pred entries of the rows under check
    entry_sel = np.repeat(check_rows, pc)
    pred_nat = rows['pred'][entry_sel].astype(np.int64)
    owner = np.repeat(np.arange(len(pc)), pc)[entry_sel]
    pred_combo = inv[owner] * (1 << 32) + pred_nat
    in_batch = np.zeros(len(pred_nat), dtype=bool)
    if len(tgt_combo):
        pos = np.clip(np.searchsorted(tgt_combo, pred_combo), 0,
                      len(tgt_combo) - 1)
        in_batch = (tgt_combo[pos] == pred_combo) & \
            (pred_nat < rows['packed'][owner])
    missing = (pred_nat > 0) & ~in_batch
    if not missing.any():
        return
    # Lazily-pending earlier changes haven't fed the index yet: land
    # them before consulting it (they were already accepted — flushing
    # here mutates only fleet device state, never the engines' causal
    # state that restore_all guards)
    if fleet.pending:
        fleet.flush()
    # Standing-index check for the remainder, in fleet numbering (reads
    # only — unknown actors/keys simply have no standing ops)
    amap = np.array([fleet.actors.index.get(a, -1) for a in nat_actors],
                    dtype=np.int64) if nat_actors else np.zeros(1, np.int64)

    def raise_dangling(p, d):
        restore_all()
        pred = f'{p >> 8}@{nat_actors[p & (_MA - 1)]}'
        raise DanglingPred(f'no matching operation for pred: {pred}',
                           doc_index=d)

    key_cache = {}
    for i in np.flatnonzero(missing):
        p = int(pred_nat[i])
        d = int(row_doc[owner[i]])
        pa = int(amap[p & (_MA - 1)])
        if pa < 0:
            raise_dangling(p, d)
        o = int(rows['obj'][owner[i]])
        kn = int(rows['key'][owner[i]])
        fk = key_cache.get((o, kn), -2)
        if fk == -2:
            ks = nat_keys[kn]
            if o == 0:
                fk = fleet.keys.index.get(ks)
            else:
                oid = f'{o >> 8}@{nat_actors[o & (_MA - 1)]}'
                fk = fleet.keys.index.get((oid, ks))
            key_cache[(o, kn)] = fk
        if fk is None:
            raise_dangling(p, d)
        pf = (p >> 8 << 8) | pa
        slot = int(slot_arr[d])
        if not bool(fleet._index_lookup(
                slot, np.array([(fk << 32) | pf], dtype=np.int64))[0]):
            raise_dangling(p, d)


def _max_pred_per_inc(pred_col, offs, counts, actor_map):
    """Per inc row: the Lamport-max remapped pred packed id (the
    reference's counter attribution target, new.js:942-945), or -1 when
    absent or any pred names an unregistered actor. The single-pred
    common case is fully vectorized; only multi-pred rows (conflicted
    counters) loop."""
    out = np.full(len(offs), -1, dtype=np.int64)
    offs = np.asarray(offs)
    counts = np.asarray(counts)
    one = counts == 1
    if one.any() and len(pred_col):
        raw = pred_col[offs[one]].astype(np.int64)
        pa = actor_map[raw & (MAX_ACTORS - 1)].astype(np.int64)
        out[one] = np.where(pa >= 0, (raw >> 8 << 8) | pa, -1)
    for i in np.flatnonzero(counts > 1):
        off, cnt = int(offs[i]), int(counts[i])
        raw = pred_col[off:off + cnt].astype(np.int64)
        pa = actor_map[raw & (MAX_ACTORS - 1)].astype(np.int64)
        if (pa < 0).any():
            continue
        out[i] = int(((raw >> 8 << 8) | pa).max())
    return out


def _has_unresolved_link(value):
    """True if a materialized tree still contains a _SeqLink (device-inexact
    sequence row) or _MapLink (recursion-backstopped subtree) anywhere,
    including inside nested maps and rendered lists."""
    if isinstance(value, (_SeqLink, _MapLink)):
        return True
    if isinstance(value, dict):
        return any(_has_unresolved_link(v) for v in value.values())
    if isinstance(value, list):
        return any(_has_unresolved_link(v) for v in value)
    return False


def materialize_docs(handles):
    """Bulk {key: value} readback for many documents; fleet-resident docs
    come from one device transfer, promoted docs from their host engine."""
    by_fleet = {}
    for handle in handles:
        state = handle['state']
        if isinstance(state, FleetDoc) and state.is_fleet:
            fleet = state.fleet
            if id(fleet) not in by_fleet:
                by_fleet[id(fleet)] = fleet.materialize_all()
    inexact_by_fleet = {}
    out = []
    for handle in handles:
        state = handle['state']
        if isinstance(state, FleetDoc) and state.is_fleet:
            fleet = state.fleet
            if fleet.exact_device:
                if id(fleet) not in inexact_by_fleet:
                    inexact_by_fleet[id(fleet)] = fleet.inexact_slots()
                if state._impl.slot in inexact_by_fleet[id(fleet)]:
                    # History fell outside the register engine's exact
                    # shape: the host mirror is authoritative
                    out.append(state.materialize())
                    continue
            if state._impl.slot in fleet.grid_overflow or \
                    state._impl.slot in fleet.del_fallback:
                # Counter spread exceeded the packing window, or the
                # doc's history contains deletes (the grid's winner view
                # after kills is best-effort): the exact host mirror is
                # authoritative for this slot
                out.append(state.materialize())
                continue
            raw = by_fleet[id(fleet)][state._impl.slot]
            if _has_unresolved_link(raw):
                # A sequence row is device-inexact (concurrent overwrite,
                # counter in list): the host mirror serves the whole doc
                out.append(state.materialize())
            else:
                out.append(raw)
        elif isinstance(state, FleetDoc):
            out.append(state.materialize())
        else:
            raise TypeError('materialize_docs needs fleet backend handles')
    return out
