"""Observable: per-objectId change subscriptions (ref frontend/observable.js)."""

from .views import MapView, ListView, get_object_id
from .text import Text
from .table import Table


class Observable:
    def __init__(self):
        self.observers = {}  # objectId -> list of callbacks

    def patch_callback(self, patch, before, after, local, changes):
        self._object_update(patch['diffs'], before, after, local, changes)

    def _object_update(self, diff, before, after, local, changes):
        """Recursively walk the patch diff tree, tracking list index offsets
        between the before and after states (ref observable.js:28-100)."""
        if not diff.get('objectId'):
            return
        for callback in self.observers.get(diff['objectId'], []):
            callback(diff, before, after, local, changes)

        def conflicts_of(obj, key):
            if isinstance(obj, MapView):
                return obj._conflicts.get(key)
            if isinstance(obj, ListView) and isinstance(key, int) and \
                    0 <= key < len(obj._conflicts):
                return obj._conflicts[key]
            return None

        if diff['type'] == 'map' and diff.get('props'):
            for prop, prop_values in diff['props'].items():
                for op_id, subdiff in prop_values.items():
                    b = conflicts_of(before, prop)
                    a = conflicts_of(after, prop)
                    self._object_update(subdiff,
                                        b.get(op_id) if b else None,
                                        a.get(op_id) if a else None,
                                        local, changes)
        elif diff['type'] == 'table' and diff.get('props'):
            for row_id, row_values in diff['props'].items():
                for op_id, subdiff in row_values.items():
                    self._object_update(subdiff,
                                        before.by_id(row_id) if before else None,
                                        after.by_id(row_id) if after else None,
                                        local, changes)
        elif diff['type'] == 'list' and diff.get('edits') is not None:
            offset = 0
            for edit in diff['edits']:
                if edit['action'] == 'insert':
                    offset -= 1
                    a = conflicts_of(after, edit['index'])
                    self._object_update(edit['value'], None,
                                        a.get(edit['elemId']) if a else None,
                                        local, changes)
                elif edit['action'] == 'multi-insert':
                    offset -= len(edit['values'])
                elif edit['action'] == 'update':
                    b = conflicts_of(before, edit['index'] + offset)
                    a = conflicts_of(after, edit['index'])
                    self._object_update(edit['value'],
                                        b.get(edit['opId']) if b else None,
                                        a.get(edit['opId']) if a else None,
                                        local, changes)
                elif edit['action'] == 'remove':
                    offset += edit['count']
        elif diff['type'] == 'text' and diff.get('edits') is not None:
            offset = 0
            for edit in diff['edits']:
                if edit['action'] == 'insert':
                    offset -= 1
                    self._object_update(edit['value'], None,
                                        after.get(edit['index']) if after else None,
                                        local, changes)
                elif edit['action'] == 'multi-insert':
                    offset -= len(edit['values'])
                elif edit['action'] == 'update':
                    self._object_update(
                        edit['value'],
                        before.get(edit['index'] + offset) if before else None,
                        after.get(edit['index']) if after else None,
                        local, changes)
                elif edit['action'] == 'remove':
                    offset += edit['count']

    def observe(self, object, callback):
        object_id = get_object_id(object)
        if not object_id:
            raise TypeError('The observed object must be part of an Automerge document')
        self.observers.setdefault(object_id, []).append(callback)
