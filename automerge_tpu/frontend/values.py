"""CRDT value types: Counter and explicit number wrappers
(ref frontend/counter.js, frontend/numbers.js)."""

MAX_SAFE_INTEGER = 2 ** 53 - 1
MIN_SAFE_INTEGER = -(2 ** 53 - 1)


class Counter:
    """An integer that can only be incremented/decremented; addition is
    commutative so concurrent increments merge trivially."""

    def __init__(self, value=0):
        self.value = value or 0

    def __int__(self):
        return self.value

    def __eq__(self, other):
        if isinstance(other, Counter):
            return self.value == other.value
        return self.value == other

    def __hash__(self):
        return hash(self.value)

    def __repr__(self):
        return f'Counter({self.value})'

    def __str__(self):
        return str(self.value)

    def __add__(self, other):
        return self.value + other

    def __radd__(self, other):
        return other + self.value

    def __lt__(self, other):
        return self.value < other

    def __le__(self, other):
        return self.value <= other

    def __gt__(self, other):
        return self.value > other

    def __ge__(self, other):
        return self.value >= other

    def to_json(self):
        return self.value


class WriteableCounter(Counter):
    """Counter bound to a change context (ref frontend/counter.js:46-65)."""

    def __init__(self, value, context, path, object_id, key):
        super().__init__(value)
        self.context = context
        self.path = path
        self.object_id = object_id
        self.key = key

    def increment(self, delta=1):
        self.context.increment(self.path, self.key, delta)
        self.value += delta
        return self.value

    def decrement(self, delta=1):
        return self.increment(-delta)


class Int:
    def __init__(self, value):
        if not isinstance(value, int) or isinstance(value, bool) or \
                not (MIN_SAFE_INTEGER <= value <= MAX_SAFE_INTEGER):
            raise ValueError(f'Value {value} cannot be an int')
        self.value = value

    def __eq__(self, other):
        return isinstance(other, Int) and self.value == other.value

    def __hash__(self):
        return hash(('Int', self.value))


class Uint:
    def __init__(self, value):
        if not isinstance(value, int) or isinstance(value, bool) or \
                not (0 <= value <= MAX_SAFE_INTEGER):
            raise ValueError(f'Value {value} cannot be a uint')
        self.value = value

    def __eq__(self, other):
        return isinstance(other, Uint) and self.value == other.value

    def __hash__(self):
        return hash(('Uint', self.value))


class Float64:
    def __init__(self, value=0.0):
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            raise ValueError(f'Value {value} cannot be a float64')
        self.value = float(value or 0.0)

    def __eq__(self, other):
        return isinstance(other, Float64) and self.value == other.value

    def __hash__(self):
        return hash(('Float64', self.value))
