"""Mutable document proxies used inside change callbacks
(ref frontend/proxies.js, which uses ES6 Proxy; here they are explicit
MutableMapping/MutableSequence-style classes bound to a Context)."""

from collections.abc import MutableMapping, MutableSequence

from .values import Counter
from .text import Text
from .table import Table
from .views import ListView, get_object_id


class MapProxy(MutableMapping):
    def __init__(self, context, object_id, path):
        object.__setattr__(self, '_context', context)
        object.__setattr__(self, '_object_id', object_id)
        object.__setattr__(self, '_path', path)

    def _target(self):
        return self._context.get_object(self._object_id)

    def __setattr__(self, name, value):
        # Attribute assignment writes to the document, mirroring the JS
        # `doc.key = value` proxy API (ref frontend/proxies.js:126-130)
        self._context.set_map_key(self._path, name, value)

    def __getattr__(self, name):
        # Only called when normal lookup fails; expose document keys as attrs
        if name.startswith('_'):
            raise AttributeError(name)
        target = object.__getattribute__(self, '_context').get_object(
            object.__getattribute__(self, '_object_id'))
        if name in target:
            return self[name]
        raise AttributeError(name)

    def __getitem__(self, key):
        if key not in self._target():
            raise KeyError(key)
        return self._context.get_object_field(self._path, self._object_id, key)

    def get(self, key, default=None):
        if key in self._target():
            return self._context.get_object_field(self._path, self._object_id, key)
        return default

    def __setitem__(self, key, value):
        self._context.set_map_key(self._path, key, value)

    def __delitem__(self, key):
        if key not in self._target():
            raise KeyError(key)
        self._context.delete_map_key(self._path, key)

    def __contains__(self, key):
        return key in self._target()

    def __iter__(self):
        return iter(list(self._target().keys()))

    def __len__(self):
        return len(self._target())

    def keys(self):
        return list(self._target().keys())

    def update(self, other=(), **kwargs):
        items = other.items() if hasattr(other, 'items') else other
        for key, value in items:
            self[key] = value
        for key, value in kwargs.items():
            self[key] = value

    def __repr__(self):
        return f'MapProxy({dict(self._target())!r})'


class ListProxy(MutableSequence):
    def __init__(self, context, object_id, path):
        self._context = context
        self._object_id = object_id
        self._path = path

    def _target(self):
        return self._context.get_object(self._object_id)

    def __len__(self):
        return len(self._target())

    def __getitem__(self, index):
        target = self._target()
        if isinstance(index, slice):
            return [self._context.get_object_field(self._path, self._object_id, i)
                    for i in range(*index.indices(len(target)))]
        if index < 0:
            index += len(target)
        if index < 0 or index >= len(target):
            raise IndexError('list index out of range')
        return self._context.get_object_field(self._path, self._object_id, index)

    def __setitem__(self, index, value):
        if isinstance(index, slice):
            indices = range(*index.indices(len(self._target())))
            values = list(value)
            if len(indices) == len(values):
                for i, v in zip(indices, values):
                    self._context.set_list_index(self._path, i, v)
            elif index.step in (1, None):
                # Contiguous slice of different length: replace via splice
                self._context.splice(self._path, indices.start,
                                     len(indices), values)
            else:
                raise ValueError(
                    f'attempt to assign sequence of size {len(values)} to '
                    f'extended slice of size {len(indices)}')
            return
        if index < 0:
            index += len(self._target())
        self._context.set_list_index(self._path, index, value)

    def __delitem__(self, index):
        if isinstance(index, slice):
            indices = range(*index.indices(len(self._target())))
            self._context.splice(self._path, indices.start, len(indices), [])
            return
        if index < 0:
            index += len(self._target())
        self._context.splice(self._path, index, 1, [])

    def insert(self, index, value):
        self._context.splice(self._path, index, 0, [value])

    def insert_at(self, index, *values):
        self._context.splice(self._path, index, 0, list(values))
        return self

    def delete_at(self, index, num_delete=1):
        self._context.splice(self._path, index, num_delete, [])
        return self

    def append(self, *values):
        self._context.splice(self._path, len(self._target()), 0, list(values))

    def extend(self, values):
        self._context.splice(self._path, len(self._target()), 0, list(values))

    def fill(self, value, start=0, end=None):
        """Set a range of elements to `value` (ref proxies.js listMethods
        fill())."""
        length = len(self._target())
        for i in range(*slice(start, end).indices(length)):
            self._context.set_list_index(self._path, i, value)
        return self

    def pop(self, index=-1):
        if index < 0:
            index += len(self._target())
        value = self[index]
        self._context.splice(self._path, index, 1, [])
        return value

    def __iter__(self):
        for i in range(len(self._target())):
            yield self[i]

    def __eq__(self, other):
        if isinstance(other, (list, tuple)):
            return list(self) == list(other)
        return NotImplemented

    def __repr__(self):
        return f'ListProxy({list(self._target()._data)!r})'


def instantiate_proxy(context, path, object_id, read_only=None):
    object = context.get_object(object_id)
    if isinstance(object, Text) or isinstance(object, Table):
        return object.get_writeable(context, path)
    if isinstance(object, ListView):
        return ListProxy(context, object_id, path)
    return MapProxy(context, object_id, path)


def root_object_proxy(context):
    context.instantiate_object = \
        lambda path, object_id, read_only=None: \
        instantiate_proxy(context, path, object_id, read_only)
    return MapProxy(context, '_root', [])
