"""Frontend: immutable document tree + mutation API (ref frontend/index.js).

Documents are RootView objects (read-only mappings) carrying hidden state:
`_options`, `_cache` (objectId -> immutable view), and `_state`
({seq, maxOp, requests, clock, deps, backendState, lastLocalChange}).
"""

import re
import time as _time

from ..common import uuid
from .apply_patch import interpret_patch, clone_root_object
from .proxies import root_object_proxy
from .context import Context
from .text import Text
from .table import Table
from .values import Counter, Int, Uint, Float64
from .observable import Observable
from .views import MapView, RootView, ListView, get_object_id

__all__ = [
    'init', 'from_', 'change', 'empty_change', 'apply_patch',
    'get_object_id', 'get_object_by_id', 'get_actor_id', 'set_actor_id',
    'get_conflicts', 'get_last_local_change', 'get_backend_state',
    'get_element_ids', 'Text', 'Table', 'Counter', 'Observable',
    'Float64', 'Int', 'Uint',
]


def _check_actor_id(actor_id):
    if not isinstance(actor_id, str):
        raise TypeError(f'Unsupported type of actorId: {type(actor_id)}')
    if not re.fullmatch(r'[0-9a-f]+', actor_id):
        raise ValueError('actorId must consist only of lowercase hex digits')
    if len(actor_id) % 2 != 0:
        raise ValueError('actorId must consist of an even number of digits')


def _update_root_object(doc, updated, state):
    """Swap updated objects into a fresh cache (ref frontend/index.js:34-68)."""
    new_doc = updated.get('_root')
    if new_doc is None:
        new_doc = clone_root_object(doc._cache['_root'])
        updated['_root'] = new_doc
    new_doc._options = doc._options
    new_doc._cache = updated
    new_doc._state = state
    for object_id, view in doc._cache.items():
        if object_id not in updated:
            updated[object_id] = view
    return new_doc


def _count_ops(ops):
    count = 0
    for op in ops:
        if op['action'] == 'set' and 'values' in op:
            count += len(op['values'])
        elif op['action'] == 'del' and op.get('multiOp'):
            count += op['multiOp']
        else:
            count += 1
    return count


def _make_change(doc, context, options):
    """(ref frontend/index.js:78-118)"""
    actor = get_actor_id(doc)
    if not actor:
        raise ValueError('Actor ID must be initialized with set_actor_id() '
                         'before making a change')
    state = dict(doc._state)
    state['seq'] += 1
    options = options or {}
    change = {
        'actor': actor,
        'seq': state['seq'],
        'startOp': state['maxOp'] + 1,
        'deps': state['deps'],
        'time': options['time'] if isinstance(options.get('time'), (int, float))
        else int(round(_time.time())),
        'message': options.get('message') if isinstance(options.get('message'), str)
        else '',
        'ops': context.ops if context else [],
    }

    backend = doc._options.get('backend')
    if backend:
        # Immediate mode: round-trip through the attached backend. The patch is
        # effectively applied twice (context echo + backend round-trip,
        # rationale: frontend/index.js:101-105)
        new_backend_state, patch, binary_change = backend.apply_local_change(
            state['backendState'], change)
        state['backendState'] = new_backend_state
        state['lastLocalChange'] = binary_change
        new_doc = _apply_patch_to_doc(doc, patch, state, True)
        patch_callback = options.get('patchCallback') or \
            doc._options.get('patchCallback')
        if patch_callback:
            patch_callback(patch, doc, new_doc, True, [binary_change])
        return [new_doc, change]
    else:
        # Async mode: queue the request for a separate backend
        queued = {'actor': actor, 'seq': change['seq'], 'before': doc}
        state['requests'] = state['requests'] + [queued]
        state['maxOp'] = state['maxOp'] + _count_ops(change['ops'])
        state['deps'] = []
        return [_update_root_object(doc, context.updated if context else {}, state),
                change]


def _apply_patch_to_doc(doc, patch, state, from_backend):
    """(ref frontend/index.js:146-162)"""
    actor = get_actor_id(doc)
    updated = {}
    interpret_patch(patch['diffs'], doc, updated)
    if from_backend:
        if 'clock' not in patch:
            raise ValueError('patch is missing clock field')
        if patch['clock'].get(actor, 0) > state['seq']:
            state['seq'] = patch['clock'][actor]
        state['clock'] = patch['clock']
        state['deps'] = patch['deps']
        state['maxOp'] = max(state['maxOp'], patch['maxOp'])
    return _update_root_object(doc, updated, state)


def init(options=None):
    """Create an empty document (ref frontend/index.js:166-202)."""
    if isinstance(options, str):
        options = {'actorId': options}
    elif options is None:
        options = {}
    elif not isinstance(options, dict):
        raise TypeError(f'Unsupported value for init() options: {options}')

    if not options.get('deferActorId'):
        if options.get('actorId') is None:
            options['actorId'] = uuid()
        _check_actor_id(options['actorId'])

    if options.get('observable'):
        patch_callback = options.get('patchCallback')
        observable = options['observable']

        def combined(patch, before, after, local, changes):
            if patch_callback:
                patch_callback(patch, before, after, local, changes)
            observable.patch_callback(patch, before, after, local, changes)
        options['patchCallback'] = combined

    root = RootView()
    cache = {'_root': root}
    state = {'seq': 0, 'maxOp': 0, 'requests': [], 'clock': {}, 'deps': []}
    if options.get('backend'):
        state['backendState'] = options['backend'].init()
        state['lastLocalChange'] = None
    root._options = options
    root._cache = cache
    root._state = state
    return root


def normalize_initial_state(initial_state):
    """Coerce a `from_` initial state to a mapping, per the reference's JS
    object-spread semantics (ref test/test.js:39-55): sequences and strings
    become index-keyed maps, scalars contribute nothing, and anything else
    non-mapping is rejected rather than silently dropped."""
    import datetime as _datetime
    from .values import Counter, Int, Uint, Float64
    if isinstance(initial_state, (list, tuple, str)):
        return {str(i): v for i, v in enumerate(initial_state)}
    if initial_state is None or isinstance(
            initial_state, (int, float, bool, _datetime.datetime,
                            Counter, Int, Uint, Float64)):
        return {}    # scalars have no enumerable properties to spread
    if not hasattr(initial_state, 'items'):
        raise TypeError('Unsupported initial state: '
                        f'{type(initial_state).__name__}')
    return initial_state


def from_(initial_state, options=None):
    return change(init(options), 'Initialization',
                  lambda doc: doc.update(
                      normalize_initial_state(initial_state)))[0]


def change(doc, options=None, callback=None):
    """Mutate the document via `callback`; returns [new_doc, change_request]
    (ref frontend/index.js:224-254)."""
    from .proxies import MapProxy
    if isinstance(doc, MapProxy):
        raise TypeError('Calls to change cannot be nested')
    if get_object_id(doc) != '_root':
        raise TypeError('The first argument to change must be the document root')
    if callable(options) and callback is None:
        options, callback = None, options
    if isinstance(options, str):
        options = {'message': options}
    if options is not None and not isinstance(options, dict):
        raise TypeError('Unsupported type of options')

    actor_id = get_actor_id(doc)
    if not actor_id:
        raise ValueError('Actor ID must be initialized with set_actor_id() '
                         'before making a change')
    context = Context(doc, actor_id)
    callback(root_object_proxy(context))

    if not context.updated:
        return [doc, None]
    return _make_change(doc, context, options)


def empty_change(doc, options=None):
    if get_object_id(doc) != '_root':
        raise TypeError('The first argument to empty_change must be the document root')
    if isinstance(options, str):
        options = {'message': options}
    if options is not None and not isinstance(options, dict):
        raise TypeError('Unsupported type of options')
    actor_id = get_actor_id(doc)
    if not actor_id:
        raise ValueError('Actor ID must be initialized with set_actor_id() '
                         'before making a change')
    return _make_change(doc, Context(doc, actor_id), options)


def apply_patch(doc, patch, backend_state=None):
    """Apply a backend patch, reconciling the async-mode request queue
    (ref frontend/index.js:288-327)."""
    if get_object_id(doc) != '_root':
        raise TypeError('The first argument to apply_patch must be the document root')
    state = dict(doc._state)

    if doc._options.get('backend'):
        if backend_state is None:
            raise ValueError('apply_patch must be called with the updated backend state')
        state['backendState'] = backend_state
        return _apply_patch_to_doc(doc, patch, state, True)

    if state['requests']:
        base_doc = state['requests'][0]['before']
        if patch.get('actor') == get_actor_id(doc):
            if state['requests'][0]['seq'] != patch.get('seq'):
                raise ValueError(
                    f"Mismatched sequence number: patch {patch.get('seq')} does not "
                    f"match next request {state['requests'][0]['seq']}")
            state['requests'] = state['requests'][1:]
        else:
            state['requests'] = list(state['requests'])
    else:
        base_doc = doc
        state['requests'] = []

    new_doc = _apply_patch_to_doc(base_doc, patch, state, True)
    if not state['requests']:
        return new_doc
    state['requests'] = list(state['requests'])
    state['requests'][0] = dict(state['requests'][0], before=new_doc)
    return _update_root_object(doc, {}, state)


def get_object_by_id(doc, object_id):
    return doc._cache.get(object_id)


def get_actor_id(doc):
    return doc._state.get('actorId') or doc._options.get('actorId')


def set_actor_id(doc, actor_id):
    _check_actor_id(actor_id)
    state = dict(doc._state, actorId=actor_id)
    return _update_root_object(doc, {}, state)


def get_conflicts(object, key):
    """Expose multi-value register conflicts (ref frontend/index.js:374-379)."""
    if isinstance(object, MapView):
        conflicts = object._conflicts.get(key)
    elif isinstance(object, ListView):
        conflicts = object._conflicts[key] if key < len(object._conflicts) else None
    else:
        return None
    if conflicts and len(conflicts) > 1:
        return conflicts
    return None


def get_last_local_change(doc):
    return doc._state.get('lastLocalChange')


def get_backend_state(doc, caller_name=None, arg_pos='first'):
    if get_object_id(doc) != '_root':
        extra = '. Note: applyChanges returns a [doc, patch] pair.' \
            if isinstance(doc, (list, tuple)) else ''
        if caller_name:
            raise TypeError(f'The {arg_pos} argument to {caller_name} must be the '
                            f'document root{extra}')
        raise TypeError(f'Argument is not an Automerge document root{extra}')
    return doc._state['backendState']


def get_element_ids(list_):
    if isinstance(list_, Text):
        return [elem['elemId'] for elem in list_.elems]
    return list(list_._elem_ids)
